"""Launcher tests (reference ``test/single/test_run.py`` analogue) plus a
real 2-process integration run (``test_static_run.py`` analogue)."""

import os
import subprocess
import sys

import pytest

from horovod_tpu.utils.platform import multiprocess_cpu_supported

# These tests launch REAL multi-process XLA computations; this jaxlib's
# CPU backend cannot run them ("Multiprocess computations aren't
# implemented on the CPU backend"), so they only run on capable jaxlib
# builds / real accelerators.
_requires_multiprocess = pytest.mark.skipif(
    not multiprocess_cpu_supported(),
    reason="this jaxlib cannot run multiprocess computations on the "
           "CPU backend")

from horovod_tpu.run import check_build, free_port, worker_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_check_build_lists_capabilities():
    text = check_build()
    assert "XLA:TPU collectives" in text
    assert "Adasum" in text
    assert "elastic" in text


def test_free_port_is_bindable():
    import socket
    p = free_port()
    with socket.socket() as s:
        s.bind(("127.0.0.1", p))


def test_worker_env_contents():
    env = worker_env(rank=1, size=4, coordinator="127.0.0.1", port=1234,
                     cpu=True, slots=2)
    assert env["HOROVOD_RANK"] == "1"
    assert env["HOROVOD_SIZE"] == "4"
    assert env["HVD_TPU_COORDINATOR_PORT"] == "1234"
    assert env["HVD_TPU_FORCE_CPU"] == "1"
    assert "--xla_force_host_platform_device_count=2" in env["XLA_FLAGS"]


def test_cli_requires_command():
    from horovod_tpu.run import run_command
    with pytest.raises(SystemExit):
        run_command(["-np", "2"])


@pytest.mark.integration
@_requires_multiprocess
def test_two_process_static_run():
    """Spawn a real 2-process job through the CLI (slow: ~30s)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # Workers must not inherit the test session's forced-cpu XLA flags in a
    # way that conflicts; launcher sets its own.
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         sys.executable, os.path.join(REPO, "examples",
                                      "allreduce_check.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "[0]<stdout>" in out.stdout
    assert "rank 0: barrier OK" in out.stdout
    assert "rank 1: barrier OK" in out.stdout


@pytest.mark.integration
def test_failing_worker_propagates_exit_code(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         sys.executable, str(bad)],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 3


# ---------------------------------------------------------------------------
# Host parsing (-H / --hostfile)
# ---------------------------------------------------------------------------


def test_parse_host_spec_forms():
    from horovod_tpu.run.hosts import parse_host_spec, total_slots
    hosts = parse_host_spec("h1:4, h2:2,h3")
    assert hosts == [("h1", 4), ("h2", 2), ("h3", 1)]
    assert total_slots(hosts) == 7
    with pytest.raises(ValueError, match="slots"):
        parse_host_spec("h1:x")
    with pytest.raises(ValueError, match="empty host"):
        parse_host_spec(":4")


def test_parse_hostfile(tmp_path):
    from horovod_tpu.run.hosts import parse_hostfile
    hf = tmp_path / "hosts"
    hf.write_text("# cluster\nnode1 slots=4\nnode2:2\nnode3\n")
    assert parse_hostfile(str(hf)) == [("node1", 4), ("node2", 2),
                                       ("node3", 1)]


def test_all_local_detection():
    from horovod_tpu.run.hosts import all_local
    assert all_local([("localhost", 2), ("127.0.0.1", 1)])
    assert not all_local([("localhost", 2), ("farawaynode", 1)])


def test_launcher_hosts_errors(tmp_path):
    from horovod_tpu.run import run_command
    with pytest.raises(SystemExit):  # remote hosts unsupported locally
        run_command(["-H", "remote1:4", "python", "x.py"])
    with pytest.raises(SystemExit):  # malformed slots -> usage error
        run_command(["-H", "localhost:x", "python", "x.py"])
    with pytest.raises(SystemExit):  # static hosts + elastic conflict
        run_command(["-H", "localhost:2", "--host-discovery-script",
                     "d.sh", "python", "x.py"])


def test_hostfile_validates_slots(tmp_path):
    from horovod_tpu.run.hosts import parse_hostfile
    bad = tmp_path / "bad"
    bad.write_text("node1:0\n")
    with pytest.raises(ValueError, match=">= 1"):
        parse_hostfile(str(bad))
    bad.write_text("node1 slots=-3\n")
    with pytest.raises(ValueError, match=">= 1"):
        parse_hostfile(str(bad))
    bad.write_text("node1:x\n")
    with pytest.raises(ValueError, match="integer"):
        parse_hostfile(str(bad))


def test_ipv6_host_specs():
    from horovod_tpu.run.hosts import all_local, parse_host_spec
    assert parse_host_spec("::1") == [("::1", 1)]
    assert parse_host_spec("[::1]:2") == [("::1", 2)]
    assert parse_host_spec("[2001:db8::2]:4") == [("2001:db8::2", 4)]
    assert all_local([("::1", 2)])


@pytest.mark.integration
@pytest.mark.parametrize("np_", [2, 3])
@_requires_multiprocess
def test_join_drains_stragglers(np_):
    """Reference JoinOp behavior: ranks stop after different batch counts;
    survivors' averages cover active ranks only; nobody deadlocks; join
    returns the last rank to join (twice -- generations reset).  np=3
    exercises concurrent metadata publishing by MULTIPLE active ranks
    while one rank drains."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["HOROVOD_JOIN_TIMEOUT"] = "60"
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", str(np_), "--cpu",
         sys.executable, os.path.join(REPO, "examples", "join_check.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    last = np_ - 1
    assert f"rank 0: join OK last={last}" in out.stdout
    assert f"rank {last}: allgatherv-during-join OK" in out.stdout
    assert f"rank {last}: grouped-during-join OK" in out.stdout
    # Round-5 deferred async batch (3 ops, one presence round) issued
    # while the other rank(s) are drained.
    assert f"rank {last}: async-ungrouped-during-join OK" in out.stdout
    # Round-6 fused flush: a mixed-dtype async batch splits into two
    # fused buckets mid-drain; drained ranks replay them bitwise from
    # the published fused layouts.
    assert f"rank {last}: fused-async-during-join OK" in out.stdout
    assert f"rank {last}: join2 OK last={last}" in out.stdout


_PEER_DEATH_SCRIPT = '''
import os, signal, sys
sys.path.insert(0, {repo!r})
import numpy as np
import jax
import horovod_tpu as hvd


def main():
    hvd.init()
    r = jax.process_index()
    x = hvd.replicated_stack(np.ones(4, np.float32))
    hvd.allreduce(x)                      # settle the comm plane
    if r == 1:
        os._exit(17)                      # die mid-job, no goodbye
    # Survivor: ignore the launcher's SIGTERM long enough to report what
    # the runtime actually raised (the launcher SIGKILLs after a grace).
    signal.signal(signal.SIGTERM, signal.SIG_IGN)
    try:
        for _ in range(3):
            hvd.allreduce(x)
        print("NOERROR", flush=True)
    except BaseException as e:
        from horovod_tpu.elastic.run_loop import _looks_like_comm_failure
        print(f"CLASS={{_looks_like_comm_failure(e)}} "
              f"TYPE={{type(e).__name__}} MSG={{str(e)[:160]}}", flush=True)
    os._exit(0)


if __name__ == "__main__":
    main()
'''


@pytest.mark.integration
@_requires_multiprocess
def test_peer_death_error_classification(tmp_path):
    """Pin the elastic classifier against the LIVE error surface of this
    JAX version: kill a peer mid-collective; the survivor's exception
    must classify as a recoverable comm failure (round-2 verdict weak #6
    -- a renamed runtime message now fails here, not in production)."""
    script = tmp_path / "peer_death.py"
    script.write_text(_PEER_DEATH_SCRIPT.format(repo=REPO))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["HOROVOD_JOIN_DISABLE"] = "1"     # hit the collective directly
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, env=env)
    text = out.stdout + out.stderr
    assert "CLASS=True" in text, text[-4000:]
    assert "NOERROR" not in text, text[-4000:]


@pytest.mark.integration
@_requires_multiprocess
def test_launcher_dash_h_derives_np():
    """-H localhost:2 with no -np runs 2 workers end-to-end."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-H", "localhost:2",
         "--cpu", sys.executable,
         os.path.join(REPO, "examples", "allreduce_check.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "rank 1: barrier OK" in out.stdout


# ---------------------------------------------------------------------------
# Secret + HTTP KV rendezvous
# ---------------------------------------------------------------------------


def test_secret_sign_verify_tamper():
    from horovod_tpu.run.secret import (check_digest, compute_digest,
                                        make_secret_key)
    k = make_secret_key()
    d = compute_digest(k, b"payload")
    assert check_digest(k, b"payload", d)
    assert not check_digest(k, b"payloaX", d)
    assert not check_digest(make_secret_key(), b"payload", d)


def test_http_kv_roundtrip_and_auth():
    from horovod_tpu.run.http_kv import KVClient, RendezvousServer
    from horovod_tpu.run.secret import make_secret_key
    secret = make_secret_key()
    srv = RendezvousServer(secret, host="127.0.0.1")
    try:
        kv = KVClient("127.0.0.1", srv.port, secret)
        assert kv.get("s", "k") is None
        kv.put("s", "k", b"value-1")
        assert kv.get("s", "k") == b"value-1"
        kv.delete("s", "k")
        assert kv.get("s", "k") is None
        # Wrong secret -> RendezvousAuthError (NOT ConnectionError: a
        # misconfigured secret must not be retried as "driver gone").
        from horovod_tpu.run.http_kv import RendezvousAuthError
        bad = KVClient("127.0.0.1", srv.port, make_secret_key())
        with pytest.raises(RendezvousAuthError, match="secret"):
            bad.put("s", "k", b"evil")
        with pytest.raises(RendezvousAuthError, match="secret"):
            bad.get("s", "k")
        assert not isinstance(RendezvousAuthError("x"), ConnectionError)
        # Stale timestamp (valid signature over it) -> 403: replay window.
        import time as _time
        from urllib.request import Request, urlopen
        from urllib.error import HTTPError
        from horovod_tpu.run.http_kv import (SIG_HEADER, TS_HEADER,
                                             _signable)
        from horovod_tpu.run.secret import compute_digest
        old_ts = repr(_time.time() - 3600)
        path = "/kv/s/k2"
        sig = compute_digest(secret, _signable("PUT", path, old_ts,
                                               b"replayed"))
        req = Request(f"http://127.0.0.1:{srv.port}{path}", data=b"replayed",
                      method="PUT",
                      headers={SIG_HEADER: sig, TS_HEADER: old_ts})
        with pytest.raises(HTTPError) as ei:
            urlopen(req, timeout=5)
        assert ei.value.code == 403
    finally:
        srv.stop()


def test_http_kv_chunked_large_object_roundtrip():
    """put_large/get_large: binary-safe chunked transfer with a
    commit-last manifest and sha256 verification -- the KV-page
    streaming transport."""
    import json
    from horovod_tpu.run.http_kv import KVClient, RendezvousServer
    from horovod_tpu.run.secret import make_secret_key
    secret = make_secret_key()
    srv = RendezvousServer(secret, host="127.0.0.1")
    try:
        kv = KVClient("127.0.0.1", srv.port, secret)
        # Binary payload (every byte value, not valid UTF-8), larger
        # than the chunk size and NOT a multiple of it.
        value = bytes(range(256)) * 1021
        parts = kv.put_large("pages", "obj", value, chunk_bytes=50_000)
        assert parts == -(-len(value) // 50_000) and parts >= 2
        assert kv.get_large("pages", "obj") == value
        # The manifest commits LAST: the raw key holds JSON, parts are
        # separate keys.
        m = json.loads(kv.get("pages", "obj"))
        assert m["parts"] == parts and m["bytes"] == len(value)
        assert kv.get("pages", "obj.part0") == value[:50_000]
        # Absent object -> None (not an error): reader polls until the
        # manifest commits.
        assert kv.get_large("pages", "missing") is None
        # Tampered part -> hash mismatch ValueError.
        kv.put("pages", "obj.part1", b"X" * 50_000)
        with pytest.raises(ValueError, match="hash mismatch"):
            kv.get_large("pages", "obj")
        # Missing part -> torn-object ValueError.
        kv.delete("pages", "obj.part1")
        with pytest.raises(ValueError, match="part 1"):
            kv.get_large("pages", "obj")
        # A plain (non-manifest) value read through get_large is
        # rejected, not misparsed.
        kv.put("pages", "plain", b"\x00\x01raw")
        with pytest.raises(ValueError, match="manifest"):
            kv.get_large("pages", "plain")
        # delete_large removes manifest + parts.
        kv.put_large("pages", "obj", value, chunk_bytes=50_000)
        kv.delete_large("pages", "obj")
        assert kv.get("pages", "obj") is None
        assert kv.get("pages", "obj.part0") is None
    finally:
        srv.stop()


def test_notifier_reads_assignment_over_http(monkeypatch):
    import json
    from horovod_tpu.elastic.notify import ASSIGNMENT_KEY, Notifier
    from horovod_tpu.run.http_kv import KVClient, RendezvousServer
    from horovod_tpu.run.secret import SECRET_ENV, make_secret_key
    secret = make_secret_key()
    srv = RendezvousServer(secret, host="127.0.0.1")
    try:
        monkeypatch.setenv(SECRET_ENV, secret)
        url = f"http://127.0.0.1:{srv.port}"
        n = Notifier(path=url, worker_id="w0")
        assert n.enabled and n.read() is None
        kv = KVClient("127.0.0.1", srv.port, secret)
        doc = {"epoch": 3, "size": 2, "port": 1234, "ranks": {"w0": 0}}
        kv.put(*ASSIGNMENT_KEY, json.dumps(doc).encode())
        got = n.updated()
        assert got == doc
        n.accept(got)
        assert n.updated() is None
    finally:
        srv.stop()


def test_kv_heartbeat_writer_and_age(monkeypatch):
    import time
    from horovod_tpu.core.stall import KVHeartbeatWriter
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.run.http_kv import RendezvousServer
    from horovod_tpu.run.secret import make_secret_key
    secret = make_secret_key()
    srv = RendezvousServer(secret, host="127.0.0.1")
    try:
        url = f"http://127.0.0.1:{srv.port}"
        w = KVHeartbeatWriter(url, "w0", secret, interval_s=0.05)
        time.sleep(0.15)
        # Driver-side age check through the same KV.
        drv = ElasticDriver.__new__(ElasticDriver)
        from horovod_tpu.run.http_kv import KVClient
        drv._kv = KVClient("127.0.0.1", srv.port, secret)
        age = drv._kv_heartbeat_age("w0")
        assert age is not None and age < 5.0
        assert drv._kv_heartbeat_age("w-unknown") is None
        w.stop()
        assert drv._kv_heartbeat_age("w0") is None  # cleaned up
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Pre-launch driver/task probe
# ---------------------------------------------------------------------------


def test_probe_report_fields():
    from horovod_tpu.run.probe import probe_report
    r = probe_report()
    assert r["framework_version"]
    assert r["jax_version"]
    assert "127.0.0.1" in r["addresses"]


def test_probe_validate_flags_skew():
    from horovod_tpu.run.probe import DriverProbe
    p = DriverProbe.__new__(DriverProbe)
    ok = {"a": {"framework_version": "1", "jax_version": "2", "python": "3.12"},
          "b": {"framework_version": "1", "jax_version": "2", "python": "3.12"}}
    p.validate(ok)
    bad = {**ok, "c": {"framework_version": "9", "jax_version": "2",
                       "python": "3.12"}}
    with pytest.raises(RuntimeError, match="framework_version"):
        p.validate(bad)


@pytest.mark.integration
def test_probe_end_to_end_local():
    from horovod_tpu.run.probe import DriverProbe
    drv = DriverProbe()
    try:
        env_probe = [drv.spawn_local_probe(w) for w in ("w0", "w1")]
        reports = drv.collect(["w0", "w1"], timeout_s=120)
        drv.validate(reports)
        assert set(reports) == {"w0", "w1"}
        for p in env_probe:
            assert p.wait(timeout=30) == 0
    finally:
        drv.stop()


def test_lightning_estimator_requires_protocol():
    # LightningEstimator is functional (no pytorch_lightning needed) but
    # demands the LightningModule protocol methods up front.
    from horovod_tpu.spark import LightningEstimator
    with pytest.raises(TypeError, match="training_step"):
        LightningEstimator(model=None)


def _identity_worker():
    return (os.environ["HOROVOD_RANK"], os.environ["HOROVOD_SIZE"])


@pytest.mark.integration
def test_programmatic_run_api():
    """horovod.run.run() parity: launch a function on N procs."""
    from horovod_tpu.run import run as hvd_run
    results = hvd_run(_identity_worker, np=2, cpu=True)
    assert results == [("0", "2"), ("1", "2")]


# -- LSF detection (reference horovod/runner/util/lsf.py) -----------------

def test_lsf_mcpu_hosts(monkeypatch):
    from horovod_tpu.run import lsf
    monkeypatch.setenv("LSB_JOBID", "123")
    monkeypatch.delenv("LSB_DJOB_RANKFILE", raising=False)
    monkeypatch.setenv("LSB_MCPU_HOSTS", "nodeA 4 nodeB 4 nodeA 2")
    assert lsf.using_lsf()
    assert lsf.get_compute_hosts() == [("nodeA", 6), ("nodeB", 4)]


def test_lsf_rankfile_preferred(monkeypatch, tmp_path):
    from horovod_tpu.run import lsf
    rf = tmp_path / "rankfile"
    # CSM-style: first line is the submission/batch node (LSB_SUB_HOST),
    # which holds no compute slot -> excluded.
    rf.write_text("batch01\nh1\nh1\nh2\n")
    monkeypatch.setenv("LSB_JOBID", "123")
    monkeypatch.setenv("LSB_SUB_HOST", "batch01")
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rf))
    monkeypatch.setenv("LSB_MCPU_HOSTS", "ignored 9")
    assert lsf.get_compute_hosts() == [("h1", 2), ("h2", 1)]


def test_lsf_rankfile_plain_single_host(monkeypatch, tmp_path):
    # Plain LSF (bsub -n 4): no separate batch line; every line is a slot
    # even when the job was submitted from hostA itself.
    from horovod_tpu.run import lsf
    rf = tmp_path / "rankfile"
    rf.write_text("hostA\nhostA\nhostA\nhostA\n")
    monkeypatch.setenv("LSB_JOBID", "123")
    monkeypatch.setenv("LSB_SUB_HOST", "hostA")
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rf))
    assert lsf.get_compute_hosts() == [("hostA", 4)]


def test_lsf_rankfile_one_slot_per_host(monkeypatch, tmp_path):
    # span[ptile=1]: every host appears once; none may be dropped.
    from horovod_tpu.run import lsf
    rf = tmp_path / "rankfile"
    rf.write_text("h1\nh2\nh3\n")
    monkeypatch.setenv("LSB_JOBID", "123")
    monkeypatch.delenv("LSB_SUB_HOST", raising=False)
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rf))
    assert lsf.get_compute_hosts() == [("h1", 1), ("h2", 1), ("h3", 1)]


def test_lsf_malformed(monkeypatch):
    from horovod_tpu.run import lsf
    monkeypatch.setenv("LSB_JOBID", "123")
    monkeypatch.delenv("LSB_DJOB_RANKFILE", raising=False)
    monkeypatch.setenv("LSB_MCPU_HOSTS", "nodeA 4 nodeB")
    with pytest.raises(ValueError):
        lsf.get_compute_hosts()


def test_lsf_not_detected(monkeypatch):
    from horovod_tpu.run import lsf
    monkeypatch.delenv("LSB_JOBID", raising=False)
    assert not lsf.using_lsf()


def test_lsf_rankfile_csm_without_subhost(monkeypatch, tmp_path):
    # CSM signature without LSB_SUB_HOST: unique first host + multi-slot
    # compute hosts -> the launch node line is dropped.
    from horovod_tpu.run import lsf
    rf = tmp_path / "rankfile"
    rf.write_text("batch01\nh1\nh1\nh2\n")
    monkeypatch.setenv("LSB_JOBID", "123")
    monkeypatch.delenv("LSB_SUB_HOST", raising=False)
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rf))
    assert lsf.get_compute_hosts() == [("h1", 2), ("h2", 1)]


def test_lsf_rankfile_uneven_plain_with_subhost(monkeypatch, tmp_path):
    # Uneven plain-LSF spread with LSB_SUB_HOST set to a login node: the
    # unique first host is a genuine compute slot and must be kept.
    from horovod_tpu.run import lsf
    rf = tmp_path / "rankfile"
    rf.write_text("nodeA\nnodeB\nnodeB\n")
    monkeypatch.setenv("LSB_JOBID", "123")
    monkeypatch.setenv("LSB_SUB_HOST", "login01")
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rf))
    assert lsf.get_compute_hosts() == [("nodeA", 1), ("nodeB", 2)]


def test_lsf_rankfile_fqdn_subhost(monkeypatch, tmp_path):
    # FQDN rankfile vs short-name LSB_SUB_HOST still drops the launch node.
    from horovod_tpu.run import lsf
    rf = tmp_path / "rankfile"
    rf.write_text("launch01.cluster.com\nh1\nh1\n")
    monkeypatch.setenv("LSB_JOBID", "123")
    monkeypatch.setenv("LSB_SUB_HOST", "launch01")
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rf))
    assert lsf.get_compute_hosts() == [("h1", 2)]


def test_apply_timeline_env_per_rank():
    from horovod_tpu.run.launch import apply_timeline_env
    # CLI flag wins and clears the HVD_TPU_ spelling.
    env = {"HVD_TPU_TIMELINE": "/tmp/old.json"}
    apply_timeline_env(env, 3, "/tmp/new")
    assert env == {"HOROVOD_TIMELINE": "/tmp/new.3"}
    # Inherited env values get the rank suffix.
    env = {"HOROVOD_TIMELINE": "/tmp/t.json"}
    apply_timeline_env(env, 1)
    assert env["HOROVOD_TIMELINE"] == "/tmp/t.json.1"
    env = {}
    apply_timeline_env(env, 0)
    assert env == {}


@pytest.mark.integration
def test_launcher_log_level_flag():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "1", "--cpu",
         "--log-level", "info", sys.executable, "-c",
         "import horovod_tpu as h; h.init()"],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "horovod_tpu initialized" in out.stdout + out.stderr


_TF1_HOOK_SCRIPT = '''
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np
import tensorflow as tf
import horovod_tpu.tensorflow as hvd

hvd.init()
r = int(os.environ["HOROVOD_RANK"])
v1 = tf.compat.v1
with tf.Graph().as_default():
    # Ranks initialize DIFFERENTLY; the hook must impose rank 0's values.
    v = v1.get_variable("w", initializer=tf.constant([100.0 * r, 1.0 + r]))
    hook = hvd.BroadcastGlobalVariablesHook(root_rank=0)
    with v1.train.MonitoredTrainingSession(hooks=[hook]) as sess:
        out = sess.run(v)
np.testing.assert_allclose(out, [0.0, 1.0])
print(f"rank {{r}}: tf1 hook OK", flush=True)
'''


@pytest.mark.integration
@_requires_multiprocess
def test_tf1_hook_broadcasts_across_processes(tmp_path):
    """The TF1 session hook moves rank 0's initial variable values to every
    rank through the mesh broadcast (reference hook semantics)."""
    script = tmp_path / "tf1_hook_check.py"
    script.write_text(_TF1_HOOK_SCRIPT.format(repo=REPO))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "rank 0: tf1 hook OK" in out.stdout
    assert "rank 1: tf1 hook OK" in out.stdout
