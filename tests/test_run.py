"""Launcher tests (reference ``test/single/test_run.py`` analogue) plus a
real 2-process integration run (``test_static_run.py`` analogue)."""

import os
import subprocess
import sys

import pytest

from horovod_tpu.run import check_build, free_port, worker_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_check_build_lists_capabilities():
    text = check_build()
    assert "XLA:TPU collectives" in text
    assert "Adasum" in text
    assert "elastic" in text


def test_free_port_is_bindable():
    import socket
    p = free_port()
    with socket.socket() as s:
        s.bind(("127.0.0.1", p))


def test_worker_env_contents():
    env = worker_env(rank=1, size=4, coordinator="127.0.0.1", port=1234,
                     cpu=True, slots=2)
    assert env["HOROVOD_RANK"] == "1"
    assert env["HOROVOD_SIZE"] == "4"
    assert env["HVD_TPU_COORDINATOR_PORT"] == "1234"
    assert env["HVD_TPU_FORCE_CPU"] == "1"
    assert "--xla_force_host_platform_device_count=2" in env["XLA_FLAGS"]


def test_cli_requires_command():
    from horovod_tpu.run import run_command
    with pytest.raises(SystemExit):
        run_command(["-np", "2"])


@pytest.mark.integration
def test_two_process_static_run():
    """Spawn a real 2-process job through the CLI (slow: ~30s)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # Workers must not inherit the test session's forced-cpu XLA flags in a
    # way that conflicts; launcher sets its own.
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         sys.executable, os.path.join(REPO, "examples",
                                      "allreduce_check.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "[0]<stdout>" in out.stdout
    assert "rank 0: barrier OK" in out.stdout
    assert "rank 1: barrier OK" in out.stdout


@pytest.mark.integration
def test_failing_worker_propagates_exit_code(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
         sys.executable, str(bad)],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 3
