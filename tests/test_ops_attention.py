"""Flash-attention kernels vs XLA reference (Pallas interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import attention_reference, flash_attention
from horovod_tpu.ops.attention import _flash


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("tq,tk", [(64, 64), (64, 128)])
def test_flash_forward_matches_reference(causal, tq, tk):
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = _rand((2, 2, tq, 32), keys[0])
    k = _rand((2, 2, tk, 32), keys[1])
    v = _rand((2, 2, tk, 32), keys[2])
    ref = attention_reference(q, k, v, causal=causal)
    got = _flash(q, k, v, q.shape[-1] ** -0.5, causal, 32, 32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    q = _rand((1, 2, 64, 16), keys[0])
    k = _rand((1, 2, 64, 16), keys[1])
    v = _rand((1, 2, 64, 16), keys[2])

    def loss_flash(q, k, v):
        o = _flash(q, k, v, q.shape[-1] ** -0.5, causal, 32, 32)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=causal)
        return jnp.sum(jnp.sin(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_gqa_kernel_broadcasts_kv_heads():
    """GQA path through the kernels (index-map broadcast, incl. backward)."""
    keys = jax.random.split(jax.random.PRNGKey(2), 3)
    q = _rand((1, 4, 32, 16), keys[0])
    k = _rand((1, 2, 32, 16), keys[1])
    v = _rand((1, 2, 32, 16), keys[2])
    kr, vr = jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1)

    out = _flash(q, k, v, q.shape[-1] ** -0.5, True, 32, 32)
    ref = attention_reference(q, kr, vr, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(_flash(q, k, v, q.shape[-1] ** -0.5,
                                      True, 32, 32)))

    def loss_ref(q, k, v):
        o = attention_reference(q, jnp.repeat(k, 2, axis=1),
                                jnp.repeat(v, 2, axis=1), causal=True)
        return jnp.sum(jnp.sin(o))

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_gqa_dispatch_path():
    keys = jax.random.split(jax.random.PRNGKey(4), 3)
    q = _rand((1, 4, 32, 16), keys[0])
    k = _rand((1, 2, 32, 16), keys[1])
    v = _rand((1, 2, 32, 16), keys[2])
    out = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, jnp.repeat(k, 2, axis=1),
                              jnp.repeat(v, 2, axis=1), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_causal_decode_alignment():
    """tq < tk causal = bottom-right aligned (KV-cache decode semantics)."""
    keys = jax.random.split(jax.random.PRNGKey(5), 3)
    q = _rand((1, 1, 8, 16), keys[0])
    k = _rand((1, 1, 64, 16), keys[1])
    v = _rand((1, 1, 64, 16), keys[2])
    got = _flash(q, k, v, q.shape[-1] ** -0.5, True, 8, 32)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_causal_tq_gt_tk_rejected():
    q = jnp.zeros((1, 1, 64, 16))
    k = jnp.zeros((1, 1, 32, 16))
    with pytest.raises(ValueError, match="tq <= tk"):
        flash_attention(q, k, k, causal=True)


def test_uneven_block_sizes_fall_back_to_divisors():
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q = _rand((1, 1, 96, 16), keys[0])  # 96 not divisible by 64
    k = _rand((1, 1, 96, 16), keys[1])
    v = _rand((1, 1, 96, 16), keys[2])
    got = _flash(q, k, v, q.shape[-1] ** -0.5, True, 64, 64)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_prime_seq_falls_back_to_reference():
    keys = jax.random.split(jax.random.PRNGKey(6), 3)
    q = _rand((1, 1, 127, 16), keys[0])  # prime: no divisor >= 8
    k = _rand((1, 1, 127, 16), keys[1])
    v = _rand((1, 1, 127, 16), keys[2])
    out = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)
