"""Flash-attention kernels vs XLA reference (Pallas interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import attention_reference, flash_attention
from horovod_tpu.ops.attention import _flash


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("tq,tk", [(64, 64), (64, 128)])
def test_flash_forward_matches_reference(causal, tq, tk):
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = _rand((2, 2, tq, 32), keys[0])
    k = _rand((2, 2, tk, 32), keys[1])
    v = _rand((2, 2, tk, 32), keys[2])
    ref = attention_reference(q, k, v, causal=causal)
    got = _flash(q, k, v, q.shape[-1] ** -0.5, causal, 32, 32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    q = _rand((1, 2, 64, 16), keys[0])
    k = _rand((1, 2, 64, 16), keys[1])
    v = _rand((1, 2, 64, 16), keys[2])

    def loss_flash(q, k, v):
        o = _flash(q, k, v, q.shape[-1] ** -0.5, causal, 32, 32)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=causal)
        return jnp.sum(jnp.sin(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_gqa_kernel_broadcasts_kv_heads():
    """GQA path through the kernels (index-map broadcast, incl. backward)."""
    keys = jax.random.split(jax.random.PRNGKey(2), 3)
    q = _rand((1, 4, 32, 16), keys[0])
    k = _rand((1, 2, 32, 16), keys[1])
    v = _rand((1, 2, 32, 16), keys[2])
    kr, vr = jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1)

    out = _flash(q, k, v, q.shape[-1] ** -0.5, True, 32, 32)
    ref = attention_reference(q, kr, vr, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(_flash(q, k, v, q.shape[-1] ** -0.5,
                                      True, 32, 32)))

    def loss_ref(q, k, v):
        o = attention_reference(q, jnp.repeat(k, 2, axis=1),
                                jnp.repeat(v, 2, axis=1), causal=True)
        return jnp.sum(jnp.sin(o))

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_gqa_dispatch_path():
    keys = jax.random.split(jax.random.PRNGKey(4), 3)
    q = _rand((1, 4, 32, 16), keys[0])
    k = _rand((1, 2, 32, 16), keys[1])
    v = _rand((1, 2, 32, 16), keys[2])
    out = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, jnp.repeat(k, 2, axis=1),
                              jnp.repeat(v, 2, axis=1), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_causal_decode_alignment():
    """tq < tk causal = bottom-right aligned (KV-cache decode semantics)."""
    keys = jax.random.split(jax.random.PRNGKey(5), 3)
    q = _rand((1, 1, 8, 16), keys[0])
    k = _rand((1, 1, 64, 16), keys[1])
    v = _rand((1, 1, 64, 16), keys[2])
    got = _flash(q, k, v, q.shape[-1] ** -0.5, True, 8, 32)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_causal_tq_gt_tk_rejected():
    q = jnp.zeros((1, 1, 64, 16))
    k = jnp.zeros((1, 1, 32, 16))
    with pytest.raises(ValueError, match="tq <= tk"):
        flash_attention(q, k, k, causal=True)


def test_uneven_block_sizes_fall_back_to_divisors():
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q = _rand((1, 1, 96, 16), keys[0])  # 96 not divisible by 64
    k = _rand((1, 1, 96, 16), keys[1])
    v = _rand((1, 1, 96, 16), keys[2])
    got = _flash(q, k, v, q.shape[-1] ** -0.5, True, 64, 64)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_prime_seq_falls_back_to_reference():
    keys = jax.random.split(jax.random.PRNGKey(6), 3)
    q = _rand((1, 1, 127, 16), keys[0])  # prime: no divisor >= 8
    k = _rand((1, 1, 127, 16), keys[1])
    v = _rand((1, 1, 127, 16), keys[2])
    out = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def _packed_segments(key, batch, t, max_segs=4):
    """Random packed-sequence ids: sorted segments like a packing loader."""
    lens = jax.random.randint(key, (batch, max_segs), 1, t)
    ids = []
    for b in range(batch):
        row = np.zeros(t, np.int32)
        pos, seg = 0, 0
        for L in np.asarray(lens[b]):
            if pos >= t:
                break
            row[pos:pos + int(L)] = seg
            pos += int(L)
            seg += 1
        row[pos:] = seg  # tail = final segment
        ids.append(row)
    return jnp.asarray(np.stack(ids))


def _dense_mask_reference(q, k, v, qseg, kseg, causal):
    """Ground truth built from an explicit dense mask (independent of
    attention_reference's own segment path)."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    mask = qseg[:, None, :, None] == kseg[:, None, None, :]
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = mask & jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
    logits = jnp.where(mask, logits, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd",
                      jax.nn.softmax(logits, axis=-1), v)


@pytest.mark.parametrize("causal", [False, True])
def test_segment_ids_match_dense_mask(causal):
    from horovod_tpu.ops.attention import _flash_seg
    keys = jax.random.split(jax.random.PRNGKey(7), 4)
    q = _rand((2, 2, 64, 32), keys[0])
    k = _rand((2, 2, 64, 32), keys[1])
    v = _rand((2, 2, 64, 32), keys[2])
    seg = _packed_segments(keys[3], 2, 64)
    ref = _dense_mask_reference(q, k, v, seg, seg, causal)
    got = _flash_seg(q, k, v, seg, seg, q.shape[-1] ** -0.5, causal,
                     32, 32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # attention_reference's own segment path agrees too.
    ref2 = attention_reference(q, k, v, causal=causal, segment_ids=seg,
                               kv_segment_ids=seg)
    np.testing.assert_allclose(np.asarray(ref2), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_segment_ids_grads_match_reference(causal):
    from horovod_tpu.ops.attention import _flash_seg
    keys = jax.random.split(jax.random.PRNGKey(8), 4)
    q = _rand((1, 2, 64, 16), keys[0])
    k = _rand((1, 2, 64, 16), keys[1])
    v = _rand((1, 2, 64, 16), keys[2])
    seg = _packed_segments(keys[3], 1, 64)

    def loss_flash(q, k, v):
        o = _flash_seg(q, k, v, seg, seg, q.shape[-1] ** -0.5, causal,
                       32, 32)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = _dense_mask_reference(q, k, v, seg, seg, causal)
        return jnp.sum(jnp.sin(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4)


def test_segment_ids_public_api_and_validation():
    keys = jax.random.split(jax.random.PRNGKey(9), 4)
    q = _rand((2, 4, 64, 16), keys[0])
    k = _rand((2, 2, 64, 16), keys[1])      # GQA: 2 kv heads
    v = _rand((2, 2, 64, 16), keys[2])
    seg = _packed_segments(keys[3], 2, 64)
    # Reference fallback (CPU dispatch) handles GQA + segments.
    out = flash_attention(q, k, v, causal=True, segment_ids=seg)
    krep = jnp.repeat(k, 2, axis=1)
    vrep = jnp.repeat(v, 2, axis=1)
    ref = _dense_mask_reference(q, krep, vrep, seg, seg, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    with pytest.raises(ValueError, match="kv_segment_ids given without"):
        flash_attention(q, k, v, kv_segment_ids=seg)
    with pytest.raises(ValueError, match="segment_ids must be"):
        flash_attention(q, k, v, segment_ids=seg[:, :32])
    with pytest.raises(ValueError, match="kv_segment_ids is required"):
        flash_attention(q, k[:, :, :32], v[:, :, :32],
                        segment_ids=seg)


def test_segment_ids_isolate_sequences():
    """Two packed sequences attend independently: packing [A|B] must equal
    attending A and B separately (the point of the feature)."""
    from horovod_tpu.ops.attention import _flash_seg
    keys = jax.random.split(jax.random.PRNGKey(10), 3)
    qa = _rand((1, 2, 32, 16), keys[0])
    qb = _rand((1, 2, 32, 16), keys[1])
    v_all = _rand((1, 2, 64, 16), keys[2])
    q_pack = jnp.concatenate([qa, qb], axis=2)
    seg = jnp.concatenate([jnp.zeros((1, 32), jnp.int32),
                           jnp.ones((1, 32), jnp.int32)], axis=1)
    packed = _flash_seg(q_pack, q_pack, v_all, seg, seg,
                        qa.shape[-1] ** -0.5, True, 32, 32)
    sep_a = attention_reference(qa, qa, v_all[:, :, :32], causal=True)
    sep_b = attention_reference(qb, qb, v_all[:, :, 32:], causal=True)
    np.testing.assert_allclose(np.asarray(packed[:, :, :32]),
                               np.asarray(sep_a), atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(packed[:, :, 32:]),
                               np.asarray(sep_b), atol=2e-5, rtol=2e-5)


def test_segment_dead_rows_zero_output_and_grads():
    """A query row whose segment matches NO key (pure padding) must give
    zero output and inject ZERO gradients -- the f32 lse for such a row
    would otherwise absorb log(l) into -1e30 and the backward would see
    p = 1 per key (a ~tk-fold gradient explosion; review regression)."""
    from horovod_tpu.ops.attention import _flash_seg
    keys = jax.random.split(jax.random.PRNGKey(11), 3)
    q = _rand((1, 1, 16, 8), keys[0])
    k = _rand((1, 1, 16, 8), keys[1])
    v = _rand((1, 1, 16, 8), keys[2])
    # Last 4 query rows carry segment 5, present in NO key row.
    qseg = jnp.asarray([[0] * 12 + [5] * 4], jnp.int32)
    kseg = jnp.zeros((1, 16), jnp.int32)

    out = _flash_seg(q, k, v, qseg, kseg, q.shape[-1] ** -0.5, False,
                     8, 8)
    np.testing.assert_allclose(np.asarray(out[0, 0, 12:]), 0.0)
    ref = attention_reference(q, k, v, segment_ids=qseg,
                              kv_segment_ids=kseg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        o = _flash_seg(q, k, v, qseg, kseg, q.shape[-1] ** -0.5, False,
                       8, 8)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, segment_ids=qseg,
                                kv_segment_ids=kseg)
        return jnp.sum(jnp.sin(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    # Dead rows contribute nothing to dq...
    np.testing.assert_allclose(np.asarray(gf[0][0, 0, 12:]), 0.0)
    # ...and the live rows' gradients match the reference everywhere.
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4)


def test_reference_defaults_kv_segment_ids():
    keys = jax.random.split(jax.random.PRNGKey(12), 3)
    q = _rand((1, 1, 32, 8), keys[0])
    k = _rand((1, 1, 32, 8), keys[1])
    v = _rand((1, 1, 32, 8), keys[2])
    seg = jnp.asarray([[0] * 16 + [1] * 16], jnp.int32)
    a = attention_reference(q, k, v, segment_ids=seg)
    b = attention_reference(q, k, v, segment_ids=seg, kv_segment_ids=seg)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="kv_segment_ids is required"):
        attention_reference(q, k[:, :, :16], v[:, :, :16],
                            segment_ids=seg)


def test_segment_lane_block_search():
    """Sequences like 1920 (no 512-aligned divisor that is a multiple of
    128 <= 512... actually 384) must keep the Pallas path by searching
    for a lane-aligned block, not fall back to the O(t^2) reference."""
    from horovod_tpu.ops.attention import _block_lane
    assert _block_lane(1920, 512) == 384
    assert _block_lane(1664, 512) == 128
    assert _block_lane(4864, 512) == 256
    assert _block_lane(1024, 512) == 512
    assert _block_lane(64, 512) == 64       # whole-seq block
    assert _block_lane(20, 512) == 0        # not an 8-multiple: fallback
    assert _block_lane(1031, 512) == 0      # prime: fallback


@pytest.mark.parametrize("causal", [False, True])
def test_segment_pruning_grads_hit_pruned_blocks(causal):
    """Block-aligned disjoint segments (32 zeros + 32 ones at bq=bk=32)
    force the backward kernels' _seg_live pruning to actually SKIP the
    cross-segment block pairs -- the random-segment grad tests never
    prune (all their block id-ranges overlap), so this is the test that
    defends gradient exactness of the pruning fast path."""
    from horovod_tpu.ops.attention import _flash_seg
    keys = jax.random.split(jax.random.PRNGKey(13), 3)
    q = _rand((1, 2, 64, 16), keys[0])
    k = _rand((1, 2, 64, 16), keys[1])
    v = _rand((1, 2, 64, 16), keys[2])
    seg = jnp.concatenate([jnp.zeros((1, 32), jnp.int32),
                           jnp.ones((1, 32), jnp.int32)], axis=1)

    def loss_flash(q, k, v):
        o = _flash_seg(q, k, v, seg, seg, q.shape[-1] ** -0.5, causal,
                       32, 32)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = _dense_mask_reference(q, k, v, seg, seg, causal)
        return jnp.sum(jnp.sin(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_segment_ids_cross_length_decode(causal):
    """tq < tk (decode with a KV cache) + distinct q/kv segment ids: the
    kernels' bottom-right-aligned causal offset must compose with the
    segment mask."""
    from horovod_tpu.ops.attention import _flash_seg
    keys = jax.random.split(jax.random.PRNGKey(14), 3)
    q = _rand((2, 2, 32, 16), keys[0])
    k = _rand((2, 2, 64, 16), keys[1])
    v = _rand((2, 2, 64, 16), keys[2])
    kseg = jnp.asarray(np.concatenate(
        [np.zeros((2, 40)), np.ones((2, 24))], axis=1).astype(np.int32))
    qseg = jnp.ones((2, 32), jnp.int32)     # queries are the live tail
    # attention_reference, not _dense_mask_reference: causal + segments
    # makes early queries DEAD (no id-1 key inside their causal range),
    # and only the real reference zeroes dead rows like the kernel.
    ref = attention_reference(q, k, v, causal=causal, segment_ids=qseg,
                              kv_segment_ids=kseg)
    got = _flash_seg(q, k, v, qseg, kseg, q.shape[-1] ** -0.5, causal,
                     32, 32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# Flash-decoding: split-KV decode kernel (HOROVOD_PALLAS / _PALLAS_DECODE).
# ---------------------------------------------------------------------------

from horovod_tpu.ops.attention import _flash_decode, decode_attention


def _decode_case(key, b=4, h=8, h_kv=8, s=128, d=32):
    keys = jax.random.split(key, 4)
    q = _rand((b, h, 1, d), keys[0])
    k = _rand((b, h_kv, s, d), keys[1])
    v = _rand((b, h_kv, s, d), keys[2])
    lengths = jax.random.randint(keys[3], (b,), 1, s + 1)
    return q, k, v, lengths


@pytest.mark.parametrize("h_kv", [8, 2])  # MHA and GQA (rep=4)
def test_flash_decode_matches_reference(h_kv):
    q, k, v, lengths = _decode_case(jax.random.PRNGKey(20), h_kv=h_kv)
    ref = decode_attention(q, k, v, lengths=lengths, force_reference=True)
    got = _flash_decode(q, k, v, lengths, q.shape[-1] ** -0.5, 32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_decode_block_straddles_length():
    """Lengths that cut a KV block mid-way exercise the per-column mask
    (not just the whole-block predication)."""
    q, k, v, _ = _decode_case(jax.random.PRNGKey(21))
    lengths = jnp.asarray([1, 31, 33, 128], jnp.int32)
    ref = decode_attention(q, k, v, lengths=lengths, force_reference=True)
    got = _flash_decode(q, k, v, lengths, q.shape[-1] ** -0.5, 32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_decode_dead_slot_exactly_zero():
    """An idle batch slot (lengths == 0) runs no live KV block, so the
    finish step's l == 0 guard must yield EXACTLY zero -- not a uniform
    average over garbage keys."""
    q, k, v, _ = _decode_case(jax.random.PRNGKey(22))
    lengths = jnp.asarray([0, 64, 0, 128], jnp.int32)
    got = _flash_decode(q, k, v, lengths, q.shape[-1] ** -0.5, 32)
    np.testing.assert_array_equal(np.asarray(got[0]), 0.0)
    np.testing.assert_array_equal(np.asarray(got[2]), 0.0)
    ref = decode_attention(q, k, v, lengths=lengths, force_reference=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_decode_masked_page_reuse():
    """Cache positions past lengths hold recycled-page garbage; poisoning
    them with huge values must not change the output (the mask, not the
    data, decides)."""
    q, k, v, _ = _decode_case(jax.random.PRNGKey(23))
    lengths = jnp.asarray([17, 40, 96, 5], jnp.int32)
    live = jnp.arange(k.shape[2])[None, None, :, None] < \
        lengths[:, None, None, None]
    k_poison = jnp.where(live, k, 1e4)
    v_poison = jnp.where(live, v, -1e4)
    clean = _flash_decode(q, k, v, lengths, q.shape[-1] ** -0.5, 32)
    poisoned = _flash_decode(q, k_poison, v_poison, lengths,
                             q.shape[-1] ** -0.5, 32)
    np.testing.assert_allclose(np.asarray(poisoned), np.asarray(clean),
                               atol=1e-6, rtol=1e-6)


def test_decode_attention_env_dispatch(monkeypatch):
    """HOROVOD_PALLAS_DECODE=1 routes decode_attention through the kernel
    (interpreter off-TPU); =0 pins the XLA reference; both agree."""
    q, k, v, lengths = _decode_case(jax.random.PRNGKey(24), h_kv=2)
    monkeypatch.setenv("HOROVOD_PALLAS_DECODE", "0")
    ref = decode_attention(q, k, v, lengths=lengths)
    monkeypatch.setenv("HOROVOD_PALLAS_DECODE", "1")
    got = decode_attention(q, k, v, lengths=lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_attention_validation():
    q = jnp.zeros((2, 4, 1, 16))
    k = jnp.zeros((2, 2, 32, 16))
    lengths = jnp.zeros((2,), jnp.int32)
    with pytest.raises(ValueError, match="single-token"):
        decode_attention(jnp.zeros((2, 4, 2, 16)), k, k, lengths=lengths)
    with pytest.raises(ValueError, match="not a multiple"):
        decode_attention(jnp.zeros((2, 3, 1, 16)), k, k, lengths=lengths)
    with pytest.raises(ValueError, match="lengths must be"):
        decode_attention(q, k, k, lengths=jnp.zeros((3,), jnp.int32))


# ---------------------------------------------------------------------------
# The unified HOROVOD_PALLAS switch (ops.pallas).
# ---------------------------------------------------------------------------

def test_pallas_switch_resolution(monkeypatch):
    from horovod_tpu.ops import pallas as _pallas
    for var in ("HOROVOD_PALLAS", "HVD_TPU_PALLAS", "HVD_TPU_FLASH",
                "HOROVOD_PALLAS_FLASH", "HOROVOD_PALLAS_DECODE"):
        monkeypatch.delenv(var, raising=False)
    # auto: follows the backend (CPU here -> off).
    assert not _pallas.pallas_enabled("flash")
    # global switch gates every family...
    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    assert _pallas.active_kernels() == _pallas.registered_kernels()
    # ...and the per-family override wins over it.
    monkeypatch.setenv("HOROVOD_PALLAS_DECODE", "0")
    assert not _pallas.pallas_enabled("flash_decode")
    assert _pallas.pallas_enabled("flash")
    with pytest.raises(ValueError, match="unknown pallas kernel family"):
        _pallas.pallas_enabled("nope")


def test_pallas_switch_legacy_flash_flag(monkeypatch):
    from horovod_tpu.ops import pallas as _pallas
    for var in ("HOROVOD_PALLAS", "HVD_TPU_PALLAS",
                "HOROVOD_PALLAS_FLASH"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("HVD_TPU_FLASH", "1")
    monkeypatch.setattr(_pallas, "_warned_legacy", False)
    with pytest.warns(DeprecationWarning, match="HVD_TPU_FLASH"):
        assert _pallas.pallas_enabled("flash")
    # The legacy flag only speaks for the flash family...
    assert not _pallas.pallas_enabled("flash_decode")
    # ...and loses to the unified per-family override.
    monkeypatch.setenv("HOROVOD_PALLAS_FLASH", "0")
    assert not _pallas.pallas_enabled("flash")


def test_pallas_kernel_contracts_are_collective_free():
    """The registry every kernel family ships: no in-kernel collectives,
    no wire-byte deltas -- what stepmodel/trace_audit build on."""
    from horovod_tpu.ops import pallas as _pallas
    fams = _pallas.registered_kernels()
    assert set(fams) >= {"flash", "flash_decode", "fused_update",
                         "bn_bwd"}
    for fam in fams:
        contract = _pallas.kernel_contract(fam)
        assert contract["collectives"] == ()
        assert contract["wire_delta_bytes"] == 0
        assert contract["site"]
