"""Checkpoint/resume helpers (rank-0-saves + broadcast idiom)."""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hv


def test_checkpoint_roundtrip_with_step(hvd, tmp_path):
    tree = {"params": {"w": jnp.arange(12.0).reshape(3, 4),
                       "b": jnp.ones((4,), jnp.bfloat16)},
            "counts": jnp.asarray([1, 2, 3], jnp.int32)}
    path = hv.checkpoint_path(str(tmp_path), step=7)
    hv.save_checkpoint(path, tree, step=7)
    like = {"params": {"w": jnp.zeros((3, 4)),
                       "b": jnp.zeros((4,), jnp.bfloat16)},
            "counts": jnp.zeros((3,), jnp.int32)}
    restored, step = hv.restore_checkpoint(path, like)
    assert step == 7
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]),
                               np.arange(12.0).reshape(3, 4))
    assert restored["params"]["b"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(restored["counts"]), [1, 2, 3])


def test_restore_missing_leaf_raises(hvd, tmp_path):
    path = str(tmp_path / "c.npz")
    hv.save_checkpoint(path, {"w": jnp.ones(3)})
    with pytest.raises(KeyError, match="lacks"):
        hv.restore_checkpoint(path, {"w": jnp.zeros(3),
                                     "extra": jnp.zeros(2)})


def test_latest_checkpoint_ordering(hvd, tmp_path):
    assert hv.latest_checkpoint(str(tmp_path)) is None
    for s in (3, 12, 7):
        hv.save_checkpoint(hv.checkpoint_path(str(tmp_path), s),
                           {"x": jnp.ones(1)}, step=s)
    latest = hv.latest_checkpoint(str(tmp_path))
    assert latest is not None and latest.endswith("0000000012.npz")
    _, step = hv.restore_checkpoint(latest, {"x": jnp.zeros(1)})
    assert step == 12


def test_zero_state_checkpoint_roundtrip(hvd, tmp_path):
    """ZeRO-1 sharded optimizer state survives save/restore: the arena
    layout is a plain pytree of [n, ...]-leading arrays, restore returns
    replicated leaves, and shard_zero_state re-places them for the step."""
    import jax
    import optax

    params = {"w": jnp.arange(20.0).reshape(4, 5) / 10.0,
              "b": jnp.ones((7,)) * 0.5}

    def loss(p, batch):
        x, y = batch
        return jnp.mean(((x @ p["w"]).sum(-1) + p["b"].sum() - y) ** 2)

    opt = optax.adam(1e-2)
    state = hv.zero_init(opt, params)
    path = hv.checkpoint_path(str(tmp_path), step=3)
    hv.save_checkpoint(path, {"opt": state, "params": params}, step=3)

    like = jax.tree.map(jnp.zeros_like, {"opt": state, "params": params})
    restored, step = hv.restore_checkpoint(path, like)
    assert step == 3
    for a, b in zip(jax.tree.leaves(restored["opt"]),
                    jax.tree.leaves(state)):
        assert a.shape == b.shape and a.dtype == b.dtype
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32))

    # Re-place on the mesh and take a live zero step from the restored
    # state: the arena plan is deterministic, so it must line up.
    z_state = hv.shard_zero_state(restored["opt"])
    assert jax.tree.leaves(z_state)[0].sharding == hv.zero_sharding()
    step_fn = hv.make_train_step(loss, opt, zero_stage=1)
    x = jnp.ones((8, 4)) * 0.1
    y = jnp.zeros((8,))
    new_params, z_state, loss_val = step_fn(
        restored["params"], z_state, (hv.shard_batch(x), hv.shard_batch(y)))
    assert np.isfinite(float(loss_val))
    for leaf in jax.tree.leaves(new_params):
        assert np.isfinite(np.asarray(leaf, np.float32)).all()


def test_orbax_sharded_roundtrip(hvd, tmp_path):
    """Sharded orbax checkpoint preserves values AND shardings."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = hv.mesh()
    sharded = jax.device_put(jnp.arange(32.0).reshape(8, 4),
                             NamedSharding(mesh, P("hvd")))
    tree = {"w": hv.replicate(jnp.ones((3, 3))),
            "data": sharded, "scale": jnp.float32(0.5)}
    d = str(tmp_path / "sharded")
    hv.save_checkpoint_sharded(d, tree, step=7)
    hv.save_checkpoint_sharded(d, tree, step=9)
    out, step = hv.restore_checkpoint_sharded(d, tree)
    assert step == 9
    np.testing.assert_allclose(np.asarray(out["data"]),
                               np.asarray(tree["data"]))
    assert out["data"].sharding == tree["data"].sharding
    out7, step7 = hv.restore_checkpoint_sharded(d, tree, step=7)
    assert step7 == 7
    none_tree, none_step = hv.restore_checkpoint_sharded(
        str(tmp_path / "empty"), tree)
    assert none_tree is None and none_step is None
