"""Exchange-plan IR cross-consumer consistency (PR 19).

One plan to rule them all: for every reference configuration the
executors, the span recorder and the auditor's ``stepmodel`` must agree
because they all consume the SAME :class:`ExchangePlan` rows from
``plan_exchange``.  Gated here:

* every ``note_leg`` call during a reference trace carries an IR leg
  row (never an ad-hoc string tag), and the recorded tag set is exactly
  the tags of those rows;
* the executed collective multiset matches the IR-rebuilt
  ``expected_exchange`` exactly (0 unaccounted, 0 missing);
* ``stepmodel``/``explain_plan`` resolve against the executors' plan
  cache entries -- cache hits only, no second planning pass;
* the ROADMAP drill: a synthetic leg kind + plan family added through
  the two registry calls is priced, scheduled, audited and span-recorded
  with ZERO new consumer code.
"""

import jax
import jax.numpy as jnp
import pytest

from horovod_tpu.analysis.stepmodel import expected_exchange
from horovod_tpu.analysis.trace_audit import (HIER_CONFIGS,
                                              PARALLEL3D_CONFIGS,
                                              SERVING_CONFIGS,
                                              STANDARD_CONFIGS, audit_step,
                                              build_standard_config)
from horovod_tpu.controller import fusion as _fusion
from horovod_tpu.timeline import spans as _spans


@pytest.fixture()
def captured_legs(monkeypatch):
    """Record every value handed to the leg normalizer (the single entry
    point both ``note_leg`` paths share)."""
    captured = []
    orig = _spans._normalize_leg

    def wrapper(leg, nbytes=None):
        captured.append(leg)
        return orig(leg, nbytes)

    monkeypatch.setattr(_spans, "_normalize_leg", wrapper)
    return captured


def _unwrap(step):
    inner = step
    while hasattr(inner, "_fn"):
        inner = inner._fn
    return inner


def _check_config(config, captured):
    rec = _spans.recorder()
    rec.reset()
    del captured[:]
    step, args, donate, name = build_standard_config(config)
    report = audit_step(step, *args, donate_argnums=donate, name=name)
    assert report.ok(), report.render()
    s = report.summary
    assert s["unaccounted_ops"] == 0 and s["missing_ops"] == 0, \
        report.render()
    assert s["matched_ops"] == s["expected_ops"] > 0

    # Every leg the trace registered is an IR row, and the recorder's
    # registry renders those rows verbatim (tag-for-tag).
    strings = [l for l in captured if isinstance(l, str)]
    assert not strings, f"{config}: string leg tags {strings}"
    rows = [l for l in captured if l is not None]
    assert rows, f"{config}: no legs registered"
    assert all(isinstance(l, _fusion.ExchangeLeg) for l in rows)
    assert {l.tag for l in rows} == set(rec.legs), config
    for leg in rows:
        if leg.nbytes:
            assert rec.legs[leg.tag]["nbytes"] > 0, leg.tag
    return report, rows


def _audit_sigs(rows):
    return {(kind, dt, int(n)) for leg in rows
            for kind, dt, n, _ in leg.audit}


@pytest.mark.parametrize("config", STANDARD_CONFIGS)
def test_standard_config_consumers_agree(hvd, captured_legs, config):
    report, rows = _check_config(config, captured_legs)
    # The auditor's expected multiset is derivable from the very audit
    # contracts the executors' noted legs carry: same IR, two readers.
    expected_sigs = {op.sig() for op in report.expected.ops}
    assert expected_sigs <= _audit_sigs(rows), config


@pytest.mark.parametrize("config", SERVING_CONFIGS)
def test_serving_config_consumers_agree(hvd, captured_legs, config):
    report, rows = _check_config(config, captured_legs)
    expected_sigs = {op.sig() for op in report.expected.ops}
    assert expected_sigs <= _audit_sigs(rows), config


@pytest.mark.parametrize("config", PARALLEL3D_CONFIGS)
def test_3d_config_consumers_agree(hvd, captured_legs, config):
    # TP/pipeline activation collectives are declared contracts (not
    # noted legs), so only the audit-green + IR-rows-only gates apply.
    _check_config(config, captured_legs)


@pytest.mark.parametrize("config", HIER_CONFIGS)
def test_hier_config_consumers_agree(captured_legs, config):
    import horovod_tpu as hvd_mod
    from horovod_tpu.parallel.mesh import build_mesh
    hvd_mod.shutdown()
    hvd_mod.init(mesh=build_mesh(jax.devices()[:8], hierarchical=True,
                                 dcn_size=2))
    try:
        report, rows = _check_config(config, captured_legs)
        expected_sigs = {op.sig() for op in report.expected.ops}
        assert expected_sigs <= _audit_sigs(rows), config
    finally:
        hvd_mod.shutdown()


def test_guard_config_consumers_agree(captured_legs, monkeypatch):
    # The guard mode is snapshotted into the config at init time.
    monkeypatch.setenv("HOROVOD_GUARD", "1")
    import horovod_tpu as hvd_mod
    hvd_mod.shutdown()
    hvd_mod.init()
    try:
        report, rows = _check_config("plain", captured_legs)
        # The SDC screen's extra psum rides the same IR: planner row in
        # the expected multiset, executor row in the span registry.
        guard = _fusion.plan_exchange("guard").legs[0]
        assert guard.tag in {l.tag for l in rows}
        assert any(op.sig() == ("psum", "float32", 2)
                   for op in report.expected.ops)
    finally:
        hvd_mod.shutdown()


@pytest.mark.parametrize("config", ("plain", "zero1", "microbatch2"))
def test_stepmodel_reuses_executor_plan_entries(hvd, config):
    """``expected_exchange`` rebuilds its multiset FROM the cached plans
    the executors made at trace time: hits only, zero new planning."""
    step, args, _, _ = build_standard_config(config)
    jax.make_jaxpr(_unwrap(step))(*args)
    before = _fusion.plan_cache_stats()
    expected = expected_exchange(args[0], step._meta)
    after = _fusion.plan_cache_stats()
    assert expected.supported
    assert after["misses"] == before["misses"], config
    assert after["hits"] > before["hits"], config


def test_explain_plan_reuses_executor_plan_entries(hvd):
    from horovod_tpu.analysis.trace_audit import _TINY_THRESHOLD
    from horovod_tpu.collectives.compression import Compression
    step, args, _, _ = build_standard_config("plain")
    jax.make_jaxpr(_unwrap(step))(*args)
    before = _fusion.plan_cache_stats()
    rows = _fusion.explain_plan(args[0], threshold_bytes=_TINY_THRESHOLD,
                                compression=Compression.fp16,
                                register=False)
    after = _fusion.plan_cache_stats()
    assert len(rows) == 2  # the two reference buckets
    assert after["misses"] == before["misses"]
    assert after["hits"] > before["hits"]


# -- the ROADMAP drill: a new leg kind touches planner + one executor only --

def _syn_build(spec):
    return [_fusion.ExchangeLeg(
        tag="syn/probe", axis="dcn", collective="psum", codec="none",
        wire_dtype="float32", elements=spec["n"], nbytes=spec["n"] * 4,
        kind="syn_probe",
        audit=(("psum", "float32", spec["n"], "probe"),))]


def test_new_leg_kind_needs_zero_consumer_code(hvd):
    _fusion.register_leg_kind("syn_probe", bandwidth="dcn",
                              doc="synthetic drill kind (tests only)")
    _fusion.register_plan_family("syn", _syn_build,
                                 lambda s: {"n": int(s["n"])})
    plan = _fusion.plan_exchange("syn", n=32)
    leg = plan.legs[0]
    # Scheduler: priced and classed from the kind registry alone.
    assert _fusion.leg_bandwidth(leg) == "dcn"
    assert _fusion.leg_cost_seconds(leg) > 0.0
    # Auditor: expected rows come straight off the IR.
    assert _fusion.ops_from_legs(plan.legs) == \
        [("psum", "float32", 32, "syn/probe/probe")]
    # Spans: the registry renders the row verbatim.
    rec = _spans.recorder()
    rec.reset()
    _spans.note_leg(leg)
    assert rec.legs["syn/probe"] == {"nbytes": 128, "buckets": 1}
    # Planner: memoized like every built-in family.
    before = _fusion.plan_cache_stats()
    again = _fusion.plan_exchange("syn", n=32)
    after = _fusion.plan_cache_stats()
    assert again is plan
    assert after["hits"] == before["hits"] + 1
    assert after["misses"] == before["misses"]
    # Scheduler integration: the DCN probe leg is issued ahead of an
    # independent ICI leg it does not depend on.
    ici = _fusion.plan_exchange("flat", size=64, dtype="float32",
                                compression=None).legs[0]
    import dataclasses
    ici = dataclasses.replace(ici, bucket=1)
    ordered = _fusion.schedule_legs([ici, leg], mode="bandwidth")
    assert ordered[0] is leg


def test_schedule_legs_orders_bandwidth_and_respects_chains(hvd):
    """DCN-first across independent chains; plan order within a bucket's
    RS -> hop -> AG chain; ``program`` mode restores plan order."""
    legs = _fusion.plan_exchange(
        "hier", size=4096, dtype="float32", n_dcn=2, n_ici=4,
        compression=None, dcn_axis="dcn", ici_axis="ici").legs
    flat = _fusion.plan_exchange("flat", size=64, dtype="float32",
                                 compression=None).legs[0]
    import dataclasses
    flat = dataclasses.replace(flat, bucket=7)
    program = [flat] + list(legs)
    ordered = _fusion.schedule_legs(program, mode="bandwidth")
    # Intra-bucket chain order is preserved...
    pos = {id(l): i for i, l in enumerate(ordered)}
    chain = [l for l in ordered if l.bucket == legs[0].bucket]
    assert [l.tag for l in chain] == [l.tag for l in legs]
    # ...and the contended-DCN hop cannot be issued later than in
    # program order (the cheap flat ICI leg no longer blocks it).
    dcn = next(l for l in legs if _fusion.leg_bandwidth(l) == "dcn")
    assert pos[id(dcn)] <= 1 + list(legs).index(dcn)
    assert _fusion.schedule_legs(program, mode="program") == program
    sim_sched = _fusion.simulate_issue(ordered)
    sim_prog = _fusion.simulate_issue(program)
    assert sim_sched["makespan_s"] <= sim_prog["makespan_s"] + 1e-12
    assert 0.0 <= sim_sched["dispatch_gap_fraction"] <= 1.0


def test_overlap_phases_round_robins_scheduled_order(hvd):
    legs = []
    import dataclasses
    base = _fusion.plan_exchange("flat", size=256, dtype="float32",
                                 compression=None).legs[0]
    for b in range(4):
        legs.append(dataclasses.replace(base, bucket=b))
    phases = _fusion.overlap_phases(legs, 2, mode="program")
    assert [len(p) for p in phases] == [2, 2]
    assert [l.bucket for l in phases[0]] == [0, 2]
    assert [l.bucket for l in phases[1]] == [1, 3]
