"""Unit tests for the JoinOp draining machinery (single-process parts).

The end-to-end protocol is exercised by the np=2/np=3 launcher tests in
``test_run.py``; these cover the pure components.
"""

import numpy as np
import pytest

from horovod_tpu.collectives import joinop


@pytest.mark.parametrize("op,dtype,expect", [
    ("sum", np.float32, 0),
    ("average", np.float32, 0),
    ("adasum", np.float32, 0),
    ("product", np.float32, 1),
    ("min", np.float32, np.inf),
    ("max", np.float32, -np.inf),
    ("min", np.int32, np.iinfo(np.int32).max),
    ("max", np.int32, np.iinfo(np.int32).min),
])
def test_identity_values(op, dtype, expect):
    assert joinop.identity_value(op, np.dtype(dtype)) == expect


def test_sync_is_noop_single_process(hvd):
    """Single-process mode: no join machinery, zero overhead path."""
    from horovod_tpu.core import process_sets as ps
    assert joinop.sync(ps.get_process_set(None)) is None


def test_join_degenerates_to_barrier_single_process(hvd):
    assert hvd.join() == -1  # reference convention: no rank joined last


def test_reset_clears_generation(hvd):
    joinop._gen = 3
    joinop._joined = True
    joinop.reset()
    assert joinop._gen == 0 and not joinop._joined


def test_replay_rejects_unknown_kind(hvd):
    with pytest.raises(RuntimeError, match="unknown join replay kind"):
        joinop._replay({"kind": "frobnicate", "shape": (1,),
                        "dtype": "float32"})


def test_replay_abort_raises_with_message(hvd):
    with pytest.raises(RuntimeError, match="root has left"):
        joinop._replay({"kind": "abort", "message": "root has left"})


def _all_codec_names():
    from horovod_tpu.collectives.compression import Compression
    return [c.__name__ for c in vars(Compression).values()
            if isinstance(c, type)]


@pytest.mark.parametrize("compression", _all_codec_names())
def test_replay_knows_every_compression_codec(hvd, compression):
    """Regression (round-4 advisor): a drained rank replaying an eager
    allreduce published with Compression.fp8 hit a KeyError (the replay
    map only knew none/fp16/bf16), crashing the drained rank and stalling
    active ranks until HOROVOD_JOIN_TIMEOUT."""
    joinop._replay({"kind": "allreduce", "name": None, "shape": (1, 4),
                    "dtype": "float32", "op": "sum", "pre": 1.0,
                    "post": 1.0, "compression": compression})


def test_deferred_async_flush_order_and_results(hvd):
    """Deferred async dispatch (round-5): ops enqueue in issue order,
    flush at synchronize() runs ALL of them (one presence round in
    multi-process mode; a passthrough here), later handles resolve
    without re-flushing."""
    from horovod_tpu.collectives import eager

    calls = []

    def mk(i):
        def thunk():
            calls.append(i)
            return np.full((2,), i, np.float32)
        return thunk

    h1, h2, h3 = eager._defer(mk(1)), eager._defer(mk(2)), eager._defer(mk(3))
    assert eager.deferred_count() == 3
    out2 = eager.synchronize(h2)          # flushes the whole batch
    assert calls == [1, 2, 3]
    assert eager.deferred_count() == 0
    np.testing.assert_array_equal(out2, np.full((2,), 2, np.float32))
    np.testing.assert_array_equal(eager.synchronize(h1),
                                  np.full((2,), 1, np.float32))
    assert eager.poll(h3) is True
    np.testing.assert_array_equal(eager.synchronize(h3),
                                  np.full((2,), 3, np.float32))


def test_deferred_async_error_reaches_every_handle(hvd):
    """A failing deferred op raises from EVERY affected handle's
    synchronize exactly once, each handle delivering its OWN fresh
    RuntimeError chained to the original failure (entries consumed; a
    retry is a KeyError, same as an unknown handle -- even when the
    triggering flush failed)."""
    from horovod_tpu.collectives import eager

    def boom():
        raise ValueError("deferred boom")

    h1 = eager._defer(boom)
    h2 = eager._defer(lambda: np.ones((2,)))
    with pytest.raises(RuntimeError, match="aborted") as e2:
        eager.synchronize(h2)             # trigger: its slot never issued
    with pytest.raises(RuntimeError, match="failed during flush") as e1:
        eager.synchronize(h1)
    # Distinct wrapper objects, one shared cause.
    assert e1.value is not e2.value
    assert isinstance(e1.value.__cause__, ValueError)
    assert e1.value.__cause__ is e2.value.__cause__
    assert "deferred boom" in str(e1.value.__cause__)
    with pytest.raises(KeyError):
        eager.synchronize(h2)             # consumed above


def test_synchronize_unknown_handle_keyerror_despite_flush_failure(hvd):
    """Round-6 fix: synchronize() of an unknown/consumed handle must
    raise KeyError even when the flush it triggered failed -- the flush
    error belongs to the deferred ops, not to a spent handle, and the
    old code's pop-default (the _PENDING sentinel) masked the KeyError
    behind the unrelated flush failure."""
    from horovod_tpu.collectives import eager

    def boom():
        raise ValueError("deferred boom 2")

    h = eager._defer(boom)
    with pytest.raises(KeyError):
        eager.synchronize(h + 1000)       # unknown handle, failing flush
    with pytest.raises(RuntimeError, match="failed during flush"):
        eager.synchronize(h)              # real handle still delivers


def test_deferred_dropped_on_shutdown(hvd):
    from horovod_tpu.collectives import eager

    eager._defer(lambda: np.ones((1,)))
    assert eager.deferred_count() == 1
    eager.reset_fences()                  # shutdown path
    assert eager.deferred_count() == 0


def test_allreduce_async_immediate_in_single_process(hvd):
    """Without the presence protocol (single process) *_async dispatches
    immediately -- nothing sits in the deferred queue."""
    from horovod_tpu.collectives import eager
    import horovod_tpu as hv

    x = hv.replicated_stack(np.ones((4,), np.float32))
    h = hv.allreduce_async(x, hv.Sum)
    assert eager.deferred_count() == 0
    out = hv.synchronize(h)
    np.testing.assert_allclose(eager.one_row(out), np.full((4,), hv.size()))


class _FakeKV:
    """Dict-backed stand-in for the coordination-service client."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, k, v, allow_overwrite=False):
        self.store[k] = v

    def key_value_dir_get(self, prefix):
        return [(k, v) for k, v in sorted(self.store.items())
                if k.startswith(prefix)]

    def blocking_key_value_get(self, k, timeout_ms):
        return self.store[k]


def test_join_timeout_env_is_honored(hvd, monkeypatch):
    """Regression: the lookup used the already-prefixed name, consulting
    HOROVOD_HOROVOD_JOIN_TIMEOUT -- the documented knob never worked."""
    monkeypatch.setenv("HOROVOD_JOIN_TIMEOUT", "123")
    assert joinop._timeout_ms() == 123_000


def test_read_last_max_seq_then_max_rank(hvd):
    """Last joiner resolves deterministically: max join seq, ties on rank
    (two processes joining between the same presence rounds)."""
    kv = _FakeKV()
    kv.key_value_set(f"{joinop._last_prefix()}{2:012d}_{5:012d}", "5")
    kv.key_value_set(f"{joinop._last_prefix()}{3:012d}_{1:012d}", "1")
    kv.key_value_set(f"{joinop._last_prefix()}{3:012d}_{2:012d}", "2")
    assert joinop._read_last(kv) == 2


def test_read_last_fallback_without_dir_get(hvd):
    """Old jaxlib (no key_value_dir_get): the single last-writer-wins
    fallback key still resolves the join."""

    class Bare:
        def __init__(self, store):
            self.store = store

        def blocking_key_value_get(self, k, timeout_ms):
            return self.store[k]

    assert joinop._read_last(Bare({joinop._last_fallback_key(): "3"})) == 3


def test_read_last_decodes_bytes(hvd):
    kv = _FakeKV()
    kv.key_value_set(f"{joinop._last_prefix()}{1:012d}_{4:012d}", b"4")
    assert joinop._read_last(kv) == 4


def test_subset_collective_raises_while_draining(hvd, monkeypatch):
    """A multi-process subset eager collective while some process is
    drained in hvd.join() fails loudly (reference: Join covers the global
    set only) instead of deadlocking on mismatched presence rounds."""
    import horovod_tpu as hv
    from horovod_tpu.collectives import eager
    from horovod_tpu.core import process_sets as ps_mod

    hv.add_process_set([0, 1, 2], name="sub_join")
    try:
        ps = ps_mod.get_process_set("sub_join")
        kv = _FakeKV()
        monkeypatch.setattr(joinop, "client", lambda: kv)
        monkeypatch.setattr(eager, "_is_multiprocess", lambda mesh: True)
        # Nothing draining: the subset dispatch skips join handling.
        assert joinop.sync(ps) is None
        # A drained process that is NOT a member of the subset cannot
        # deadlock it (its presence psum shares no Gloo pairs with a
        # survivors-only program) -- no error.
        kv.key_value_set(joinop._drain_key(5), "5")
        assert joinop.sync(ps) is None
        # A drained MEMBER process deadlocks the subset program: raise.
        kv.key_value_set(joinop._drain_key(0), "0")
        with pytest.raises(RuntimeError, match="drained in hvd.join"):
            joinop.sync(ps)
    finally:
        hv.remove_process_set("sub_join")
