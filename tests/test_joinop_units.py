"""Unit tests for the JoinOp draining machinery (single-process parts).

The end-to-end protocol is exercised by the np=2/np=3 launcher tests in
``test_run.py``; these cover the pure components.
"""

import numpy as np
import pytest

from horovod_tpu.collectives import joinop


@pytest.mark.parametrize("op,dtype,expect", [
    ("sum", np.float32, 0),
    ("average", np.float32, 0),
    ("adasum", np.float32, 0),
    ("product", np.float32, 1),
    ("min", np.float32, np.inf),
    ("max", np.float32, -np.inf),
    ("min", np.int32, np.iinfo(np.int32).max),
    ("max", np.int32, np.iinfo(np.int32).min),
])
def test_identity_values(op, dtype, expect):
    assert joinop.identity_value(op, np.dtype(dtype)) == expect


def test_sync_is_noop_single_process(hvd):
    """Single-process mode: no join machinery, zero overhead path."""
    from horovod_tpu.core import process_sets as ps
    assert joinop.sync(ps.get_process_set(None)) is None


def test_join_degenerates_to_barrier_single_process(hvd):
    assert hvd.join() == -1  # reference convention: no rank joined last


def test_reset_clears_generation(hvd):
    joinop._gen = 3
    joinop._joined = True
    joinop.reset()
    assert joinop._gen == 0 and not joinop._joined


def test_replay_rejects_unknown_kind(hvd):
    with pytest.raises(RuntimeError, match="unknown join replay kind"):
        joinop._replay({"kind": "frobnicate", "shape": (1,),
                        "dtype": "float32"})


def test_replay_abort_raises_with_message(hvd):
    with pytest.raises(RuntimeError, match="root has left"):
        joinop._replay({"kind": "abort", "message": "root has left"})
