"""Native C++ coordination core: handles, scheduler, cache, timeline."""

import json
import threading
import time

import pytest

from horovod_tpu import _core

pytestmark = pytest.mark.skipif(
    not _core.available(),
    reason=f"native core unavailable: {_core.unavailable_reason()}")


def test_version_string():
    assert b"hvdcore" in _core.get_lib().hvd_core_version()


# ---------------------------------------------------------------------------
# HandleManager
# ---------------------------------------------------------------------------


def test_handle_lifecycle():
    hm = _core.NativeHandles()
    h = hm.create()
    assert hm.poll(h) == 0
    hm.done(h, 0)
    assert hm.poll(h) == 1
    assert hm.wait(h) == 0
    hm.release(h)
    assert hm.poll(h) == -1


def test_handle_error_propagation():
    hm = _core.NativeHandles()
    h = hm.create()
    hm.done(h, 7, "peer vanished")
    assert hm.wait(h) == 7
    assert hm.error(h) == "peer vanished"
    hm.release(h)


def test_handle_wait_blocks_across_threads():
    hm = _core.NativeHandles()
    h = hm.create()
    results = {}

    def waiter():
        results["status"] = hm.wait(h, timeout_s=10.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()  # still blocked
    hm.done(h, 0)
    t.join(timeout=5.0)
    assert results["status"] == 0
    hm.release(h)


def test_handle_wait_timeout():
    hm = _core.NativeHandles()
    h = hm.create()
    assert hm.wait(h, timeout_s=0.05) == -2  # timeout
    hm.release(h)


# ---------------------------------------------------------------------------
# Cycle scheduler
# ---------------------------------------------------------------------------


def test_scheduler_batches_within_cycle():
    batches = []
    done = threading.Event()

    def on_batch(payloads):
        batches.append(payloads)
        done.set()

    sched = _core.NativeScheduler(on_batch, cycle_ms=20.0)
    try:
        for i in range(5):
            sched.enqueue(("grad", i), name=f"g{i}", dtype_code=1,
                          nbytes=1000)
        assert done.wait(5.0)
        time.sleep(0.05)  # allow the cycle to finish draining
        got = [p for b in batches for p in b]
        assert sorted(got) == [("grad", i) for i in range(5)]
        # All five fit one fusion bucket -> exactly one batch.
        assert len(batches) == 1
    finally:
        sched.stop()


def test_scheduler_fusion_threshold_splits_batches():
    batches = []

    def on_batch(payloads):
        batches.append(payloads)

    # Threshold 3 KB, tensors of 1 KB -> groups of <= 3.
    sched = _core.NativeScheduler(on_batch, cycle_ms=1000.0,
                                  fusion_bytes=3000)
    try:
        for i in range(7):
            sched.enqueue(i, name=f"g{i}", dtype_code=1, nbytes=1000)
        sched.flush()
        time.sleep(0.1)
        assert sorted(p for b in batches for p in b) == list(range(7))
        assert all(len(b) <= 3 for b in batches)
        assert len(batches) >= 3
    finally:
        sched.stop()


def test_scheduler_groups_by_dtype():
    batches = []

    def on_batch(payloads):
        batches.append(payloads)

    sched = _core.NativeScheduler(on_batch, cycle_ms=1000.0)
    try:
        sched.enqueue("f32_a", name="a", dtype_code=1, nbytes=10)
        sched.enqueue("f16_a", name="b", dtype_code=2, nbytes=10)
        sched.enqueue("f32_b", name="c", dtype_code=1, nbytes=10)
        sched.flush()
        time.sleep(0.1)
        assert len(batches) == 2
        by_first = {b[0][:3]: b for b in batches}
        assert sorted(by_first["f32"]) == ["f32_a", "f32_b"]
        assert by_first["f16"] == ["f16_a"]
    finally:
        sched.stop()


def test_scheduler_full_buffer_dispatches_early():
    """Hitting the fusion threshold cuts the cycle short."""
    done = threading.Event()

    def on_batch(payloads):
        done.set()

    # Cycle of 10 s -- only the full-buffer path can dispatch quickly.
    sched = _core.NativeScheduler(on_batch, cycle_ms=10_000.0,
                                  fusion_bytes=1000)
    try:
        t0 = time.perf_counter()
        sched.enqueue("big", name="big", dtype_code=1, nbytes=2000)
        assert done.wait(5.0)
        assert time.perf_counter() - t0 < 5.0
    finally:
        sched.stop()


def test_scheduler_handle_integration():
    """End-to-end: enqueue -> batch callback completes handles."""
    hm = _core.NativeHandles()

    def on_batch(payloads):
        for h in payloads:
            hm.done(h, 0)

    sched = _core.NativeScheduler(on_batch, cycle_ms=5.0)
    try:
        handles = []
        for i in range(4):
            h = hm.create()
            sched.enqueue(h, name=f"t{i}", dtype_code=1, nbytes=100,
                          handle=h)
            handles.append(h)
        for h in handles:
            assert hm.wait(h, timeout_s=5.0) == 0
            hm.release(h)
    finally:
        sched.stop()


# ---------------------------------------------------------------------------
# ResponseCache
# ---------------------------------------------------------------------------


def test_cache_lru_eviction():
    cache = _core.NativeCache(capacity=3)
    for sig in ("a", "b", "c"):
        cache.insert(sig)
    assert cache.lookup("a")  # refresh a
    cache.insert("d")         # evicts b (LRU)
    assert cache.lookup("a")
    assert not cache.lookup("b")
    assert cache.lookup("c") and cache.lookup("d")
    assert len(cache) == 3
    hits, misses = cache.stats()
    assert hits >= 4 and misses >= 1


# ---------------------------------------------------------------------------
# Timeline
# ---------------------------------------------------------------------------


def test_timeline_writes_valid_chrome_trace(tmp_path):
    path = str(tmp_path / "timeline.json")
    tl = _core.NativeTimeline(path)
    tl.event("allreduce.grad0", "NEGOTIATE_ALLREDUCE", "B", 10.0)
    tl.event("allreduce.grad0", "NEGOTIATE_ALLREDUCE", "E", 60.0)
    tl.event("allreduce.grad0", "ALLREDUCE", "X", 70.0, dur_us=230.0)
    tl.close()
    events = json.load(open(path))
    assert len(events) == 3
    assert events[2]["ph"] == "X" and events[2]["dur"] == 230.0
    assert events[0]["name"] == "allreduce.grad0"


def test_deterministic_flush_watermark_excludes_late_enqueues():
    """A stale flush flag must not sweep up requests enqueued after the
    flush call (SPMD bucket divergence regression: rank A's late flush
    wakeup grabbed 3 of the next step's 4 gradients and cut a different
    fused bucket than rank B)."""
    import time
    batches = []

    def on_batch(payloads):
        batches.append(list(payloads))

    sched = _core.NativeScheduler(on_batch, cycle_ms=1.0,
                                  deterministic=True)
    try:
        sched.enqueue("a", name="g.a", dtype_code=0, nbytes=8)
        sched.enqueue("b", name="g.b", dtype_code=0, nbytes=8)
        sched.flush()
        assert [sorted(b) for b in batches] == [["a", "b"]]
        # Enqueue after the flush: cycle ticks alone must NOT dispatch it
        # in deterministic mode, even though flush flags were just set.
        sched.enqueue("c", name="g.c", dtype_code=0, nbytes=8)
        time.sleep(0.05)  # many cycle ticks
        assert len(batches) == 1
        assert sched.pending() == 1
        sched.flush()
        assert [sorted(b) for b in batches] == [["a", "b"], ["c"]]
    finally:
        sched.stop()


def test_deterministic_rapid_flush_then_enqueue_race():
    """Tight loop of (enqueue x4, flush) must always cut 4-element batches
    -- the exact pattern of per-step gradient sync."""
    batches = []

    def on_batch(payloads):
        batches.append(list(payloads))

    sched = _core.NativeScheduler(on_batch, cycle_ms=0.1,
                                  deterministic=True)
    try:
        for step in range(200):
            for j in range(4):
                sched.enqueue(f"{step}/{j}", name=f"g.{j}", dtype_code=0,
                              nbytes=8)
            sched.flush()
    finally:
        sched.stop()
    assert len(batches) == 200
    assert all(len(b) == 4 for b in batches)


def test_thread_affinity_env(monkeypatch):
    """HOROVOD_THREAD_AFFINITY pins the native cycle thread: the
    scheduler must start (PinThread runs in Start), batch, and stop with
    the env set -- including the reference's comma-separated form and a
    malformed value, both of which must be non-fatal."""
    import threading
    import time

    for value in ("0", "0,1", "not-a-cpu"):
        batches, done = [], threading.Event()

        def on_batch(payloads, batches=batches, done=done):
            batches.append(payloads)
            done.set()

        monkeypatch.setenv("HOROVOD_THREAD_AFFINITY", value)
        sched = _core.NativeScheduler(on_batch, cycle_ms=20.0)
        try:
            sched.enqueue(("g", 0), name="g0", dtype_code=1, nbytes=8)
            assert done.wait(5.0), f"no batch under affinity={value!r}"
            time.sleep(0.05)
        finally:
            sched.stop()
        assert [p for b in batches for p in b] == [("g", 0)]
