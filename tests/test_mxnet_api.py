"""Contract tests for the MXNet shim against a fake ``mxnet`` module.

MXNet is EOL and uninstallable here (SURVEY.md section 3.4), but the shim
must not rot silently: a minimal fake -- NDArray with asnumpy/__setitem__,
``mx.nd.array``, ``mx.optimizer.Optimizer``, ``mx.gluon.Trainer`` -- is
injected via sys.modules so every public shim function EXECUTES.  All
ranks hold replicated data (single process owns all virtual devices), so
Average == identity and Sum == value * size, the same convention as the
torch/TF shim tests.
"""

import sys
import types

import numpy as np
import pytest

import horovod_tpu.mxnet as hvd_mx


class FakeNDArray:
    def __init__(self, data, ctx="cpu(0)", dtype=None):
        self._a = np.array(data, dtype=dtype)
        self.context = ctx

    def asnumpy(self):
        return self._a.copy()

    @property
    def shape(self):
        return self._a.shape

    def __setitem__(self, key, value):
        if isinstance(value, FakeNDArray):
            value = value._a
        self._a[key] = np.asarray(value, self._a.dtype)


class FakeParameter:
    def __init__(self, value, grad_req="write"):
        self._data = FakeNDArray(value)
        self._grad = FakeNDArray(np.ones_like(np.asarray(value)))
        self.grad_req = grad_req

    def data(self):
        return self._data

    def list_grad(self):
        return [self._grad]


class FakeOptimizer:
    """Stands in for ``mx.optimizer.Optimizer``: records update calls."""

    def __init__(self):
        self.rescale_grad = 1.0
        self.updates = []

    def update(self, index, weight, grad, state):
        self.updates.append(("update", index))

    def update_multi_precision(self, index, weight, grad, state):
        self.updates.append(("ump", index))


def _build_fake_mxnet():
    mx = types.ModuleType("mxnet")
    nd = types.ModuleType("mxnet.nd")
    nd.array = FakeNDArray
    optimizer = types.ModuleType("mxnet.optimizer")
    optimizer.Optimizer = FakeOptimizer
    gluon = types.ModuleType("mxnet.gluon")

    class Trainer:
        def __init__(self, params, optimizer, optimizer_params=None,
                     kvstore=None):
            vals = params.values() if hasattr(params, "values") else params
            self._params = list(vals)
            self._scale = (optimizer_params or {}).get("rescale_grad", 1.0)

    gluon.Trainer = Trainer
    mx.nd, mx.optimizer, mx.gluon = nd, optimizer, gluon
    return mx


@pytest.fixture()
def fake_mx(monkeypatch):
    mx = _build_fake_mxnet()
    monkeypatch.setitem(sys.modules, "mxnet", mx)
    return mx


def test_requires_mxnet_guidance(hvd, monkeypatch):
    """Without the package, every tensor API raises with guidance."""
    monkeypatch.delitem(sys.modules, "mxnet", raising=False)
    with pytest.raises(ImportError, match="mxnet"):
        hvd_mx.allreduce(FakeNDArray([1.0]))


def test_allreduce_and_inplace(hvd, n_devices, fake_mx):
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = hvd_mx.allreduce(FakeNDArray(x), name="mx.ar")
    assert isinstance(out, FakeNDArray) and out.context == "cpu(0)"
    np.testing.assert_allclose(out.asnumpy(), x)

    out = hvd_mx.allreduce(FakeNDArray(x), average=False, name="mx.ar_sum")
    np.testing.assert_allclose(out.asnumpy(), x * n_devices)

    t = FakeNDArray(x)
    ret = hvd_mx.allreduce_(t, op=hvd_mx.Sum, name="mx.ar_")
    assert ret is t
    np.testing.assert_allclose(t.asnumpy(), x * n_devices)


def test_grouped_ops(hvd, n_devices, fake_mx):
    xs = [np.arange(4, dtype=np.float32),
          np.arange(8, dtype=np.float32).reshape(n_devices, -1)]
    outs = hvd_mx.grouped_allreduce([FakeNDArray(a) for a in xs],
                                    name="mx.gar")
    for o, a in zip(outs, xs):
        np.testing.assert_allclose(o.asnumpy(), a)

    outs = hvd_mx.grouped_allgather([FakeNDArray(a) for a in xs],
                                    name="mx.gag")
    for o, a in zip(outs, xs):
        np.testing.assert_allclose(o.asnumpy(),
                                   np.concatenate([a] * n_devices, axis=0))

    rs_in = np.arange(n_devices * 2, dtype=np.float32).reshape(n_devices, 2)
    outs = hvd_mx.grouped_reducescatter([FakeNDArray(rs_in)], name="mx.grs")
    # Rank 0's shard of the average == row 0 (replicated inputs).
    np.testing.assert_allclose(outs[0].asnumpy(), rs_in[:1])


def test_allgather_broadcast_reducescatter(hvd, n_devices, fake_mx):
    x = np.arange(3, dtype=np.float32)
    out = hvd_mx.allgather(FakeNDArray(x), name="mx.ag")
    np.testing.assert_allclose(out.asnumpy(),
                               np.concatenate([x] * n_devices))

    out = hvd_mx.broadcast(FakeNDArray(x), root_rank=0, name="mx.bc")
    np.testing.assert_allclose(out.asnumpy(), x)
    t = FakeNDArray(x * 0)
    ret = hvd_mx.broadcast_(t, 0, name="mx.bc_")
    assert ret is t

    rs_in = np.arange(n_devices * 2, dtype=np.float32).reshape(n_devices, 2)
    out = hvd_mx.reducescatter(FakeNDArray(rs_in), op=hvd_mx.Sum,
                               name="mx.rs")
    np.testing.assert_allclose(out.asnumpy(), rs_in[:1] * n_devices)


def test_alltoall_even_and_splits(hvd, n_devices, fake_mx):
    x = np.arange(n_devices * 2, dtype=np.float32).reshape(n_devices, 2)
    out = hvd_mx.alltoall(FakeNDArray(x), name="mx.a2a")
    # Identical senders: rank 0 receives every sender's chunk 0.
    np.testing.assert_allclose(out.asnumpy(), np.tile(x[:1], (n_devices, 1)))

    splits = np.array([2] + [1] * (n_devices - 1), np.int32)
    data = np.arange(int(splits.sum()), dtype=np.float32)[:, None]
    recv, rsplits = hvd_mx.alltoall(FakeNDArray(data),
                                    splits=FakeNDArray(splits),
                                    name="mx.a2av")
    assert rsplits.asnumpy().tolist() == [2] * n_devices
    np.testing.assert_allclose(recv.asnumpy(),
                               np.tile(data[:2], (n_devices, 1)))


def test_broadcast_parameters_and_objects(hvd, fake_mx):
    p = FakeParameter(np.arange(4.0))
    raw = FakeNDArray(np.arange(3.0))
    hvd_mx.broadcast_parameters({"w": p, "b": raw}, root_rank=0)
    np.testing.assert_allclose(p.data().asnumpy(), np.arange(4.0))
    with pytest.raises(ValueError, match="dict-like"):
        hvd_mx.broadcast_parameters([p])

    obj = {"step": 3, "arr": np.arange(2.0)}
    got = hvd_mx.broadcast_object(obj, root_rank=0)
    assert got["step"] == 3
    gathered = hvd_mx.allgather_object({"r": 0}, name="mx.ago")
    assert len(gathered) == hvd_mx.size()


def test_distributed_optimizer(hvd, n_devices, fake_mx):
    base = FakeOptimizer()
    opt = hvd_mx.DistributedOptimizer(base, op=hvd_mx.Sum)
    g = FakeNDArray(np.ones(4, np.float32))
    opt.update(0, FakeNDArray(np.zeros(4)), g, None)
    np.testing.assert_allclose(g.asnumpy(), np.full(4, n_devices))
    # Grouped path: tuple index with matching grad list.
    gs = [FakeNDArray(np.ones(2, np.float32)),
          FakeNDArray(np.full(2, 2.0, np.float32))]
    opt.update_multi_precision((1, 2), [None, None], gs, None)
    np.testing.assert_allclose(gs[0].asnumpy(), np.full(2, n_devices))
    np.testing.assert_allclose(gs[1].asnumpy(), np.full(2, 2.0 * n_devices))
    assert opt.updates == [("update", 0), ("ump", (1, 2))]


def test_distributed_trainer(hvd, n_devices, fake_mx):
    params = {"w": FakeParameter(np.arange(4.0).astype(np.float32)),
              "frozen": FakeParameter(np.zeros(2, np.float32),
                                      grad_req="null")}
    trainer = hvd_mx.DistributedTrainer(
        params, "sgd", {"rescale_grad": 1.0})
    assert trainer._scale == pytest.approx(1.0 / hvd_mx.size())
    trainer._allreduce_grads()
    # Trainable grad summed across ranks; frozen param untouched.
    np.testing.assert_allclose(params["w"].list_grad()[0].asnumpy(),
                               np.full(4, n_devices, np.float32))
    np.testing.assert_allclose(params["frozen"].list_grad()[0].asnumpy(),
                               np.ones(2, np.float32))
