"""Latency-hiding flag pack tests (:mod:`horovod_tpu.core.xla_flags`).

All tests drive :func:`apply_xla_flags` with explicit env dicts and
platforms -- never the process environment -- so they are hermetic and
run identically on the CPU backend.
"""

import pytest

from horovod_tpu.core import xla_flags


def _all_pack_flags():
    return [f for flags in xla_flags.XLA_FLAG_PACK.values() for f in flags]


def test_cpu_platform_is_noop():
    env = {"JAX_PLATFORMS": "cpu"}
    report = xla_flags.apply_xla_flags(env=env)
    assert report.platform == "cpu"
    assert report.is_noop
    assert report.applied == {}
    assert set(report.rejected) == set(_all_pack_flags())
    assert all(why == "cpu backend" for why in report.rejected.values())
    # env untouched: no flag vars created.
    assert env == {"JAX_PLATFORMS": "cpu"}


def test_tpu_platform_applies_full_pack():
    env = {}
    report = xla_flags.apply_xla_flags(env=env, platform="tpu")
    assert not report.is_noop
    assert report.rejected == {}
    assert set(report.applied_flags) == set(_all_pack_flags())
    for var, flags in xla_flags.XLA_FLAG_PACK.items():
        for f in flags:
            assert f in env[var].split()
    # The scheduler flag specifically must land in XLA_FLAGS.
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" \
        in env["XLA_FLAGS"]


def test_user_set_flag_wins():
    user = "--xla_tpu_enable_latency_hiding_scheduler=false"
    env = {"XLA_FLAGS": user}
    report = xla_flags.apply_xla_flags(env=env, platform="tpu")
    assert report.rejected == {
        "--xla_tpu_enable_latency_hiding_scheduler=true": "user-set"}
    # The user's value is preserved verbatim, pack flags appended after.
    assert env["XLA_FLAGS"].split()[0] == user
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" \
        not in env["XLA_FLAGS"].split()
    assert "--xla_enable_async_all_gather=true" in env["XLA_FLAGS"].split()


def test_apply_is_idempotent():
    env = {}
    xla_flags.apply_xla_flags(env=env, platform="tpu")
    snapshot = dict(env)
    second = xla_flags.apply_xla_flags(env=env, platform="tpu")
    # Second application rejects everything as user-set; env unchanged.
    assert second.is_noop
    assert all(why == "user-set" for why in second.rejected.values())
    assert env == snapshot


def test_detect_platform_prefers_env_vars():
    assert xla_flags.detect_platform({"JAX_PLATFORMS": "tpu,cpu"}) == "tpu"
    assert xla_flags.detect_platform({"JAX_PLATFORM_NAME": "CPU"}) == "cpu"
    # No override: falls back to the libtpu-install probe.
    import importlib.util
    expected = "tpu" if importlib.util.find_spec("libtpu") else "cpu"
    assert xla_flags.detect_platform({}) == expected


def test_report_summary_lists_applied_and_rejected():
    env = {"XLA_FLAGS": "--xla_enable_async_all_gather=false"}
    report = xla_flags.apply_xla_flags(env=env, platform="tpu")
    text = report.summary()
    assert "platform=tpu" in text
    assert "+ XLA_FLAGS: --xla_tpu_enable_latency_hiding_scheduler=true" \
        in text
    assert "- --xla_enable_async_all_gather=true  (user-set)" in text


def test_apply_records_last_report():
    env = {"JAX_PLATFORMS": "cpu"}
    report = xla_flags.apply(env=env)
    assert xla_flags.last_report() is report
    assert report.is_noop


def test_real_env_apply_on_cpu_backend_is_noop(monkeypatch):
    """Applying to os.environ under the test harness (JAX_PLATFORMS=cpu)
    must not mutate the environment."""
    import os
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    before_xla = os.environ.get("XLA_FLAGS")
    before_libtpu = os.environ.get("LIBTPU_INIT_ARGS")
    report = xla_flags.apply_xla_flags()
    assert report.is_noop
    assert os.environ.get("XLA_FLAGS") == before_xla
    assert os.environ.get("LIBTPU_INIT_ARGS") == before_libtpu
