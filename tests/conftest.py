"""Test harness: 8 virtual CPU devices, mirroring the reference's
``mpirun -np N`` localhost test strategy (SURVEY.md section 4/7)."""

import os
import sys
from os.path import abspath, dirname

# Must run before jax initializes its backends.  The environment
# pre-configures jax_platforms="axon,cpu" (TPU plugin), which overrides the
# JAX_PLATFORMS env var; force_host_device_count forces the CPU backend via
# jax.config so tests get the 8-device virtual mesh.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, dirname(dirname(abspath(__file__))))
from horovod_tpu.utils.platform import force_host_device_count  # noqa: E402

force_host_device_count(8, cpu=True)

import jax  # noqa: E402
import pytest  # noqa: E402

assert len(jax.devices()) >= 8, jax.devices()


@pytest.fixture(scope="session")
def n_devices():
    return len(jax.devices())


@pytest.fixture()
def hvd():
    """Fresh-initialized framework per test."""
    import horovod_tpu as hvd_mod
    hvd_mod.shutdown()
    hvd_mod.init()
    yield hvd_mod
    hvd_mod.shutdown()
