"""Test harness: 8 virtual CPU devices, mirroring the reference's
``mpirun -np N`` localhost test strategy (SURVEY.md section 4/7)."""

import os

# Must be set before jax initializes its backends.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# The environment pre-configures jax_platforms="axon,cpu" (TPU plugin), which
# overrides the env var; force the CPU backend explicitly so tests get the
# 8-device virtual mesh.
jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) >= 8, jax.devices()


@pytest.fixture(scope="session")
def n_devices():
    return len(jax.devices())


@pytest.fixture()
def hvd():
    """Fresh-initialized framework per test."""
    import horovod_tpu as hvd_mod
    hvd_mod.shutdown()
    hvd_mod.init()
    yield hvd_mod
    hvd_mod.shutdown()
