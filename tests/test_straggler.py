"""Cross-rank trace plane tests: span recorder, straggler monitor,
clock sync over the KV plane, the offline merge CLI, and the metrics /
health endpoints the plane feeds.

The acceptance surface for the trace plane (ISSUE 9): per-step span
summaries flow from the instrumented step into the straggler monitor,
``horovod_straggler_*`` / ``horovod_step_skew_*`` appear on a live
``/metrics`` endpoint, per-rank timelines carry a wall-clock anchor and
merge into one Perfetto trace, and the chaos ``slow`` fault is
attributed to the injected rank.
"""

import json
import logging
import time
import urllib.error
import urllib.request

import numpy as np
import optax
import pytest

import horovod_tpu as hv
from horovod_tpu.core.state import global_state
from horovod_tpu.timeline import Timeline
from horovod_tpu.timeline import metrics as M
from horovod_tpu.timeline import spans
from horovod_tpu.timeline.straggler import StragglerMonitor
from horovod_tpu.timeline.sync import TracePlane, estimate_clock_offset


@pytest.fixture(autouse=True)
def _fresh():
    hv.shutdown()
    M.reset_metrics()
    spans.recorder().reset()
    yield
    hv.shutdown()
    M.reset_metrics()
    spans.recorder().reset()


def _summary(rank, step, wall, spans_d=None):
    return {"rank": rank, "step": step, "t0_us": 1e12 + step * 1e6,
            "wall_s": wall, "spans": spans_d or {"dispatch": wall},
            "legs": {}}


# -- SpanRecorder -----------------------------------------------------------

def test_span_recorder_summary_and_listener():
    rec = spans.SpanRecorder()
    rec.configure(rank=3)
    rec.set_step(7)
    with rec.span("exchange", leg="allreduce", bucket_id=0,
                  fuse_key="fused@0"):
        time.sleep(0.002)
    rec.add("fence", 0.05, leg="allreduce")
    got = []
    rec.add_listener(got.append)
    rec.add_listener(got.append)  # identity-idempotent
    s = rec.step_boundary(7, 0.1, t0_unix_us=123.0)
    assert len(got) == 1 and got[0] is s
    assert s["rank"] == 3 and s["step"] == 7 and s["t0_us"] == 123.0
    assert s["wall_s"] == 0.1
    assert set(s["spans"]) == {"exchange", "fence"}
    assert s["legs"]["allreduce"]["count"] == 2
    assert spans.dominant_span(s) == "fence"
    # the boundary consumed the accumulator: a rerun is empty
    s2 = rec.step_boundary(7, 0.1)
    assert s2["spans"] == {}
    assert spans.dominant_span(s2) == "compute"


def test_span_listener_exceptions_do_not_break_boundary():
    rec = spans.SpanRecorder()

    def boom(_):
        raise RuntimeError("observer bug")

    got = []
    rec.add_listener(boom)
    rec.add_listener(got.append)
    s = rec.step_boundary(1, 0.5)
    assert got == [s]


def test_note_leg_accumulates_registry():
    rec = spans.SpanRecorder()
    rec.note_leg("zero_rs", nbytes=1024, bucket_id=0)
    rec.note_leg("zero_rs", nbytes=2048, bucket_id=1)
    rec.note_leg("ef_exchange", nbytes=16)
    assert rec.legs["zero_rs"] == {"nbytes": 3072, "buckets": 2}
    assert rec.legs["ef_exchange"]["buckets"] == 1


def test_span_and_emit_mirror_into_timeline(tmp_path):
    path = str(tmp_path / "tl.json")
    tl = Timeline(path, rank=4)
    rec = spans.SpanRecorder()
    rec.configure(rank=4, timeline=tl)
    rec.set_step(9)
    with rec.span("exchange", name="spans", leg="allreduce",
                  bucket_id=2, fuse_key="fused@0"):
        pass
    rec.add("dispatch_gap", 0.001, emit=True)
    tl.close()
    events = json.load(open(path))
    b = [e for e in events if e.get("ph") == "B"]
    x = [e for e in events if e.get("ph") == "X"]
    assert b and b[0]["name"] == "exchange"
    assert b[0]["args"] == {"rank": 4, "step": 9, "leg": "allreduce",
                            "bucket_id": 2, "fuse_key": "fused@0"}
    assert x and x[0]["name"] == "dispatch_gap"
    assert x[0]["dur"] == pytest.approx(1000.0)
    assert x[0]["args"]["step"] == 9


# -- wall-clock anchor (satellite: timelines must be mergeable) -------------

def test_timeline_clock_anchor_is_first_event(tmp_path):
    path = str(tmp_path / "tl.json")
    before = time.time() * 1e6
    tl = Timeline(path, rank=2, hostname="host2")
    tl.begin("t", "ALLREDUCE")
    tl.end("t", "ALLREDUCE")
    tl.close()
    events = json.load(open(path))
    first = events[0]
    assert first["name"] == "clock_anchor" and first["ph"] == "M"
    assert first["args"]["rank"] == 2
    assert first["args"]["hostname"] == "host2"
    assert abs(first["args"]["epoch_unix_us"] - before) < 60e6


def test_timeline_anchor_rank_falls_back_to_env(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_RANK", "5")
    tl = Timeline(str(tmp_path / "tl.json"))
    tl.close()
    assert tl.rank == 5


# -- StragglerMonitor -------------------------------------------------------

def test_monitor_names_slow_rank_and_dominant_span():
    mon = StragglerMonitor(world=4, stall_check_time=0.0)
    for step in range(1, 6):
        for r in range(4):
            if r == 2:
                mon.observe(_summary(r, step, 0.15, {
                    "dispatch": 0.05, "dispatch_gap": 0.10}))
            else:
                mon.observe(_summary(r, step, 0.10))
    rep = mon.report()
    assert rep["straggler_rank"] == 2
    assert rep["dominant_span"] == "dispatch_gap"
    assert rep["lateness_s"] == pytest.approx(0.05, rel=0.05)
    assert rep["skew_s"] == pytest.approx(0.05, rel=0.05)
    assert set(rep["per_rank_wall_s"]) == {0, 1, 2, 3}
    text = mon.render()
    assert "rank 2" in text and "dispatch_gap" in text
    assert "<-- straggler" in text


def test_monitor_ewma_converges():
    mon = StragglerMonitor(world=1, alpha=0.5, stall_check_time=0.0)
    mon.observe(_summary(0, 1, 1.0))
    mon.observe(_summary(0, 2, 0.0))
    assert mon.report()["per_rank_wall_s"][0] == pytest.approx(0.5)


def test_monitor_never_raises_on_malformed():
    mon = StragglerMonitor()
    mon.observe({})
    mon.observe({"rank": "x", "step": 1, "wall_s": 0.1})
    mon.observe({"rank": 0, "step": None, "wall_s": 0.1})
    assert mon.report()["straggler_rank"] is None
    assert "no observations" in mon.render()


def test_monitor_stall_warning_once_and_rearms(caplog):
    mon = StragglerMonitor(world=2, stall_check_time=5.0)
    mon.observe(_summary(0, 1, 0.1), now=0.0)
    mon.observe(_summary(1, 1, 0.1), now=0.0)
    with caplog.at_level(logging.WARNING, "horovod_tpu.timeline"):
        mon.observe(_summary(1, 2, 0.1), now=10.0)  # rank 0 silent 10s
    stalls = [r for r in caplog.records if "has published no step" in
              r.getMessage()]
    assert len(stalls) == 1 and "rank 0" in stalls[0].getMessage()
    caplog.clear()
    with caplog.at_level(logging.WARNING, "horovod_tpu.timeline"):
        mon.observe(_summary(1, 3, 0.1), now=11.0)  # still silent: no spam
    assert not [r for r in caplog.records
                if "has published no step" in r.getMessage()]
    mon.observe(_summary(0, 4, 0.1), now=12.0)      # rank 0 back: re-arms
    with caplog.at_level(logging.WARNING, "horovod_tpu.timeline"):
        mon.observe(_summary(1, 5, 0.1), now=30.0)
    assert [r for r in caplog.records
            if "has published no step" in r.getMessage()]


def test_monitor_exports_metric_families():
    mon = StragglerMonitor(world=2, stall_check_time=0.0)
    mon.observe(_summary(0, 1, 0.1))
    mon.observe(_summary(1, 1, 0.3, {"fence": 0.25, "dispatch": 0.05}))
    text = M.render_prometheus()
    assert "# TYPE horovod_straggler_rank gauge" in text
    assert "horovod_straggler_rank 1" in text
    assert "horovod_straggler_lateness_seconds" in text
    assert 'horovod_straggler_rank_wall_seconds{rank="1"}' in text
    assert "# TYPE horovod_step_skew_seconds histogram" in text
    assert "horovod_step_skew_last_seconds" in text


# -- live run: straggler families reach /metrics ----------------------------

@pytest.mark.integration
def test_straggler_metrics_in_live_run(monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS_PORT", "0")
    hv.init()
    st = global_state()
    assert st.straggler is not None
    server = st.metrics_server
    assert server is not None

    rng = np.random.RandomState(0)
    params = hv.replicate({"w": rng.randn(16, 4).astype(np.float32)})
    opt = hv.DistributedOptimizer(optax.sgd(0.05))
    state = hv.replicate(opt.init({"w": rng.randn(16, 4).astype(
        np.float32)}))

    def loss_fn(pr, x):
        import jax.numpy as jnp
        return jnp.mean((x @ pr["w"]) ** 2)

    step = hv.make_train_step(loss_fn, opt)
    for _ in range(3):
        x = np.asarray(rng.randn(2 * hv.size(), 16), np.float32)
        params, state, _ = step(params, state, hv.shard_batch(x))

    # Cross-rank summaries arrive through the same monitor the local
    # feed uses (in multi-host runs the TracePlane delivers these).
    st.straggler.observe(_summary(1, 2, 0.5, {"dispatch_gap": 0.4,
                                              "dispatch": 0.1}))
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=10) as r:
        text = r.read().decode()
    for family in ("horovod_straggler_rank",
                   "horovod_straggler_lateness_seconds",
                   "horovod_straggler_rank_wall_seconds",
                   "horovod_step_skew_seconds",
                   "horovod_step_skew_last_seconds"):
        assert f"# TYPE {family} " in text, family
    assert "horovod_straggler_rank 1" in text
    hv.shutdown()


# -- /healthz must answer unsigned even with HMAC auth (satellite fix) ------

def test_healthz_unsigned_with_auth_enabled():
    from horovod_tpu.run.metrics_server import MetricsServer
    M.registry().counter("t_health_total").inc()
    server = MetricsServer(port=0, secret_key="s3cret")
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/healthz", timeout=10) as r:
            assert r.status == 200
            assert r.read() == b"ok\n"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=10)
        assert e.value.code == 403  # /metrics stays protected
    finally:
        server.stop()


# -- clock sync + KV trace plane --------------------------------------------

def _kv_pair():
    from horovod_tpu.run.http_kv import KVClient, RendezvousServer
    from horovod_tpu.run.secret import make_secret_key
    secret = make_secret_key()
    srv = RendezvousServer(secret, host="127.0.0.1")
    kv = KVClient("127.0.0.1", srv.port, secret)
    return srv, kv


def test_server_time_and_offset_estimate():
    srv, kv = _kv_pair()
    try:
        t = kv.server_time()
        assert abs(t - time.time()) < 60.0
        offset, rtt = estimate_clock_offset(kv, samples=4)
        # Same host, same clock: offset bounded by the round trip.
        assert rtt >= 0.0
        assert abs(offset) <= max(rtt, 0.05)
    finally:
        srv.stop()


def test_server_time_rejects_unsigned():
    from horovod_tpu.run.http_kv import KVClient
    srv, kv = _kv_pair()
    try:
        bad = KVClient("127.0.0.1", srv.port, "wrong-secret")
        from horovod_tpu.run.http_kv import RendezvousAuthError
        with pytest.raises(RendezvousAuthError):
            bad.server_time()
    finally:
        srv.stop()


def test_trace_plane_publish_collect_and_merge(tmp_path):
    srv, kv = _kv_pair()
    try:
        mon = StragglerMonitor(world=2, stall_check_time=0.0)
        plane0 = TracePlane(kv, rank=0, size=2, publish_steps=2,
                            monitor=mon)
        plane1 = TracePlane(kv, rank=1, size=2, publish_steps=2)
        s1 = _summary(1, 2, 0.3, {"fence": 0.2, "dispatch": 0.1})
        s0 = _summary(0, 2, 0.1)
        plane1.on_summary(s1)
        mon.observe(s0)            # rank 0's local feed
        plane0.on_summary(s0)      # publishes + collects the fleet
        assert plane0.on_summary(_summary(0, 3, 0.1)) is None  # off-cadence
        got = plane0._collected[2]
        assert {s["rank"] for s in got} == {0, 1}
        assert plane0.step_skew(2) == pytest.approx(0.2, rel=0.05)
        rep = mon.report()
        assert rep["straggler_rank"] == 1
        assert rep["dominant_span"] == "fence"
        # offsets: same host, so rank 1's offset to rank 0 is ~rtt-bounded
        assert abs(plane0.rank_offset(1)) < 1.0

        out = str(tmp_path / "merged.json")
        n = plane0.write_merged(out)
        assert n == 2
        events = json.load(open(out))
        pids = {e["pid"] for e in events if e["ph"] == "X"}
        assert pids == {1, 2}
        names = {e["name"] for e in events if e["ph"] == "X"}
        assert "step 2" in names and "fence" in names
    finally:
        srv.stop()


def test_trace_plane_survives_kv_outage():
    srv, kv = _kv_pair()
    try:
        plane = TracePlane(kv, rank=0, size=1, publish_steps=1)
    finally:
        srv.stop()
    # Server is gone: publishing must swallow the transport error.
    plane.on_summary(_summary(0, 5, 0.1))


def test_init_wires_trace_plane_from_assignment_env(monkeypatch):
    from horovod_tpu.elastic.notify import ASSIGNMENT_ENV
    from horovod_tpu.run.secret import SECRET_ENV
    srv, kv = _kv_pair()
    try:
        monkeypatch.setenv("HOROVOD_TRACE_SYNC", "1")
        monkeypatch.setenv("HOROVOD_TRACE_PUBLISH_STEPS", "3")
        monkeypatch.setenv(ASSIGNMENT_ENV,
                           f"http://127.0.0.1:{srv.port}")
        monkeypatch.setenv(SECRET_ENV, kv.secret_key)
        hv.init()
        st = global_state()
        assert st.trace_plane is not None
        assert st.trace_plane.publish_steps == 3
        assert st.trace_plane.rank == 0
        # its offset landed on the KV plane for the fleet to read
        raw = kv.get("trace", "offset/0")
        assert raw is not None and "offset_s" in json.loads(raw)
        hv.shutdown()
        assert global_state().trace_plane is None
    finally:
        srv.stop()


def test_trace_sync_without_kv_degrades_to_warning(monkeypatch, caplog):
    monkeypatch.setenv("HOROVOD_TRACE_SYNC", "1")
    monkeypatch.delenv("HVD_TPU_ELASTIC_ASSIGNMENT", raising=False)
    with caplog.at_level(logging.WARNING):
        hv.init()
    assert global_state().trace_plane is None  # degraded, not fatal


# -- chaos `slow` fault -----------------------------------------------------

def test_chaos_slow_spec_parse_and_fire():
    from horovod_tpu.elastic import chaos
    seed, faults = chaos.parse_spec("seed=3;slow@step=2,rank=1,secs=0.03")
    assert seed == 3
    assert faults[0].kind == "slow" and faults[0].secs == 0.03
    try:
        inj = chaos.install("slow@step=2,rank=1,secs=0.03", rank=1, size=2)
        inj.on_step(1)
        assert inj.fired_kinds == []
        t0 = time.perf_counter()
        inj.on_step(2)
        assert time.perf_counter() - t0 >= 0.03
        assert inj.fired_kinds == ["slow"]
        inj.on_step(2)  # once-only latch
        assert inj.fired_kinds == ["slow"]

        other = chaos.install("slow@step=2,rank=1,secs=0.03",
                              rank=0, size=2)
        other.on_step(2)  # wrong rank: must not fire
        assert other.fired_kinds == []
    finally:
        chaos.reset()


# -- offline merge CLI ------------------------------------------------------

def _write_rank_trace(tmp_path, rank, sleep_s):
    tl = Timeline(str(tmp_path / f"timeline_r{rank}.json"), rank=rank,
                  hostname=f"h{rank}")
    tl.begin("step", "dispatch", args={"rank": rank, "step": 1})
    time.sleep(sleep_s)
    tl.end("step", "dispatch")
    rec = spans.SpanRecorder()
    rec.configure(rank=rank, timeline=tl)
    rec.set_step(1)
    rec.add("dispatch_gap", 0.05 if rank == 1 else 0.001, emit=True)
    tl.close()


@pytest.mark.integration
def test_merge_cli_end_to_end(tmp_path, capsys):
    from horovod_tpu.timeline.__main__ import main
    for r in range(2):
        _write_rank_trace(tmp_path, r, 0.01)
    assert main(["--merge", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "merged 2 rank trace(s)" in out
    assert "straggler: rank 1" in out
    assert "dispatch_gap" in out
    merged_path = tmp_path / "merged_timeline.json"
    merged = json.load(open(merged_path))
    assert isinstance(merged, list) and merged
    pids = {e["pid"] for e in merged if e.get("ph") in ("B", "E", "X")}
    assert pids == {1, 2}  # one pid per rank
    pnames = {(e["pid"], e["args"]["name"]) for e in merged
              if e.get("name") == "process_name"}
    assert (1, "rank 0 (h0)") in pnames and (2, "rank 1 (h1)") in pnames
    # timestamps were re-anchored: every event sits on rank 0's clock
    assert all(e["ts"] >= 0 for e in merged if "ts" in e)


def test_merge_skips_anchorless_files(tmp_path, capsys):
    from horovod_tpu.timeline.__main__ import main, merge
    _write_rank_trace(tmp_path, 0, 0.005)
    (tmp_path / "old_style.json").write_text(json.dumps(
        [{"name": "x", "ph": "B", "pid": 1, "tid": 0, "ts": 0.0}]))
    (tmp_path / "garbage.json").write_text("{not json")
    rep = merge(str(tmp_path), str(tmp_path / "merged.json"))
    assert rep["ranks"] == 1
    assert len(rep["skipped"]) == 2


def test_merge_empty_dir_exits_cleanly(tmp_path):
    from horovod_tpu.timeline.__main__ import merge
    with pytest.raises(SystemExit):
        merge(str(tmp_path), str(tmp_path / "merged.json"))


def test_merge_classifier_buckets_phases():
    from horovod_tpu.timeline.__main__ import classify
    assert classify("dispatch") == "compute"
    assert classify("dispatch_gap") == "dispatch_gap"
    assert classify("FENCE") == "fence"
    assert classify("fence") == "fence"
    assert classify("ALLREDUCE") == "exchange"
    assert classify("NEGOTIATE_ALLGATHER") == "negotiate"
    assert classify("bucket") == "exchange"
    assert classify("whatever") == "compute"
