"""SLO-driven elastic serving control plane: policy, drain, evict, audit.

The contract under test, per layer:

* **policy** -- pure-python decision function: dead-rank mandatory
  shrink beats everything, straggler eviction beats voluntary moves,
  voluntary moves need ``hysteresis`` consecutive breaches plus an
  elapsed cooldown, and every target stays on the valid tp ladder.
* **drain** -- a request mid-decode at shrink time either finishes on
  the old mesh with bitwise-identical tokens (completion path) or is
  suspended, re-prefilled on the post-resize mesh from prompt + emitted
  tokens, and continues within sampling tolerance (re-prefill path);
  either way suspension frees its KV pages exactly.
* **eviction** -- the StragglerMonitor hook fires once (latched) only
  for a SUSTAINED over-threshold straggler, and ``evict`` forgets the
  rank so attribution tracks the survivors.
* **closed loop** -- a chaos drill (kill@ + slow@) ends with the dead
  rank resized away, the slow rank auto-evicted, zero lost requests,
  zero leaked pages, and every decision visible as ``horovod_ctl_*``
  metrics and ``ctl/*`` span-recorder legs.
* **audit** -- the serving-tp-decode trace audit still matches its plan
  on the post-shrink mesh (``serving_decode_resized``).
"""

import jax
import numpy as np
import pytest

from horovod_tpu.analysis.trace_audit import audit_standard_configs
from horovod_tpu.elastic import run_loop as _run_loop
from horovod_tpu.elastic.run_loop import apply_resize
from horovod_tpu.models.transformer import LLAMA_SERVE, LlamaLM
from horovod_tpu.serving import (CacheConfig, ContinuousBatchScheduler,
                                 Decision, PagedKVCache, PolicyConfig,
                                 Request, ScalePolicy, ServingControlPlane,
                                 ServingEngine, SLOSample, valid_tp_sizes)
from horovod_tpu.timeline import spans
from horovod_tpu.timeline.metrics import (histogram_quantile,
                                          histogram_window, registry,
                                          render_prometheus)
from horovod_tpu.timeline.straggler import StragglerMonitor

import jax.numpy as jnp

CFG = LLAMA_SERVE


@pytest.fixture(scope="module")
def base_params():
    model = LlamaLM(CFG, dtype=jnp.float32)
    return model, model.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 4), jnp.int32))


def _req(rid, plen=4, out=4, arrival=0.0):
    return Request(rid=rid, prompt=np.full((plen,), rid % 7, np.int32),
                   max_new_tokens=out, arrival_s=arrival)


def _sample(now_s=0.0, queue=0, p99=None, occ=0.5, mesh=(0, 1),
            healthy=(0, 1, 2, 3, 4, 5, 6, 7), dead=(), evict=None):
    return SLOSample(now_s=now_s, queue_depth=queue, ttft_p99_s=p99,
                     occupancy=occ, mesh_size=len(mesh),
                     mesh_ranks=tuple(mesh), healthy=tuple(healthy),
                     dead_ranks=tuple(dead), evict_candidate=evict)


# ---------------------------------------------------------------------------
# Policy: ladder, hysteresis, cooldown, precedence
# ---------------------------------------------------------------------------


def test_valid_tp_sizes_ladder():
    assert valid_tp_sizes(CFG, 8) == [1, 2, 4, 8]
    assert valid_tp_sizes(CFG, 5) == [1, 2, 4]

    class _Odd:
        num_heads, num_kv_heads, ffn_hidden = 6, 2, 24

    # 4 does not divide num_heads=6: the ladder skips it.
    assert valid_tp_sizes(_Odd, 8) == [1, 2]


def test_policy_config_from_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_CTL_QUEUE_HIGH", "3")
    monkeypatch.setenv("HOROVOD_CTL_TTFT_SLO_S", "2.5")
    monkeypatch.setenv("HOROVOD_CTL_MAX_TP", "4")
    cfg = PolicyConfig.from_env()
    assert cfg.queue_high == 3
    assert cfg.ttft_slo_s == 2.5
    assert cfg.max_tp == 4
    assert cfg.hysteresis == PolicyConfig().hysteresis  # untouched default


def test_policy_grow_needs_hysteresis_then_cooldown():
    cfg = PolicyConfig(hysteresis=2, cooldown_s=1.0, queue_high=8)
    pol = ScalePolicy(cfg, [1, 2, 4, 8])
    assert pol.decide(_sample(now_s=0.0, queue=10)).is_hold  # breach 1/2
    d = pol.decide(_sample(now_s=0.1, queue=10))             # breach 2/2
    assert (d.action, d.target_size) == ("grow", 4)
    pol.mark_applied(d, 0.1)
    # Still overloaded, but inside the cooldown: hold.
    assert pol.decide(_sample(now_s=0.3, queue=10)).is_hold
    assert pol.decide(_sample(now_s=0.5, queue=10)).is_hold
    d = pol.decide(_sample(now_s=1.2, queue=10))
    assert (d.action, d.target_size) == ("grow", 4)


def test_policy_grow_capped_by_healthy_and_ladder_top():
    cfg = PolicyConfig(hysteresis=1, cooldown_s=0.0)
    pol = ScalePolicy(cfg, [1, 2, 4, 8])
    # Only 3 healthy devices: no valid size above 2 fits.
    assert pol.decide(_sample(queue=10, mesh=(0, 1),
                              healthy=(0, 1, 2))).is_hold
    # Already at the top of the ladder: nothing to grow into.
    assert pol.decide(_sample(queue=10,
                              mesh=tuple(range(8)))).is_hold


def test_policy_shrink_on_underload():
    cfg = PolicyConfig(hysteresis=2, cooldown_s=0.0, occupancy_low=0.25)
    pol = ScalePolicy(cfg, [1, 2, 4, 8])
    assert pol.decide(_sample(occ=0.1, mesh=(0, 1, 2, 3))).is_hold
    d = pol.decide(_sample(occ=0.1, mesh=(0, 1, 2, 3)))
    assert (d.action, d.target_size) == ("shrink", 2)
    # A queued request means the low occupancy is transient: no shrink.
    pol2 = ScalePolicy(cfg, [1, 2, 4, 8])
    for t in range(4):
        assert pol2.decide(_sample(now_s=t, occ=0.1, queue=1,
                                   mesh=(0, 1, 2, 3))).is_hold


def test_policy_ttft_breach_counts_as_overload():
    cfg = PolicyConfig(hysteresis=1, cooldown_s=0.0, ttft_slo_s=0.5)
    pol = ScalePolicy(cfg, [1, 2, 4, 8])
    d = pol.decide(_sample(p99=0.9))
    assert (d.action, d.target_size) == ("grow", 4)
    # None p99 (empty window) is not a breach.
    pol2 = ScalePolicy(cfg, [1, 2, 4, 8])
    assert pol2.decide(_sample(p99=None)).is_hold


def test_policy_dead_rank_bypasses_debounce():
    cfg = PolicyConfig(hysteresis=99, cooldown_s=1e9)
    pol = ScalePolicy(cfg, [1, 2, 4, 8])
    d = pol.decide(_sample(mesh=(0, 1, 2, 3, 4, 5, 6, 7),
                           healthy=(0, 1, 2, 3, 4, 5, 6), dead=(7,)))
    assert (d.action, d.reason, d.target_size) == ("shrink", "rank-dead", 4)
    # No healthy device left that fits any valid size: hold, not crash.
    d = pol.decide(_sample(mesh=(0,), healthy=(), dead=(0,)))
    assert d.is_hold and "no-viable-size" in d.reason


def test_policy_evict_precedence_and_latch():
    cfg = PolicyConfig(hysteresis=99, cooldown_s=1e9)
    pol = ScalePolicy(cfg, [1, 2, 4, 8])
    s = _sample(mesh=(0, 1, 2, 3), healthy=(0, 1, 2, 3, 4),
                evict=(2, 0.4))
    d = pol.decide(s)
    assert (d.action, d.evict_rank, d.target_size) == ("evict", 2, 4)
    assert "straggler-lateness" in d.reason
    # Same candidate again: already evicted, never re-issued.
    assert pol.decide(s).is_hold
    # A candidate that already left the mesh is ignored.
    assert pol.decide(_sample(mesh=(0, 1), evict=(5, 0.4))).is_hold


# ---------------------------------------------------------------------------
# Histogram window/quantile arithmetic (the controller's TTFT p99 sensor)
# ---------------------------------------------------------------------------


def test_histogram_quantile_interpolation():
    snap = {"buckets": {"0.1": 5, "1.0": 10, "+Inf": 10},
            "sum": 4.0, "count": 10}
    assert histogram_quantile(snap, 0.5) == pytest.approx(0.1)
    assert histogram_quantile(snap, 0.99) == pytest.approx(0.982)
    # Overflow observations clamp to the highest finite bound.
    over = {"buckets": {"0.25": 0, "+Inf": 4}, "sum": 9.0, "count": 4}
    assert histogram_quantile(over, 0.5) == pytest.approx(0.25)
    assert histogram_quantile({"buckets": {}, "count": 0}, 0.5) is None


def test_histogram_window_diffs_cumulative_snapshots():
    h = registry().histogram("test_ctl_ttft_window", "test histogram",
                             buckets=(0.1, 1.0))
    for _ in range(5):
        h.observe(0.05)
    base = h.snapshot()
    for _ in range(5):
        h.observe(0.5)
    win = histogram_window(h.snapshot(), base)
    assert win["count"] == 5
    # All 5 windowed observations sit in the (0.1, 1.0] bucket.
    assert histogram_quantile(win, 0.5) == pytest.approx(0.55)
    # No baseline: the window is the whole snapshot.
    assert histogram_window(base, None) is base


# ---------------------------------------------------------------------------
# apply_resize: the shared training/serving reset sequence
# ---------------------------------------------------------------------------


class _FakeElasticState:
    """Training-shaped carrier recording the reset call sequence."""

    def __init__(self, fail=False):
        self.fail = fail
        self.calls = []

    def resize(self, old_size, new_size):
        self.calls.append(("resize", old_size, new_size))
        if self.fail:
            raise RuntimeError("repartition failed")
        return "ok"

    def on_reset(self):
        self.calls.append(("on_reset",))


class _SyncOnlyState:
    def __init__(self):
        self.calls = []

    def on_reset(self):
        self.calls.append(("on_reset",))


def _ranks_lost():
    return registry().counter("horovod_elastic_ranks_lost",
                              "Ranks lost across elastic recoveries").value


def test_apply_resize_shrink_order_and_counter():
    st = _FakeElasticState()
    before = _ranks_lost()
    apply_resize(st, 8, 4)
    assert st.calls == [("resize", 8, 4), ("on_reset",)]
    assert _ranks_lost() - before == 4


def test_apply_resize_grow_and_noop_paths():
    st = _FakeElasticState()
    before = _ranks_lost()
    apply_resize(st, 2, 4)
    assert st.calls == [("resize", 2, 4), ("on_reset",)]
    assert _ranks_lost() == before        # growth loses nothing
    st = _FakeElasticState()
    apply_resize(st, 4, 4)                # same size: reset only
    assert st.calls == [("on_reset",)]
    st = _FakeElasticState()
    apply_resize(st, None, 4)             # first rendezvous
    assert st.calls == [("on_reset",)]


def test_apply_resize_falls_back_to_plain_sync():
    st = _FakeElasticState(fail=True)
    apply_resize(st, 4, 2)                # must not raise
    assert st.calls == [("resize", 4, 2), ("on_reset",)]
    st = _SyncOnlyState()
    before = _ranks_lost()
    apply_resize(st, 4, 2)
    assert st.calls == [("on_reset",)]
    assert _ranks_lost() - before == 2


def test_training_loop_uses_extracted_apply_resize():
    # The elastic training loop's reset block is exactly the extracted
    # hook -- the serving control plane and the training loop share one
    # resize sequence (covered behaviorally by tests/test_elastic.py).
    assert "apply_resize" in _run_loop._elastic_loop.__code__.co_names


# ---------------------------------------------------------------------------
# Straggler eviction hook: sustained streak, latch, evict-forgets
# ---------------------------------------------------------------------------


def _obs(rank, step, wall):
    return {"rank": rank, "step": step, "t0_us": 0.0, "wall_s": wall,
            "spans": {}, "legs": {}}


def test_eviction_hook_fires_once_for_sustained_straggler():
    mon = StragglerMonitor(world=3, stall_check_time=0)
    fired = []
    mon.add_eviction_hook(0.1, lambda r, l: fired.append((r, l)))
    for rnd in range(4):
        for r in range(3):
            mon.observe(_obs(r, rnd, 0.5 if r == 2 else 0.01))
    assert len(fired) == 1                # latched after the first fire
    rank, lateness = fired[0]
    assert rank == 2 and lateness >= 0.1
    mon.evict(2)
    rep = mon.report()
    assert 2 not in rep["per_rank_wall_s"]
    assert rep["straggler_rank"] != 2


def test_eviction_streak_resets_when_lateness_recovers():
    # High alpha so one fast report pulls the EWMA back under the
    # threshold: a recovered rank must restart the sustained streak.
    mon = StragglerMonitor(world=3, alpha=0.9, stall_check_time=0)
    fired = []
    mon.add_eviction_hook(0.1, lambda r, l: fired.append(r))
    mon.observe(_obs(0, 0, 0.2))
    mon.observe(_obs(1, 0, 0.01))
    mon.observe(_obs(2, 0, 0.01))         # streak 2 for rank 0
    mon.observe(_obs(0, 1, 0.01))         # recovers: lateness < 0.1
    mon.observe(_obs(1, 1, 0.01))
    mon.observe(_obs(2, 1, 0.01))
    assert fired == []                    # never 3 consecutive
    mon.observe(_obs(0, 2, 0.2))          # slow again: streak restarts
    mon.observe(_obs(1, 2, 0.01))
    assert fired == []                    # streak 2 < world
    mon.observe(_obs(2, 2, 0.01))
    assert fired == [0]


# ---------------------------------------------------------------------------
# Scheduler drain lifecycle: draining label, suspend frees pages exactly
# ---------------------------------------------------------------------------


def test_scheduler_drain_suspend_restore_cycle():
    ccfg = CacheConfig(num_layers=1, num_kv_heads=2, head_dim=4, slots=2,
                       page_size=4, max_len=16)
    cache = PagedKVCache(ccfg)
    sched = ContinuousBatchScheduler(2, cache)
    for i in range(2):
        sched.submit(_req(i, plen=6))
    for slot, req in sched.admit(0.0):
        cache.reserve(slot, req.prompt_len + 1)
    assert cache.allocated_pages == 4     # 2 slots x 2 pages
    sched.pause_admission()
    sched.submit(_req(9))
    assert sched.admit(0.1) == []         # admission gate closed
    for slot in list(sched.active):
        assert sched.mark_draining(slot).state == "draining"
    assert sched.draining_slots == [0, 1]
    assert sched._m_slot_states.labels(state="draining").value == 2
    suspended = [sched.suspend(slot) for slot in sorted(sched.active)]
    assert [r.state for r in suspended] == ["suspended", "suspended"]
    assert all(r.slot == -1 for r in suspended)
    # Suspension released every page: the sweep recovers nothing.
    assert cache.allocated_pages == 0
    assert cache.release_all() == 0
    slot = sched.restore(suspended[0])
    assert suspended[0].state == "decode" and suspended[0].slot == slot
    sched.resume_admission()
    assert [r.rid for _, r in sched.admit(0.2)] == [9]


# ---------------------------------------------------------------------------
# Drain paths on the real engine
# ---------------------------------------------------------------------------


class ScriptedPolicy:
    """Deterministic decision source: ``script`` maps decide-call index
    to a Decision; everything else holds."""

    def __init__(self, script):
        self.script = dict(script)
        self.calls = 0
        self.applied = []

    def decide(self, sample):
        d = self.script.pop(self.calls, None)
        self.calls += 1
        return d if d is not None else Decision("hold", "scripted")

    def mark_applied(self, decision, now_s):
        self.applied.append(decision.action)


_ENGINE_KW = dict(slots=2, page_size=8, max_len=64)


def _mesh2():
    from jax.sharding import Mesh
    devs = jax.devices()[:2]
    return Mesh(np.asarray(devs, dtype=object).reshape(2), ("tp",))


@pytest.fixture(scope="module")
def baseline_tokens(base_params):
    """Undisturbed tp=2 serve of the reference request."""
    _, params = base_params
    eng = ServingEngine(CFG, params, mesh=_mesh2(), **_ENGINE_KW)
    req = _req(0, plen=8, out=12)
    eng.serve([req])
    return list(req.tokens)


def test_drain_completion_path_bitwise(base_params, baseline_tokens):
    _, params = base_params
    # Shrink scripted mid-decode, but the drain budget is large enough
    # for the request to finish on the mesh it started on: tokens must
    # be bitwise identical to the undisturbed run.
    plane = ServingControlPlane(
        CFG, params, devices=jax.devices()[:2], initial_tp=2,
        policy=ScriptedPolicy({2: Decision("shrink", "scripted",
                                           target_size=1)}),
        policy_config=PolicyConfig(interval_s=0.0, drain_steps=64),
        **_ENGINE_KW)
    req = _req(0, plen=8, out=12)
    rep = plane.serve([req])
    assert list(req.tokens) == baseline_tokens
    assert rep.drained_completed == 1 and rep.drained_reprefilled == 0
    assert rep.drain_leaked_pages == 0 and rep.lost_requests == 0
    assert rep.mesh_size_final == 1 and rep.resizes == 1
    assert plane.engine.cache.allocated_pages == 0


def test_drain_reprefill_path_across_shrink(base_params, baseline_tokens):
    _, params = base_params
    # Zero drain budget: the mid-decode request is suspended and
    # re-prefilled on the tp=1 mesh.  The prefix emitted before the
    # shrink is bitwise identical; the continuation after re-prefill is
    # within decode-step sampling tolerance (greedy over logits that
    # agree to ~1e-4 across mesh sizes), and the request still runs to
    # its full token budget with every page accounted for.
    plane = ServingControlPlane(
        CFG, params, devices=jax.devices()[:2], initial_tp=2,
        policy=ScriptedPolicy({2: Decision("shrink", "scripted",
                                           target_size=1)}),
        policy_config=PolicyConfig(interval_s=0.0, drain_steps=0),
        **_ENGINE_KW)
    req = _req(0, plen=8, out=12)
    rep = plane.serve([req])
    assert rep.drained_reprefilled == 1 and rep.drained_completed == 0
    assert rep.drain_leaked_pages == 0 and rep.lost_requests == 0
    assert rep.mesh_size_final == 1
    # Decide-call 2 fires after the 2nd decode step: prefill token +
    # 3 decode tokens are already out and must match the baseline.
    assert list(req.tokens[:4]) == baseline_tokens[:4]
    assert len(req.tokens) == 12          # ran to completion post-resize
    assert plane.engine.cache.allocated_pages == 0


def test_drain_reprefill_same_mesh_is_bitwise(base_params, baseline_tokens):
    _, params = base_params
    # Same-size scripted transition (a spare swap with no spare: the
    # surviving ranks ARE the old ranks).  Re-prefill back onto an
    # identical mesh must reproduce the undisturbed tokens bitwise --
    # the resume state (prompt + emitted tokens) carries everything.
    plane = ServingControlPlane(
        CFG, params, devices=jax.devices()[:2], initial_tp=2,
        policy=ScriptedPolicy({2: Decision("shrink", "scripted-swap",
                                           target_size=2)}),
        policy_config=PolicyConfig(interval_s=0.0, drain_steps=0),
        **_ENGINE_KW)
    req = _req(0, plen=8, out=12)
    rep = plane.serve([req])
    assert rep.drained_reprefilled == 1
    assert rep.drain_leaked_pages == 0 and rep.lost_requests == 0
    assert rep.mesh_size_final == 2 and rep.resizes == 1
    assert list(req.tokens) == baseline_tokens


# ---------------------------------------------------------------------------
# The closed loop: kill@ + slow@ chaos drill
# ---------------------------------------------------------------------------


def test_closed_loop_chaos_drill(base_params):
    _, params = base_params
    spans.recorder().reset()
    plane = ServingControlPlane(
        CFG, params, devices=jax.devices()[:4], initial_tp=4,
        policy_config=PolicyConfig(
            interval_s=0.01, ttft_slo_s=10.0, queue_high=1000,
            occupancy_low=-1.0, hysteresis=2, cooldown_s=0.1,
            evict_lateness_s=0.05, drain_steps=4, max_tp=4),
        chaos_spec="kill@step=6,rank=3;slow@step=12,rank=1,secs=0.3",
        slots=4, page_size=8, max_len=64)
    reqs = [_req(i, plen=4, out=16) for i in range(12)]
    rep = plane.serve(reqs)

    # Nothing lost, nothing leaked: every admitted request completed
    # across two disruptive transitions.
    assert rep.lost_requests == 0
    assert rep.serving.completed == 12
    assert rep.drain_leaked_pages == 0
    assert plane.engine.cache.allocated_pages == 0

    # kill@rank=3 forced a mandatory shrink off the dead device...
    assert rep.dead_ranks == [3]
    assert any(d["action"] == "shrink" and d["reason"] == "rank-dead"
               for d in rep.decisions)
    assert 3 not in plane.mesh_ranks
    # ...and slow@rank=1 was evicted by the lateness EWMA closed loop.
    assert rep.evicted_ranks == [1]
    assert any(d["action"] == "evict" and d["evict_rank"] == 1
               for d in rep.decisions)
    assert 1 not in plane.mesh_ranks
    assert rep.resizes >= 2 and rep.mesh_size_final == 2
    assert rep.drained_completed + rep.drained_reprefilled >= 1

    # Every decision is visible to the observability plane: metric
    # families and span-recorder ctl legs.
    text = render_prometheus()
    for fam in ("horovod_ctl_decisions_total",
                "horovod_ctl_resizes_total",
                "horovod_ctl_evictions_total",
                "horovod_ctl_drained_requests_total",
                "horovod_ctl_mesh_size",
                "horovod_ctl_healthy_ranks"):
        assert fam in text, fam
    legs = set()
    for acc in spans.recorder()._acc.values():
        legs.update(acc["legs"])
    assert "ctl/fault/kill" in legs and "ctl/fault/slow" in legs
    assert "ctl/shrink/rank-dead" in legs
    assert any(l.startswith("ctl/evict/straggler-lateness") for l in legs)

    counts = rep.decision_counts
    assert counts.get("shrink", 0) >= 1 and counts.get("evict", 0) >= 1


# ---------------------------------------------------------------------------
# Post-shrink trace audit
# ---------------------------------------------------------------------------


def test_post_shrink_audit_matches_on_resized_mesh(hvd):
    reports = audit_standard_configs(("serving_decode_resized",))
    rep = reports["serving_decode_resized"]
    assert rep.ok(), rep.render()
    s = rep.summary
    # One activation psum per row-parallel closure: attn_wo + mlp_down
    # per layer, all matched against the plan on the resized mesh.
    assert s["matched_ops"] == s["expected_ops"] == 2 * CFG.num_layers
    assert s["unaccounted_ops"] == 0 and s["missing_ops"] == 0
    assert any("resized decode mesh" in n for n in rep.expected.notes)
