"""Torch-shim tests (reference ``test/parallel/test_torch.py`` model).

Single-process mode: every device is a rank and eager inputs are
replicated, so Average == identity and Sum == value * size; optimizer
behavior must match plain torch exactly.  Multi-process behavior is
covered by the launcher integration test running pytorch_mnist.py.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import horovod_tpu.torch as thvd


@pytest.fixture()
def hvd_t(hvd):
    # Core initialized by the `hvd` fixture; the torch shim shares it.
    yield thvd


def test_identity_and_size(hvd_t, n_devices):
    assert hvd_t.is_initialized()
    assert hvd_t.size() == n_devices
    assert hvd_t.tpu_built() and not hvd_t.nccl_built()


@pytest.mark.parametrize("dtype", [torch.float32, torch.float16,
                                   torch.int32, torch.int64])
def test_allreduce_dtypes(hvd_t, n_devices, dtype):
    t = torch.arange(6).reshape(2, 3).to(dtype)
    out = hvd_t.allreduce(t, op=thvd.Sum)
    assert out.dtype == dtype
    np.testing.assert_allclose(out.to(torch.float32).numpy(),
                               t.to(torch.float32).numpy() * n_devices)


def test_allreduce_average_is_identity_single_proc(hvd_t):
    t = torch.randn(4, 4)
    out = hvd_t.allreduce(t)  # Average over identical replicas
    np.testing.assert_allclose(out.numpy(), t.numpy(), rtol=1e-6)


def test_allreduce_inplace(hvd_t, n_devices):
    t = torch.ones(3)
    ret = hvd_t.allreduce_(t, op=thvd.Sum)
    assert ret is t
    np.testing.assert_allclose(t.numpy(), n_devices)


def test_async_handle_roundtrip(hvd_t, n_devices):
    t = torch.full((5,), 2.0)
    h = hvd_t.allreduce_async_(t, op=thvd.Sum)
    out = hvd_t.synchronize(h)
    assert out is t
    np.testing.assert_allclose(t.numpy(), 2.0 * n_devices)


def test_broadcast_and_allgather(hvd_t, n_devices):
    t = torch.randn(2, 2)
    out = hvd_t.broadcast(t, root_rank=0)
    np.testing.assert_allclose(out.numpy(), t.numpy(), rtol=1e-6)
    g = hvd_t.allgather(torch.ones(2, 3))
    assert g.shape == (2 * n_devices, 3)


def test_alltoall_splits(hvd_t, n_devices):
    """Reference parity: alltoall(tensor, splits) does an uneven exchange
    and returns (received, received_splits).  Single-process mode: every
    rank replicates the same (tensor, splits), so rank 0 receives its
    block 0 from each of the n identical senders."""
    n = n_devices
    sp = torch.tensor([(i % 3) + 1 for i in range(n)], dtype=torch.int64)
    tot = int(sp.sum())
    t = torch.arange(tot * 2, dtype=torch.float32).reshape(tot, 2)
    out, rsp = hvd_t.alltoall(t, splits=sp)
    assert isinstance(out, torch.Tensor) and out.dtype == t.dtype
    block0 = t.numpy()[: int(sp[0])]
    np.testing.assert_allclose(out.numpy(), np.tile(block0, (n, 1)))
    np.testing.assert_array_equal(rsp.numpy(), np.full(n, int(sp[0])))


def test_alltoall_even_returns_bare_tensor(hvd_t, n_devices):
    n = n_devices
    t = torch.arange(n * 2, dtype=torch.float32)
    out = hvd_t.alltoall(t)
    assert isinstance(out, torch.Tensor)  # no splits -> no tuple
    # Replicated senders: receiver 0 gets its chunk 0 from all n senders.
    np.testing.assert_allclose(out.numpy(), np.tile(t.numpy()[:2], n))


def test_grouped_allgather_reducescatter(hvd_t, n_devices):
    n = n_devices
    ts = [torch.randn(3, 2), torch.randn(5)]
    outs = hvd_t.grouped_allgather(ts)
    for t, o in zip(ts, outs):
        # replicated single-process input: concat of n identical copies
        np.testing.assert_allclose(o.numpy(),
                                   np.concatenate([t.numpy()] * n), rtol=1e-6)
    rs_in = [torch.randn(n * 2, 3), torch.randn(n)]
    outs = hvd_t.grouped_reducescatter(rs_in, op=thvd.Sum)
    for t, o in zip(rs_in, outs):
        expect = t.numpy()[: t.shape[0] // n] * n  # rank-0 shard of sum
        np.testing.assert_allclose(o.numpy(), expect, rtol=1e-5)


def test_more_async_variants(hvd_t, n_devices):
    n = n_devices
    t = torch.randn(3, 2)
    h = hvd_t.allgather_async(t)
    g = hvd_t.synchronize(h)
    np.testing.assert_allclose(g.numpy(),
                               np.concatenate([t.numpy()] * n), rtol=1e-6)
    h = hvd_t.broadcast_async(t, root_rank=0)
    np.testing.assert_allclose(hvd_t.synchronize(h).numpy(), t.numpy(),
                               rtol=1e-6)
    u = torch.randn(3, 2)
    h = hvd_t.broadcast_async_(u, root_rank=0)
    assert hvd_t.synchronize(h) is u
    rs_in = torch.randn(n * 2, 3)
    h = hvd_t.reducescatter_async(rs_in, op=thvd.Sum)
    np.testing.assert_allclose(hvd_t.synchronize(h).numpy(),
                               rs_in.numpy()[:2] * n, rtol=1e-5)
    a2a_in = torch.arange(n * 2, dtype=torch.float32)
    h = hvd_t.alltoall_async(a2a_in)
    np.testing.assert_allclose(hvd_t.synchronize(h).numpy(),
                               np.tile(a2a_in.numpy()[:2], n), rtol=1e-6)
    sp = torch.tensor([1] * n)
    h = hvd_t.alltoall_async(torch.randn(n, 2), splits=sp)
    out, rsp = hvd_t.synchronize(h)
    assert out.shape == (n, 2) and tuple(rsp.shape) == (n,)


def test_grouped_allreduce(hvd_t, n_devices):
    ts = [torch.ones(3), torch.full((2, 2), 2.0)]
    outs = hvd_t.grouped_allreduce(ts, op=thvd.Sum)
    np.testing.assert_allclose(outs[0].numpy(), n_devices)
    np.testing.assert_allclose(outs[1].numpy(), 2.0 * n_devices)


def test_gradient_predivide_factor(hvd_t, n_devices):
    """Reference semantics: grads scale by 1/factor before the sum and
    factor/size after; the result equals a plain Average (modulo
    rounding), and factor=1 stays the Average path."""
    torch.manual_seed(3)
    model = torch.nn.Linear(4, 2)
    ref = torch.nn.Linear(4, 2)
    ref.load_state_dict(model.state_dict())
    x = torch.randn(6, 4)

    opt = hvd_t.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        gradient_predivide_factor=2.0)
    opt_ref = hvd_t.DistributedOptimizer(
        torch.optim.SGD(ref.parameters(), lr=0.1),
        named_parameters=ref.named_parameters())
    for o, m in ((opt, model), (opt_ref, ref)):
        o.zero_grad()
        m(x).pow(2).mean().backward()
        o.step()
    for a, b in zip(model.parameters(), ref.parameters()):
        np.testing.assert_allclose(a.detach().numpy(), b.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError, match="requires op=Average"):
        hvd_t.DistributedOptimizer(
            torch.optim.SGD(torch.nn.Linear(2, 2).parameters(), lr=0.1),
            op=thvd.Sum, gradient_predivide_factor=2.0)


def test_optimizer_matches_plain_sgd(hvd_t):
    torch.manual_seed(0)
    m = torch.nn.Linear(8, 4)
    ref = torch.nn.Linear(8, 4)
    ref.load_state_dict(m.state_dict())
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(m.parameters(), lr=0.1, momentum=0.9),
        named_parameters=m.named_parameters())
    ropt = torch.optim.SGD(ref.parameters(), lr=0.1, momentum=0.9)
    x, y = torch.randn(16, 8), torch.randint(0, 4, (16,))
    for _ in range(5):
        opt.zero_grad()
        F.cross_entropy(m(x), y).backward()
        opt.step()
        ropt.zero_grad()
        F.cross_entropy(ref(x), y).backward()
        ropt.step()
    np.testing.assert_allclose(m.weight.detach().numpy(),
                               ref.weight.detach().numpy(), atol=1e-6)


def test_optimizer_backward_passes_per_step(hvd_t):
    torch.manual_seed(0)
    m = torch.nn.Linear(4, 2)
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(m.parameters(), lr=0.1),
        named_parameters=m.named_parameters(),
        backward_passes_per_step=2)
    x, y = torch.randn(8, 4), torch.randint(0, 2, (8,))
    opt.zero_grad()
    F.cross_entropy(m(x), y).backward()   # pass 1: local only
    assert not opt._pending
    F.cross_entropy(m(x), y).backward()   # pass 2: triggers allreduce
    assert opt._pending
    opt.step()
    assert not opt._pending


def test_zero_grad_with_pending_raises(hvd_t):
    m = torch.nn.Linear(4, 2)
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(m.parameters(), lr=0.1),
        named_parameters=m.named_parameters())
    F.cross_entropy(m(torch.randn(4, 4)), torch.randint(0, 2, (4,))).backward()
    with pytest.raises(AssertionError, match="pending"):
        opt.zero_grad()
    opt.synchronize()
    opt.zero_grad()


def test_synchronize_drains_all_handles_on_error(hvd_t):
    """Round-6 fix: a failing handle must not abort the drain -- later
    params' handles would stay pending forever (their flush already
    consumed them) and every subsequent step() would KeyError over the
    real failure.  synchronize() drains everything and re-raises the
    first error once the table is empty."""
    m = torch.nn.Linear(4, 2)
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(m.parameters(), lr=0.1),
        named_parameters=m.named_parameters())
    F.cross_entropy(m(torch.randn(4, 4)), torch.randint(0, 2, (4,))).backward()
    assert opt._pending
    # Corrupt the FIRST pending entry with a handle that raises; the
    # healthy handles behind it must still be drained.
    params = list(opt._pending)
    opt._pending[params[0]] = ("eager", 10**9)   # unknown handle: KeyError
    with pytest.raises(KeyError):
        opt.synchronize()
    assert not opt._pending                      # fully drained
    opt.zero_grad()                              # no "pending" assertion


def test_broadcast_parameters_state_dict(hvd_t):
    m = torch.nn.Linear(3, 3)
    before = {k: v.clone() for k, v in m.state_dict().items()}
    hvd_t.broadcast_parameters(m.state_dict(), root_rank=0)
    for k, v in m.state_dict().items():
        np.testing.assert_allclose(v.numpy(), before[k].numpy(), rtol=1e-6)


def test_broadcast_optimizer_state(hvd_t):
    m = torch.nn.Linear(3, 3)
    opt = torch.optim.SGD(m.parameters(), lr=0.5, momentum=0.9)
    F.mse_loss(m(torch.randn(2, 3)), torch.randn(2, 3)).backward()
    opt.step()
    hvd_t.broadcast_optimizer_state(opt, root_rank=0)
    assert opt.param_groups[0]["lr"] == 0.5


def test_compression_namespace(hvd_t):
    t = torch.randn(16)
    out = hvd_t.allreduce(t, compression=thvd.Compression.fp16)
    np.testing.assert_allclose(out.numpy(), t.numpy(), rtol=1e-2, atol=1e-2)


def test_native_cycle_batching_fuses_grads(hvd_t):
    """The native scheduler groups a backward's grads into one fused
    dispatch (RunLoopOnce parity), and training still converges."""
    from horovod_tpu import _core
    from horovod_tpu.torch_api import batching
    if not _core.available():
        pytest.skip(f"native core unavailable: {_core.unavailable_reason()}")

    model = torch.nn.Sequential(torch.nn.Linear(8, 16), torch.nn.Tanh(),
                                torch.nn.Linear(16, 2))
    opt = hvd_t.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.2),
        named_parameters=model.named_parameters())

    calls = []
    orig = batching.GradBatcher._on_batch

    def spy(self, payloads):
        calls.append(len(payloads))
        return orig(self, payloads)

    batching.GradBatcher._on_batch = spy
    try:
        x = torch.randn(32, 8)
        y = torch.randint(0, 2, (32,))
        losses = []
        for _ in range(10):
            opt.zero_grad()
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            losses.append(float(loss))
    finally:
        batching.GradBatcher._on_batch = orig

    assert batching._batcher is not None, "native batcher did not engage"
    assert sum(calls) == 4 * 10  # every grad went through the scheduler
    # Fusion actually happened: fewer dispatches than tensors.
    assert len(calls) < sum(calls)
    assert losses[-1] < losses[0]


def test_torch_state_commit_restore_sync(hvd):
    ht = thvd
    model = torch.nn.Linear(4, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    state = ht.elastic.TorchState(model=model, optimizer=opt, batch=3)
    w0 = model.weight.detach().clone()
    # Mutate everything, then roll back.
    with torch.no_grad():
        model.weight.add_(1.0)
    state.batch = 99
    state.restore()
    assert torch.allclose(model.weight, w0)
    assert state.batch == 3
    # Train a step so optimizer state exists, commit, perturb, restore.
    loss = model(torch.ones(2, 4)).sum()
    loss.backward()
    opt.step()
    state.batch = 4
    state.commit()
    w1 = model.weight.detach().clone()
    with torch.no_grad():
        model.weight.mul_(0.0)
    state.restore()
    assert torch.allclose(model.weight, w1)
    # sync() broadcasts rank 0's copy (single-process: a no-op round trip)
    state.sync()
    assert torch.allclose(model.weight, w1)
    assert state.batch == 4


def test_torch_state_elastic_run_decorator(hvd):
    ht = thvd
    model = torch.nn.Linear(2, 1)
    state = ht.elastic.TorchState(model=model, batch=0)

    @ht.elastic.run
    def train(st):
        while st.batch < 3:
            st.batch += 1
            st.commit()
        return st.batch

    assert train(state) == 3


def test_torch_state_sync_bf16_model(hvd):
    ht = thvd
    model = torch.nn.Linear(3, 2).to(torch.bfloat16)
    state = ht.elastic.TorchState(model=model, batch=0)
    w = model.weight.detach().clone()
    state.sync()  # must not crash on the bf16 -> numpy wire conversion
    assert model.weight.dtype == torch.bfloat16
    assert torch.allclose(model.weight.float(), w.float())


def test_torch_allgather_equal_dims_still_works(hvd):
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = thvd.allgather(t, name="tag")
    n = thvd.size()
    assert out.shape == (2 * n, 3)
    np.testing.assert_allclose(out[:2].numpy(), t.numpy())


def test_grouped_allreduce_async_roundtrip(hvd_t):
    ts = [torch.ones(3) * (i + 1) for i in range(3)]
    h = hvd_t.grouped_allreduce_async(ts, op=hvd_t.Sum,
                                          name="gaa")
    outs = hvd_t.synchronize(h)
    n = hvd_t.size()
    for i, o in enumerate(outs):
        np.testing.assert_allclose(o.numpy(), np.full(3, (i + 1) * n))
    # In-place variant writes back into the inputs.
    ts2 = [torch.ones(2) * 3.0, torch.ones(2) * 5.0]
    h2 = hvd_t.grouped_allreduce_async_(ts2, name="gaa_")
    hvd_t.synchronize(h2)  # Average over identical rows == identity
    np.testing.assert_allclose(ts2[0].numpy(), [3.0, 3.0])
    np.testing.assert_allclose(ts2[1].numpy(), [5.0, 5.0])


def test_sparse_grad_requires_flag(hvd_t):
    # After zero_grad(set_to_none=True) -- the torch default -- a sparse
    # backward materializes a sparse .grad; without sparse_as_dense the
    # hook must refuse loudly (reference semantics), not mis-reduce.
    emb = torch.nn.Embedding(8, 4, sparse=True)
    opt = hvd_t.DistributedOptimizer(
        torch.optim.SGD(emb.parameters(), lr=0.1),
        named_parameters=emb.named_parameters())
    opt.zero_grad()
    with pytest.raises(ValueError, match="sparse_as_dense"):
        emb(torch.tensor([1, 2])).sum().backward()


def test_sparse_as_dense_trains(hvd_t):
    emb = torch.nn.Embedding(8, 4, sparse=True)
    opt = hvd_t.DistributedOptimizer(
        torch.optim.SGD(emb.parameters(), lr=0.5),
        named_parameters=emb.named_parameters(), sparse_as_dense=True)
    before = emb.weight.detach().clone()
    opt.zero_grad()
    emb(torch.tensor([1, 2])).sum().backward()
    opt.step()
    after = emb.weight.detach()
    assert not torch.allclose(before[1], after[1])
    assert torch.allclose(before[0], after[0])  # untouched row


def test_sparse_allreduce_async(hvd_t):
    # Single-process replicated semantics: every rank holds the same
    # sparse tensor, so Average == the original and Sum == value * size.
    dense = torch.zeros(6, 3)
    dense[1] = 2.0
    dense[4] = -1.0
    sp = dense.to_sparse_coo()
    h = hvd_t.sparse_allreduce_async(sp, name="spar")
    out = hvd_t.synchronize(h)
    assert out.is_sparse
    np.testing.assert_allclose(out.to_dense().numpy(), dense.numpy(),
                               rtol=1e-6)
    h2 = hvd_t.sparse_allreduce_async(sp, name="spar_sum", op=hvd_t.Sum)
    out2 = hvd_t.synchronize(h2)
    np.testing.assert_allclose(out2.to_dense().numpy(),
                               dense.numpy() * hvd_t.size(), rtol=1e-6)
    with pytest.raises(ValueError, match="sparse tensor"):
        hvd_t.sparse_allreduce_async(dense)
