"""Transformer model family: Llama decoder, BERT encoder, LoRA."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.models import (
    BERT_TINY, Bert, LLAMA_TINY, LlamaConfig, LlamaLM, lora_mask, merge_lora,
)


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


def test_llama_forward_shapes(rng):
    cfg = LLAMA_TINY
    model = LlamaLM(cfg, dtype=jnp.float32)
    tokens = jax.random.randint(rng, (2, 16), 0, cfg.vocab_size)
    params = model.init(rng, tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_remat_matches_and_cuts_residuals(rng):
    """remat=True must be numerically identical fwd AND bwd, while the
    autodiff residuals saved across the fwd->bwd boundary shrink (the
    jax.checkpoint memory trade)."""
    cfg = LLAMA_TINY
    tokens = jax.random.randint(rng, (2, 32), 0, cfg.vocab_size)

    def grads(remat):
        model = LlamaLM(cfg, dtype=jnp.float32, remat=remat)
        params = model.init(jax.random.PRNGKey(1), tokens)

        def loss(p):
            lo = model.apply(p, tokens)
            return jnp.mean(lo ** 2)
        return params, jax.grad(loss)(params), loss

    p0, g0, loss0 = grads(False)
    p1, g1, loss1 = grads(True)
    assert (jax.tree.structure(g0) == jax.tree.structure(g1))
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1),
                    strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    def residual_bytes(loss, params):
        # Size of the values saved between forward and backward.
        _, vjp = jax.vjp(loss, params)
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree.leaves(vjp))

    assert residual_bytes(loss1, p1) < residual_bytes(loss0, p0)


def test_llama_causality(rng):
    """Changing a future token must not change past logits."""
    cfg = LLAMA_TINY
    model = LlamaLM(cfg, dtype=jnp.float32)
    tokens = jax.random.randint(rng, (1, 16), 0, cfg.vocab_size)
    params = model.init(rng, tokens)
    base = model.apply(params, tokens)
    perturbed = tokens.at[0, 10].set((tokens[0, 10] + 1) % cfg.vocab_size)
    out = model.apply(params, perturbed)
    np.testing.assert_allclose(np.asarray(base[0, :10]),
                               np.asarray(out[0, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(base[0, 10:]), np.asarray(out[0, 10:]))


def test_llama_trains(rng):
    cfg = LLAMA_TINY
    model = LlamaLM(cfg, dtype=jnp.float32)
    tokens = jax.random.randint(rng, (4, 32), 0, cfg.vocab_size)
    params = model.init(rng, tokens)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, tokens):
        def loss_fn(p):
            logits = model.apply(p, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tokens[:, 1:]).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5, losses


def test_bert_forward_and_train(rng):
    cfg = BERT_TINY
    model = Bert(cfg, dtype=jnp.float32)
    tokens = jax.random.randint(rng, (2, 24), 0, cfg.vocab_size)
    params = model.init(rng, tokens)
    mlm, nsp = model.apply(params, tokens)
    assert mlm.shape == (2, 24, cfg.vocab_size)
    assert nsp.shape == (2, 2)

    labels = tokens
    nsp_labels = jnp.array([0, 1])
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            mlm, nsp = model.apply(p, tokens)
            l1 = optax.softmax_cross_entropy_with_integer_labels(
                mlm, labels).mean()
            l2 = optax.softmax_cross_entropy_with_integer_labels(
                nsp, nsp_labels).mean()
            return l1 + l2
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(15):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5, losses


def test_lora_init_is_identity(rng):
    """lora_b zero-init: adapter output starts exactly at base output."""
    cfg = LLAMA_TINY
    tokens = jax.random.randint(rng, (1, 8), 0, cfg.vocab_size)
    base = LlamaLM(cfg, dtype=jnp.float32)
    lora = LlamaLM(cfg, dtype=jnp.float32, lora_rank=4)
    base_params = base.init(rng, tokens)
    lora_params = lora.init(rng, tokens)

    # Graft base weights into the lora tree so non-adapter params agree.
    def graft(lp, bp):
        if isinstance(lp, dict):
            return {k: (graft(lp[k], bp[k]) if k in bp else lp[k])
                    for k in lp}
        return bp
    grafted = graft(jax.device_get(lora_params), jax.device_get(base_params))
    out_base = base.apply(base_params, tokens)
    out_lora = lora.apply(grafted, tokens)
    np.testing.assert_allclose(np.asarray(out_base), np.asarray(out_lora),
                               atol=1e-6)


def test_lora_mask_and_training(rng):
    cfg = LLAMA_TINY
    model = LlamaLM(cfg, dtype=jnp.float32, lora_rank=4)
    tokens = jax.random.randint(rng, (2, 16), 0, cfg.vocab_size)
    params = model.init(rng, tokens)
    mask = lora_mask(params)
    leaves_mask, _ = jax.tree_util.tree_flatten(mask)
    assert any(leaves_mask) and not all(leaves_mask)

    opt = optax.multi_transform(
        {"lora": optax.adam(1e-2), "frozen": optax.set_to_zero()},
        jax.tree.map(lambda m: "lora" if m else "frozen", mask))
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = model.apply(p, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tokens[:, 1:]).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    before = jax.device_get(params)
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state)
    after = jax.device_get(params)

    flat_b = jax.tree_util.tree_flatten_with_path(before)[0]
    flat_a = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_flatten_with_path(after)[0]}
    flat_m = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_flatten_with_path(mask)[0]}
    changed_lora = changed_base = 0
    for path, v in flat_b:
        key = jax.tree_util.keystr(path)
        same = np.allclose(np.asarray(v), np.asarray(flat_a[key]))
        if flat_m[key]:
            # lora_b starts at zero and only moves if its grad is nonzero;
            # lora_a must move once lora_b has.
            changed_lora += 0 if same else 1
        else:
            changed_base += 0 if same else 1
    assert changed_base == 0
    assert changed_lora > 0


def test_merge_lora_matches_adapter_output(rng):
    cfg = LlamaConfig(vocab_size=64, num_layers=1, num_heads=2,
                      num_kv_heads=1, head_dim=8, d_model=16, ffn_hidden=32,
                      max_seq_len=32)
    model = LlamaLM(cfg, dtype=jnp.float32, lora_rank=2)
    tokens = jax.random.randint(rng, (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(7), tokens)
    # Give the adapters nonzero weights so the merge is meaningful.
    params = jax.tree_util.tree_map_with_path(
        lambda p, x: (x + 0.01 if any(getattr(k, "key", None) == "lora_b"
                                      for k in p) else x), params)
    out_adapter = model.apply(params, tokens)
    merged = merge_lora(jax.device_get(params))
    base_model = LlamaLM(cfg, dtype=jnp.float32, lora_rank=0)
    out_merged = base_model.apply(merged, tokens)
    np.testing.assert_allclose(np.asarray(out_adapter),
                               np.asarray(out_merged), atol=1e-4)


def test_llama_distributed_train_step(rng):
    """Full framework path: grads allreduced over the mesh via hvd."""
    cfg = LLAMA_TINY
    devs = jax.devices()
    hvd.shutdown()
    hvd.init()
    try:
        model = LlamaLM(cfg, dtype=jnp.float32)
        tokens = jax.random.randint(rng, (2 * len(devs), 16), 0,
                                    cfg.vocab_size)
        params = model.init(rng, tokens[:1])
        opt = hvd.DistributedOptimizer(optax.sgd(0.05))
        params = hvd.replicate(params, hvd.mesh())
        opt_state = opt.init(params)

        from horovod_tpu.training import make_train_step

        def loss_fn(p, batch):
            logits = model.apply(p, batch)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], batch[:, 1:]).mean()

        step = make_train_step(loss_fn, opt)
        l0 = None
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, tokens)
            l0 = float(loss) if l0 is None else l0
        assert float(loss) < l0
    finally:
        hvd.shutdown()


def test_llama_packed_sequences_match_separate():
    """Packing [A|B] with segment_ids + restarting RoPE positions must
    reproduce running A and B separately (the packed-training contract:
    docs/api.md flash-attention segment masking)."""
    import horovod_tpu.models as zoo
    m = zoo.LlamaLM(zoo.LLAMA_TINY, dtype=jnp.float32)
    key = jax.random.PRNGKey(0)
    ta = jax.random.randint(key, (1, 16), 0, 256)
    tb = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 256)
    packed = jnp.concatenate([ta, tb], axis=1)
    seg = jnp.concatenate([jnp.zeros((1, 16), jnp.int32),
                           jnp.ones((1, 16), jnp.int32)], axis=1)
    params = m.init(key, packed)
    out_packed = m.apply(params, packed, segment_ids=seg)
    out_a = m.apply(params, ta)
    out_b = m.apply(params, tb)
    np.testing.assert_allclose(np.asarray(out_packed[:, :16]),
                               np.asarray(out_a), atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(out_packed[:, 16:]),
                               np.asarray(out_b), atol=2e-4, rtol=2e-4)


def test_bert_segment_ids_isolate_padding():
    """Pad tokens with their own segment id must not perturb live-token
    encodings (padding isolation without an attention-mask tensor)."""
    import horovod_tpu.models as zoo
    m = zoo.Bert(zoo.BERT_TINY, dtype=jnp.float32)
    key = jax.random.PRNGKey(2)
    toks = jax.random.randint(key, (1, 24), 0, 256)
    params = m.init(key, toks)
    # Same 24 live tokens, plus 8 pad tokens in a foreign segment.
    padded = jnp.concatenate(
        [toks, jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0, 256)],
        axis=1)
    seg = jnp.concatenate([jnp.zeros((1, 24), jnp.int32),
                           jnp.ones((1, 8), jnp.int32)], axis=1)
    mlm_pad, _ = m.apply(params, padded, pack_segment_ids=seg)
    mlm_ref, _ = m.apply(params, toks)
    np.testing.assert_allclose(np.asarray(mlm_pad[:, :24]),
                               np.asarray(mlm_ref), atol=2e-4, rtol=2e-4)


# ---------------------------------------------------------------------------
# int8 frozen base (the Llama-3 8B single-chip LoRA layout)
# ---------------------------------------------------------------------------


def test_quantize_frozen_base_converts_and_loads(rng):
    """quantize_frozen_base maps an f32-base LoRA tree onto the
    base_dtype="int8" layout, and the int8 model's forward matches the
    f32 model within per-channel quantization error."""
    from horovod_tpu.models import quantize_frozen_base

    cfg = LLAMA_TINY
    tokens = jax.random.randint(rng, (2, 16), 0, cfg.vocab_size)
    f32 = LlamaLM(cfg, dtype=jnp.float32, lora_rank=4)
    q8 = LlamaLM(cfg, dtype=jnp.float32, lora_rank=4, base_dtype="int8")
    p32 = f32.init(rng, tokens)
    pq8_expected = q8.init(rng, tokens)
    pq8 = quantize_frozen_base(p32)
    # Same tree structure as a natively-initialized int8 model.
    assert (jax.tree_util.tree_structure(pq8)
            == jax.tree_util.tree_structure(pq8_expected))
    out32 = np.asarray(f32.apply(p32, tokens))
    outq8 = np.asarray(q8.apply(pq8, tokens))
    # Per-channel symmetric int8: ~0.4% relative error per matmul; the
    # tiny model chains 2 layers, so allow a few percent of the logit
    # scale.
    denom = np.abs(out32).max()
    assert np.abs(out32 - outq8).max() / denom < 0.05


def test_int8_base_lora_grads_match_f32_base(rng):
    """BASELINE config 4 enabler: LoRA adapter gradients computed against
    the int8-quantized frozen base match the f32-base gradients within
    quantization tolerance -- training the adapters on the quantized base
    optimizes the same objective to first order."""
    from horovod_tpu.models import (merge_frozen, quantize_frozen_base,
                                    split_frozen)

    cfg = LLAMA_TINY
    tokens = jax.random.randint(rng, (4, 16), 0, cfg.vocab_size)
    f32 = LlamaLM(cfg, dtype=jnp.float32, lora_rank=4)
    q8 = LlamaLM(cfg, dtype=jnp.float32, lora_rank=4, base_dtype="int8")
    p32 = f32.init(rng, tokens)
    # Perturb lora_b away from zero so lora_a grads are nonzero too.
    p32 = jax.tree_util.tree_map_with_path(
        lambda path, x: x + 0.01 if any(
            getattr(k, "key", None) == "lora_b" for k in path) else x, p32)
    pq8 = quantize_frozen_base(p32)

    def xent(model, params):
        logits = model.apply(params, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], tokens[:, 1:]).mean()

    t32, fz32 = split_frozen(p32)
    tq8, fzq8 = split_frozen(pq8)
    g32 = jax.grad(lambda t: xent(f32, merge_frozen(t, fz32)))(t32)
    gq8 = jax.grad(lambda t: xent(q8, merge_frozen(t, fzq8)))(tq8)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g32)[0],
            jax.tree_util.tree_flatten_with_path(gq8)[0]):
        a, b = np.asarray(a), np.asarray(b)
        scale = max(np.abs(a).max(), 1e-8)
        assert np.abs(a - b).max() / scale < 0.1, (
            jax.tree_util.keystr(path), np.abs(a - b).max() / scale)


def test_int8_base_trains_with_frozen_step(rng, hvd):
    """End-to-end: split_frozen + make_train_step(with_frozen=True) on the
    8-device mesh -- adapter-only grads, falling loss."""
    from horovod_tpu.models import merge_frozen, split_frozen

    cfg = LLAMA_TINY
    model = LlamaLM(cfg, dtype=jnp.float32, lora_rank=4, base_dtype="int8")
    n = hvd.size()
    tokens = jax.random.randint(rng, (2 * n, 16), 0, cfg.vocab_size)
    params = model.init(rng, tokens[:1])
    trainable, frozen = split_frozen(params)
    assert all("lora" in jax.tree_util.keystr(p)
               for p, _ in jax.tree_util.tree_flatten_with_path(trainable)[0])
    opt = hvd.DistributedOptimizer(optax.adamw(5e-3))
    trainable = hvd.replicate(trainable)
    frozen = hvd.replicate(frozen)
    opt_state = opt.init(trainable)

    def loss_fn(tp, fz, t):
        logits = model.apply(merge_frozen(tp, fz), t)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], t[:, 1:]).mean()

    step = hvd.make_train_step(loss_fn, opt, with_frozen=True)
    data = hvd.shard_batch(tokens)
    losses = []
    for _ in range(10):
        trainable, opt_state, loss = step(trainable, opt_state, data, frozen)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
