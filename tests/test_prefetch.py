"""Double-buffered device prefetcher tests (:mod:`horovod_tpu.data`).

The producer thread stages host batches onto the mesh ``depth`` ahead of
the consumer; with ``stack_steps=k`` it groups k batches into the
``make_train_loop`` stacked layout and drops a trailing partial group.
"""

import threading
import time

import numpy as np
import pytest

import horovod_tpu as hv


def _host_batches(n, shape=(16, 3)):
    return [{"x": np.full(shape, i, np.float32),
             "y": np.full((shape[0],), i, np.int32)} for i in range(n)]


def test_prefetcher_yields_all_batches_on_device(hvd):
    batches = _host_batches(5)
    with hv.DevicePrefetcher(batches, depth=2) as pf:
        out = list(pf)
    assert len(out) == 5
    bat = hv.batch_sharding()
    for i, b in enumerate(out):
        np.testing.assert_array_equal(np.asarray(b["x"]), batches[i]["x"])
        assert b["x"].sharding.is_equivalent_to(bat, b["x"].ndim)
    assert pf.dropped_remainder == 0


def test_prefetcher_stacks_steps_and_drops_remainder(hvd):
    batches = _host_batches(5)
    with hv.DevicePrefetcher(batches, stack_steps=2) as pf:
        out = list(pf)
    # 5 host batches / 2 per group -> 2 full groups, 1 dropped.
    assert len(out) == 2
    assert pf.dropped_remainder == 1
    sb = hv.stacked_batch_sharding()
    for g, b in enumerate(out):
        assert b["x"].shape == (2, 16, 3)
        assert b["x"].sharding.is_equivalent_to(sb, b["x"].ndim)
        np.testing.assert_array_equal(np.asarray(b["x"][1]),
                                      batches[2 * g + 1]["x"])


def test_prefetcher_feeds_train_loop(hvd):
    """End-to-end: prefetched stacked windows drive make_train_loop."""
    import jax
    import jax.numpy as jnp
    import optax

    k = 2
    opt = hv.DistributedOptimizer(optax.sgd(0.1))
    params = hv.replicate({"w": jnp.zeros((3, 2), jnp.float32)})
    opt_state = hv.replicate(opt.init(params))
    loop = hv.make_train_loop(
        lambda p, b: jnp.mean((b["x"] @ p["w"]) ** 2) +
        0.0 * jnp.sum(b["y"]), opt, steps_per_execution=k)
    with hv.prefetch_to_device(_host_batches(4), stack_steps=k) as pf:
        seen = 0
        for window in pf:
            params, opt_state, losses = loop(params, opt_state, window)
            assert losses.shape == (k,)
            seen += 1
    assert seen == 2
    assert all(np.isfinite(x).all() for x in jax.tree.leaves(
        jax.tree.map(np.asarray, params)))


def test_prefetcher_propagates_producer_errors(hvd):
    def gen():
        yield {"x": np.zeros((16, 3), np.float32)}
        raise RuntimeError("input pipeline boom")

    pf = hv.DevicePrefetcher(gen(), depth=2)
    next(pf)  # the good batch
    with pytest.raises(RuntimeError, match="input pipeline boom"):
        next(pf)
    pf.close()


def test_prefetcher_close_stops_producer_promptly(hvd):
    produced = [0]

    def endless():
        while True:
            produced[0] += 1
            yield {"x": np.zeros((16, 3), np.float32)}

    pf = hv.DevicePrefetcher(endless(), depth=2)
    next(pf)
    pf.close()
    assert not pf._thread.is_alive()
    # Bounded queue: the producer never ran far ahead of depth.
    assert produced[0] <= 2 + 2 + 1


def test_prefetcher_rejects_bad_args(hvd):
    with pytest.raises(ValueError):
        hv.DevicePrefetcher([], depth=0)
    with pytest.raises(ValueError):
        hv.DevicePrefetcher([], stack_steps=0)


def test_prefetcher_empty_iterator(hvd):
    with hv.DevicePrefetcher([], depth=2) as pf:
        assert list(pf) == []


def test_prefetcher_surfaces_error_even_when_sentinel_is_lost(
        hvd, monkeypatch):
    """A poisoned iterator must raise on the consumer's next __next__
    even if the producer's error sentinel never lands in the queue
    (regression: the consumer used to block forever on a starved queue)."""
    from horovod_tpu.data.prefetch import _Stop

    orig_put = hv.DevicePrefetcher._put

    def lossy_put(self, item):
        if isinstance(item, _Stop) and item.error is not None:
            return False  # drop the error sentinel on the floor
        return orig_put(self, item)

    monkeypatch.setattr(hv.DevicePrefetcher, "_put", lossy_put)

    def gen():
        yield {"x": np.zeros((16, 3), np.float32)}
        raise RuntimeError("poisoned iterator")

    pf = hv.DevicePrefetcher(gen(), depth=2)
    next(pf)  # the good batch still arrives first (FIFO preserved)
    with pytest.raises(RuntimeError, match="poisoned iterator"):
        next(pf)
    # The producer thread must have exited cleanly, not be stuck.
    pf._thread.join(timeout=5.0)
    assert not pf._thread.is_alive()
    # Subsequent iteration stays terminated.
    with pytest.raises(StopIteration):
        next(pf)
