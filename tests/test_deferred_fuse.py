"""Fused deferred-async flush + backend-scoped fencing (round 6).

The tentpole behavior under test: ``flush_deferred`` groups compatible
pending ``*_async`` ops through the fusion planner and dispatches ONE
collective per bucket, scattering results back per handle; the
multi-process eager fence is scoped to the CPU/Gloo transport.  The
multi-process end-to-end path (fused flush while a rank is drained) runs
in ``test_run.py``/``examples/join_check.py``; these cover the planner,
the scatter, the error protocol, the fence gating, and the published
fused metadata on the virtual single-process mesh by forcing the
deferred path through ``eager._defer_applies``.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

from horovod_tpu.collectives import eager, joinop
from horovod_tpu.collectives.compression import Compression
from horovod_tpu.core.state import global_state


def _force_defer(monkeypatch):
    """Route *_async enqueues through the deferred queue on the
    single-process test mesh (where the presence protocol -- the normal
    trigger -- does not apply)."""
    monkeypatch.setattr(eager, "_defer_applies", lambda ps: True)


def test_mixed_dtype_and_codec_pending_set_splits_into_buckets(
        hvd, monkeypatch):
    """4x f32 + 2x f64 (same op) fuse into one bucket each; an Average op
    and an fp16-codec op are incompatible with both and stay per-op."""
    _force_defer(monkeypatch)
    n = hvd.size()
    hs = [hvd.allreduce_async(
        hvd.replicated_stack(np.full((3,), i + 1.0, np.float32)),
        hvd.Sum, name=f"f32_{i}") for i in range(4)]
    hs += [hvd.allreduce_async(
        hvd.replicated_stack(np.full((2, 2), 10.0 * (i + 1), np.float64)),
        hvd.Sum, name=f"f64_{i}") for i in range(2)]
    h_avg = hvd.allreduce_async(
        hvd.replicated_stack(np.full((5,), 6.0, np.float32)))
    h_fp16 = hvd.allreduce_async(
        hvd.replicated_stack(np.full((4,), 2.0, np.float32)), hvd.Sum,
        compression=Compression.fp16)
    assert eager.deferred_count() == 8

    vals = [hvd.synchronize(h) for h in hs]
    for i in range(4):
        assert vals[i].shape == (n, 3)
        np.testing.assert_allclose(np.asarray(vals[i]), n * (i + 1.0))
    for i in range(2):
        assert vals[4 + i].shape == (n, 2, 2)
        np.testing.assert_allclose(np.asarray(vals[4 + i]),
                                   n * 10.0 * (i + 1))
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h_avg)), 6.0)
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h_fp16)), n * 2.0)

    st = eager.deferred_fuse_stats()
    assert st == {"flushes": 1, "fused_buckets": 2, "fused_ops": 6,
                  "singleton_ops": 2}


def test_mixed_scale_factors_do_not_fuse(hvd, monkeypatch):
    """prescale/postscale are program parameters: ops differing in them
    must not share a bucket (the fused collective has ONE scale pair)."""
    _force_defer(monkeypatch)
    n = hvd.size()
    h1 = hvd.allreduce_async(
        hvd.replicated_stack(np.full((2,), 1.0, np.float32)), hvd.Sum)
    h2 = hvd.allreduce_async(
        hvd.replicated_stack(np.full((2,), 1.0, np.float32)), hvd.Sum,
        prescale_factor=0.5)
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h1)), n * 1.0)
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h2)), n * 0.5)
    st = eager.deferred_fuse_stats()
    assert st["fused_buckets"] == 0 and st["singleton_ops"] == 2


def test_threshold_splits_same_key_ops_into_multiple_buckets(
        hvd, monkeypatch):
    """Per-rank row bytes cap the bucket: 3x 16-byte rows under a 32-byte
    threshold pack as [2-op bucket, 1-op singleton]."""
    _force_defer(monkeypatch)
    st = global_state()
    st.config = dataclasses.replace(st.config, deferred_fuse_threshold=32)
    n = hvd.size()
    hs = [hvd.allreduce_async(
        hvd.replicated_stack(np.full((4,), i + 1.0, np.float32)),
        hvd.Sum, name=f"t{i}") for i in range(3)]
    for i, h in enumerate(hs):
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   n * (i + 1.0))
    stats = eager.deferred_fuse_stats()
    assert stats["fused_buckets"] == 1
    assert stats["fused_ops"] == 2
    assert stats["singleton_ops"] == 1


def test_single_pending_op_has_no_fusion_overhead(hvd, monkeypatch):
    """One pending op dispatches on the plain per-op path: no concat, no
    unfuse program, no fused bucket accounted."""
    _force_defer(monkeypatch)
    n = hvd.size()
    h = hvd.allreduce_async(
        hvd.replicated_stack(np.full((3,), 5.0, np.float32)), hvd.Sum)
    assert eager.deferred_count() == 1
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)), n * 5.0)
    st = eager.deferred_fuse_stats()
    assert st == {"flushes": 1, "fused_buckets": 0, "fused_ops": 0,
                  "singleton_ops": 1}


def test_deferred_fuse_disabled_keeps_per_op_dispatch(hvd, monkeypatch):
    """HOROVOD_DEFERRED_FUSE=0 (config off): the round-5 behavior -- every
    pending op its own collective, results unchanged."""
    _force_defer(monkeypatch)
    st = global_state()
    st.config = dataclasses.replace(st.config, deferred_fuse=False)
    n = hvd.size()
    hs = [hvd.allreduce_async(
        hvd.replicated_stack(np.full((3,), i + 1.0, np.float32)),
        hvd.Sum) for i in range(4)]
    for i, h in enumerate(hs):
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   n * (i + 1.0))
    stats = eager.deferred_fuse_stats()
    assert stats["fused_buckets"] == 0 and stats["singleton_ops"] == 4


def test_double_synchronize_after_fused_flush_raises_keyerror(
        hvd, monkeypatch):
    """The round-5 handle contract survives fusion: a fused handle is
    consumed by its first synchronize; retrying is a KeyError."""
    _force_defer(monkeypatch)
    h1 = hvd.allreduce_async(
        hvd.replicated_stack(np.ones((2,), np.float32)), hvd.Sum)
    h2 = hvd.allreduce_async(
        hvd.replicated_stack(np.ones((2,), np.float32)), hvd.Sum)
    hvd.synchronize(h1)
    hvd.synchronize(h2)
    assert eager.deferred_fuse_stats()["fused_buckets"] == 1
    with pytest.raises(KeyError):
        hvd.synchronize(h1)
    with pytest.raises(KeyError):
        hvd.synchronize(h2)


def test_fused_dispatch_failure_stamps_every_member_handle(
        hvd, monkeypatch):
    """A failed fused bucket writes a FRESH error (chained to the shared
    cause) into every member handle; ops in later units abort."""
    _force_defer(monkeypatch)
    boom = RuntimeError("transport down")

    def raising_allreduce(*a, **k):
        raise boom
    h1 = hvd.allreduce_async(
        hvd.replicated_stack(np.ones((2,), np.float32)), hvd.Sum)
    h2 = hvd.allreduce_async(
        hvd.replicated_stack(np.ones((2,), np.float32)), hvd.Sum)
    h3 = hvd.allreduce_async(
        hvd.replicated_stack(np.ones((2,), np.float64)), hvd.Average)
    monkeypatch.setattr(eager, "allreduce", raising_allreduce)
    errs = []
    for h in (h1, h2, h3):
        with pytest.raises(RuntimeError) as ei:
            hvd.synchronize(h)
        errs.append(ei.value)
    assert errs[0] is not errs[1]
    assert errs[0].__cause__ is boom and errs[1].__cause__ is boom
    assert "failed during flush" in str(errs[0])
    assert "aborted" in str(errs[2])


def test_malformed_input_falls_back_to_per_op_error(hvd, monkeypatch):
    """An input that is not a rank stack cannot fuse; its per-op dispatch
    raises the SAME ValueError immediate dispatch would have, and a
    well-formed op sharing the flush still has its error stamped per the
    batch-abort protocol."""
    _force_defer(monkeypatch)
    h_bad = hvd.allreduce_async(np.float32(3.0), hvd.Sum)  # scalar
    h_ok = hvd.allreduce_async(
        hvd.replicated_stack(np.ones((2,), np.float32)), hvd.Sum)
    with pytest.raises(RuntimeError, match="failed during flush") as ei:
        hvd.synchronize(h_bad)
    assert isinstance(ei.value.__cause__, ValueError)
    assert "rank-stacked" in str(ei.value.__cause__)
    with pytest.raises(RuntimeError, match="aborted"):
        hvd.synchronize(h_ok)


def test_fused_metadata_published_with_layout(hvd, monkeypatch):
    """When a rank is drained (mocked mask), the fused bucket publishes
    kind + fused shape + op count + per-rank widths -- everything a
    drained rank needs to replay the bucket collective bitwise."""
    _force_defer(monkeypatch)
    n = hvd.size()

    class _KV:
        def __init__(self):
            self.store = {}

        def key_value_set(self, k, v, allow_overwrite=False):
            self.store[k] = v
    kv = _KV()
    mask = np.ones((n,), np.int32)
    mask[-1] = 0
    monkeypatch.setattr(joinop, "client", lambda: kv)
    monkeypatch.setattr(joinop, "sync", lambda ps: mask.copy())
    h1 = hvd.allreduce_async(
        hvd.replicated_stack(np.full((3,), 1.0, np.float32)), hvd.Sum)
    h2 = hvd.allreduce_async(
        hvd.replicated_stack(np.full((2, 2), 2.0, np.float32)), hvd.Sum)
    hvd.synchronize(h1)
    hvd.synchronize(h2)
    ops = {k: json.loads(v) for k, v in kv.store.items()
           if "/op/" in k}
    assert len(ops) == 1, kv.store
    meta = next(iter(ops.values()))
    assert meta["kind"] == "allreduce"
    assert tuple(meta["shape"]) == (n, 7)
    assert meta["fused_ops"] == 2
    assert meta["fused_widths"] == [3, 4]
    # The thread-local must not leak past the dispatch.
    assert getattr(eager._fused_meta_tls, "extra", None) is None


def test_replay_validates_fused_widths(hvd):
    """joinop._replay derives the fused layout from the metadata and
    rejects a record whose widths disagree with the bucket shape."""
    n = hvd.size()
    good = {"kind": "allreduce", "name": "b", "shape": (n, 5),
            "dtype": "float32", "op": "sum", "pre": 1.0, "post": 1.0,
            "compression": "NoneCompressor",
            "fused_ops": 2, "fused_widths": [2, 3]}
    joinop._replay(good)  # single-process: dispatches a real allreduce
    bad = dict(good, fused_widths=[2, 2])
    with pytest.raises(RuntimeError, match="fused replay metadata"):
        joinop._replay(bad)


def test_fused_nondefault_codec_publishes_and_replays_bitwise(
        hvd, monkeypatch):
    """PR-5 satellite: a deferred-fused bucket carrying a non-default
    codec (PowerSGD) publishes codec name + factor widths with the fused
    layout, and a drained rank -- whose process never ran the codec
    factory -- resolves the codec from the name alone and replays the
    bucket collective bitwise (same shape, same codec program)."""
    import horovod_tpu.collectives.compression as comp_mod
    from horovod_tpu.collectives.compression import (
        Compression, powersgd_compressor, powersgd_factor_widths)
    _force_defer(monkeypatch)
    n = hvd.size()
    codec = powersgd_compressor(2)

    class _KV:
        def __init__(self):
            self.store = {}

        def key_value_set(self, k, v, allow_overwrite=False):
            self.store[k] = v
    kv = _KV()
    mask = np.ones((n,), np.int32)
    mask[-1] = 0
    monkeypatch.setattr(joinop, "client", lambda: kv)
    monkeypatch.setattr(joinop, "sync", lambda ps: mask.copy())
    h1 = hvd.allreduce_async(
        hvd.replicated_stack(np.full((3,), 1.0, np.float32)), hvd.Sum,
        compression=codec)
    h2 = hvd.allreduce_async(
        hvd.replicated_stack(np.full((4,), 2.0, np.float32)), hvd.Sum,
        compression=codec)
    out1 = hvd.synchronize(h1)
    out2 = hvd.synchronize(h2)
    assert eager.deferred_fuse_stats()["fused_buckets"] == 1
    ops = {k: json.loads(v) for k, v in kv.store.items() if "/op/" in k}
    assert len(ops) == 1, kv.store
    meta = next(iter(ops.values()))
    assert meta["compression"] == "PowerSGD2Compressor"
    assert meta["fused_widths"] == [3, 4]
    assert meta["factor_widths"] == \
        list(powersgd_factor_widths(7, 2))

    # Drained-rank side: wipe the parameterized-codec registry so the
    # replay must re-derive the class from the published name, then
    # replay the record -- the dispatched program is keyed on the same
    # (shape, codec) signature the active ranks compiled, so a cache hit
    # here IS the bitwise-identity evidence.
    for attr in list(vars(Compression)):
        if attr.startswith(("PowerSGD", "TopK")):
            delattr(Compression, attr)
    monkeypatch.setattr(joinop, "_replaying", False)
    st = global_state()
    hits_before = st.cache.hits
    joinop._replay(meta)
    assert st.cache.hits == hits_before + 1
    assert hasattr(Compression, "PowerSGD2Compressor")
    # Active-side outputs themselves are replica-consistent and the
    # low-rank program preserved the unfused slicing.
    assert np.asarray(out1).shape == (n, 3)
    assert np.asarray(out2).shape == (n, 4)

    # A corrupt record (factor widths disagreeing with shape + rank)
    # must be rejected, not silently replayed against a diverging
    # program.
    bad = dict(meta, factor_widths=[5, 5])
    with pytest.raises(RuntimeError, match="low-rank replay metadata"):
        joinop._replay(bad)


def test_flush_plan_reuses_shared_plan_cache(hvd, monkeypatch):
    """Identical async batches hit the memoized eager-flush plan (the
    shared controller.fusion ExecutableCache), not a fresh plan."""
    from horovod_tpu.controller import fusion
    _force_defer(monkeypatch)

    def batch():
        hs = [hvd.allreduce_async(
            hvd.replicated_stack(np.full((3,), 1.0, np.float32)),
            hvd.Sum) for _ in range(3)]
        for h in hs:
            hvd.synchronize(h)
    batch()
    before = fusion.plan_cache_stats()
    batch()
    after = fusion.plan_cache_stats()
    # The repeat flush is planning-free: no new cache misses, and both
    # the flush-unit plan and the per-bucket exchange-plan IR rows
    # resolve as hits against the first batch's entries.
    assert after["hits"] > before["hits"]
    assert after["misses"] == before["misses"]
    assert eager.deferred_fuse_stats()["fused_buckets"] == 2


def test_flush_emits_timeline_counters(hvd, monkeypatch):
    """The flush plan surfaces as ONE counters snapshot:
    deferred_fused_buckets + fused-vs-singleton op counts."""
    _force_defer(monkeypatch)
    recorded = []

    class _TL:
        def counters(self, values, track="counters"):
            recorded.append(dict(values))

        def counter(self, name, value, track="counters"):
            recorded.append({name: value})

        def range(self, tensor, phase, args=None):
            import contextlib
            return contextlib.nullcontext()
    monkeypatch.setattr(global_state(), "timeline", _TL())
    hs = [hvd.allreduce_async(
        hvd.replicated_stack(np.ones((2,), np.float32)), hvd.Sum)
        for _ in range(3)]
    h_single = hvd.allreduce_async(
        hvd.replicated_stack(np.ones((2,), np.float64)), hvd.Sum)
    for h in hs + [h_single]:
        hvd.synchronize(h)
    snaps = [r for r in recorded if "deferred_fused_buckets" in r]
    assert snaps == [{"deferred_fused_buckets": 1, "deferred_fused_ops": 3,
                      "deferred_singleton_ops": 1}]


def test_timeline_counters_event_shape(tmp_path):
    """Timeline.counters writes one 'C' event carrying every value."""
    from horovod_tpu.timeline import Timeline
    path = str(tmp_path / "tl.json")
    tl = Timeline(path)
    tl.counters({"a": 1, "b": 2.5})
    tl.close()
    events = json.loads(open(path).read())
    cs = [e for e in events if e.get("ph") == "C"]
    assert len(cs) == 1
    assert cs[0]["args"] == {"a": 1.0, "b": 2.5}


# ---------------------------------------------------------------------------
# Backend-scoped fencing.
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _FakeDevice:
    process_index: int
    platform: str


class _FakeMesh:
    """Duck-typed mesh: just enough surface for the fence helpers."""

    def __init__(self, platforms_by_proc):
        self.devices = np.array(
            [_FakeDevice(p, plat) for p, plat in platforms_by_proc],
            dtype=object)


class _BarrierSpy:
    def __init__(self):
        self.calls = []

    def wait_at_barrier(self, name, timeout_ms, process_ids=None):
        self.calls.append((name, tuple(process_ids)))


def _spy_block(monkeypatch):
    calls = []
    real = jax.block_until_ready
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda x: calls.append(1) or real(x))
    return calls


def test_eager_fence_skipped_on_tpu_like_backend(hvd, monkeypatch):
    """A multi-process mesh on a TPU-like backend must NOT pay the
    block_until_ready + named barrier (the two hazards it closes are
    Gloo-transport properties) -- but the fence SEQUENCE still advances,
    because join replay keys op metadata on it."""
    mesh = _FakeMesh([(0, "tpu"), (1, "tpu")])
    spy = _BarrierSpy()
    monkeypatch.setattr(jax._src.distributed.global_state, "client", spy,
                        raising=False)
    blocks = _spy_block(monkeypatch)
    seq_before = eager._peek_next_seq((0, 1))
    eager._eager_fence(mesh, np.zeros((2,)))
    assert blocks == []
    assert spy.calls == []
    assert eager._peek_next_seq((0, 1)) == seq_before + 1


def test_eager_fence_cpu_transport_unchanged(hvd, monkeypatch):
    """The CPU/Gloo multi-process path keeps both halves of the fence:
    local completion + the sequence-named coordination barrier."""
    mesh = _FakeMesh([(0, "cpu"), (1, "cpu")])
    spy = _BarrierSpy()
    monkeypatch.setattr(jax._src.distributed.global_state, "client", spy,
                        raising=False)
    blocks = _spy_block(monkeypatch)
    seq = eager._peek_next_seq((0, 1))
    eager._eager_fence(mesh, np.zeros((2,)))
    assert blocks == [1]
    assert spy.calls == [(f"hvd_eager_fence_0_1_{seq}", (0, 1))]


def test_eager_fence_noop_single_process(hvd, monkeypatch):
    """Single-process meshes skip the fence entirely on every backend --
    including the sequence bump (there is nobody to coordinate with)."""
    mesh = _FakeMesh([(0, "cpu"), (0, "cpu")])
    spy = _BarrierSpy()
    monkeypatch.setattr(jax._src.distributed.global_state, "client", spy,
                        raising=False)
    blocks = _spy_block(monkeypatch)
    seq_before = eager._peek_next_seq((0,))
    eager._eager_fence(mesh, np.zeros((2,)))
    assert blocks == [] and spy.calls == []
    assert eager._peek_next_seq((0,)) == seq_before


def test_transport_predicate_reads_mesh_platform(hvd):
    assert eager._transport_needs_fence(_FakeMesh([(0, "cpu"), (1, "cpu")]))
    assert not eager._transport_needs_fence(
        _FakeMesh([(0, "tpu"), (1, "tpu")]))
    assert not eager._transport_needs_fence(
        _FakeMesh([(0, "gpu"), (1, "gpu")]))


def test_real_eager_dispatch_on_mocked_tpu_mesh_skips_fence(
        hvd, monkeypatch):
    """End-to-end through _run: with the mesh reported multi-process and
    TPU-backed, an eager allreduce must issue no barrier wait.  (The
    compute itself still runs on the virtual CPU devices; only the
    platform probe is mocked.)"""
    spy = _BarrierSpy()
    monkeypatch.setattr(jax._src.distributed.global_state, "client", spy,
                        raising=False)
    monkeypatch.setattr(eager, "_mesh_platform", lambda mesh: "tpu")
    monkeypatch.setenv("HOROVOD_JOIN_DISABLE", "1")
    n = hvd.size()
    out = hvd.allreduce(
        hvd.replicated_stack(np.ones((3,), np.float32)), hvd.Sum)
    np.testing.assert_allclose(eager.one_row(out), n * 1.0)
    assert spy.calls == []
