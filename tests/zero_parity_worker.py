"""Multi-process zero1-vs-replicated parity worker.

Launched by ``python -m horovod_tpu.run -np {2,4} --cpu`` from
``tests/test_zero.py``: every process drives the same 5 steps through the
replicated DistributedOptimizer step and the zero_stage=1 step (uneven,
padded leaf sizes + a bf16 leaf + the LoRA ``with_frozen`` layout) and
rank 0 prints ``ZERO PARITY OK`` when the parameters agree.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd

_BASE = {
    "w": np.random.RandomState(0).randn(4, 5).astype(np.float32),
    "b": np.random.RandomState(1).randn(7).astype(np.float32),
    "half": np.random.RandomState(2).randn(13).astype(np.float32),
}


def fresh():
    return {"w": jnp.asarray(_BASE["w"]), "b": jnp.asarray(_BASE["b"]),
            "half": jnp.asarray(_BASE["half"], jnp.bfloat16)}


def host(x):
    """Replicated global array -> this process's local copy."""
    return np.asarray(jax.device_get(x.addressable_data(0)), np.float32)


def loss_fn(p, batch):
    x, y = batch
    pred = ((x @ p["w"]).sum(-1) + p["b"].sum()
            + p["half"].astype(jnp.float32).sum())
    return jnp.mean((pred - y) ** 2)


def frozen_loss_fn(p, fz, batch):
    x, y = batch
    return loss_fn(p, batch) + jnp.mean((x @ fz["base"]) * 0.1)


def local_batch(step, world, rank, rows_per=4):
    """Deterministic global batch; each process contributes its rows."""
    rng = np.random.RandomState(1000 + step)
    x = rng.randn(world * rows_per, 4).astype(np.float32)
    y = rng.randn(world * rows_per).astype(np.float32)
    sl = slice(rank * rows_per, (rank + 1) * rows_per)
    return hvd.shard_batch_from_local((x[sl], y[sl]))


def check_close(tag, a_tree, b_tree):
    for k in a_tree:
        a, b = host(a_tree[k]), host(b_tree[k])
        atol = 5e-2 if a_tree[k].dtype == jnp.bfloat16 else 5e-5
        np.testing.assert_allclose(a, b, atol=atol,
                                   err_msg=f"{tag}:{k}")


def main():
    hvd.init()
    world, rank = hvd.size(), hvd.rank()
    opt = optax.adam(1e-2)

    # --- plain layout ---
    rep_step = hvd.make_train_step(loss_fn, hvd.DistributedOptimizer(opt))
    rep_params, rep_state = fresh(), opt.init(fresh())
    z_step = hvd.make_train_step(loss_fn, opt, zero_stage=1)
    z_params = fresh()
    z_state = hvd.zero_init(opt, z_params)
    for i in range(5):
        batch = local_batch(i, world, rank)
        rep_params, rep_state, rl = rep_step(rep_params, rep_state, batch)
        batch = local_batch(i, world, rank)
        z_params, z_state, zl = z_step(z_params, z_state, batch)
        np.testing.assert_allclose(float(rl), float(zl), rtol=1e-5)
    check_close("plain", rep_params, z_params)

    # --- LoRA with_frozen layout ---
    frozen = {"base": jnp.asarray(
        np.random.RandomState(7).randn(4).astype(np.float32))}
    rep_step = hvd.make_train_step(frozen_loss_fn,
                                   hvd.DistributedOptimizer(opt),
                                   with_frozen=True)
    rep_params, rep_state = fresh(), opt.init(fresh())
    z_step = hvd.make_train_step(frozen_loss_fn, opt, with_frozen=True,
                                 zero_stage=1)
    z_params = fresh()
    z_state = hvd.zero_init(opt, z_params)
    for i in range(5):
        batch = local_batch(100 + i, world, rank)
        rep_params, rep_state, _ = rep_step(rep_params, rep_state, batch,
                                            frozen)
        batch = local_batch(100 + i, world, rank)
        z_params, z_state, _ = z_step(z_params, z_state, batch, frozen)
    check_close("frozen", rep_params, z_params)

    if rank == 0:
        print(f"ZERO PARITY OK (world={world})", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
