"""Model zoo + flax train-step tests (BN stat sync, hierarchical mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hv
from horovod_tpu.models import LeNet, ResNet
from horovod_tpu.models.resnet import BasicBlock, BottleneckBlock
from horovod_tpu.training import make_flax_train_step


def test_lenet_forward(hvd):
    model = LeNet()
    x = jnp.ones((2, 28, 28, 1))
    v = model.init(jax.random.PRNGKey(0), x)
    assert model.apply(v, x).shape == (2, 10)


def test_tiny_resnet_trains_and_syncs_bn(hvd, n_devices):
    model = ResNet(stage_sizes=[1, 1], block_cls=BasicBlock, num_classes=4,
                   num_filters=8, dtype=jnp.float32)
    n = n_devices
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2 * n, 16, 16, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, 2 * n), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    params, stats = variables["params"], variables["batch_stats"]
    opt = hv.DistributedOptimizer(optax.sgd(0.05))
    params = hv.replicate(params)
    stats = hv.replicate(stats)
    opt_state = hv.replicate(opt.init(params))
    step = make_flax_train_step(model.apply, opt)
    batch = hv.shard_batch((x, y))
    losses = []
    for _ in range(8):
        params, stats, opt_state, loss = step(params, stats, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # BN stats must be replicated (identical across devices) after sync.
    mean_leaf = jax.tree.leaves(stats)[0]
    assert np.isfinite(np.asarray(mean_leaf)).all()


def test_flax_step_on_hierarchical_mesh(n_devices):
    hv.shutdown()
    from horovod_tpu.parallel.mesh import build_mesh
    mesh = build_mesh(jax.devices()[:n_devices], hierarchical=True,
                      dcn_size=2)
    hv.init(mesh=mesh)
    assert hv.reduce_axes() == ("dcn", "ici")
    model = ResNet(stage_sizes=[1], block_cls=BottleneckBlock, num_classes=4,
                   num_filters=8, dtype=jnp.float32)
    x = jnp.ones((2 * n_devices, 16, 16, 3))
    y = jnp.zeros((2 * n_devices,), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    opt = hv.DistributedOptimizer(optax.sgd(0.1),
                                  compression=hv.Compression.bf16)
    params = hv.replicate(v["params"])
    stats = hv.replicate(v["batch_stats"])
    opt_state = hv.replicate(opt.init(params))
    step = make_flax_train_step(model.apply, opt)
    p2, s2, o2, loss = step(params, stats, opt_state, hv.shard_batch((x, y)))
    assert np.isfinite(float(loss))
    hv.shutdown()


def test_space_to_depth_stem_parity(hvd):
    """The s2d stem is EXACTLY the standard 7x7/2 stem: transform a
    standard conv_init kernel with s2d_conv_init_kernel and the two models
    must agree to float tolerance on random input."""
    from horovod_tpu.models.resnet import s2d_conv_init_kernel

    kw = dict(stage_sizes=[1, 1], block_cls=BottleneckBlock, num_classes=5,
              num_filters=8, dtype=jnp.float32)
    std = ResNet(**kw)
    s2d = ResNet(space_to_depth=True, **kw)
    rng = np.random.RandomState(0)
    # 32x32 input: any even spatial size works.
    x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    v_std = std.init(jax.random.PRNGKey(1), x, train=False)
    params = jax.tree.map(lambda a: a, v_std["params"])
    params["conv_init"] = {
        "kernel": s2d_conv_init_kernel(v_std["params"]["conv_init"]["kernel"])}
    out_std = std.apply(v_std, x, train=False)
    out_s2d = s2d.apply({"params": params,
                         "batch_stats": v_std["batch_stats"]}, x,
                        train=False)
    np.testing.assert_allclose(np.asarray(out_s2d), np.asarray(out_std),
                               rtol=1e-5, atol=1e-5)


def test_inception_v3_forward(hvd):
    from horovod_tpu.models import InceptionV3
    model = InceptionV3(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 75, 75, 3))
    v = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(v, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32
    # 2048-channel final feature map is the V3 signature.
    assert v["params"]["Dense_0"]["kernel"].shape[0] == 2048


def test_inception_v3_aux_head_trains(hvd):
    from horovod_tpu.models import InceptionV3
    model = InceptionV3(num_classes=5, aux_logits=True, dtype=jnp.float32)
    x = jnp.ones((1, 139, 139, 3))
    v = model.init({"params": jax.random.PRNGKey(0),
                    "dropout": jax.random.PRNGKey(1)}, x, train=True)
    logits, aux = model.apply(v, x, train=True,
                              rngs={"dropout": jax.random.PRNGKey(2)},
                              mutable=["batch_stats"])[0]
    assert logits.shape == (1, 5) and aux.shape == (1, 5)


def test_vgg16_forward_and_param_shape(hvd):
    from horovod_tpu.models import VGG16
    model = VGG16(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    v = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out = model.apply(v, x, train=False)
    assert out.shape == (2, 10)
    # 13 convs + 3 dense = VGG-16's 16 weight layers.
    convs = [k for k in v["params"] if k.startswith("Conv")]
    denses = [k for k in v["params"] if k.startswith("Dense")]
    assert len(convs) == 13 and len(denses) == 3


def test_vgg_bn_variant_trains(hvd, n_devices):
    from horovod_tpu.models import VGG
    model = VGG(depth=16, num_classes=4, batch_norm=True, dropout_rate=0.0,
                dtype=jnp.float32)
    n = n_devices
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, 32, 32, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, n), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    params, stats = v["params"], v["batch_stats"]
    opt = hv.DistributedOptimizer(optax.sgd(0.01))
    params, stats = hv.replicate(params), hv.replicate(stats)
    opt_state = hv.replicate(opt.init(params))
    step = make_flax_train_step(model.apply, opt)
    batch = hv.shard_batch((x, y))
    losses = []
    for _ in range(4):
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
