"""Gaussian-process Bayesian optimization for the autotuner.

Reference: ``horovod/common/optim/gaussian_process.cc`` (RBF-kernel GP
regression) + ``bayesian_optimization.cc`` (expected-improvement
acquisition over the tuning space).  Numpy-only, small-n (the tuner takes
tens of samples, so exact Cholesky solves are free).

The search space is normalized to the unit hypercube; callers hand in a
discrete candidate grid (distinct fusion thresholds force an XLA retrace
each, so the tuner must not propose a continuum of values).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np


class GaussianProcess:
    """Exact GP regression with an RBF kernel (fixed hyperparameters)."""

    def __init__(self, length_scale: float = 0.25, noise: float = 1e-4):
        self.length_scale = length_scale
        self.noise = noise
        self._X: Optional[np.ndarray] = None
        self._L: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / self.length_scale**2)

    def fit(self, X: np.ndarray, y: np.ndarray) -> None:
        X = np.atleast_2d(np.asarray(X, np.float64))
        y = np.asarray(y, np.float64)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        K = self._kernel(X, X) + self.noise * np.eye(len(X))
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, yn))
        self._X = X

    def predict(self, Xs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(mean, std) at query points, de-normalized."""
        Xs = np.atleast_2d(np.asarray(Xs, np.float64))
        Ks = self._kernel(Xs, self._X)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(1.0 - (v**2).sum(0), 1e-12, None)
        return (mu * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))


def _norm_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z**2) / math.sqrt(2.0 * math.pi)


def expected_improvement(mu: np.ndarray, sigma: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    """EI for MAXIMIZATION of the objective."""
    imp = mu - best - xi
    z = imp / sigma
    return imp * _norm_cdf(z) + sigma * _norm_pdf(z)


class BayesianOptimizer:
    """EI-driven search over a discrete candidate grid (maximization).

    ``grid``: array [n, d] of candidate points in ORIGINAL units.
    Normalization to [0, 1]^d happens internally.
    """

    def __init__(self, grid: Sequence[Sequence[float]],
                 warmup: int = 4):
        self.grid = np.atleast_2d(np.asarray(grid, np.float64))
        lo = self.grid.min(0)
        span = self.grid.max(0) - lo
        span[span == 0] = 1.0
        self._norm = (self.grid - lo) / span
        self.warmup = warmup
        self._X: List[int] = []    # sampled grid indices
        self._y: List[float] = []

    def observe(self, index: int, score: float) -> None:
        self._X.append(index)
        self._y.append(float(score))

    def suggest(self) -> Optional[int]:
        """Next grid index to try; None when the grid is exhausted."""
        remaining = [i for i in range(len(self.grid)) if i not in self._X]
        if not remaining:
            return None
        if len(self._y) < self.warmup:
            # Deterministic spread over the grid for warmup (SPMD ranks
            # must agree): evenly-strided unsampled points.
            return remaining[(len(self._y) * len(remaining)) //
                             max(1, self.warmup)]
        gp = GaussianProcess()
        gp.fit(self._norm[self._X], np.asarray(self._y))
        mu, sigma = gp.predict(self._norm[remaining])
        ei = expected_improvement(mu, sigma, max(self._y))
        return remaining[int(np.argmax(ei))]

    @property
    def n_observed(self) -> int:
        return len(self._y)

    @property
    def best_index(self) -> Optional[int]:
        if not self._y:
            return None
        return self._X[int(np.argmax(self._y))]
