"""Online autotuning of fusion threshold + cycle time (ParameterManager).

The reference (``horovod/common/parameter_manager.cc`` driving the GP
Bayesian optimization in ``optim/bayesian_optimization.cc``) tunes the
fusion threshold and cycle time against observed throughput, with rank 0
deciding and broadcasting so every rank applies identical values.  Same
architecture here:

* the tunable surface is the gradient bucket size (``fusion_threshold``)
  and -- when the native cycle scheduler is active (torch shim) -- the
  cycle time;
* scoring is observed bytes/sec over ``steps_per_sample`` steps;
* the search is expected-improvement Bayesian optimization over a
  discrete grid (:mod:`horovod_tpu.autotune.gp`), seeded with a strided
  warmup.  Discrete because every distinct fusion threshold costs one
  XLA retrace -- a continuum would thrash the executable cache;
* in multi-process mode rank 0's decisions are pickle-broadcast at
  sample boundaries (the reference's coordinator-decides model), so SPMD
  processes never cut divergent buckets while tuning;
* ``HOROVOD_AUTOTUNE=1`` enables, ``HOROVOD_AUTOTUNE_LOG`` persists the
  sampled configurations as CSV and warm-starts the next run (reference
  warm-start file behavior).

Round 3 widened the surface to the reference ParameterManager's other
knobs where a real choice survives under XLA:

* **hierarchical allreduce** (on 2-axis (dcn, ici) meshes only): XLA's
  own schedule for a both-axes ``psum`` vs the explicit two-level
  reduce-scatter/DCN-allreduce/allgather
  (:func:`~horovod_tpu.collectives.ops.hierarchical_allreduce`);
* **compression codec** (OPT-IN via ``HOROVOD_AUTOTUNE_COMPRESSION=1``,
  because it changes wire numerics): configured default vs bf16 vs fp16
  vs fp8 (e4m3 exchange-level codec, ``compression.py``).  PR 5 extends
  the same axis with error-feedback codec candidates via
  ``HOROVOD_AUTOTUNE_CODEC=powersgd:<r>,topk:<f>,...`` (probed in their
  stateless form -- see ``Autotuner.__init__``);
* **ZeRO exchange** (OPT-IN via ``HOROVOD_AUTOTUNE_ZERO=1`` on a
  ``HOROVOD_ZERO=1`` run): reduce-scatter + allgather vs allreduce
  gradient exchange over the sharded arena (``optim/zero.py``) -- the
  state layout is fixed at step build time, so the axis only opens when
  the run is zero-configured.

PR 2 adds the latency-hiding axes:

* **exchange chunk size** (OPT-IN via ``HOROVOD_AUTOTUNE_CHUNK=1``,
  because scatter-reduce chunks change reduction order): 0 (monolithic
  bucket allreduce) vs chunked reduce-scatter + all-gather exchange
  (``collectives/ops.py::chunked_allreduce``).  Trace-time: flows
  through :meth:`Autotuner.trace_key`.
* **steps per execution** (OPT-IN via
  ``HOROVOD_AUTOTUNE_STEPS_PER_EXEC=1``): how many train steps
  ``make_train_loop`` compiles into one ``lax.scan`` executable.  This
  is a BUILD-time structural knob -- it changes the loop's input shapes
  -- so it is NOT part of ``trace_key()``; ``make_train_loop`` reads
  :meth:`Autotuner.steps_per_exec` when it is (re)built, and the score
  loop in ``training._maybe_tuned`` normalizes per-step time by k.

PR 3 adds the backward-overlap axis:

* **microbatches** (OPT-IN via ``HOROVOD_AUTOTUNE_MICROBATCH=1``): how
  many sub-batches the train step splits the batch into for the
  per-bucket comm/compute overlap (``training.py``, ``microbatches=``).
  BUILD-time like steps-per-exec (k changes the unrolled step
  structure), so it is excluded from ``trace_key()``; closed on
  zero-configured runs (the two exchanges are build-time exclusive).

The response-cache toggle stays collapsed: an executable-cache hit is
always strictly cheaper than a retrace, so there is nothing to search.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from .gp import BayesianOptimizer

_MiB = 1024 * 1024
_THRESHOLDS = [2 * _MiB, 8 * _MiB, 32 * _MiB, 64 * _MiB, 128 * _MiB]
_CYCLES_MS = [0.5, 1.0, 5.0]
MAX_SAMPLES = 12
# Compression axis encoding (grid value -> codec); 0 keeps whatever the
# optimizer was configured with.  Codes >= COMP_CODEC_BASE are
# error-feedback codec candidates from HOROVOD_AUTOTUNE_CODEC, positional
# in that comma list (see Autotuner.__init__).
COMP_DEFAULT, COMP_BF16, COMP_FP16, COMP_FP8 = 0, 1, 2, 3
COMP_CODEC_BASE = 4
# Hierarchical DCN-leg codec axis encoding (grid member 8): what rides
# the cross-slice hop of the two-level exchange when the hierarchical
# axis is on.  0 keeps the sample's plain codec on every leg.
HIER_DCN_NONE, HIER_DCN_BF16, HIER_DCN_FP16, HIER_DCN_FP8 = 0, 1, 2, 3
# MoE all_to_all codec axis encoding (grid member 9): the wire dtype of
# the dispatch/combine shuffle in ``parallel.moe.moe_ffn`` (PR 18).
MOE_NONE, MOE_BF16, MOE_FP16 = 0, 1, 2
_MOE_CODES = {MOE_NONE: "none", MOE_BF16: "bf16", MOE_FP16: "fp16"}


def _grid(thresholds, cycles, hiers, comps, zeros, chunks, steps, micros,
          hcodecs, moes) -> List[Tuple[int, float, int, int, int, int, int,
                                       int, int, int]]:
    # A DCN-leg codec without the hierarchical schedule is meaningless
    # (there is no separate DCN hop to compress), so those combinations
    # are pruned rather than burning sample budget re-measuring the flat
    # exchange.
    return [(t, c, h, k, z, ch, sp, mb, hc, mo) for t in thresholds
            for c in cycles for h in hiers for k in comps for z in zeros
            for ch in chunks for sp in steps for mb in micros
            for hc in hcodecs for mo in moes if not (h == 0 and hc != 0)]


def modeled_exchange_seconds(payload_bytes: float, *, n_dcn: int,
                             n_ici: int, hierarchical: bool,
                             ici_bw: float, dcn_bw: float,
                             ici_wire_scale: float = 1.0,
                             dcn_wire_scale: float = 1.0,
                             quantize_s: float = 0.0,
                             phase_overhead_s: float = 0.0) -> float:
    """Analytic per-link ring cost of one gradient exchange.

    The candidate scorer for the hierarchical/per-leg-codec axes when no
    wall clock is trustworthy (dry runs, the committed autotune demo):
    a flat ring moves ``2 (n-1)/n * bytes`` over the SLOWEST link it
    crosses, while the two-level schedule moves the full payload over ICI
    and only the ``1/n_ici`` shard over DCN -- with each leg's wire bytes
    scaled by that leg's codec (``*_wire_scale``).  ``quantize_s`` prices
    the codec's cast/quantize work, ``phase_overhead_s`` one collective
    launch (the hierarchical schedule pays two extra phases).
    """
    n = n_dcn * n_ici
    if hierarchical and n_dcn > 1:
        return (2 * (n_ici - 1) / n_ici * payload_bytes * ici_wire_scale
                / ici_bw
                + 2 * (n_dcn - 1) / n_dcn
                * (payload_bytes * dcn_wire_scale / n_ici) / dcn_bw
                + 2 * phase_overhead_s + quantize_s)
    return (2 * (n - 1) / n * payload_bytes * ici_wire_scale
            / min(ici_bw, dcn_bw) + phase_overhead_s + quantize_s)


def _mesh_is_two_level() -> bool:
    """True when the initialized mesh has two non-trivial axes (a real
    dcn x ici factorization) -- otherwise the hierarchical knob has
    nothing to choose between."""
    from ..core.state import global_state
    mesh = global_state().mesh
    return (mesh is not None and len(mesh.axis_names) == 2
            and all(s > 1 for s in mesh.devices.shape))


class Autotuner:
    """Feed ``record_step(seconds, nbytes)`` per training step; read the
    current ``fusion_threshold()`` / ``cycle_time_ms()``."""

    def __init__(self, config, steps_per_sample: int = 10,
                 candidates: Optional[List[int]] = None,
                 max_samples: int = MAX_SAMPLES,
                 cycle_candidates: Optional[List[float]] = None):
        self.candidates = list(candidates or _THRESHOLDS)
        if config.fusion_threshold not in self.candidates:
            self.candidates.append(config.fusion_threshold)
        import sys
        # The cycle-time axis only matters when the native cycle scheduler
        # (torch shim grad batching) is in play; tuning it in a pure-JAX
        # run would burn most of the sample budget re-measuring identical
        # configurations under noise.  ``cycle_candidates`` pins the axis
        # explicitly (the resident-module heuristic sees every import the
        # process ever made, not whether THIS run drives the shim).
        if cycle_candidates is not None:
            cycles = list(cycle_candidates)
        else:
            torch_shim = ("horovod_tpu.torch_api" in sys.modules
                          or "horovod_tpu.torch" in sys.modules)
            cycles = list(_CYCLES_MS) if torch_shim else []
        if config.cycle_time not in cycles:
            cycles.append(config.cycle_time)
        # Hierarchical-allreduce choice only exists on a true 2-level
        # mesh; compression retuning is opt-in (it changes numerics).
        hiers = [0, 1] if _mesh_is_two_level() else \
            [1 if config.hierarchical_allreduce else 0]
        from ..core.config import _env, _env_bool
        comps = [COMP_DEFAULT, COMP_BF16, COMP_FP16, COMP_FP8] \
            if _env_bool("AUTOTUNE_COMPRESSION") else [COMP_DEFAULT]
        # Error-feedback codec candidates (HOROVOD_AUTOTUNE_CODEC, a comma
        # list of "powersgd:<rank>" / "topk:<fraction>" specs): each spec
        # extends the compression axis with its own code from
        # COMP_CODEC_BASE upward, mapped back to the compressor by
        # ``compression_override``.  The probe samples run the STATELESS
        # form of the codec (no residual state threads through the tuner),
        # so the score measures wire/ortho cost, not converged quality.
        # Codes above the fixed four are positional in the env list --
        # reorder the list between runs and a warm-start log's codec rows
        # re-seed a different candidate, so keep the list stable.
        self._codec_axis = {}
        codec_spec = _env("AUTOTUNE_CODEC")
        if codec_spec:
            from ..collectives.compression import parse_compression
            for i, tok in enumerate(
                    t.strip() for t in codec_spec.split(",") if t.strip()):
                code = COMP_CODEC_BASE + i
                self._codec_axis[code] = parse_compression(tok)
                if code not in comps:
                    comps.append(code)
        # ZeRO exchange axis (opt-in, HOROVOD_AUTOTUNE_ZERO=1): only a
        # zero-configured run can switch -- the sharded state layout is
        # fixed at step build time, so the searchable pair is the
        # reduce-scatter+allgather exchange (1) vs the allreduce exchange
        # (0) over the same arena (optim/zero.py::_use_reducescatter).
        configured_zero = 1 if getattr(config, "zero_stage", 0) else 0
        self.tunes_zero = bool(_env_bool("AUTOTUNE_ZERO") and
                               configured_zero)
        zeros = [0, 1] if self.tunes_zero else [configured_zero]
        # Chunked-exchange axis (opt-in, HOROVOD_AUTOTUNE_CHUNK=1: scatter-
        # reduce chunks change reduction order): monolithic vs chunked
        # RS+AG exchange (collectives/ops.py::chunked_allreduce).
        configured_chunk = int(getattr(config, "exchange_chunk_bytes", 0))
        if _env_bool("AUTOTUNE_CHUNK"):
            chunks = sorted({0, 4 * _MiB, 16 * _MiB, configured_chunk})
        else:
            chunks = [configured_chunk]
        # Steps-per-execution axis (opt-in,
        # HOROVOD_AUTOTUNE_STEPS_PER_EXEC=1): build-time knob read by
        # make_train_loop, not a trace_key member (it changes the loop's
        # input shapes, so the loop must be rebuilt to apply it).
        configured_steps = max(1, int(getattr(config, "steps_per_exec", 1)))
        if _env_bool("AUTOTUNE_STEPS_PER_EXEC"):
            steps = sorted({1, 4, 16, configured_steps})
        else:
            steps = [configured_steps]
        # Microbatch axis (opt-in, HOROVOD_AUTOTUNE_MICROBATCH=1): the
        # backward-overlap exchange's k (training.py, microbatches=).
        # BUILD-time like steps-per-exec -- k changes the unrolled step
        # structure, so the step is rebuilt, not retraced, and the axis is
        # excluded from trace_key.  Zero-configured runs pin k=1 (the two
        # exchanges are mutually exclusive at build time).
        configured_micro = max(1, int(getattr(config, "microbatches", 1)))
        if _env_bool("AUTOTUNE_MICROBATCH") and not configured_zero:
            micros = sorted({1, 2, 4, configured_micro})
        else:
            micros = [configured_micro]
        # Hierarchical DCN-leg codec axis (opt-in, HOROVOD_AUTOTUNE_HIER=1
        # on a two-level mesh; it changes wire numerics on the cross-slice
        # hop only): which codec rides the DCN leg of the two-level
        # exchange (collectives/ops.py::hierarchical_allreduce's
        # ``dcn_codec``).  The ICI legs keep the sample's plain codec --
        # contended DCN with fast ICI is exactly where per-leg compression
        # pays (the bench's contended_dcn scenario).
        self.tunes_hier_codec = bool(_env_bool("AUTOTUNE_HIER")
                                     and _mesh_is_two_level())
        hcodecs = [HIER_DCN_NONE, HIER_DCN_BF16, HIER_DCN_FP16,
                   HIER_DCN_FP8] if self.tunes_hier_codec \
            else [HIER_DCN_NONE]
        # MoE all_to_all codec axis (opt-in, HOROVOD_AUTOTUNE_MOE=1; it
        # narrows the expert shuffle's wire numerics): which codec the
        # dispatch/combine all_to_all pair of ``parallel.moe.moe_ffn``
        # casts its slot tensors to.  Trace-time -- the cast is part of
        # the traced step -- so it rides trace_key.  Without the opt-in
        # the axis pins to the configured HOROVOD_MOE_COMPRESSION.
        configured_moe = {v: k for k, v in _MOE_CODES.items()}.get(
            str(getattr(config, "moe_compression", None) or "none").lower(),
            MOE_NONE)
        self.tunes_moe = bool(_env_bool("AUTOTUNE_MOE"))
        moes = [MOE_NONE, MOE_BF16, MOE_FP16] if self.tunes_moe \
            else [configured_moe]
        self.grid = _grid(sorted(self.candidates), sorted(cycles), hiers,
                          comps, zeros, chunks, steps, micros, hcodecs,
                          moes)
        self.steps_per_sample = steps_per_sample
        self.max_samples = min(max_samples, len(self.grid))
        self.log_path = config.autotune_log
        self.warm_start_skipped = 0
        self._opt = BayesianOptimizer(
            [(float(t), c, float(h), float(k), float(z), float(ch),
              float(sp), float(mb), float(hc), float(mo))
             for t, c, h, k, z, ch, sp, mb, hc, mo in self.grid])
        self._samples: List[tuple] = []
        self._best: Optional[Tuple[int, float]] = None
        self._step = 0
        self._accum_s = 0.0
        self._accum_bytes = 0
        # Discard the first recorded step of every sample: a config
        # switch retraces, and on the tunnelled chip that first step
        # carries minutes of XLA compile -- folding it into the score
        # would bury the signal (the reference's ParameterManager
        # likewise scores warm cycles only).
        self._skip_next = True
        self._warm_start()
        self._idx = self._next_index()

    # -- current knobs ----------------------------------------------------
    def _current(self) -> Tuple[int, float, int, int, int, int, int, int,
                                int, int]:
        return self._best or self.grid[self._idx]

    def fusion_threshold(self) -> int:
        return self._current()[0]

    def cycle_time_ms(self) -> float:
        return self._current()[1]

    def hierarchical_explicit(self) -> bool:
        """Use the explicit two-level (dcn, ici) allreduce schedule."""
        return bool(self._current()[2])

    def hier_dcn_codec(self):
        """DCN-leg codec of the current sample (None = no per-leg codec).
        Only meaningful when the hierarchical axis is on -- the grid
        prunes the other combinations."""
        code = int(self._current()[8])
        if not code or not self.hierarchical_explicit():
            return None
        from ..collectives.compression import Compression
        return {HIER_DCN_BF16: Compression.bf16,
                HIER_DCN_FP16: Compression.fp16,
                HIER_DCN_FP8: Compression.fp8}[code]

    def compression_override(self, configured):
        """The codec this sample runs with (``configured`` unless the
        opt-in compression axis picked another).  When the hier DCN-codec
        axis is active, the result is the per-leg composite: the plain
        codec (psum-compatible) on the ICI legs, the axis's codec on the
        DCN hop."""
        from ..collectives.compression import Compression
        k = self._current()[3]
        if k == COMP_BF16:
            override = Compression.bf16
        elif k == COMP_FP16:
            override = Compression.fp16
        elif k == COMP_FP8:
            override = Compression.fp8
        elif k >= COMP_CODEC_BASE:
            override = self._codec_axis[k]
        else:
            override = configured
        hc = self.hier_dcn_codec()
        if hc is not None:
            from ..collectives.compression import (hier_leg_compressor,
                                                   is_hier_legs)
            if is_hier_legs(override):
                return override  # configured per-leg codec wins
            ici = override if (override is not None and getattr(
                override, "wire_format", "") == "") else "none"
            return hier_leg_compressor(ici, hc)
        return override

    def zero_stage(self) -> int:
        """The ZeRO exchange value of the current sample (0 = allreduce
        exchange, 1 = reduce-scatter + allgather; optim/zero.py)."""
        return int(self._current()[4])

    def exchange_chunk_bytes(self) -> int:
        """Chunked-exchange size of the current sample (0 = monolithic
        bucket allreduce; collectives/ops.py::chunked_allreduce)."""
        return int(self._current()[5])

    def steps_per_exec(self) -> int:
        """Scan-loop steps-per-execution of the current sample.  Applied
        when ``make_train_loop`` is (re)built -- a BUILD-time knob, not
        part of :meth:`trace_key` (it changes the loop's input shapes)."""
        return int(self._current()[6])

    def microbatches(self) -> int:
        """Backward-overlap microbatch count of the current sample.
        Applied when a train step is (re)built (``training.microbatches``
        resolver) -- a BUILD-time knob like :meth:`steps_per_exec`, not
        part of :meth:`trace_key`."""
        return int(self._current()[7])

    def moe_codec(self) -> str:
        """MoE all_to_all wire codec of the current sample
        (``"none"``/``"bf16"``/``"fp16"``; ``parallel.moe.moe_ffn``)."""
        return _MOE_CODES[int(self._current()[9])]

    def trace_key(self) -> tuple:
        """The TRACE-TIME knobs of the current sample (the compiled step
        cache in ``training.make_train_step`` keys on this).  Cycle time
        is deliberately excluded: it is a RUNTIME knob applied through
        ``_apply_to_batcher``, and keying on it would recompile an
        identical trace for every cycle-axis sample.  Steps-per-exec and
        microbatches are likewise excluded (build-time structural knobs).
        The MoE codec IS a member: the cast is part of the traced step."""
        thr, _cyc, hier, comp, zero, chunk, _sp, _mb, hc, mo = \
            self._current()
        return (thr, hier, comp, zero, chunk, hc, mo)

    @property
    def done(self) -> bool:
        return self._best is not None

    # -- sampling loop ----------------------------------------------------
    def record_step(self, seconds: float, nbytes: int) -> None:
        """Report one training step's wall time and gradient bytes."""
        if self._best is not None:
            return
        if self._skip_next:
            self._skip_next = False  # compile/retrace step: not scored
            return
        self._accum_s += seconds
        self._accum_bytes += nbytes
        self._step += 1
        if self._step < self.steps_per_sample:
            return
        score = self._accum_bytes / max(self._accum_s, 1e-9)  # bytes/s
        self._opt.observe(self._idx, score)
        self._samples.append(self.grid[self._idx] + (score,))
        from ..timeline import metrics as _metrics
        reg = _metrics.registry()
        reg.counter("horovod_autotune_samples_total",
                    "Autotuner samples scored (one per sample window)"
                    ).inc()
        reg.gauge("horovod_autotune_score_bytes_per_second",
                  "Most recent autotuner sample score").set(score)
        self._step = 0
        self._accum_s = 0.0
        self._accum_bytes = 0
        self._idx = self._next_index()
        self._skip_next = True
        self._apply_to_batcher()

    def _next_index(self) -> int:
        """Pick the next configuration (rank 0 decides; others follow)."""
        if self._opt.n_observed >= self.max_samples:
            self._finish()
            return self._opt.best_index or 0
        nxt = self._sync(self._opt.suggest())
        if nxt is None:
            self._finish()
            return self._opt.best_index or 0
        return nxt

    def _sync(self, value):
        """Broadcast rank 0's decision in multi-process mode (the
        reference's coordinator-decides model): per-rank scores differ,
        and diverging fusion thresholds would cut mismatched buckets."""
        import jax
        if jax.process_count() == 1:
            return value
        from ..optim.functions import broadcast_object
        return broadcast_object(value, root_rank=0)

    def _finish(self) -> None:
        if self._best is not None:
            return
        best = self._sync(self._opt.best_index)
        self._best = self.grid[best if best is not None else 0]
        self._write_log()
        self._apply_to_batcher()

    def _apply_to_batcher(self) -> None:
        """Push current knobs into the native cycle scheduler (torch
        shim), mirroring the ParameterManager owning the C++ knobs."""
        import sys
        mod = sys.modules.get("horovod_tpu.torch_api.batching")
        if mod is None:
            return
        b = mod._batcher
        if b is not None:
            b._sched.update_tuning(self.cycle_time_ms(),
                                   self.fusion_threshold())

    # -- warm start / log -------------------------------------------------
    def _warm_start(self) -> None:
        """Seed the optimizer from the previous run's log.

        Only rank 0 reads the file (it may exist on rank 0's filesystem
        alone); the observation list is broadcast so every process sees
        the identical sampling schedule -- a rank-local read would desync
        the broadcast protocol and deadlock.
        """
        obs: List[tuple] = []
        skipped = 0
        if self.log_path and os.path.exists(self.log_path):
            try:
                with open(self.log_path) as f:
                    lines = list(f)
            except OSError:  # pragma: no cover - unreadable log
                lines = []
            for line in lines:
                if line.startswith(("fusion", "#")) or not line.strip():
                    continue
                parts = line.strip().split(",")
                # Each malformed row is SKIPPED, never fatal: one corrupt
                # line (a half-written row after a crash, a hand edit, a
                # future format) must not throw away the whole warm start
                # or crash the tuner.  Skips are counted and warned once.
                try:
                    if len(parts) == 3:     # pre-round-3 log format
                        cfg = (int(float(parts[0])), float(parts[1]),
                               0, COMP_DEFAULT, 0, 0, 1, 1, 0, 0)
                        score = float(parts[2])
                    elif len(parts) == 5:   # rounds 3-5: no zero axis
                        cfg = (int(float(parts[0])), float(parts[1]),
                               int(float(parts[2])),
                               int(float(parts[3])), 0, 0, 1, 1, 0, 0)
                        score = float(parts[4])
                    elif len(parts) == 6:   # PR-1: zero, no chunk/steps
                        cfg = (int(float(parts[0])), float(parts[1]),
                               int(float(parts[2])),
                               int(float(parts[3])),
                               int(float(parts[4])), 0, 1, 1, 0, 0)
                        score = float(parts[5])
                    elif len(parts) == 8:   # PR-2: chunk + steps axes
                        cfg = (int(float(parts[0])), float(parts[1]),
                               int(float(parts[2])),
                               int(float(parts[3])),
                               int(float(parts[4])),
                               int(float(parts[5])),
                               int(float(parts[6])), 1, 0, 0)
                        score = float(parts[7])
                    elif len(parts) in (9, 10, 11):
                        # PR-3: microbatch axis; PR-11 appends the hier
                        # DCN-codec axis; PR-18 appends the MoE codec
                        # axis.  Positional: missing trailing axes load
                        # as their pre-widening default (0).
                        cfg = (int(float(parts[0])), float(parts[1]),
                               int(float(parts[2])),
                               int(float(parts[3])),
                               int(float(parts[4])),
                               int(float(parts[5])),
                               int(float(parts[6])),
                               int(float(parts[7])),
                               int(float(parts[8]))
                               if len(parts) >= 10 else 0,
                               int(float(parts[9]))
                               if len(parts) == 11 else 0)
                        score = float(parts[-1])
                    else:                   # unknown column count
                        skipped += 1
                        continue
                except ValueError:          # non-numeric cell
                    skipped += 1
                    continue
                if not np.isfinite(score):
                    # A NaN/inf score would poison the GP posterior (every
                    # expected-improvement comparison turns NaN).
                    skipped += 1
                    continue
                if cfg in self.grid:
                    obs.append((self.grid.index(cfg), score))
        if skipped:
            import warnings
            warnings.warn(
                f"autotune warm start: skipped {skipped} unusable row(s) "
                f"in {self.log_path} (unknown column count or NaN/inf "
                "score)", RuntimeWarning, stacklevel=2)
        self.warm_start_skipped = skipped
        obs = self._sync(obs)
        for idx, score in obs:
            self._opt.observe(idx, score)
            # Keep warm rows in _samples so _write_log preserves them --
            # otherwise a warm-started run truncates the log and the
            # warm start survives exactly one restart.
            self._samples.append(self.grid[idx] + (score,))

    def _write_log(self) -> None:
        if not self.log_path:
            return
        with open(self.log_path, "w") as f:
            f.write("fusion_threshold_bytes,cycle_time_ms,hierarchical,"
                    "compression,zero,exchange_chunk_bytes,steps_per_exec,"
                    "microbatches,hier_dcn_codec,moe_codec,"
                    "score_bytes_per_s\n")
            for thr, cyc, hier, comp, zero, chunk, sp, mb, hc, mo, score \
                    in self._samples:
                f.write(f"{thr},{cyc},{hier},{comp},{zero},{chunk},{sp},"
                        f"{mb},{hc},{mo},{score}\n")
            f.write("# best," + ",".join(str(v) for v in self._best) + "\n")
