"""Online autotuning of the fusion threshold (ParameterManager analogue).

The reference (``horovod/common/parameter_manager.cc`` + Bayesian
optimization in ``optim/bayesian_optimization.cc``) tunes fusion threshold
and cycle time against observed throughput.  On TPU there is no cycle time
(no background loop), so the tunable surface is the gradient bucket size.
Round-1 implementation is the reference's documented fallback strategy --
discrete candidate sweep scored by observed step throughput -- with the GP
surrogate as a later upgrade.

Usage: the training loop reports ``record_step(seconds, bytes)`` each step;
every ``steps_per_sample`` steps the tuner moves to the next candidate, and
after one full sweep it locks in the argmax.  ``HOROVOD_AUTOTUNE=1``
enables it; ``HOROVOD_AUTOTUNE_LOG`` writes the CSV of samples, matching
the reference's warm-start log format in spirit.
"""

from __future__ import annotations

import time
from typing import List, Optional

_MiB = 1024 * 1024
_CANDIDATES = [2 * _MiB, 8 * _MiB, 32 * _MiB, 64 * _MiB, 128 * _MiB]


class Autotuner:
    def __init__(self, config, steps_per_sample: int = 10,
                 candidates: Optional[List[int]] = None):
        self.candidates = list(candidates or _CANDIDATES)
        base = config.fusion_threshold
        if base not in self.candidates:
            self.candidates.append(base)
        self.steps_per_sample = steps_per_sample
        self.log_path = config.autotune_log
        self._idx = 0
        self._step = 0
        self._accum_s = 0.0
        self._accum_bytes = 0
        self._scores: List[float] = []
        self._best: Optional[int] = None
        self._samples: List[tuple] = []

    def fusion_threshold(self) -> int:
        if self._best is not None:
            return self._best
        return self.candidates[self._idx]

    @property
    def done(self) -> bool:
        return self._best is not None

    def record_step(self, seconds: float, nbytes: int) -> None:
        """Report one training step's wall time and gradient bytes."""
        if self._best is not None:
            return
        self._accum_s += seconds
        self._accum_bytes += nbytes
        self._step += 1
        if self._step < self.steps_per_sample:
            return
        score = self._accum_bytes / max(self._accum_s, 1e-9)  # bytes/s
        self._samples.append((self.candidates[self._idx], score))
        self._scores.append(score)
        self._step = 0
        self._accum_s = 0.0
        self._accum_bytes = 0
        self._idx += 1
        if self._idx >= len(self.candidates):
            best_i = max(range(len(self._scores)),
                         key=lambda i: self._scores[i])
            self._best = self.candidates[best_i]
            self._write_log()

    def _write_log(self) -> None:
        if not self.log_path:
            return
        with open(self.log_path, "w") as f:
            f.write("fusion_threshold_bytes,score_bytes_per_s\n")
            for thr, score in self._samples:
                f.write(f"{thr},{score}\n")
            f.write(f"# best,{self._best}\n")
