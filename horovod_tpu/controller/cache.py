"""Executable cache: the ResponseCache analogue.

The reference's ``response_cache.cc`` caches negotiated responses so that
steady-state cycles skip the full rank-0 gather/broadcast and instead
allreduce a small bit vector.  Under SPMD the negotiation result for a
given request signature is fully determined at trace time, so the analogue
is a bounded LRU of *compiled executables* keyed by the request signature
(names, shapes, dtypes, op, process set): a hit dispatches a pre-compiled
fused program with zero Python re-trace cost; a miss traces + compiles
(the "negotiation").

``HOROVOD_CACHE_CAPACITY`` (default 1024) bounds the table as in the
reference.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Hashable, Optional, Tuple


class ExecutableCache:
    """Bounded LRU mapping request signatures -> compiled callables."""

    def __init__(self, capacity: int = 1024):
        self.capacity = max(1, int(capacity))
        self._od: "collections.OrderedDict[Hashable, Any]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get_or_build(self, key: Hashable, build: Callable[[], Any]) -> Any:
        with self._lock:
            if key in self._od:
                self._od.move_to_end(key)
                self.hits += 1
                return self._od[key]
        # Build outside the lock: tracing/compiling can be slow and build()
        # must not deadlock against other cache users.
        value = build()
        with self._lock:
            if key in self._od:
                self._od.move_to_end(key)
                self.hits += 1
                return self._od[key]
            self.misses += 1
            self._od[key] = value
            while len(self._od) > self.capacity:
                self._od.popitem(last=False)
                self.evictions += 1
            return value

    def clear(self) -> None:
        with self._lock:
            self._od.clear()

    def stats(self) -> Tuple[int, int, int]:
        return self.hits, self.misses, self.evictions

    def __len__(self) -> int:
        return len(self._od)


def signature(kind: str,
              name: Optional[str],
              shapes_dtypes: Tuple,
              op: Optional[str],
              process_set: str,
              extra: Tuple = ()) -> Tuple:
    """Build a request-signature key (Request wire-format analogue --
    reference ``horovod/common/message.h::Request`` carries exactly these
    fields: op type, tensor name, dtype, shape, process set)."""
    return (kind, name, shapes_dtypes, op, process_set) + tuple(extra)
