"""Tensor fusion: the HBM-resident fusion-buffer analogue.

The reference's ``fusion_buffer_manager.cc`` keeps a persistent 64 MiB
device buffer; the background thread memcpys ready gradients in (batched
D2D CUDA kernels), runs ONE collective, and memcpys out.  Under XLA the
same idea is expressed functionally at trace time: leaves are raveled and
concatenated into flat per-dtype buffers no larger than the fusion
threshold, one ``psum`` is emitted per buffer, and the results are sliced
back out.  XLA fuses the pack/unpack with neighbouring elementwise work, so
no copy kernels are written by hand, and donation keeps the buffers from
doubling HBM footprint.

``HOROVOD_FUSION_THRESHOLD`` (default 64 MiB) controls bucket size, exactly
as in the reference (SURVEY.md section 5.6).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.state import global_state
from .cache import ExecutableCache


@dataclasses.dataclass(frozen=True)
class _LeafSpec:
    index: int            # position in the original leaf list
    shape: Tuple[int, ...]
    size: int


@dataclasses.dataclass(frozen=True)
class FusionSpec:
    """Static description of how leaves were packed into flat buffers."""
    buffers: Tuple[Tuple[Any, Tuple[_LeafSpec, ...]], ...]  # (dtype, leaves)
    num_leaves: int


def _threshold() -> int:
    st = global_state()
    if st.config is not None:
        if st.autotuner is not None:
            return st.autotuner.fusion_threshold()
        return st.config.fusion_threshold
    return 64 * 1024 * 1024


def exchange_chunk_bytes() -> int:
    """Resolved chunk size for the chunked gradient exchange (0 = off).

    Reads ``HOROVOD_EXCHANGE_CHUNK_MB`` through the parsed config; when the
    autotuner is active its chunk-size axis wins (like ``_threshold``).
    """
    st = global_state()
    if st.config is not None:
        if st.autotuner is not None:
            return st.autotuner.exchange_chunk_bytes()
        return st.config.exchange_chunk_bytes
    return 0


# Bucket-plan memoization (ResponseCache spirit): the eager path replans
# identical gradient lists every step, and plan_buckets is pure in
# (shapes, dtypes, threshold).  Bounded LRU so shape-polymorphic callers
# cannot grow it without bound; capacity follows HOROVOD_CACHE_CAPACITY.
_plan_cache: Optional[ExecutableCache] = None


def _get_plan_cache() -> ExecutableCache:
    global _plan_cache
    st = global_state()
    cap = st.config.cache_capacity if st.config is not None else 1024
    if _plan_cache is None or _plan_cache.capacity != cap:
        _plan_cache = ExecutableCache(capacity=cap)
    return _plan_cache


def plan_cache_stats() -> dict:
    """Hit/miss/eviction counters for the memoized bucket planner."""
    c = _get_plan_cache()
    return {"hits": c.hits, "misses": c.misses, "evictions": c.evictions,
            "size": len(c)}


def clear_plan_cache() -> None:
    global _plan_cache
    _plan_cache = None


def plan_cache_enabled() -> bool:
    """Whether plan memoization is on (``HOROVOD_PLAN_CACHE``, default 1).

    ``0`` / ``false`` / ``off`` disables the shared plan cache: every
    planner call rebuilds from scratch.  Diagnostic knob -- replan counts
    in the bench and the consistency tests assume the cache is on.
    """
    return os.environ.get("HOROVOD_PLAN_CACHE", "1").strip().lower() \
        not in ("0", "false", "off")


def exchange_schedule_mode() -> str:
    """Leg-issue order policy (``HOROVOD_EXCHANGE_SCHEDULE``).

    ``bandwidth`` (default): :func:`schedule_legs` issues ready legs in
    bandwidth order -- contended-DCN legs before independent ICI legs,
    ties broken by modeled leg cost then program order.  ``program``:
    legs issue exactly in plan order (the pre-IR behaviour).
    """
    mode = os.environ.get("HOROVOD_EXCHANGE_SCHEDULE", "bandwidth")
    mode = mode.strip().lower()
    return mode if mode in ("bandwidth", "program") else "bandwidth"


def _memo(key: Tuple, build):
    """Route a planner memoization through the shared plan cache
    (identity when ``HOROVOD_PLAN_CACHE`` disables it)."""
    if not plan_cache_enabled():
        return build()
    return _get_plan_cache().get_or_build(key, build)


def plan_key(leaves: Sequence[Any], threshold_bytes: int,
             extra: Tuple = ()) -> Tuple:
    """Hashable memoization key for a bucket plan: per-leaf (shape, dtype)
    plus the threshold and any caller context (e.g. process-set name)."""
    return (tuple((tuple(x.shape), str(jnp.dtype(x.dtype))) for x in leaves),
            int(threshold_bytes)) + tuple(extra)


def plan_buckets(leaves: Sequence[Any],
                 threshold_bytes: Optional[int] = None,
                 reverse: bool = False,
                 extra: Tuple = ()) -> FusionSpec:
    """Greedily pack leaves into per-dtype buckets of <= threshold bytes.

    Order within a dtype follows leaf order (gradients arrive in reverse
    topological order, which keeps adjacent-layer gradients adjacent in the
    buffer -- same locality the reference's cycle batching produces).

    ``reverse=True`` walks the leaves last-to-first instead: the
    bucket-READY ordering for the backward-overlap exchange.  Flax/optax
    trees flatten in parameter (forward) order, so the LAST leaves are the
    layers whose gradients the backward pass finishes FIRST -- emitting
    their buckets first matches upstream Horovod's fusion-cycle behaviour
    (ready gradients go on the wire while earlier layers still compute).
    Unpack is index-addressed, so leaf recovery is order-independent.

    Leaves may be concrete arrays OR abstract ``jax.ShapeDtypeStruct``s
    (anything with ``.shape``/``.dtype``): the plan depends only on shapes
    and dtypes, so the scan-loop runner can plan its exchange ahead of data.
    Plans are memoized in a bounded LRU (see :func:`plan_cache_stats`).

    ``extra`` is folded into the memo key for caller context that changes
    what a bucket MEANS without changing its packing -- e.g. the exchange
    codec name, so an error-feedback plan (whose bucket sizes fix the
    residual-state shapes) never aliases a plain plan of the same leaves.
    """
    if threshold_bytes is None:
        threshold_bytes = _threshold()
    leaves = [x if hasattr(x, "dtype") else jnp.asarray(x) for x in leaves]
    key = plan_key(leaves, threshold_bytes,
                   extra=(("rev",) if reverse else ()) + tuple(extra))
    return _memo(
        key, lambda: _plan_buckets_uncached(leaves, threshold_bytes, reverse))


def _plan_buckets_uncached(leaves: Sequence[Any],
                           threshold_bytes: int,
                           reverse: bool = False) -> FusionSpec:
    by_dtype: dict = {}
    indexed = list(enumerate(leaves))
    if reverse:
        indexed.reverse()
    for i, x in indexed:
        by_dtype.setdefault(jnp.dtype(x.dtype), []).append(
            _LeafSpec(i, tuple(x.shape), int(np.prod(x.shape, dtype=np.int64))))
    buffers: List[Tuple[Any, Tuple[_LeafSpec, ...]]] = []
    for dt, specs in by_dtype.items():
        itemsize = jnp.dtype(dt).itemsize
        cur: List[_LeafSpec] = []
        cur_bytes = 0
        for s in specs:
            nbytes = s.size * itemsize
            if cur and cur_bytes + nbytes > threshold_bytes:
                buffers.append((dt, tuple(cur)))
                cur, cur_bytes = [], 0
            cur.append(s)
            cur_bytes += nbytes
        if cur:
            buffers.append((dt, tuple(cur)))
    return FusionSpec(buffers=tuple(buffers), num_leaves=len(leaves))


def plan_eager_flush(leaves: Sequence[Any], k: int,
                     threshold_bytes: Optional[int] = None,
                     extra: Tuple = ()) -> FusionSpec:
    """Bucket plan for the fused deferred-async flush (eager path).

    Same greedy per-dtype packing as :func:`plan_buckets`, but the eager
    layout is RANK-STACKED (``[k, ...]`` with ``k`` local ranks), so
    bucket sizes are counted over each op's per-rank row -- the payload a
    rank actually puts on the wire -- not over the whole stack.  Each
    returned ``_LeafSpec``'s shape/size describe that flat row
    (``size == prod(shape) // k``); ``index`` addresses the caller's leaf
    list as usual.  Memoized in the shared plan cache under an
    eager-flush-scoped key (``extra`` carries caller context such as the
    process-set name).
    """
    if threshold_bytes is None:
        threshold_bytes = _threshold()
    leaves = [x if hasattr(x, "dtype") else jnp.asarray(x) for x in leaves]
    k = max(int(k), 1)
    key = plan_key(leaves, threshold_bytes,
                   extra=("eager_flush", k) + tuple(extra))

    def build():
        rows = [jax.ShapeDtypeStruct(
            (int(np.prod(x.shape, dtype=np.int64)) // k,), x.dtype)
            for x in leaves]
        return _plan_buckets_uncached(rows, threshold_bytes)

    return _memo(key, build)


def pack(leaves: Sequence[jax.Array], spec: FusionSpec) -> List[jax.Array]:
    """Ravel+concat leaves into flat buffers per the spec."""
    out = []
    for dt, lspecs in spec.buffers:
        if len(lspecs) == 1:
            s = lspecs[0]
            out.append(jnp.ravel(leaves[s.index]))
        else:
            out.append(jnp.concatenate(
                [jnp.ravel(leaves[s.index]) for s in lspecs]))
    return out


def unpack(buffers: Sequence[jax.Array], spec: FusionSpec) -> List[jax.Array]:
    """Slice flat buffers back into the original leaf list order."""
    leaves: List[Optional[jax.Array]] = [None] * spec.num_leaves
    for buf, (dt, lspecs) in zip(buffers, spec.buffers):
        off = 0
        for s in lspecs:
            leaves[s.index] = buf[off:off + s.size].reshape(s.shape)
            off += s.size
    assert all(l is not None for l in leaves)
    return leaves  # type: ignore[return-value]


def fuse_flat(xs: Sequence[jax.Array],
              threshold_bytes: Optional[int] = None
              ) -> Tuple[List[jax.Array], FusionSpec]:
    spec = plan_buckets(xs, threshold_bytes)
    return pack(xs, spec), spec


def unfuse_flat(buffers: Sequence[jax.Array], spec: FusionSpec
                ) -> List[jax.Array]:
    return unpack(buffers, spec)


def fused_tree_collective(tree, collective_fn,
                          threshold_bytes: Optional[int] = None,
                          extra: Tuple = ()):
    """Apply ``collective_fn(flat_buffer) -> flat_buffer`` to a whole pytree
    through the fusion buffers.  This is the gradient hot path used by
    :class:`horovod_tpu.optim.DistributedOptimizer`.  ``extra`` is caller
    context for the plan memo key (see :func:`plan_buckets`).
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    spec = plan_buckets(leaves, threshold_bytes, extra=extra)
    buffers = pack(leaves, spec)
    reduced = [collective_fn(b) for b in buffers]
    return jax.tree.unflatten(treedef, unpack(reduced, spec))


# -- explicit leg planning (two-level exchange) ----------------------------

@dataclasses.dataclass(frozen=True)
class ExchangeLeg:
    """One typed row of the exchange-plan IR: which mesh axis the leg
    moves over, which collective it emits, the codec riding that hop,
    and the closed-form operand/wire accounting the spans, auditor and
    bench all gate on.

    ``elements`` is the collective's first-operand element count (what
    the jaxpr auditor records); ``nbytes`` the wire payload bytes the
    matching ``spans.note_leg`` call reports for the leg.  ``audit`` is
    the leg's contract with ``analysis.stepmodel``: the exact
    ``(kind, dtype, elements, label)`` collective rows the traced step
    must contain for this leg (label is a suffix the model prefixes with
    its bucket tag).  ``kind`` indexes :data:`LEG_KINDS` (bandwidth
    class for the scheduler); ``fence`` records the eager fence policy
    in force when the plan was built; ``kernel`` names a Pallas kernel
    family when the leg is a kernel contract rather than a collective.
    """
    tag: str          # span tag: hier/ici_rs | zero_rs | moe/a2a_* | ...
    axis: str         # mesh axis name(s) the leg moves over
    collective: str   # reduce_scatter | psum | all_gather | fp8_gather |
                      # powersgd | topk | all_to_all | none
    codec: str        # codec name applied on this leg
    wire_dtype: str
    elements: int
    nbytes: int
    kind: str = ""    # LEG_KINDS key: flat_ar | ici_rs | dcn_ar | ...
    bucket: int = 0   # bucket / arena / layer index within the plan
    leaves: int = 0   # leaf count packed into the leg's bucket (0 = n/a)
    fence: str = ""   # eager fence policy snapshot (see _fence_policy)
    audit: Tuple[Tuple[str, str, int, str], ...] = ()
    kernel: str = ""  # Pallas kernel family for kind="kernel" legs


def hier_mesh_shape() -> Optional[Tuple[int, int]]:
    """``(n_dcn, n_ici)`` when the world mesh is the two-level
    ``(dcn, ici)`` communicator, else ``None``."""
    st = global_state()
    m = st.mesh
    if m is None:
        return None
    names = tuple(m.axis_names)
    if len(names) != 2:
        return None
    return (int(m.shape[names[0]]), int(m.shape[names[1]]))


def hier_requested(compression=None) -> bool:
    """Whether the two-level exchange is in effect for the gradient path:
    a per-leg codec always requests it; otherwise the config flag /
    topology spec or the autotuner's hierarchical axis."""
    from ..collectives.compression import is_hier_legs
    if compression is not None and is_hier_legs(compression):
        return True
    st = global_state()
    cfg = st.config
    if cfg is not None and cfg.hierarchical_allreduce:
        return True
    if cfg is not None and getattr(cfg, "hierarchical", None):
        from ..parallel.mesh import parse_topology_spec
        try:
            if parse_topology_spec(cfg.hierarchical)[0]:
                return True
        except ValueError:
            pass
    if st.autotuner is not None:
        return bool(st.autotuner.hierarchical_explicit())
    return False


def plan_hier_legs(size: int, dtype, *, n_dcn: int, n_ici: int,
                   compression=None, dcn_axis: str = "dcn",
                   ici_axis: str = "ici", ici_codec=None,
                   dcn_codec=None) -> List[ExchangeLeg]:
    """Closed-form leg plan for one bucket of the two-level exchange.

    Thin wrapper over ``plan_exchange("hier", ...)`` -- the memoized IR
    planner mirrors ``ops.hierarchical_allreduce`` exactly (padding
    quantum, per-leg wire dtypes, ``note_leg`` byte accounting), so the
    bench's payload gate, the auditor's ``stepmodel`` and the op itself
    all consume the SAME plan object.  ``compression`` may be ``None``,
    a cast codec (the bucket is cast before the exchange: every leg
    rides the wire dtype), or a per-leg ``ici:...,dcn:...`` codec;
    alternatively pass resolved ``ici_codec``/``dcn_codec`` classes
    directly (the executor's calling convention).
    """
    return list(plan_exchange(
        "hier", size=int(size), dtype=str(jnp.dtype(dtype)),
        n_dcn=int(n_dcn), n_ici=int(n_ici), compression=compression,
        ici_codec=ici_codec, dcn_codec=dcn_codec,
        dcn_axis=dcn_axis, ici_axis=ici_axis).legs)


def plan_moe_alltoall(n_experts: int, capacity: int, d_model: int, *,
                      dtype=jnp.float32, compression=None,
                      axis: str = "model") -> List[ExchangeLeg]:
    """Closed-form leg plan for one MoE layer's all_to_all pair.

    Thin wrapper over ``plan_exchange("moe", ...)``; mirrors
    ``parallel.moe.moe_ffn`` exactly: the dispatch leg moves the
    f32 ``(E, C, d)`` slot tensor (split experts, concat slots), the
    combine leg moves the same payload back, and ``compression`` (the
    ``HOROVOD_MOE_COMPRESSION`` / autotuner-MoE-axis codec) narrows both
    legs' wire dtype.  ``elements`` is the per-device operand element
    count the jaxpr auditor records for each ``all_to_all``; ``nbytes``
    matches the ``moe/a2a_*`` ``note_leg`` accounting byte-for-byte.
    """
    return list(plan_exchange(
        "moe", n_experts=int(n_experts), capacity=int(capacity),
        d_model=int(d_model), dtype=dtype, compression=compression,
        axis=axis).legs)


# -- plan introspection ----------------------------------------------------

def _fence_policy() -> str:
    """Human-readable fence policy the eager plane would apply to a
    collective dispatched right now (compiled steps never fence: XLA
    schedules their collectives)."""
    st = global_state()
    if st.mesh is None:
        return "unfenced(no-mesh)"
    from ..collectives.eager import _mesh_platform, _transport_needs_fence
    platform = _mesh_platform(st.mesh)
    if _transport_needs_fence(st.mesh):
        return f"barrier+block({platform})"
    return f"compiler-scheduled({platform})"


def explain_plan(params, threshold_bytes: Optional[int] = None,
                 compression=None, reverse: bool = False,
                 extra: Tuple = (), register: bool = True,
                 moe: Optional[dict] = None) -> List[dict]:
    """Render the planner's decision for ``params`` as structured rows.

    One dict per bucket: ``bucket`` index, ``dtype``, ``leaves`` count,
    ``elements``, raw ``bytes``, ``wire_bytes`` under ``compression``
    (a spec string or codec class; None = uncompressed), the ``codec``
    name, the eager ``fence`` policy, and the ``fuse_key`` the plan
    memoizes under.  The rows come from the SAME :func:`plan_buckets`
    call the exchange makes -- error-feedback codecs fold the
    ``("ef", codec)`` context exactly like ``ef_bucket_plan`` -- so
    bucket count and per-bucket bytes match the emitted exchange by
    construction (asserted in tests/test_metrics.py).

    ``register=True`` also publishes the rows as ``horovod_plan_*``
    gauges so ``/metrics`` exposes the current plan.  Printable via
    ``python -m horovod_tpu.run --explain-plan`` (:func:`render_plan`).

    ``moe`` prices a model's MoE all_to_all traffic alongside the
    gradient buckets: a dict with ``n_experts``, ``capacity`` and
    ``d_model`` (optional ``layers`` -- MoE layer count, default 1 --
    plus ``compression`` and ``axis``) appends one extra row whose legs
    come from :func:`plan_moe_alltoall`, one dispatch/combine pair per
    layer.
    """
    from ..collectives.compression import (is_error_feedback,
                                           parse_compression,
                                           wire_payload_bytes)
    leaves = jax.tree.leaves(params)
    comp = parse_compression(compression) if compression is not None \
        else None
    if threshold_bytes is None:
        threshold_bytes = _threshold()
    plan_extra = tuple(extra)
    if comp is not None and is_error_feedback(comp):
        # Mirror optim.distributed.ef_bucket_plan's memo context so the
        # explained plan IS the exchange's plan (same cache entry).
        plan_extra = ("ef", comp.__name__) + plan_extra
    spec = plan_buckets(leaves, threshold_bytes, reverse=reverse,
                        extra=plan_extra)
    codec = comp.__name__ if comp is not None else "none"
    fence = _fence_policy()
    hier_shape = hier_mesh_shape() if hier_requested(comp) else None
    rows = []
    for i, (dt, lspecs) in enumerate(spec.buffers):
        dtype = str(jnp.dtype(dt))
        size = sum(s.size for s in lspecs)
        itemsize = jnp.dtype(dt).itemsize
        raw = size * itemsize
        legs = None
        if hier_shape is not None:
            try:
                legs = plan_hier_legs(size, dt, n_dcn=hier_shape[0],
                                      n_ici=hier_shape[1], compression=comp)
            except ValueError:
                legs = None  # codec the two-level path doesn't route
        if legs is not None:
            wire = sum(l.nbytes for l in legs)
        elif comp is not None:
            wire = wire_payload_bytes(comp, size, itemsize)
        else:
            wire = raw
        rows.append({
            "bucket": i, "dtype": dtype, "leaves": len(lspecs),
            "elements": int(size), "bytes": int(raw),
            "wire_bytes": int(wire), "codec": codec, "fence": fence,
            "fuse_key": "|".join(
                [dtype, f"thr={int(threshold_bytes)}", codec]
                + (["rev"] if reverse else [])),
            "legs": [dataclasses.asdict(l) for l in legs]
            if legs is not None else None,
        })
    if moe is not None:
        layers = int(moe.get("layers", 1))
        pair = plan_moe_alltoall(
            moe["n_experts"], moe["capacity"], moe["d_model"],
            dtype=moe.get("dtype", jnp.float32),
            compression=moe.get("compression"),
            axis=moe.get("axis", "model"))
        moe_legs = pair * layers
        elements = sum(l.elements for l in moe_legs)
        raw = elements * jnp.dtype(moe.get("dtype", jnp.float32)).itemsize
        rows.append({
            "bucket": len(rows), "dtype": pair[0].wire_dtype,
            "leaves": 0, "elements": int(elements), "bytes": int(raw),
            "wire_bytes": int(sum(l.nbytes for l in moe_legs)),
            "codec": pair[0].codec, "fence": fence,
            "fuse_key": "|".join(
                ["moe", f"E={int(moe['n_experts'])}",
                 f"C={int(moe['capacity'])}", f"d={int(moe['d_model'])}",
                 f"L={layers}", pair[0].codec]),
            "legs": [dataclasses.asdict(l) for l in moe_legs],
        })
    if register:
        register_plan_gauges(rows)
    return rows


def register_plan_gauges(rows: List[dict]) -> None:
    """Publish explain_plan rows into the metrics registry."""
    from ..timeline import metrics as _metrics
    reg = _metrics.registry()
    reg.gauge("horovod_plan_buckets",
              "Bucket count of the most recently explained exchange plan"
              ).set(len(rows))
    by_bytes = reg.gauge(
        "horovod_plan_bucket_bytes",
        "Raw bytes per bucket of the explained plan",
        labelnames=("bucket", "dtype"))
    by_wire = reg.gauge(
        "horovod_plan_bucket_wire_bytes",
        "Wire bytes per bucket of the explained plan",
        labelnames=("bucket", "dtype"))
    for r in rows:
        labels = {"bucket": str(r["bucket"]), "dtype": r["dtype"]}
        by_bytes.labels(**labels).set(r["bytes"])
        by_wire.labels(**labels).set(r["wire_bytes"])


def render_plan(rows: List[dict]) -> str:
    """Fixed-width table rendering of :func:`explain_plan` rows."""
    if not rows:
        return "(empty plan: no leaves)"
    cols = ("bucket", "dtype", "leaves", "elements", "bytes",
            "wire_bytes", "codec", "fence", "fuse_key")
    table = [cols] + [tuple(str(r[c]) for c in cols) for r in rows]
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    lines = ["  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
             for row in table]
    lines.insert(1, "  ".join("-" * w for w in widths))
    for r in rows:
        for leg in r.get("legs") or ():
            lines.append(
                f"    bucket {r['bucket']} leg {leg['tag']}: "
                f"{leg['collective']}@{leg['axis']} codec={leg['codec']} "
                f"{leg['wire_dtype']} {leg['elements']}el {leg['nbytes']}B")
    total_raw = sum(r["bytes"] for r in rows)
    total_wire = sum(r["wire_bytes"] for r in rows)
    ratio = f" (ratio {total_raw / total_wire:.1f}x)" \
        if 0 < total_wire < total_raw else ""
    lines.append(f"total: {len(rows)} bucket(s), {total_raw} bytes raw, "
                 f"{total_wire} bytes wire{ratio}")
    return "\n".join(lines)


# -- exchange-plan IR ------------------------------------------------------
#
# One typed plan object for EVERY exchange the framework emits.  Each
# consumer (flat/chunked/hierarchical/compressed allreduce, eager flush,
# ZeRO arena, EF exchange, microbatch pipe, guard screen, serving decode,
# MoE all_to_all) asks ``plan_exchange(family, **spec)`` for its legs and
# then (a) notes each leg into the span ledger verbatim and (b) emits the
# collectives the legs describe.  ``analysis.stepmodel`` rebuilds its
# expected-collective multiset from the SAME memoized plan (the ``audit``
# rows), so expectation and emission can only diverge if an executor
# diverges from its own plan.  Adding a new leg kind = register a kind +
# a family here, consume the legs in ONE executor; spans/auditor/bench
# pick it up with zero new code (the ROADMAP success test; exercised in
# tests/test_plan_ir.py).

#: Registry of leg kinds -> {"bandwidth": dcn|ici|local, "doc": ...}.
#: The scheduler uses the bandwidth class to order ready legs (DCN
#: before ICI before local) and to price them (see leg_cost_seconds).
LEG_KINDS: Dict[str, dict] = {}


def register_leg_kind(kind: str, *, bandwidth: str = "ici",
                      doc: str = "") -> None:
    """Register (or re-register) a leg kind with its bandwidth class."""
    if bandwidth not in ("dcn", "ici", "local"):
        raise ValueError(f"bandwidth class must be dcn|ici|local, "
                         f"got {bandwidth!r}")
    LEG_KINDS[kind] = {"bandwidth": bandwidth, "doc": doc}


register_leg_kind("flat_ar", bandwidth="ici",
                  doc="flat fused-bucket allreduce (single psum)")
register_leg_kind("ici_rs", bandwidth="ici",
                  doc="two-level exchange: intra-slice reduce-scatter")
register_leg_kind("dcn_ar", bandwidth="dcn",
                  doc="two-level exchange: cross-slice hop under DCN codec")
register_leg_kind("ici_ag", bandwidth="ici",
                  doc="two-level exchange: intra-slice allgather")
register_leg_kind("chunked", bandwidth="ici",
                  doc="chunked RS+AG sweep over the wire buffer")
register_leg_kind("zero_rs", bandwidth="ici",
                  doc="ZeRO arena reduce-scatter (or psum fallback)")
register_leg_kind("zero_ag", bandwidth="ici",
                  doc="ZeRO arena shard allgather")
register_leg_kind("ef", bandwidth="ici",
                  doc="error-feedback exchange (ledger + factored legs)")
register_leg_kind("fp8", bandwidth="ici",
                  doc="quantized fp8 gather-sum allreduce")
register_leg_kind("mb_rs", bandwidth="ici",
                  doc="microbatch pipe per-microbatch reduce-scatter")
register_leg_kind("mb_ag", bandwidth="ici",
                  doc="microbatch pipe closing allgather")
register_leg_kind("guard", bandwidth="ici",
                  doc="SDC guard screen vector psum")
register_leg_kind("serving_psum", bandwidth="ici",
                  doc="serving TP decode row-parallel activation psum")
register_leg_kind("serving_verify", bandwidth="ici",
                  doc="speculative-verify row-parallel activation psum")
register_leg_kind("moe_a2a", bandwidth="ici",
                  doc="MoE dispatch/combine all_to_all")
register_leg_kind("kernel", bandwidth="local",
                  doc="Pallas kernel contract: no wire traffic")


@dataclasses.dataclass(frozen=True)
class ExchangePlan:
    """A full exchange plan: an ordered tuple of typed legs.

    Hashable and memoized by :func:`plan_exchange`; ``fingerprint`` is a
    process-stable key for whole-plan executable memoization (see
    :func:`plan_executable`)."""
    family: str
    legs: Tuple[ExchangeLeg, ...]

    @property
    def fingerprint(self) -> str:
        digest = hashlib.sha1(
            repr((self.family, self.legs)).encode()).hexdigest()[:16]
        return f"{self.family}:{len(self.legs)}:{digest}"

    def by_tag(self, tag: str) -> Tuple[ExchangeLeg, ...]:
        return tuple(l for l in self.legs if l.tag == tag)

    def by_kind(self, kind: str) -> Tuple[ExchangeLeg, ...]:
        return tuple(l for l in self.legs if l.kind == kind)

    def wire_bytes(self) -> int:
        return int(sum(l.nbytes for l in self.legs))

    def ops(self) -> List[Tuple[str, str, int, str]]:
        return ops_from_legs(self.legs)


def ops_from_legs(legs: Sequence[ExchangeLeg],
                  tag: Optional[str] = None
                  ) -> List[Tuple[str, str, int, str]]:
    """Flatten legs' audit contracts into ``(kind, dtype, elements,
    label)`` rows -- the stepmodel's ExpectedOp tuples.  ``tag`` prefixes
    each row's label (default: the leg's span tag; pass ``""`` for
    families whose audit rows carry complete labels)."""
    out: List[Tuple[str, str, int, str]] = []
    for leg in legs:
        prefix = leg.tag if tag is None else tag
        for kind, dt, elements, suffix in leg.audit:
            label = f"{prefix}/{suffix}" if prefix else suffix
            out.append((kind, dt, int(elements), label))
    return out


def _wire_cast_dtype(comp, dtype) -> "jnp.dtype":
    """Dtype a cast codec puts on the wire for a ``dtype`` bucket
    (identical condition to ``stepmodel._wire_dtype``)."""
    dt = jnp.dtype(dtype)
    wd = getattr(comp, "wire_dtype", None)
    if (wd is not None and jnp.issubdtype(dt, jnp.floating)
            and dt.itemsize > jnp.dtype(wd).itemsize):
        return jnp.dtype(wd)
    return dt


_XPLAN_BUILDERS: Dict[str, Any] = {}
_XPLAN_CANON: Dict[str, Any] = {}


def register_plan_family(family: str, builder, canon=None) -> None:
    """Register an exchange-plan family.

    ``builder(spec) -> List[ExchangeLeg]`` produces the legs from a
    CANONICAL spec dict; ``canon(spec) -> spec`` normalizes caller
    arguments into that canonical, hashable form (so an executor call
    and a stepmodel call that mean the same exchange share one cache
    entry).  This is the only extension point new leg kinds need."""
    _XPLAN_BUILDERS[family] = builder
    if canon is not None:
        _XPLAN_CANON[family] = canon


def plan_exchange(family: str, **spec) -> ExchangePlan:
    """THE planner: one memoized entry point for every exchange family.

    Canonicalizes ``spec``, folds the eager fence policy into the memo
    key (plans are mesh-platform-scoped), and builds the leg list at
    most once per distinct exchange shape.  All executors and the
    read-only consumers (``stepmodel``/``explain_plan``/spans) call
    through here, so replans are shared across train, eager and serving
    steps (see ``plan_cache_stats``)."""
    if family not in _XPLAN_BUILDERS:
        raise ValueError(
            f"unknown exchange-plan family {family!r} "
            f"(registered: {sorted(_XPLAN_BUILDERS)})")
    canon = _XPLAN_CANON.get(family)
    cspec = canon(spec) if canon is not None \
        else {k: spec[k] for k in sorted(spec)}
    fence = _fence_policy()
    key = ("xplan", family, fence) + tuple(sorted(cspec.items()))

    def build() -> ExchangePlan:
        legs = tuple(dataclasses.replace(l, fence=fence)
                     for l in _XPLAN_BUILDERS[family](cspec))
        return ExchangePlan(family=family, legs=legs)

    return _memo(key, build)


# -- family canons + builders ----------------------------------------------

def _parse_comp(comp):
    from ..collectives.compression import Compression, parse_compression
    return parse_compression(comp) if comp is not None else Compression.none


def _canon_flat(spec: dict) -> dict:
    comp = _parse_comp(spec.get("compression"))
    dt = _wire_cast_dtype(comp, spec.get("dtype", "float32"))
    return {"size": int(spec["size"]), "wire_dtype": str(dt),
            "axis": str(spec.get("axis", ""))}


def _build_flat(spec: dict) -> List[ExchangeLeg]:
    dt = jnp.dtype(spec["wire_dtype"])
    size = spec["size"]
    return [ExchangeLeg(
        tag="flat_ar", axis=spec["axis"], collective="psum", codec="none",
        wire_dtype=str(dt), elements=size, nbytes=size * dt.itemsize,
        kind="flat_ar", audit=(("psum", str(dt), size, "allreduce"),))]


def _canon_hier(spec: dict) -> dict:
    from ..collectives.compression import Compression, is_hier_legs
    dt = jnp.dtype(spec.get("dtype", "float32"))
    floating = jnp.issubdtype(dt, jnp.floating)
    ici_c = spec.get("ici_codec")
    dcn_c = spec.get("dcn_codec")
    if ici_c is None and dcn_c is None:
        comp = _parse_comp(spec.get("compression"))
        if is_hier_legs(comp):
            ici_c, dcn_c = comp.ici, comp.dcn
        elif getattr(comp, "wire_format", ""):
            raise ValueError(
                f"{comp.__name__} is an exchange-level codec; the "
                f"two-level path takes it per leg (ici:...,dcn:...)")
        else:
            # A flat cast codec compresses the bucket BEFORE the
            # exchange: the op sees the already-cast buffer, so every
            # leg (padding, shard, wire accounting) lives in the wire
            # domain.
            wd = getattr(comp, "wire_dtype", None)
            if (floating and wd is not None
                    and jnp.dtype(wd).itemsize < dt.itemsize):
                dt = jnp.dtype(wd)
            ici_c = dcn_c = Compression.none
    else:
        ici_c = ici_c if ici_c is not None else Compression.none
        dcn_c = dcn_c if dcn_c is not None else Compression.none
    if not floating:
        ici_c = dcn_c = Compression.none
    return {"size": int(spec["size"]), "dtype": str(dt),
            "n_dcn": int(spec["n_dcn"]), "n_ici": int(spec["n_ici"]),
            "ici": ici_c, "dcn": dcn_c,
            "dcn_axis": str(spec.get("dcn_axis", "dcn")),
            "ici_axis": str(spec.get("ici_axis", "ici"))}


def _build_hier(spec: dict) -> List[ExchangeLeg]:
    from ..collectives.compression import (is_error_feedback, is_fp8,
                                           is_powersgd,
                                           powersgd_factor_widths,
                                           topk_count, wire_payload_bytes)
    from ..collectives.ops import microbatch_pad_quantum
    size = spec["size"]
    dt = jnp.dtype(spec["dtype"])
    floating = jnp.issubdtype(dt, jnp.floating)
    n_dcn, n_ici = spec["n_dcn"], spec["n_ici"]
    ici_c, dcn_c = spec["ici"], spec["dcn"]
    dcn_axis, ici_axis = spec["dcn_axis"], spec["ici_axis"]
    if n_dcn <= 1:
        # Single slice: the op statically falls back to the flat psum.
        return [ExchangeLeg(
            tag="flat_ar", axis=f"{dcn_axis},{ici_axis}",
            collective="psum", codec="none", wire_dtype=str(dt),
            elements=size, nbytes=size * dt.itemsize, kind="flat_ar",
            audit=(("psum", str(dt), size, "flat-ar"),))]
    quantum = microbatch_pad_quantum(n_ici)
    padded = size + (-size) % quantum
    shard = padded // n_ici
    itemsize = dt.itemsize
    ici_itemsize = itemsize
    ici_dt = str(dt)
    wd = getattr(ici_c, "wire_dtype", None)
    if floating and wd is not None and jnp.dtype(wd).itemsize < itemsize:
        ici_itemsize = jnp.dtype(wd).itemsize
        ici_dt = str(jnp.dtype(wd))
    if floating and is_powersgd(dcn_c):
        dcn_coll, dcn_dt = "powersgd", "float32"
        pw, qw = powersgd_factor_widths(shard, dcn_c.rank)
        dcn_audit = (("psum", "float32", pw, "dcn-psum-P"),
                     ("psum", "float32", qw, "dcn-psum-Q"))
    elif floating and is_error_feedback(dcn_c):
        dcn_coll, dcn_dt = "topk", "float32"
        k = min(topk_count(shard, dcn_c.fraction), shard)
        dcn_audit = (("all_gather", "float32", k, "dcn-gather-values"),
                     ("all_gather", "int32", k, "dcn-gather-indices"))
    elif floating and is_fp8(dcn_c):
        # Quantized gather-sum: e4m3 shards + one f32 scale per slice.
        dcn_coll, dcn_dt = "fp8_gather", "float8_e4m3fn"
        dcn_audit = (("all_gather", "float8_e4m3fn", shard,
                      "dcn-gather-q"),
                     ("all_gather", "float32", 1, "dcn-gather-scale"))
    else:
        dcn_coll = "psum"
        dwd = getattr(dcn_c, "wire_dtype", None)
        dcn_dt = str(jnp.dtype(dwd)) if floating and dwd is not None \
            and jnp.dtype(dwd).itemsize < itemsize else str(dt)
        dcn_audit = (("psum", dcn_dt, shard, "dcn-ar"),)
    return [
        ExchangeLeg(tag="hier/ici_rs", axis=ici_axis,
                    collective="reduce_scatter", codec=ici_c.__name__,
                    wire_dtype=ici_dt, elements=padded,
                    nbytes=padded * ici_itemsize, kind="ici_rs",
                    audit=(("reduce_scatter", ici_dt, padded, "ici-rs"),)),
        ExchangeLeg(tag="hier/dcn_ar", axis=dcn_axis, collective=dcn_coll,
                    codec=dcn_c.__name__, wire_dtype=dcn_dt,
                    elements=shard,
                    nbytes=wire_payload_bytes(dcn_c, shard, itemsize),
                    kind="dcn_ar", audit=dcn_audit),
        ExchangeLeg(tag="hier/ici_ag", axis=ici_axis,
                    collective="all_gather", codec=ici_c.__name__,
                    wire_dtype=ici_dt, elements=shard,
                    nbytes=padded * ici_itemsize, kind="ici_ag",
                    audit=(("all_gather", ici_dt, shard, "ici-ag"),)),
    ]


def _canon_chunked(spec: dict) -> dict:
    comp = _parse_comp(spec.get("compression"))
    dt = _wire_cast_dtype(comp, spec.get("dtype", "float32"))
    return {"size": int(spec["size"]), "wire_dtype": str(dt),
            "chunk_bytes": int(spec["chunk_bytes"]),
            "world": int(spec["world"])}


def _build_chunked(spec: dict) -> List[ExchangeLeg]:
    dt = jnp.dtype(spec["wire_dtype"])
    size, world = spec["size"], spec["world"]
    item = dt.itemsize
    chunk_elems = max(1, spec["chunk_bytes"] // item)
    chunk_elems += (-chunk_elems) % world
    audit: List[Tuple[str, str, int, str]] = []
    for j, off in enumerate(range(0, size, chunk_elems)):
        piece = min(chunk_elems, size - off)
        padded = piece + (-piece) % world
        audit.append(("reduce_scatter", str(dt), padded, f"chunk{j}-rs"))
        audit.append(("all_gather", str(dt), padded // world,
                      f"chunk{j}-ag"))
    return [ExchangeLeg(
        tag="chunked_rs_ag", axis="", collective="reduce_scatter",
        codec="none", wire_dtype=str(dt), elements=size,
        nbytes=size * item, kind="chunked", audit=tuple(audit))]


def _canon_powersgd(spec: dict) -> dict:
    return {"size": int(spec["size"]), "rank": int(spec["rank"])}


def _build_powersgd(spec: dict) -> List[ExchangeLeg]:
    from ..collectives.compression import (powersgd_compressor,
                                           powersgd_factor_widths,
                                           powersgd_matrix_shape)
    size, rank = spec["size"], spec["rank"]
    m, c = powersgd_matrix_shape(size)
    r = max(1, min(rank, m, c))
    pw, qw = powersgd_factor_widths(size, rank)
    return [ExchangeLeg(
        tag="powersgd_allreduce", axis="", collective="powersgd",
        codec=powersgd_compressor(rank).__name__, wire_dtype="float32",
        elements=size, nbytes=2 * r * (m + c) * 4, kind="ef",
        audit=(("psum", "float32", pw, "psum-P"),
               ("psum", "float32", qw, "psum-Q")))]


def _canon_topk(spec: dict) -> dict:
    return {"size": int(spec["size"]), "fraction": float(spec["fraction"])}


def _build_topk(spec: dict) -> List[ExchangeLeg]:
    from ..collectives.compression import topk_compressor, topk_count
    size = spec["size"]
    k = min(topk_count(size, spec["fraction"]), size)
    return [ExchangeLeg(
        tag="topk_allreduce", axis="", collective="topk",
        codec=topk_compressor(spec["fraction"]).__name__,
        wire_dtype="float32", elements=size, nbytes=8 * k, kind="ef",
        audit=(("all_gather", "float32", k, "gather-values"),
               ("all_gather", "int32", k, "gather-indices")))]


def _canon_fp8(spec: dict) -> dict:
    return {"size": int(spec["size"]), "world": int(spec["world"])}


def _build_fp8(spec: dict) -> List[ExchangeLeg]:
    size, world = spec["size"], spec["world"]
    padded = size + (-size) % world
    # stepmodel declines the flat fp8 path (unmodeled), so no audit rows.
    return [ExchangeLeg(
        tag="fp8_allreduce", axis="", collective="fp8_gather",
        codec="fp8", wire_dtype="float8_e4m3fn", elements=padded,
        nbytes=2 * padded, kind="fp8", audit=())]


def _canon_ef(spec: dict) -> dict:
    comp = _parse_comp(spec["compression"])
    return {"size": int(spec["size"]),
            "dtype": str(jnp.dtype(spec["dtype"])), "comp": comp}


def _build_ef(spec: dict) -> List[ExchangeLeg]:
    from ..collectives.compression import is_powersgd, wire_payload_bytes
    comp = spec["comp"]
    size = spec["size"]
    dt = jnp.dtype(spec["dtype"])
    ledger_nbytes = wire_payload_bytes(comp, size, dt.itemsize)
    if not jnp.issubdtype(dt, jnp.floating):
        # Non-float buckets ride the plain flat psum; the ledger leg IS
        # the exchange.
        return [ExchangeLeg(
            tag="ef_exchange", axis="", collective="psum",
            codec=comp.__name__, wire_dtype=str(dt), elements=size,
            nbytes=ledger_nbytes, kind="ef",
            audit=(("psum", str(dt), size, "allreduce"),))]
    # Floating buckets: the ledger leg accounts the factored wire payload
    # once (audit-free), and the nested powersgd/topk leg carries the
    # collective contract (its own note fires inside the op).
    ledger = ExchangeLeg(
        tag="ef_exchange", axis="", collective="ledger",
        codec=comp.__name__, wire_dtype="float32", elements=size,
        nbytes=ledger_nbytes, kind="ef", audit=())
    if is_powersgd(comp):
        nested = _build_powersgd({"size": size, "rank": int(comp.rank)})
    else:
        nested = _build_topk({"size": size,
                              "fraction": float(comp.fraction)})
    return [ledger] + nested


def _canon_zero(spec: dict) -> dict:
    comp = _parse_comp(spec.get("compression"))
    ax_shape = spec.get("axes_shape")
    ax_shape = tuple(int(a) for a in ax_shape) \
        if ax_shape and len(ax_shape) == 2 else None
    axes = spec.get("axes") or ()
    axes = tuple(str(a) for a in axes) if ax_shape is not None else ()
    return {"buffers": tuple(
                (str(jnp.dtype(d)), int(s), int(p), int(sh))
                for d, s, p, sh in spec["buffers"]),
            "world": int(spec["world"]), "comp": comp,
            "axes_shape": ax_shape, "axes": axes,
            "use_rs": bool(spec["use_rs"])}


def _build_zero(spec: dict) -> List[ExchangeLeg]:
    from ..collectives.compression import is_hier_legs
    comp = spec["comp"]
    use_rs = spec["use_rs"]
    two_level = spec["axes_shape"]
    hier = is_hier_legs(comp) and two_level is not None
    axis = ",".join(spec["axes"])
    if two_level is not None:
        n_dcn, n_ici = two_level
        # Axis extents in the order the RS loop scatters over them: a
        # per-leg codec flips to (ici, dcn) so only the 1/n_ici shard
        # crosses DCN.
        rs_order = (n_ici, n_dcn) if hier else (n_dcn, n_ici)
    rs_legs: List[ExchangeLeg] = []
    ag_legs: List[ExchangeLeg] = []
    for i, (dts, size, padded, shard) in enumerate(spec["buffers"]):
        item = jnp.dtype(dts).itemsize
        rs_audit: Tuple = ()
        ag_audit: Tuple = ()
        if size >= 1:
            if use_rs and two_level is not None:
                rows = []
                running = padded
                for j, n_a in enumerate(rs_order):
                    rows.append(("reduce_scatter", dts, running,
                                 f"reduce-scatter-ax{j}"))
                    running //= n_a
                rs_audit = tuple(rows)
            elif use_rs:
                rs_audit = (("reduce_scatter", dts, padded,
                             "reduce-scatter"),)
            else:
                rs_audit = (("psum", dts, padded, "allreduce"),)
            if hier:
                # compressed_allgather over (dcn,) then (ici,), each hop
                # at its leg codec's wire dtype.
                ag_audit = (
                    ("all_gather", str(_wire_cast_dtype(comp.dcn, dts)),
                     shard, "allgather-dcn"),
                    ("all_gather", str(_wire_cast_dtype(comp.ici, dts)),
                     shard * n_dcn, "allgather-ici"))
            elif two_level is not None:
                # ops.allgather gathers reversed(axes): ici first.
                wire = str(_wire_cast_dtype(comp, dts))
                ag_audit = (("all_gather", wire, shard, "allgather-ici"),
                            ("all_gather", wire, shard * n_ici,
                             "allgather-dcn"))
            else:
                ag_audit = (("all_gather",
                             str(_wire_cast_dtype(comp, dts)), shard,
                             "allgather"),)
        rs_legs.append(ExchangeLeg(
            tag="zero_rs" if use_rs else "zero_allreduce", axis=axis,
            collective="reduce_scatter" if use_rs else "psum",
            codec="none", wire_dtype=dts, elements=padded,
            nbytes=padded * item, kind="zero_rs", bucket=i,
            audit=rs_audit))
        ag_legs.append(ExchangeLeg(
            tag="zero_ag", axis=axis, collective="all_gather",
            codec=comp.__name__, wire_dtype=dts, elements=shard,
            nbytes=shard * item, kind="zero_ag", bucket=i,
            audit=ag_audit))
    # RS legs for every arena, then AG legs: the executor's note order.
    return rs_legs + ag_legs


def _canon_microbatch(spec: dict) -> dict:
    comp = _parse_comp(spec.get("compression"))
    return {"buffers": tuple((str(jnp.dtype(d)), int(s))
                             for d, s in spec["buffers"]),
            "k": int(spec["k"]), "world": int(spec["world"]),
            "comp": comp}


def _build_microbatch(spec: dict) -> List[ExchangeLeg]:
    from ..collectives.ops import microbatch_pad_quantum
    comp = spec["comp"]
    k, world = spec["k"], spec["world"]
    q = microbatch_pad_quantum(world)
    rs_legs: List[ExchangeLeg] = []
    ag_legs: List[ExchangeLeg] = []
    for i, (dts, size) in enumerate(spec["buffers"]):
        padded = size + (-size) % q
        wire = _wire_cast_dtype(comp, dts)
        rs_legs.append(ExchangeLeg(
            tag="microbatch_rs", axis="", collective="reduce_scatter",
            codec=comp.__name__, wire_dtype=str(wire), elements=padded,
            nbytes=size * wire.itemsize, kind="mb_rs", bucket=i,
            audit=tuple(("reduce_scatter", str(wire), padded,
                         f"scatter-mb{j}") for j in range(k))))
        ag_legs.append(ExchangeLeg(
            tag="microbatch_ag", axis="", collective="all_gather",
            codec=comp.__name__, wire_dtype=str(wire),
            elements=padded // world,
            nbytes=(padded // world) * wire.itemsize, kind="mb_ag",
            bucket=i,
            audit=(("all_gather", str(wire), padded // world,
                    "allgather"),)))
    return rs_legs + ag_legs


def _canon_serving(spec: dict) -> dict:
    return {"kind": str(spec.get("kind", "serving_decode")),
            "layers": int(spec["layers"]), "slots": int(spec["slots"]),
            "width": int(spec.get("width", 1)),
            "d_model": int(spec["d_model"]),
            "dtype": str(jnp.dtype(spec.get("dtype", "float32"))),
            "axis": str(spec.get("axis", "tp"))}


def _build_serving(spec: dict) -> List[ExchangeLeg]:
    kind = spec["kind"]
    leg_kind = "serving_verify" if kind == "serving_verify" \
        else "serving_psum"
    dt = jnp.dtype(spec["dtype"])
    elements = spec["slots"] * spec["width"] * spec["d_model"]
    nbytes = elements * dt.itemsize
    legs = []
    for li in range(spec["layers"]):
        for part in ("attn_wo", "mlp_down"):
            legs.append(ExchangeLeg(
                tag=f"{kind}/layer{li}/{part}", axis=spec["axis"],
                collective="psum", codec="none", wire_dtype=str(dt),
                elements=elements, nbytes=nbytes, kind=leg_kind,
                bucket=li,
                audit=(("psum", str(dt), elements,
                        f"layer{li}/{part}/allreduce"),)))
    return legs


def _build_guard(spec: dict) -> List[ExchangeLeg]:
    # The 2-wide screen vector psum the SDC guard prepends to the step.
    return [ExchangeLeg(
        tag="guard/screen", axis="", collective="psum", codec="none",
        wire_dtype="float32", elements=2, nbytes=8, kind="guard",
        audit=(("psum", "float32", 2, "guard/screen"),))]


def _canon_moe(spec: dict) -> dict:
    from ..parallel.moe import resolve_moe_compression
    return {"n_experts": int(spec["n_experts"]),
            "capacity": int(spec["capacity"]),
            "d_model": int(spec["d_model"]),
            "dtype": str(jnp.dtype(spec.get("dtype", jnp.float32))),
            "codec": resolve_moe_compression(spec.get("compression")),
            "axis": str(spec.get("axis", "model"))}


def _build_moe(spec: dict) -> List[ExchangeLeg]:
    from ..parallel.moe import _MOE_CODECS
    wire = _MOE_CODECS[spec["codec"]]
    dt = jnp.dtype(spec["dtype"])
    wire_dt = jnp.dtype(wire) if wire is not None else dt
    elements = spec["n_experts"] * spec["capacity"] * spec["d_model"]
    nbytes = elements * wire_dt.itemsize
    return [ExchangeLeg(
        tag=f"moe/a2a_{name}", axis=spec["axis"],
        collective="all_to_all", codec=spec["codec"],
        wire_dtype=str(wire_dt), elements=elements, nbytes=nbytes,
        kind="moe_a2a",
        audit=(("all_to_all", str(wire_dt), elements, f"a2a-{name}"),))
        for name in ("dispatch", "combine")]


def _canon_kernel(spec: dict) -> dict:
    return {"kernel": str(spec["kernel"]), "nbytes": int(spec["nbytes"])}


def _build_kernel(spec: dict) -> List[ExchangeLeg]:
    # Kernel contract: HBM traffic accounting only, no wire collective.
    return [ExchangeLeg(
        tag=f"pallas/{spec['kernel']}", axis="", collective="none",
        codec="none", wire_dtype="", elements=0, nbytes=spec["nbytes"],
        kind="kernel", kernel=spec["kernel"], audit=())]


register_plan_family("flat", _build_flat, _canon_flat)
register_plan_family("hier", _build_hier, _canon_hier)
register_plan_family("chunked", _build_chunked, _canon_chunked)
register_plan_family("powersgd", _build_powersgd, _canon_powersgd)
register_plan_family("topk", _build_topk, _canon_topk)
register_plan_family("fp8", _build_fp8, _canon_fp8)
register_plan_family("ef", _build_ef, _canon_ef)
register_plan_family("zero", _build_zero, _canon_zero)
register_plan_family("microbatch", _build_microbatch, _canon_microbatch)
register_plan_family("serving", _build_serving, _canon_serving)
register_plan_family("guard", _build_guard)
register_plan_family("moe", _build_moe, _canon_moe)
register_plan_family("kernel", _build_kernel, _canon_kernel)


def hier_mesh_axes() -> Optional[Tuple[str, str]]:
    """``(dcn_axis, ici_axis)`` names of the two-level world mesh, else
    ``None`` -- so read-only consumers canonicalize hier plans with the
    SAME axis names the executor uses (one cache entry, not two)."""
    st = global_state()
    m = st.mesh
    if m is None:
        return None
    names = tuple(m.axis_names)
    if len(names) != 2:
        return None
    return (str(names[0]), str(names[1]))


# -- overlap-aware leg scheduler -------------------------------------------

_BW_RANK = {"dcn": 2, "ici": 1, "local": 0}


def leg_bandwidth(leg: ExchangeLeg) -> str:
    """Bandwidth class a leg occupies: its kind's registered class,
    promoted to ``dcn`` when the leg's axis list names the DCN axis
    (e.g. a ZeRO allgather whose outer hop crosses slices)."""
    cls = LEG_KINDS.get(leg.kind, {}).get("bandwidth", "ici")
    if cls == "local":
        return "local"
    axes = tuple(a.strip() for a in leg.axis.split(",") if a.strip())
    if cls == "dcn" or "dcn" in axes:
        return "dcn"
    return cls


def leg_cost_seconds(leg: ExchangeLeg, chip=None) -> float:
    """Modeled issue cost: leg wire bytes over the bandwidth class's
    effective allreduce rate (the autotuner's contended-DCN ChipSpec
    model; defaults to v5e)."""
    bw = leg_bandwidth(leg)
    if bw == "local":
        return 0.0
    if chip is None:
        from ..utils.scaling import V5E
        chip = V5E
    rate = chip.dcn_allreduce_bytes_per_s if bw == "dcn" \
        else chip.ici_allreduce_bytes_per_s
    return float(leg.nbytes) / max(float(rate), 1.0)


def schedule_legs(legs: Sequence[ExchangeLeg], mode: Optional[str] = None,
                  chip=None) -> List[ExchangeLeg]:
    """Order legs for issue: bandwidth-aware greedy list scheduling.

    Legs sharing a ``bucket`` form an ordered dependency chain (RS ->
    hop -> AG must stay in plan order); across chains the scheduler
    replays the two-link contention model :func:`simulate_issue` prices
    and repeatedly issues the chain head that can START earliest --
    breaking ties by slowest bandwidth class (DCN before ICI before
    local), then modeled cost, then plan order.  A chain's downstream
    leg (an AG waiting on its DCN hop) therefore never head-of-line
    blocks its link while an independent chain's leg is ready: the idle
    window the hop leaves on the ICI link is filled with the next
    bucket's RS.  ``mode="program"`` (or
    ``HOROVOD_EXCHANGE_SCHEDULE=program``) returns plan order.
    Deterministic in its inputs: safe to call at trace time under SPMD.
    """
    mode = exchange_schedule_mode() if mode is None else str(mode)
    ordered = list(legs)
    if mode != "bandwidth" or len(ordered) <= 1:
        return ordered
    chains: Dict[int, List[int]] = {}
    for idx, leg in enumerate(ordered):
        chains.setdefault(int(leg.bucket), []).append(idx)
    heads = {b: 0 for b in chains}
    free = {"dcn": 0.0, "ici": 0.0}
    done: Dict[int, float] = {}
    out: List[ExchangeLeg] = []
    while len(out) < len(ordered):
        best = None
        for b in chains:
            pos = heads[b]
            if pos >= len(chains[b]):
                continue
            idx = chains[b][pos]
            leg = ordered[idx]
            bw = leg_bandwidth(leg)
            start = max(free.get(bw, 0.0), done.get(b, 0.0))
            score = (start, -_BW_RANK.get(bw, 1),
                     -leg_cost_seconds(leg, chip), idx)
            if best is None or score < best[0]:
                best = (score, b, idx)
        assert best is not None
        _, b, idx = best
        heads[b] += 1
        leg = ordered[idx]
        bw = leg_bandwidth(leg)
        start = max(free.get(bw, 0.0), done.get(b, 0.0))
        end = start + leg_cost_seconds(leg, chip)
        if bw in free:
            free[bw] = end
        done[b] = end
        out.append(leg)
    return out


def overlap_phases(legs: Sequence[ExchangeLeg], k: int,
                   mode: Optional[str] = None,
                   chip=None) -> List[List[ExchangeLeg]]:
    """Partition scheduled legs into ``k`` issue phases, one per
    backward microbatch: the generalization of the ``microbatches=k``
    overlap to arbitrary leg DAGs.  Phase ``j`` holds the legs that go
    on the wire while microbatch ``j``'s backward still computes;
    round-robin over the scheduled order keeps every phase's class mix
    balanced (each phase leads with the most-contended ready leg)."""
    k = max(int(k), 1)
    ordered = schedule_legs(legs, mode=mode, chip=chip)
    phases: List[List[ExchangeLeg]] = [[] for _ in range(k)]
    for i, leg in enumerate(ordered):
        phases[i % k].append(leg)
    return phases


def simulate_issue(legs: Sequence[ExchangeLeg], chip=None) -> dict:
    """Price an issue order on the two-link contention model.

    Each bandwidth class is one link; a leg starts when its link is free
    AND its bucket's previous leg finished (the RS->hop->AG chain).
    Returns the modeled makespan, per-class busy seconds, and the
    dispatch-gap fraction: how much of the makespan the critical link
    sits idle waiting on dispatch order.  Purely a host-side model (the
    bench's A/B metric) -- it never touches the wire."""
    free = {"dcn": 0.0, "ici": 0.0}
    busy = {"dcn": 0.0, "ici": 0.0}
    done: Dict[int, float] = {}
    makespan = 0.0
    for leg in legs:
        bw = leg_bandwidth(leg)
        cost = leg_cost_seconds(leg, chip)
        start = max(free.get(bw, 0.0), done.get(int(leg.bucket), 0.0))
        end = start + cost
        if bw in free:
            free[bw] = end
            busy[bw] += cost
        done[int(leg.bucket)] = end
        makespan = max(makespan, end)
    crit = max(busy.values()) if any(busy.values()) else 0.0
    gap = max(0.0, 1.0 - crit / makespan) if makespan > 0 else 0.0
    return {"makespan_s": makespan, "busy_s": dict(busy),
            "dispatch_gap_fraction": gap}


def plan_executable(plan: ExchangePlan, build, extra: Tuple = ()):
    """Memoize a whole-plan executable by plan fingerprint.

    Steps that share exchange structure (an eager flush, a serving
    decode step, a train step replayed under a new closure) share one
    compiled executable through the session ``ExecutableCache`` --
    ``build()`` runs at most once per (fingerprint, extra).  Falls back
    to the plan cache before ``hvd.init`` wires the session cache."""
    if not plan_cache_enabled():
        return build()
    st = global_state()
    cache = st.cache if st.cache is not None else _get_plan_cache()
    return cache.get_or_build(
        ("plan_exec", plan.fingerprint) + tuple(extra), build)
