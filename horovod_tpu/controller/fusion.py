"""Tensor fusion: the HBM-resident fusion-buffer analogue.

The reference's ``fusion_buffer_manager.cc`` keeps a persistent 64 MiB
device buffer; the background thread memcpys ready gradients in (batched
D2D CUDA kernels), runs ONE collective, and memcpys out.  Under XLA the
same idea is expressed functionally at trace time: leaves are raveled and
concatenated into flat per-dtype buffers no larger than the fusion
threshold, one ``psum`` is emitted per buffer, and the results are sliced
back out.  XLA fuses the pack/unpack with neighbouring elementwise work, so
no copy kernels are written by hand, and donation keeps the buffers from
doubling HBM footprint.

``HOROVOD_FUSION_THRESHOLD`` (default 64 MiB) controls bucket size, exactly
as in the reference (SURVEY.md section 5.6).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.state import global_state
from .cache import ExecutableCache


@dataclasses.dataclass(frozen=True)
class _LeafSpec:
    index: int            # position in the original leaf list
    shape: Tuple[int, ...]
    size: int


@dataclasses.dataclass(frozen=True)
class FusionSpec:
    """Static description of how leaves were packed into flat buffers."""
    buffers: Tuple[Tuple[Any, Tuple[_LeafSpec, ...]], ...]  # (dtype, leaves)
    num_leaves: int


def _threshold() -> int:
    st = global_state()
    if st.config is not None:
        if st.autotuner is not None:
            return st.autotuner.fusion_threshold()
        return st.config.fusion_threshold
    return 64 * 1024 * 1024


def exchange_chunk_bytes() -> int:
    """Resolved chunk size for the chunked gradient exchange (0 = off).

    Reads ``HOROVOD_EXCHANGE_CHUNK_MB`` through the parsed config; when the
    autotuner is active its chunk-size axis wins (like ``_threshold``).
    """
    st = global_state()
    if st.config is not None:
        if st.autotuner is not None:
            return st.autotuner.exchange_chunk_bytes()
        return st.config.exchange_chunk_bytes
    return 0


# Bucket-plan memoization (ResponseCache spirit): the eager path replans
# identical gradient lists every step, and plan_buckets is pure in
# (shapes, dtypes, threshold).  Bounded LRU so shape-polymorphic callers
# cannot grow it without bound; capacity follows HOROVOD_CACHE_CAPACITY.
_plan_cache: Optional[ExecutableCache] = None


def _get_plan_cache() -> ExecutableCache:
    global _plan_cache
    st = global_state()
    cap = st.config.cache_capacity if st.config is not None else 1024
    if _plan_cache is None or _plan_cache.capacity != cap:
        _plan_cache = ExecutableCache(capacity=cap)
    return _plan_cache


def plan_cache_stats() -> dict:
    """Hit/miss/eviction counters for the memoized bucket planner."""
    c = _get_plan_cache()
    return {"hits": c.hits, "misses": c.misses, "evictions": c.evictions,
            "size": len(c)}


def clear_plan_cache() -> None:
    global _plan_cache
    _plan_cache = None


def plan_key(leaves: Sequence[Any], threshold_bytes: int,
             extra: Tuple = ()) -> Tuple:
    """Hashable memoization key for a bucket plan: per-leaf (shape, dtype)
    plus the threshold and any caller context (e.g. process-set name)."""
    return (tuple((tuple(x.shape), str(jnp.dtype(x.dtype))) for x in leaves),
            int(threshold_bytes)) + tuple(extra)


def plan_buckets(leaves: Sequence[Any],
                 threshold_bytes: Optional[int] = None,
                 reverse: bool = False,
                 extra: Tuple = ()) -> FusionSpec:
    """Greedily pack leaves into per-dtype buckets of <= threshold bytes.

    Order within a dtype follows leaf order (gradients arrive in reverse
    topological order, which keeps adjacent-layer gradients adjacent in the
    buffer -- same locality the reference's cycle batching produces).

    ``reverse=True`` walks the leaves last-to-first instead: the
    bucket-READY ordering for the backward-overlap exchange.  Flax/optax
    trees flatten in parameter (forward) order, so the LAST leaves are the
    layers whose gradients the backward pass finishes FIRST -- emitting
    their buckets first matches upstream Horovod's fusion-cycle behaviour
    (ready gradients go on the wire while earlier layers still compute).
    Unpack is index-addressed, so leaf recovery is order-independent.

    Leaves may be concrete arrays OR abstract ``jax.ShapeDtypeStruct``s
    (anything with ``.shape``/``.dtype``): the plan depends only on shapes
    and dtypes, so the scan-loop runner can plan its exchange ahead of data.
    Plans are memoized in a bounded LRU (see :func:`plan_cache_stats`).

    ``extra`` is folded into the memo key for caller context that changes
    what a bucket MEANS without changing its packing -- e.g. the exchange
    codec name, so an error-feedback plan (whose bucket sizes fix the
    residual-state shapes) never aliases a plain plan of the same leaves.
    """
    if threshold_bytes is None:
        threshold_bytes = _threshold()
    leaves = [x if hasattr(x, "dtype") else jnp.asarray(x) for x in leaves]
    cache = _get_plan_cache()
    key = plan_key(leaves, threshold_bytes,
                   extra=(("rev",) if reverse else ()) + tuple(extra))
    return cache.get_or_build(
        key, lambda: _plan_buckets_uncached(leaves, threshold_bytes, reverse))


def _plan_buckets_uncached(leaves: Sequence[Any],
                           threshold_bytes: int,
                           reverse: bool = False) -> FusionSpec:
    by_dtype: dict = {}
    indexed = list(enumerate(leaves))
    if reverse:
        indexed.reverse()
    for i, x in indexed:
        by_dtype.setdefault(jnp.dtype(x.dtype), []).append(
            _LeafSpec(i, tuple(x.shape), int(np.prod(x.shape, dtype=np.int64))))
    buffers: List[Tuple[Any, Tuple[_LeafSpec, ...]]] = []
    for dt, specs in by_dtype.items():
        itemsize = jnp.dtype(dt).itemsize
        cur: List[_LeafSpec] = []
        cur_bytes = 0
        for s in specs:
            nbytes = s.size * itemsize
            if cur and cur_bytes + nbytes > threshold_bytes:
                buffers.append((dt, tuple(cur)))
                cur, cur_bytes = [], 0
            cur.append(s)
            cur_bytes += nbytes
        if cur:
            buffers.append((dt, tuple(cur)))
    return FusionSpec(buffers=tuple(buffers), num_leaves=len(leaves))


def plan_eager_flush(leaves: Sequence[Any], k: int,
                     threshold_bytes: Optional[int] = None,
                     extra: Tuple = ()) -> FusionSpec:
    """Bucket plan for the fused deferred-async flush (eager path).

    Same greedy per-dtype packing as :func:`plan_buckets`, but the eager
    layout is RANK-STACKED (``[k, ...]`` with ``k`` local ranks), so
    bucket sizes are counted over each op's per-rank row -- the payload a
    rank actually puts on the wire -- not over the whole stack.  Each
    returned ``_LeafSpec``'s shape/size describe that flat row
    (``size == prod(shape) // k``); ``index`` addresses the caller's leaf
    list as usual.  Memoized in the shared plan cache under an
    eager-flush-scoped key (``extra`` carries caller context such as the
    process-set name).
    """
    if threshold_bytes is None:
        threshold_bytes = _threshold()
    leaves = [x if hasattr(x, "dtype") else jnp.asarray(x) for x in leaves]
    k = max(int(k), 1)
    cache = _get_plan_cache()
    key = plan_key(leaves, threshold_bytes,
                   extra=("eager_flush", k) + tuple(extra))

    def build():
        rows = [jax.ShapeDtypeStruct(
            (int(np.prod(x.shape, dtype=np.int64)) // k,), x.dtype)
            for x in leaves]
        return _plan_buckets_uncached(rows, threshold_bytes)

    return cache.get_or_build(key, build)


def pack(leaves: Sequence[jax.Array], spec: FusionSpec) -> List[jax.Array]:
    """Ravel+concat leaves into flat buffers per the spec."""
    out = []
    for dt, lspecs in spec.buffers:
        if len(lspecs) == 1:
            s = lspecs[0]
            out.append(jnp.ravel(leaves[s.index]))
        else:
            out.append(jnp.concatenate(
                [jnp.ravel(leaves[s.index]) for s in lspecs]))
    return out


def unpack(buffers: Sequence[jax.Array], spec: FusionSpec) -> List[jax.Array]:
    """Slice flat buffers back into the original leaf list order."""
    leaves: List[Optional[jax.Array]] = [None] * spec.num_leaves
    for buf, (dt, lspecs) in zip(buffers, spec.buffers):
        off = 0
        for s in lspecs:
            leaves[s.index] = buf[off:off + s.size].reshape(s.shape)
            off += s.size
    assert all(l is not None for l in leaves)
    return leaves  # type: ignore[return-value]


def fuse_flat(xs: Sequence[jax.Array],
              threshold_bytes: Optional[int] = None
              ) -> Tuple[List[jax.Array], FusionSpec]:
    spec = plan_buckets(xs, threshold_bytes)
    return pack(xs, spec), spec


def unfuse_flat(buffers: Sequence[jax.Array], spec: FusionSpec
                ) -> List[jax.Array]:
    return unpack(buffers, spec)


def fused_tree_collective(tree, collective_fn,
                          threshold_bytes: Optional[int] = None,
                          extra: Tuple = ()):
    """Apply ``collective_fn(flat_buffer) -> flat_buffer`` to a whole pytree
    through the fusion buffers.  This is the gradient hot path used by
    :class:`horovod_tpu.optim.DistributedOptimizer`.  ``extra`` is caller
    context for the plan memo key (see :func:`plan_buckets`).
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    spec = plan_buckets(leaves, threshold_bytes, extra=extra)
    buffers = pack(leaves, spec)
    reduced = [collective_fn(b) for b in buffers]
    return jax.tree.unflatten(treedef, unpack(reduced, spec))


# -- explicit leg planning (two-level exchange) ----------------------------

@dataclasses.dataclass(frozen=True)
class ExchangeLeg:
    """One hop of a bucket's exchange: which mesh axis it moves over,
    which collective it emits, the codec riding that hop, and the
    closed-form operand/wire accounting the spans and the bench gate on.

    ``elements`` is the collective's first-operand element count (what
    the jaxpr auditor records); ``nbytes`` the wire payload bytes the
    matching ``spans.note_leg`` call reports for the leg.
    """
    tag: str          # span tag: hier/ici_rs | hier/dcn_ar | hier/ici_ag
    axis: str         # mesh axis name the leg moves over
    collective: str   # reduce_scatter | psum | all_gather | fp8_gather |
                      # powersgd | topk
    codec: str        # codec name applied on this leg
    wire_dtype: str
    elements: int
    nbytes: int


def hier_mesh_shape() -> Optional[Tuple[int, int]]:
    """``(n_dcn, n_ici)`` when the world mesh is the two-level
    ``(dcn, ici)`` communicator, else ``None``."""
    st = global_state()
    m = st.mesh
    if m is None:
        return None
    names = tuple(m.axis_names)
    if len(names) != 2:
        return None
    return (int(m.shape[names[0]]), int(m.shape[names[1]]))


def hier_requested(compression=None) -> bool:
    """Whether the two-level exchange is in effect for the gradient path:
    a per-leg codec always requests it; otherwise the config flag /
    topology spec or the autotuner's hierarchical axis."""
    from ..collectives.compression import is_hier_legs
    if compression is not None and is_hier_legs(compression):
        return True
    st = global_state()
    cfg = st.config
    if cfg is not None and cfg.hierarchical_allreduce:
        return True
    if cfg is not None and getattr(cfg, "hierarchical", None):
        from ..parallel.mesh import parse_topology_spec
        try:
            if parse_topology_spec(cfg.hierarchical)[0]:
                return True
        except ValueError:
            pass
    if st.autotuner is not None:
        return bool(st.autotuner.hierarchical_explicit())
    return False


def plan_hier_legs(size: int, dtype, *, n_dcn: int, n_ici: int,
                   compression=None, dcn_axis: str = "dcn",
                   ici_axis: str = "ici") -> List[ExchangeLeg]:
    """Closed-form leg plan for one bucket of the two-level exchange.

    Mirrors ``ops.hierarchical_allreduce`` exactly -- padding quantum,
    per-leg wire dtypes, and the ``note_leg`` byte accounting -- so the
    bench's payload gate and the auditor's ``stepmodel`` consume the SAME
    arithmetic the exchange emits.  ``compression`` may be ``None``, a
    cast codec (the bucket is cast before the exchange: every leg rides
    the wire dtype), or a per-leg ``ici:...,dcn:...`` codec.
    """
    from ..collectives.compression import (Compression, is_error_feedback,
                                           is_fp8, is_hier_legs,
                                           is_powersgd, parse_compression,
                                           wire_payload_bytes)
    from ..collectives.ops import microbatch_pad_quantum
    size = int(size)
    dt = jnp.dtype(dtype)
    floating = jnp.issubdtype(dt, jnp.floating)
    comp = parse_compression(compression) if compression is not None \
        else Compression.none
    if is_hier_legs(comp):
        ici_c, dcn_c = comp.ici, comp.dcn
    elif getattr(comp, "wire_format", ""):
        raise ValueError(
            f"{comp.__name__} is an exchange-level codec; the two-level "
            f"path takes it per leg (ici:...,dcn:...)")
    else:
        # A flat cast codec compresses the bucket BEFORE the exchange:
        # the op sees the already-cast buffer, so every leg (padding,
        # shard, and wire accounting included) lives in the wire domain.
        wd = getattr(comp, "wire_dtype", None)
        if (floating and wd is not None
                and jnp.dtype(wd).itemsize < dt.itemsize):
            dt = jnp.dtype(wd)
        ici_c, dcn_c = Compression.none, Compression.none
    if not floating:
        ici_c, dcn_c = Compression.none, Compression.none
    if n_dcn <= 1:
        # Single slice: the op statically falls back to the flat psum.
        return [ExchangeLeg(tag="flat_ar", axis=f"{dcn_axis},{ici_axis}",
                            collective="psum", codec="none",
                            wire_dtype=str(dt), elements=size,
                            nbytes=size * dt.itemsize)]
    quantum = microbatch_pad_quantum(n_ici)
    padded = size + (-size) % quantum
    shard = padded // n_ici
    itemsize = dt.itemsize
    ici_itemsize = itemsize
    ici_dt = str(dt)
    wd = getattr(ici_c, "wire_dtype", None)
    if floating and wd is not None and jnp.dtype(wd).itemsize < itemsize:
        ici_itemsize = jnp.dtype(wd).itemsize
        ici_dt = str(jnp.dtype(wd))
    if is_powersgd(dcn_c):
        dcn_coll, dcn_dt = "powersgd", "float32"
    elif is_error_feedback(dcn_c):
        dcn_coll, dcn_dt = "topk", "float32"
    elif is_fp8(dcn_c):
        dcn_coll, dcn_dt = "fp8_gather", "float8_e4m3fn"
    else:
        dcn_coll = "psum"
        dwd = getattr(dcn_c, "wire_dtype", None)
        dcn_dt = str(jnp.dtype(dwd)) if floating and dwd is not None \
            and jnp.dtype(dwd).itemsize < itemsize else str(dt)
    return [
        ExchangeLeg(tag="hier/ici_rs", axis=ici_axis,
                    collective="reduce_scatter", codec=ici_c.__name__,
                    wire_dtype=ici_dt, elements=padded,
                    nbytes=padded * ici_itemsize),
        ExchangeLeg(tag="hier/dcn_ar", axis=dcn_axis, collective=dcn_coll,
                    codec=dcn_c.__name__, wire_dtype=dcn_dt,
                    elements=shard,
                    nbytes=wire_payload_bytes(dcn_c, shard, itemsize)),
        ExchangeLeg(tag="hier/ici_ag", axis=ici_axis,
                    collective="all_gather", codec=ici_c.__name__,
                    wire_dtype=ici_dt, elements=shard,
                    nbytes=padded * ici_itemsize),
    ]


def plan_moe_alltoall(n_experts: int, capacity: int, d_model: int, *,
                      dtype=jnp.float32, compression=None,
                      axis: str = "model") -> List[ExchangeLeg]:
    """Closed-form leg plan for one MoE layer's all_to_all pair.

    Mirrors ``parallel.moe.moe_ffn`` exactly: the dispatch leg moves the
    f32 ``(E, C, d)`` slot tensor (split experts, concat slots), the
    combine leg moves the same payload back, and ``compression`` (the
    ``HOROVOD_MOE_COMPRESSION`` / autotuner-MoE-axis codec) narrows both
    legs' wire dtype.  ``elements`` is the per-device operand element
    count the jaxpr auditor records for each ``all_to_all``; ``nbytes``
    matches the ``moe/a2a_*`` ``note_leg`` accounting byte-for-byte.
    """
    from ..parallel.moe import _MOE_CODECS, resolve_moe_compression
    codec = resolve_moe_compression(compression)
    wire = _MOE_CODECS[codec]
    dt = jnp.dtype(dtype)
    wire_dt = jnp.dtype(wire) if wire is not None else dt
    elements = int(n_experts) * int(capacity) * int(d_model)
    nbytes = elements * wire_dt.itemsize
    return [
        ExchangeLeg(tag="moe/a2a_dispatch", axis=axis,
                    collective="all_to_all", codec=codec,
                    wire_dtype=str(wire_dt), elements=elements,
                    nbytes=nbytes),
        ExchangeLeg(tag="moe/a2a_combine", axis=axis,
                    collective="all_to_all", codec=codec,
                    wire_dtype=str(wire_dt), elements=elements,
                    nbytes=nbytes),
    ]


# -- plan introspection ----------------------------------------------------

def _fence_policy() -> str:
    """Human-readable fence policy the eager plane would apply to a
    collective dispatched right now (compiled steps never fence: XLA
    schedules their collectives)."""
    st = global_state()
    if st.mesh is None:
        return "unfenced(no-mesh)"
    from ..collectives.eager import _mesh_platform, _transport_needs_fence
    platform = _mesh_platform(st.mesh)
    if _transport_needs_fence(st.mesh):
        return f"barrier+block({platform})"
    return f"compiler-scheduled({platform})"


def explain_plan(params, threshold_bytes: Optional[int] = None,
                 compression=None, reverse: bool = False,
                 extra: Tuple = (), register: bool = True,
                 moe: Optional[dict] = None) -> List[dict]:
    """Render the planner's decision for ``params`` as structured rows.

    One dict per bucket: ``bucket`` index, ``dtype``, ``leaves`` count,
    ``elements``, raw ``bytes``, ``wire_bytes`` under ``compression``
    (a spec string or codec class; None = uncompressed), the ``codec``
    name, the eager ``fence`` policy, and the ``fuse_key`` the plan
    memoizes under.  The rows come from the SAME :func:`plan_buckets`
    call the exchange makes -- error-feedback codecs fold the
    ``("ef", codec)`` context exactly like ``ef_bucket_plan`` -- so
    bucket count and per-bucket bytes match the emitted exchange by
    construction (asserted in tests/test_metrics.py).

    ``register=True`` also publishes the rows as ``horovod_plan_*``
    gauges so ``/metrics`` exposes the current plan.  Printable via
    ``python -m horovod_tpu.run --explain-plan`` (:func:`render_plan`).

    ``moe`` prices a model's MoE all_to_all traffic alongside the
    gradient buckets: a dict with ``n_experts``, ``capacity`` and
    ``d_model`` (optional ``layers`` -- MoE layer count, default 1 --
    plus ``compression`` and ``axis``) appends one extra row whose legs
    come from :func:`plan_moe_alltoall`, one dispatch/combine pair per
    layer.
    """
    from ..collectives.compression import (is_error_feedback,
                                           parse_compression,
                                           wire_payload_bytes)
    leaves = jax.tree.leaves(params)
    comp = parse_compression(compression) if compression is not None \
        else None
    if threshold_bytes is None:
        threshold_bytes = _threshold()
    plan_extra = tuple(extra)
    if comp is not None and is_error_feedback(comp):
        # Mirror optim.distributed.ef_bucket_plan's memo context so the
        # explained plan IS the exchange's plan (same cache entry).
        plan_extra = ("ef", comp.__name__) + plan_extra
    spec = plan_buckets(leaves, threshold_bytes, reverse=reverse,
                        extra=plan_extra)
    codec = comp.__name__ if comp is not None else "none"
    fence = _fence_policy()
    hier_shape = hier_mesh_shape() if hier_requested(comp) else None
    rows = []
    for i, (dt, lspecs) in enumerate(spec.buffers):
        dtype = str(jnp.dtype(dt))
        size = sum(s.size for s in lspecs)
        itemsize = jnp.dtype(dt).itemsize
        raw = size * itemsize
        legs = None
        if hier_shape is not None:
            try:
                legs = plan_hier_legs(size, dt, n_dcn=hier_shape[0],
                                      n_ici=hier_shape[1], compression=comp)
            except ValueError:
                legs = None  # codec the two-level path doesn't route
        if legs is not None:
            wire = sum(l.nbytes for l in legs)
        elif comp is not None:
            wire = wire_payload_bytes(comp, size, itemsize)
        else:
            wire = raw
        rows.append({
            "bucket": i, "dtype": dtype, "leaves": len(lspecs),
            "elements": int(size), "bytes": int(raw),
            "wire_bytes": int(wire), "codec": codec, "fence": fence,
            "fuse_key": "|".join(
                [dtype, f"thr={int(threshold_bytes)}", codec]
                + (["rev"] if reverse else [])),
            "legs": [dataclasses.asdict(l) for l in legs]
            if legs is not None else None,
        })
    if moe is not None:
        layers = int(moe.get("layers", 1))
        pair = plan_moe_alltoall(
            moe["n_experts"], moe["capacity"], moe["d_model"],
            dtype=moe.get("dtype", jnp.float32),
            compression=moe.get("compression"),
            axis=moe.get("axis", "model"))
        moe_legs = pair * layers
        elements = sum(l.elements for l in moe_legs)
        raw = elements * jnp.dtype(moe.get("dtype", jnp.float32)).itemsize
        rows.append({
            "bucket": len(rows), "dtype": pair[0].wire_dtype,
            "leaves": 0, "elements": int(elements), "bytes": int(raw),
            "wire_bytes": int(sum(l.nbytes for l in moe_legs)),
            "codec": pair[0].codec, "fence": fence,
            "fuse_key": "|".join(
                ["moe", f"E={int(moe['n_experts'])}",
                 f"C={int(moe['capacity'])}", f"d={int(moe['d_model'])}",
                 f"L={layers}", pair[0].codec]),
            "legs": [dataclasses.asdict(l) for l in moe_legs],
        })
    if register:
        register_plan_gauges(rows)
    return rows


def register_plan_gauges(rows: List[dict]) -> None:
    """Publish explain_plan rows into the metrics registry."""
    from ..timeline import metrics as _metrics
    reg = _metrics.registry()
    reg.gauge("horovod_plan_buckets",
              "Bucket count of the most recently explained exchange plan"
              ).set(len(rows))
    by_bytes = reg.gauge(
        "horovod_plan_bucket_bytes",
        "Raw bytes per bucket of the explained plan",
        labelnames=("bucket", "dtype"))
    by_wire = reg.gauge(
        "horovod_plan_bucket_wire_bytes",
        "Wire bytes per bucket of the explained plan",
        labelnames=("bucket", "dtype"))
    for r in rows:
        labels = {"bucket": str(r["bucket"]), "dtype": r["dtype"]}
        by_bytes.labels(**labels).set(r["bytes"])
        by_wire.labels(**labels).set(r["wire_bytes"])


def render_plan(rows: List[dict]) -> str:
    """Fixed-width table rendering of :func:`explain_plan` rows."""
    if not rows:
        return "(empty plan: no leaves)"
    cols = ("bucket", "dtype", "leaves", "elements", "bytes",
            "wire_bytes", "codec", "fence", "fuse_key")
    table = [cols] + [tuple(str(r[c]) for c in cols) for r in rows]
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    lines = ["  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
             for row in table]
    lines.insert(1, "  ".join("-" * w for w in widths))
    for r in rows:
        for leg in r.get("legs") or ():
            lines.append(
                f"    bucket {r['bucket']} leg {leg['tag']}: "
                f"{leg['collective']}@{leg['axis']} codec={leg['codec']} "
                f"{leg['wire_dtype']} {leg['elements']}el {leg['nbytes']}B")
    total_raw = sum(r["bytes"] for r in rows)
    total_wire = sum(r["wire_bytes"] for r in rows)
    ratio = f" (ratio {total_raw / total_wire:.1f}x)" \
        if 0 < total_wire < total_raw else ""
    lines.append(f"total: {len(rows)} bucket(s), {total_raw} bytes raw, "
                 f"{total_wire} bytes wire{ratio}")
    return "\n".join(lines)
