"""Expert parallelism: Switch-style MoE layer with all_to_all dispatch.

The reference's only MoE-relevant primitive is ``hvd.alltoall`` (SURVEY.md
sections 3.8/5.7 -- "the primitive MoE users call manually").  Here the
whole layer is first-class: a top-k router with capacity, an ``all_to_all``
that moves token slots to the ranks owning their experts over the ``ep``
mesh axis, dense expert FFNs batched on the MXU, and the return
``all_to_all`` + weighted combine.  The dispatch/combine use the standard
one-hot einsum formulation (Switch Transformer, arXiv:2101.03961), which
XLA fuses into the surrounding matmuls; dropped tokens (over capacity)
pass through with zero expert contribution, as in the original.

SPMD layout inside ``shard_map``: tokens sharded over ``ep`` (each rank
holds t_l tokens), experts sharded over ``ep`` (each rank holds
E / ep_size experts, so E % ep_size == 0).  Router params are replicated.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..collectives import ops as _ops
from .mesh import EP_AXIS

# Wire codecs for the two MoE all_to_all legs: the (E, C, d) f32 slot
# tensors are cast down before the shuffle and back up after it.  The
# expert matmuls and the weighted combine still run on the full-precision
# values, so only the wire payload narrows (same contract as the fp16/bf16
# gradient codecs in ``collectives.compression``).
_MOE_CODECS = {"none": None, "bf16": jnp.bfloat16, "fp16": jnp.float16}


def resolve_moe_compression(compression=None):
    """Resolve the MoE all_to_all wire codec: explicit argument, else the
    autotuner's MoE axis (``HOROVOD_AUTOTUNE_MOE=1``), else the config's
    ``HOROVOD_MOE_COMPRESSION``.  Returns ``"none"``/``"bf16"``/``"fp16"``."""
    if compression is None:
        from ..core.state import global_state
        st = global_state()
        tuner = st.autotuner
        if tuner is not None and getattr(tuner, "tunes_moe", False):
            compression = tuner.moe_codec()
        elif st.config is not None and st.config.moe_compression:
            compression = st.config.moe_compression
    name = str(compression or "none").lower()
    if name not in _MOE_CODECS:
        raise ValueError(
            f"unknown MoE compression {compression!r}: expected one of "
            f"{sorted(_MOE_CODECS)}")
    return name


def _a2a_leg(slots, *, axis, split_axis, concat_axis, codec, leg):
    """One MoE all_to_all leg: note the plan-IR row (tag + planned wire
    payload) for the trace auditor, cast to the wire dtype, shuffle,
    cast back to f32."""
    from ..timeline import spans as _spans
    wire = _MOE_CODECS[codec]
    _spans.note_leg(leg)
    if wire is not None:
        slots = slots.astype(wire)
    out = _ops.alltoall(slots, axes=axis, split_axis=split_axis,
                        concat_axis=concat_axis)
    return out.astype(jnp.float32)


def moe_ffn(x, router_kernel, w_up, w_down, *, capacity_factor: float = 1.25,
            top_k: int = 1, axis: str = EP_AXIS,
            activation: Callable = jax.nn.gelu,
            router_noise_rng: Optional[jax.Array] = None,
            compression: Optional[str] = None):
    """Mixture-of-experts FFN over the ``ep`` axis.

    Local shapes: x (t_l, d); router_kernel (d, E) replicated;
    w_up (E_l, d, f) and w_down (E_l, f, d) sharded on the expert dim
    (E_l = E / ep).  Returns ``(y, aux_loss)``: the (t_l, d) output and
    the scalar Switch load-balance loss (add ``~1e-2 * aux`` to the
    training loss).

    Capacity is per source rank: ``C = ceil(top_k * t_l / E *
    capacity_factor)`` slots per (rank, expert), so each expert receives
    up to ``ep * C`` tokens globally -- the Switch per-device capacity
    rule, and every rank derives the same static C so shapes stay static
    for XLA.

    ``compression`` picks the wire codec for the two all_to_all legs
    (``"bf16"``/``"fp16"``/``"none"``); ``None`` defers to the autotuner's
    MoE axis and then ``HOROVOD_MOE_COMPRESSION`` -- see
    :func:`resolve_moe_compression`.
    """
    codec = resolve_moe_compression(compression)
    ep = jax.lax.axis_size(axis)
    t_l, d = x.shape
    e_local = w_up.shape[0]
    n_experts = e_local * ep
    capacity = int(max(4, -(-top_k * t_l * capacity_factor // n_experts)))

    logits = x.astype(jnp.float32) @ router_kernel.astype(jnp.float32)
    if router_noise_rng is not None:
        logits = logits + jax.random.gumbel(router_noise_rng, logits.shape)
    probs = jax.nn.softmax(logits, axis=-1)  # (t_l, E)

    # Top-k dispatch masks with per-expert position (capacity) accounting.
    dispatch = jnp.zeros((t_l, n_experts, capacity), jnp.float32)
    combine = jnp.zeros((t_l, n_experts, capacity), jnp.float32)
    position_base = jnp.zeros((n_experts,), jnp.int32)
    remaining = probs
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                    # (t_l,)
        onehot = jax.nn.one_hot(idx, n_experts, dtype=jnp.float32)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot       # (t_l, E)
        pos = pos + position_base[None, :] * onehot
        keep = (pos < capacity) * onehot                        # (t_l, E)
        slot = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32), capacity,
                              dtype=jnp.float32)                # (t_l, C)
        gate = (probs * onehot).sum(-1, keepdims=True)          # (t_l, 1)
        dispatch = dispatch + keep[:, :, None] * slot[:, None, :]
        combine = combine + gate[..., None] * keep[:, :, None] \
            * slot[:, None, :]
        position_base = position_base + onehot.sum(0).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)

    # Both shuffle legs come from the shared exchange-plan IR (one
    # memoized plan per (E, C, d, codec, axis) shape).
    from ..controller import fusion as _fusion
    mplan = _fusion.plan_exchange(
        "moe", n_experts=n_experts, capacity=capacity, d_model=d,
        compression=codec, axis=axis)
    # (t_l, E, C) x (t_l, d) -> (E, C, d): slots for every global expert.
    slots = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # all_to_all: split the expert dim across ranks, concat token slots ->
    # (E_l, ep * C, d): every slot destined for my local experts.
    slots = _a2a_leg(slots, axis=axis, split_axis=0, concat_axis=1,
                     codec=codec, leg=mplan.legs[0])
    h = jnp.einsum("ecd,edf->ecf", slots.astype(x.dtype), w_up)
    h = activation(h)
    out = jnp.einsum("ecf,efd->ecd", h, w_down)
    # Route results back: split slots, concat experts -> (E, C, d).
    out = _a2a_leg(out.astype(jnp.float32), axis=axis, split_axis=1,
                   concat_axis=0, codec=codec, leg=mplan.legs[1])
    y = jnp.einsum("tec,ecd->td", combine, out)
    return y.astype(x.dtype), _load_balance_loss(probs, dispatch)


def _load_balance_loss(probs, dispatch):
    """Switch aux loss: E * dot(mean router prob, mean tokens-per-expert)."""
    n_experts = probs.shape[-1]
    density = dispatch.sum(-1).mean(0)        # fraction routed per expert
    density_proxy = probs.mean(0)             # mean router prob per expert
    return n_experts * jnp.sum(density * density_proxy)


def init_moe_params(rng, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32):
    """Replicated-layout MoE params: router (d, E), w_up (E, d, f),
    w_down (E, f, d).  Shard the expert dim over ``ep`` before shard_map."""
    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = d_model ** -0.5
    return {
        "router": jax.random.normal(k1, (d_model, n_experts),
                                    jnp.float32) * scale_in,
        "w_up": (jax.random.normal(k2, (n_experts, d_model, d_ff),
                                   jnp.float32) * scale_in).astype(dtype),
        "w_down": (jax.random.normal(k3, (n_experts, d_ff, d_model),
                                     jnp.float32)
                   * d_ff ** -0.5).astype(dtype),
    }
