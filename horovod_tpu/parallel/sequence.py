"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has *no* sequence-dim sharding anywhere (SURVEY.md section
5.7); its ``alltoall`` op is the only primitive a user could build Ulysses
from.  On TPU, long-context is first-class, so both schemes ship here as
SPMD functions for use inside ``jax.shard_map`` with the sequence dim
sharded over the ``sp`` mesh axis:

* **Ring attention** (Liu et al., arXiv:2310.01889): K/V blocks circulate
  around the sp ring via ``ppermute`` while each rank's queries stay put;
  partial attention outputs merge with the online-softmax rule (running
  max / sum-of-exp), so the full (t x t) score matrix never materialises
  and per-chip memory stays O(t/sp).  Compute-comm overlap comes from XLA
  pipelining the ppermute against the block matmuls; causal masking uses
  global positions so blocks strictly in the future are skipped
  numerically (their contribution multiplies to zero weight).

* **Ulysses** (Jacobs et al., arXiv:2309.14509): two ``all_to_all``s swap
  the sharding between the sequence dim and the heads dim, so the full
  sequence is local during attention (enabling the Pallas flash kernel)
  with heads/sp sharded instead.  Requires ``num_heads % sp == 0``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..collectives import ops as _ops
from .mesh import SP_AXIS

_NEG_INF = -1e30


def ring_attention(q, k, v, *, causal: bool = False,
                   scale: Optional[float] = None, axis: str = SP_AXIS,
                   segment_ids=None):
    """Attention over a sequence sharded on the ``axis`` ring.

    Shapes (local shards): q (b, h, t_l, d), k/v (b, h, t_l, d), where the
    global sequence length is ``t_l * sp`` and rank r holds positions
    ``[r*t_l, (r+1)*t_l)``.  Returns the local output shard (b, h, t_l, d).

    ``segment_ids`` (local shard, ``(b, t_l)`` int): packed-sequence /
    padding masking with the same semantics as
    :func:`horovod_tpu.ops.flash_attention` -- queries attend only
    equal-id keys.  The kv id shard circulates the ring alongside K/V
    (int traffic, negligible next to the kv blocks).  One id vector
    serves both sides (self-attention), so a pad segment attends itself
    -- truly dead rows cannot arise here; the zero-output guard below is
    defensive, matching the flash kernel's dead-row semantics anyway.

    Numerics are f32 online-softmax regardless of input dtype (matching
    the Pallas flash kernel's accumulator discipline); output is cast back
    to the input dtype.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    sp = jax.lax.axis_size(axis)
    my = jax.lax.axis_index(axis)
    b, h, t_l, d = q.shape
    out_dtype = q.dtype

    qf = q.astype(jnp.float32) * scale
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    q_pos = my * t_l + jnp.arange(t_l)  # global positions of local queries

    def merge_block(state, kb, vb, kseg_b, src):
        """Online-softmax merge of the block that originated at rank src."""
        m, l, acc = state
        scores = jnp.einsum("bhtd,bhsd->bhts", qf, kb.astype(jnp.float32))
        if causal:
            k_pos = src * t_l + jnp.arange(t_l)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG_INF)
        if segment_ids is not None:
            smask = (segment_ids[:, None, :, None]
                     == kseg_b[:, None, None, :])
            scores = jnp.where(smask, scores, _NEG_INF)
        block_m = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, block_m)
        # Renormalise the running accumulator to the new max.
        correction = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])
        l = l * correction + jnp.sum(p, axis=-1)
        acc = (acc * correction[..., None]
               + jnp.einsum("bhts,bhsd->bhtd", p, vb.astype(jnp.float32)))
        return new_m, l, acc

    m0 = jnp.full((b, h, t_l), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_l), jnp.float32)
    acc0 = jnp.zeros((b, h, t_l, d), jnp.float32)
    has_seg = segment_ids is not None
    # The kv-id shard rides the ring only when packing is active; the
    # default path carries no id tensor and issues no id ppermute.
    kseg0 = segment_ids if has_seg else None
    # Local block first (no comm), then sp-1 ring rotations: permute at the
    # top of each step so no dead final transfer is issued.
    state = merge_block((m0, l0, acc0), k, v, kseg0, my)

    def step(carry, s):
        kb, vb, kseg_b, state = carry
        kb = _ops.ppermute(kb, perm, axes=axis)
        vb = _ops.ppermute(vb, perm, axes=axis)
        if has_seg:
            kseg_b = _ops.ppermute(kseg_b, perm, axes=axis)
        state = merge_block(state, kb, vb, kseg_b, (my - s) % sp)
        return (kb, vb, kseg_b, state), ()

    if sp > 1:
        (kb, vb, kseg_b, state), _ = jax.lax.scan(
            step, (k, v, kseg0, state), jnp.arange(1, sp))
    m, l, acc = state
    # Fully-masked rows are unreachable here (one shared id vector:
    # every token matches at least itself, and plain causal always sees
    # the diagonal); the guard is purely defensive, kept aligned with
    # flash_attention's dead-row zero-output semantics.
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = acc / safe_l[..., None]
    if segment_ids is not None:
        out = jnp.where((m <= _NEG_INF / 2)[..., None], 0.0, out)
    return out.astype(out_dtype)


def ulysses_attention(q, k, v, *, causal: bool = False,
                      scale: Optional[float] = None, axis: str = SP_AXIS,
                      attn_fn=None, segment_ids=None):
    """Ulysses attention: all_to_all seq<->heads, local attention between.

    Local input shards: (b, h, t_l, d) with the *sequence* sharded.  After
    the first all_to_all each rank holds (b, h/sp, t, d) -- full sequence,
    a slice of heads -- so any single-device attention kernel applies;
    ``attn_fn(q, k, v, causal=..., scale=...)`` defaults to the fused
    Pallas flash attention.  A second all_to_all restores seq sharding.

    ``segment_ids`` (local shard, ``(b, t_l)`` int): the full-sequence id
    vector is reassembled with one tiny ``all_gather`` and handed to
    ``attn_fn`` (which must accept ``segment_ids=``, as
    :func:`flash_attention` does -- packing there also prunes whole
    block pairs).
    """
    if attn_fn is None:
        from horovod_tpu.ops.attention import flash_attention
        attn_fn = flash_attention
    sp = jax.lax.axis_size(axis)
    if q.shape[1] % sp:
        raise ValueError(f"heads {q.shape[1]} not divisible by sp={sp}")

    # (b, h, t_l, d): split heads (axis 1) across ranks, gather seq (2).
    to_seq = partial(_ops.alltoall, axes=axis, split_axis=1, concat_axis=2)
    to_heads = partial(_ops.alltoall, axes=axis, split_axis=2,
                       concat_axis=1)
    kwargs = {}
    if segment_ids is not None:
        kwargs["segment_ids"] = _ops.allgather(segment_ids, axes=axis,
                                               axis=1, tiled=True)
    o = attn_fn(to_seq(q), to_seq(k), to_seq(v), causal=causal,
                scale=scale, **kwargs)
    return to_heads(o)
