"""Parallelism strategies over the device mesh.

DP (the reference's whole scope) lives in ``collectives``/``optim``; this
package adds the model-parallel axes the TPU fabric makes first-class:
tensor (``tp``), sequence/context (``sequence``: ring attention, Ulysses),
pipeline (``pipeline``) and expert (``moe``) parallelism, all as SPMD
functions composed inside ``jax.shard_map`` over a
:func:`build_parallel_mesh` ``(dp, pp, ep, sp, tp)`` mesh.
"""

from .mesh import (  # noqa: F401
    DCN_AXIS, DP_AXIS, EP_AXIS, FLAT_AXES, HIER_AXES, HVD_AXIS, ICI_AXIS,
    PARALLEL_AXES, PP_AXIS, SP_AXIS, TP_AXIS, build_mesh,
    build_parallel_mesh, mesh_axes, mesh_size,
)
from .tp import (  # noqa: F401
    column_parallel, row_parallel, shard_tp_params, tp_mlp,
)
from .sequence import ring_attention, ulysses_attention  # noqa: F401
from .pipeline import (  # noqa: F401
    pipeline_apply, split_microbatches, stack_stage_params,
)
from .moe import init_moe_params, moe_ffn  # noqa: F401
