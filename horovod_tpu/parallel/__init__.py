"""Parallelism strategies over the device mesh.

DP (the reference's whole scope) lives in ``collectives``/``optim``; this
package adds the model-parallel axes the TPU fabric makes first-class:
tensor (``tp``), sequence/context (``sequence``: ring attention, Ulysses),
pipeline (``pipeline``) and expert (``moe``) parallelism, all as SPMD
functions composed inside ``jax.shard_map`` over a
:func:`build_parallel_mesh` ``(dp, pp, ep, sp, tp)`` mesh.
"""

from .mesh import (  # noqa: F401
    DATA_AXIS, DCN_AXIS, DP_AXIS, EP_AXIS, FLAT_AXES, HIER_AXES, HVD_AXIS,
    ICI_AXIS, MODEL_AXIS, MODEL_PARALLEL_AXES, PARALLEL_AXES, PIPE_AXIS,
    PP_AXIS, SP_AXIS, THREED_AXES, TP_AXIS, build_3d_mesh, build_mesh,
    build_parallel_mesh, data_axes, mesh_axes, mesh_size, model_axes,
)
from .tp import (  # noqa: F401
    column_parallel, copy_to_tp, reduce_from_tp, row_parallel,
    shard_tp_params, tp_mlp,
    tp_param_specs,
)
from .sequence import ring_attention, ulysses_attention  # noqa: F401
from .pipeline import (  # noqa: F401
    pipeline_apply, split_microbatches, stack_stage_params,
)
from .moe import (  # noqa: F401
    init_moe_params, moe_ffn, resolve_moe_compression,
)
