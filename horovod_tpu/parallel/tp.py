"""Tensor parallelism: Megatron-style column/row-parallel projections.

Not in the reference (SURVEY.md section 3.8 -- it ships ``alltoall`` as the
only building block and no TP anywhere); built here TPU-first because the
ICI mesh makes TP a first-class strategy.  The design is the standard
pairing (Shoeybi et al., arXiv:1909.08053) expressed as SPMD functions for
use inside ``jax.shard_map`` over the ``tp`` mesh axis:

* ``column_parallel``: kernel split on the *output* dim; no communication
  in forward (the input is replicated over tp), each rank holds an output
  shard.  The backward psum over input grads is inserted by autodiff.
* ``row_parallel``: kernel split on the *input* dim; forward ends in one
  ``psum`` over tp.  Backward needs no collective.

A column->row pair (e.g. FFN up/down, or QKV->output projection) therefore
costs exactly one allreduce forward and one backward -- both of which XLA
overlaps with the surrounding matmuls on the MXU.

These are *functions over local shards*, not flax modules: inside
``shard_map`` the params pytree is already sharded (kernel leading/trailing
dims carry the tp extent), so modules would just obscure which collectives
run.  ``shard_tp_params`` produces the sharded kernels from a replicated
init.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..collectives import ops as _ops
from ..collectives.reduce_op import Sum
from .mesh import TP_AXIS


def column_parallel(x, kernel, bias=None, *, axis: str = TP_AXIS):
    """y_local = x @ kernel_local (+ bias_local).

    ``kernel``: local shard (d_in, d_out / tp).  Output is sharded on the
    feature dim; follow with :func:`row_parallel` (or an all_gather if the
    sharded activation is needed whole).  ``axis`` is unused in forward
    math but documents the pairing; keep it for symmetry.
    """
    del axis
    y = x @ kernel
    if bias is not None:
        y = y + bias
    return y


def row_parallel(x, kernel, bias=None, *, axis: str = TP_AXIS):
    """y = psum_tp(x_local @ kernel_local) (+ bias).

    ``x``: activation sharded on the feature dim (d_model / tp), as
    produced by :func:`column_parallel`.  ``kernel``: local shard
    (d_in / tp, d_out).  Bias is added *after* the psum (it is replicated;
    adding per-rank would multiply it by tp).
    """
    y = _ops.allreduce(x @ kernel, Sum, axes=axis)
    if bias is not None:
        y = y + bias
    return y


def shard_tp_params(params, tp_rank, tp_size, *, column_keys=("wq", "wk",
                    "wv", "w_gate", "w_up", "w_in"),
                    row_keys=("wo", "w_down", "w_out")):
    """Slice a replicated transformer param tree into this rank's TP shard.

    Column-parallel kernels are split on the output (last) dim, row-parallel
    on the input (first of the 2D kernel) dim.  Key sets default to the
    ``horovod_tpu.models.transformer`` naming; anything else is left
    replicated.  Works on host or device trees; intended for tests and for
    preparing per-rank shards fed to ``shard_map``.
    """

    def shard(path, leaf):
        names = [getattr(k, "key", "") for k in path]
        if "kernel" not in names or leaf.ndim < 2:
            return leaf
        owner = names[-2] if names[-1] == "kernel" else ""
        if owner in column_keys:
            if leaf.shape[-1] % tp_size:
                raise ValueError(
                    f"{owner}.kernel output dim {leaf.shape[-1]} not "
                    f"divisible by tp={tp_size}")
            width = leaf.shape[-1] // tp_size
            return leaf[..., tp_rank * width:(tp_rank + 1) * width]
        if owner in row_keys:
            if leaf.shape[0] % tp_size:
                raise ValueError(
                    f"{owner}.kernel input dim {leaf.shape[0]} not "
                    f"divisible by tp={tp_size}")
            width = leaf.shape[0] // tp_size
            return leaf[tp_rank * width:(tp_rank + 1) * width]
        return leaf

    return jax.tree_util.tree_map_with_path(shard, params)


def tp_mlp(x, w_up, w_down, *, axis: str = TP_AXIS,
           activation=jax.nn.silu, w_gate: Optional[jnp.ndarray] = None):
    """Column->row parallel MLP: one fused psum for the whole block.

    With ``w_gate`` supplied this is the SwiGLU used by the Llama family;
    without, a plain 2-layer MLP.  ``w_up``/``w_gate`` are column shards,
    ``w_down`` a row shard.
    """
    up = column_parallel(x, w_up)
    if w_gate is not None:
        up = activation(column_parallel(x, w_gate)) * up
    else:
        up = activation(up)
    return row_parallel(up, w_down, axis=axis)
