"""Tensor parallelism: Megatron-style column/row-parallel projections.

Not in the reference (SURVEY.md section 3.8 -- it ships ``alltoall`` as the
only building block and no TP anywhere); built here TPU-first because the
ICI mesh makes TP a first-class strategy.  The design is the standard
pairing (Shoeybi et al., arXiv:1909.08053) expressed as SPMD functions for
use inside ``jax.shard_map`` over the ``tp`` mesh axis:

* ``column_parallel``: kernel split on the *output* dim; no communication
  in forward (the input is replicated over tp), each rank holds an output
  shard.  The input's cotangent is a per-rank PARTIAL sum; wrap the input
  with :func:`copy_to_tp` (Megatron's "f" operator) so backward closes it
  with one psum -- ``shard_map(check_vma=False)`` will NOT insert it.
* ``row_parallel``: kernel split on the *input* dim; forward ends in one
  ``psum`` over tp whose backward is IDENTITY (the "g" operator, pinned
  via ``custom_vjp`` -- the raw psum transposes to another psum, which
  would scale every upstream gradient by the tp extent).

A column->row pair (e.g. FFN up/down, or QKV->output projection) therefore
costs exactly one allreduce forward and one backward -- both of which XLA
overlaps with the surrounding matmuls on the MXU.

These are *functions over local shards*, not flax modules: inside
``shard_map`` the params pytree is already sharded (kernel leading/trailing
dims carry the tp extent), so modules would just obscure which collectives
run.  ``shard_tp_params`` produces the sharded kernels from a replicated
init.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..collectives import ops as _ops
from ..collectives.reduce_op import Sum
from .mesh import TP_AXIS


def copy_to_tp(x, *, axis: str = TP_AXIS):
    """Megatron "f": identity forward, ``psum`` over ``axis`` backward.

    Place on an activation that feeds column-parallel layers.  Each tp
    rank's backward produces only its shard's contribution to the input
    cotangent; the psum here merges them so everything upstream (layer
    norms, embeddings, the residual stream) sees the FULL gradient.  One
    ``copy_to_tp`` covers every column layer reading the same tensor
    (q/k/v, or up+gate), costing a single backward allreduce per block.
    """

    @jax.custom_vjp
    def f(y):
        return y

    f.defvjp(lambda y: (y, None),
             lambda _, g: (_ops.allreduce(g, Sum, axes=axis),))
    return f(x)


def reduce_from_tp(x, *, axis: str = TP_AXIS):
    """Megatron "g": ``psum`` over ``axis`` forward, identity backward.

    The closing allreduce of a row-parallel layer.  The backward MUST be
    identity -- the output cotangent is already replicated over tp, and
    the raw psum's transpose is another psum, which would multiply every
    upstream gradient by the tp extent.
    """

    @jax.custom_vjp
    def g_op(y):
        return _ops.allreduce(y, Sum, axes=axis)

    g_op.defvjp(lambda y: (_ops.allreduce(y, Sum, axes=axis), None),
                lambda _, g: (g,))
    return g_op(x)


def column_parallel(x, kernel, bias=None, *, axis: str = TP_AXIS):
    """y_local = x @ kernel_local (+ bias_local).

    ``kernel``: local shard (d_in, d_out / tp).  Output is sharded on the
    feature dim; follow with :func:`row_parallel` (or an all_gather if the
    sharded activation is needed whole).  ``axis`` is unused in forward
    math but documents the pairing; keep it for symmetry.
    """
    del axis
    y = x @ kernel
    if bias is not None:
        y = y + bias
    return y


def row_parallel(x, kernel, bias=None, *, axis: str = TP_AXIS):
    """y = psum_tp(x_local @ kernel_local) (+ bias).

    ``x``: activation sharded on the feature dim (d_model / tp), as
    produced by :func:`column_parallel`.  ``kernel``: local shard
    (d_in / tp, d_out).  Bias is added *after* the psum (it is replicated;
    adding per-rank would multiply it by tp).  The psum rides
    :func:`reduce_from_tp`, so its backward is identity.
    """
    y = reduce_from_tp(x @ kernel, axis=axis)
    if bias is not None:
        y = y + bias
    return y


def shard_tp_params(params, tp_rank, tp_size, *, column_keys=("wq", "wk",
                    "wv", "w_gate", "w_up", "w_in"),
                    row_keys=("wo", "w_down", "w_out")):
    """Slice a replicated transformer param tree into this rank's TP shard.

    Column-parallel kernels are split on the output (last) dim, row-parallel
    on the input (first of the 2D kernel) dim.  Key sets default to the
    ``horovod_tpu.models.transformer`` naming; anything else is left
    replicated.  Works on host or device trees; intended for tests and for
    preparing per-rank shards fed to ``shard_map``.
    """

    def shard(path, leaf):
        names = [getattr(k, "key", "") for k in path]
        if "kernel" not in names or leaf.ndim < 2:
            return leaf
        owner = names[-2] if names[-1] == "kernel" else ""
        if owner in column_keys:
            if leaf.shape[-1] % tp_size:
                raise ValueError(
                    f"{owner}.kernel output dim {leaf.shape[-1]} not "
                    f"divisible by tp={tp_size}")
            width = leaf.shape[-1] // tp_size
            return leaf[..., tp_rank * width:(tp_rank + 1) * width]
        if owner in row_keys:
            if leaf.shape[0] % tp_size:
                raise ValueError(
                    f"{owner}.kernel input dim {leaf.shape[0]} not "
                    f"divisible by tp={tp_size}")
            width = leaf.shape[0] // tp_size
            return leaf[tp_rank * width:(tp_rank + 1) * width]
        return leaf

    return jax.tree_util.tree_map_with_path(shard, params)


def tp_param_specs(params, *, axis: str = TP_AXIS,
                   column_keys=("wq", "wk", "wv", "w_gate", "w_up", "w_in"),
                   row_keys=("wo", "w_down", "w_out")):
    """PartitionSpec tree for a TP train step over natural-dim shards.

    The train-side counterpart of ``serving.decode_param_specs``, same
    key convention (`shard_tp_params`): column kernels split on the
    output dim ``P(None, axis)``, row kernels on the input dim
    ``P(axis, None)``, everything else replicated -- with one training
    difference: column-layer BIASES are split ``P(axis)`` too.  A bias
    added before the row psum lives on the sharded feature dim, so its
    gradient is per-shard; leaving it replicated (the serving layout,
    where params are read-only) would let tp ranks diverge, since the
    DP exchange averages over the data axes only.  Row-layer biases add
    after the psum on replicated activations and stay ``P()``.

    Pass the result as ``make_train_step(..., tp=..., param_specs=...)``;
    the checkpoint saved from the step reassembles the FULL tree (the
    out_specs concatenate the shards), so it loads directly into the
    serving plane's replicated-params decode path.
    """
    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        names = [getattr(k, "key", "") for k in path]
        if len(names) < 2 or names[-1] not in ("kernel", "bias"):
            return P()
        owner = names[-2]
        if owner in column_keys:
            if names[-1] == "kernel" and leaf.ndim == 2:
                return P(None, axis)
            if names[-1] == "bias" and leaf.ndim == 1:
                return P(axis)
        elif owner in row_keys and names[-1] == "kernel" \
                and leaf.ndim == 2:
            return P(axis, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def tp_mlp(x, w_up, w_down, *, axis: str = TP_AXIS,
           activation=jax.nn.silu, w_gate: Optional[jnp.ndarray] = None):
    """Column->row parallel MLP: one fused psum for the whole block.

    With ``w_gate`` supplied this is the SwiGLU used by the Llama family;
    without, a plain 2-layer MLP.  ``w_up``/``w_gate`` are column shards,
    ``w_down`` a row shard.  The input rides one :func:`copy_to_tp` (both
    column layers read it), so the block costs exactly one allreduce
    forward and one backward.
    """
    x = copy_to_tp(x, axis=axis)
    up = column_parallel(x, w_up)
    if w_gate is not None:
        up = activation(column_parallel(x, w_gate)) * up
    else:
        up = activation(up)
    return row_parallel(up, w_down, axis=axis)
