"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

Not in the reference (SURVEY.md section 3.8: DP only).  TPU-first design:
the pipeline is a *collective* program -- every rank runs the same scan;
stage-to-stage transfer is a ``ppermute`` shift over the ``pp`` axis, which
on TPU compiles to a neighbour DMA over ICI.  The schedule is GPipe
(fill, steady state, drain): with S stages and M microbatches the loop runs
``M + S - 1`` ticks and bubble fraction (S-1)/(M+S-1).

The stage function is applied to *this rank's* stage params, so the params
pytree fed to :func:`pipeline_apply` must carry a leading stage dim sharded
over ``pp`` (use :func:`stack_stage_params` + shard_map in_specs).
Backward is pure autodiff: reverse-mode turns the forward ppermute shift
into the reverse shift, giving the standard 1F-then-1B schedule without any
hand-written backward plumbing.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..collectives import ops as _ops
from ..collectives.reduce_op import Sum
from .mesh import PP_AXIS


def stack_stage_params(per_stage_params) -> Any:
    """Stack a list of per-stage param pytrees along a new leading dim.

    The result is what you shard over ``pp`` (spec ``P('pp', ...)`` on
    every leaf) before calling :func:`pipeline_apply` inside shard_map.
    """
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stage_params: Any, microbatches: jnp.ndarray,
                   *, axis: str = PP_AXIS) -> jnp.ndarray:
    """Run microbatches through the stage pipeline; SPMD over ``axis``.

    Args:
      stage_fn: ``(params_for_one_stage, x) -> y`` with ``y.shape ==
        x.shape`` (inter-stage activations must be shape-invariant, as in
        any homogeneous-stage pipeline).
      stage_params: *local* param shard inside shard_map -- leading dim 1
        (this rank's stage); squeezed internally.
      microbatches: (M, mb, ...) -- the same array on every pp rank
        (replicated over ``axis``; other mesh axes may shard the mb dim).

    Returns:
      (M, mb, ...) final-stage outputs, identical on every pp rank
      (the last stage's results are broadcast with a psum-mask, so the
      loss can be computed uniformly).
    """
    size = jax.lax.axis_size(axis)
    my = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    m = microbatches.shape[0]
    ticks = m + size - 1
    perm = [(i, (i + 1) % size) for i in range(size)]
    zero_mb = jnp.zeros_like(microbatches[0])

    def tick(carry, t):
        incoming, outputs = carry
        # Stage 0 injects microbatch t (while t < m); later stages consume
        # what arrived from the left neighbour.
        mb_in = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, m - 1), keepdims=False)
        mb_in = jnp.where(t < m, mb_in, zero_mb)
        x = jnp.where(my == 0, mb_in, incoming)
        y = stage_fn(params, x)
        # Last stage banks microbatch (t - size + 1) once it's real.
        out_idx = t - (size - 1)
        banked = jax.lax.dynamic_update_index_in_dim(
            outputs, y, jnp.maximum(out_idx, 0), axis=0)
        outputs = jnp.where(out_idx >= 0, banked, outputs)
        incoming = _ops.ppermute(y, perm, axes=axis)
        return (incoming, outputs), ()

    outputs0 = jnp.zeros((m,) + microbatches.shape[1:],
                         microbatches.dtype)
    (_, outputs), _ = jax.lax.scan(
        tick, (zero_mb, outputs0), jnp.arange(ticks))
    # Only the last rank's bank is real; broadcast it over the pp axis.
    outputs = jnp.where(my == size - 1, outputs, jnp.zeros_like(outputs))
    return _ops.allreduce(outputs, Sum, axes=axis)


def split_microbatches(batch: jnp.ndarray, n: int) -> jnp.ndarray:
    """(B, ...) -> (n, B/n, ...) microbatch view for the pipeline."""
    if batch.shape[0] % n:
        raise ValueError(f"batch {batch.shape[0]} not divisible by {n}")
    return batch.reshape(n, batch.shape[0] // n, *batch.shape[1:])
