"""Device-mesh construction for the ICI/DCN fabric.

TPU-native replacement for the reference's communicator setup
(``horovod/common/mpi/mpi_context.cc`` -- global, local-node and cross-node
MPI communicators).  On TPU the communicator *is* the mesh: a
:class:`jax.sharding.Mesh` whose axes map onto physical links.

* Flat mode: one axis ``"hvd"`` over every addressable device.  XLA routes
  the collective over ICI within a slice (and DCN between slices if the
  runtime spans them).
* Hierarchical mode (``NCCLHierarchicalAllreduce`` analogue): a 2-D mesh
  ``("dcn", "ici")`` -- the outer axis spans processes/slices over DCN, the
  inner axis spans each process's local chips over ICI.  A hierarchical
  allreduce is then ``psum`` over ``("ici", "dcn")`` which XLA lowers to
  reduce-scatter(ICI) -> allreduce(DCN) -> all-gather(ICI), exactly the
  NCCL+MPI sandwich the reference hand-codes.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names.
HVD_AXIS = "hvd"      # flat data-parallel axis
DCN_AXIS = "dcn"      # cross-slice (data-center network) axis
ICI_AXIS = "ici"      # intra-slice (inter-chip interconnect) axis

# The axis (or axes, innermost-last) a collective reduces over for a mesh
# built by :func:`build_mesh`.
FLAT_AXES: Tuple[str, ...] = (HVD_AXIS,)
HIER_AXES: Tuple[str, ...] = (DCN_AXIS, ICI_AXIS)


def parse_topology_spec(spec: Optional[str],
                        n: Optional[int] = None
                        ) -> Tuple[bool, Optional[int]]:
    """``HOROVOD_HIERARCHICAL`` topology spec -> ``(hierarchical, dcn_size)``.

    - unset / ``""`` / ``off``/``0``/``false``: not hierarchical;
    - ``auto``/``on``/``1``/``true``: two-level, outer axis derived from
      the process grouping (the elastic assignment's device layout);
    - ``rows,cols``: explicit ``(dcn, ici)`` extents -- ``rows`` slices of
      ``cols`` chips.  ``rows * cols`` must equal the device count when
      ``n`` is known.

    ``dcn_size is None`` means "group by owning process" (see
    :func:`build_mesh`).
    """
    if spec is None:
        return False, None
    s = str(spec).strip().lower()
    if s in ("", "0", "off", "false", "no"):
        return False, None
    if s in ("auto", "1", "on", "true", "yes"):
        return True, None
    parts = [p.strip() for p in s.split(",")]
    if len(parts) == 2 and all(p.isdigit() for p in parts):
        rows, cols = int(parts[0]), int(parts[1])
        if rows < 1 or cols < 1:
            raise ValueError(
                f"bad HOROVOD_HIERARCHICAL spec {spec!r}: extents must "
                f"be >= 1")
        if n is not None and rows * cols != n:
            raise ValueError(
                f"HOROVOD_HIERARCHICAL={spec!r} names a {rows}x{cols} "
                f"topology but the mesh has {n} devices")
        return True, rows
    raise ValueError(
        f"bad HOROVOD_HIERARCHICAL spec {spec!r}: expected "
        f"auto|off|<rows>,<cols>")


def build_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    hierarchical: bool = False,
    dcn_size: Optional[int] = None,
) -> Mesh:
    """Build the global communicator mesh.

    Args:
      devices: devices to include; defaults to ``jax.devices()`` (all
        devices across all processes -- the MPI_COMM_WORLD analogue).
      hierarchical: build the 2-D ``(dcn, ici)`` mesh.  Requires the device
        count to factor as ``num_processes * devices_per_process``.
      dcn_size: explicit outer-axis extent for the hierarchical mesh
        (overrides the process grouping; used to emulate a multi-slice
        topology on a single process, e.g. in multi-chip dry runs).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if not hierarchical:
        return Mesh(np.asarray(devices, dtype=object).reshape(n), (HVD_AXIS,))
    if dcn_size is not None:
        if n % dcn_size:
            raise ValueError(f"{n} devices do not factor into dcn={dcn_size}")
        grid = np.asarray(devices, dtype=object).reshape(dcn_size,
                                                         n // dcn_size)
        return Mesh(grid, (DCN_AXIS, ICI_AXIS))

    # Group by owning process: DCN axis = processes, ICI axis = local chips.
    procs = sorted({d.process_index for d in devices})
    per_proc = [sorted((d for d in devices if d.process_index == p),
                       key=lambda d: d.id) for p in procs]
    counts = {len(ds) for ds in per_proc}
    if len(counts) != 1:
        raise ValueError(
            f"hierarchical mesh needs equal devices per process, got {counts}")
    grid = np.asarray(per_proc, dtype=object)
    return Mesh(grid, (DCN_AXIS, ICI_AXIS))


# Canonical axis names for the full parallelism mesh (outermost first).
# dp rides DCN (gradient allreduce tolerates its latency), pp crosses
# slice/neighbor links once per microbatch, ep/sp ride ICI, and tp sits
# innermost on the fastest ICI loops (it's latency-critical: two
# collectives per matmul pair).
DP_AXIS = "dp"
PP_AXIS = "pp"
EP_AXIS = "ep"
SP_AXIS = "sp"
TP_AXIS = "tp"
PARALLEL_AXES: Tuple[str, ...] = (DP_AXIS, PP_AXIS, EP_AXIS, SP_AXIS,
                                  TP_AXIS)


def build_parallel_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    dp: int = 1,
    pp: int = 1,
    ep: int = 1,
    sp: int = 1,
    tp: int = 1,
) -> Mesh:
    """Build the 5-axis ``(dp, pp, ep, sp, tp)`` parallelism mesh.

    Any axis may be 1 (degenerate); the product must equal the device
    count.  This generalises :func:`build_mesh` beyond pure data
    parallelism: the reference framework only ever builds the DP
    communicator (SURVEY.md section 3.8), while this mesh carries tensor,
    pipeline, sequence (context) and expert parallelism as first-class
    axes for the model-parallel layers in ``horovod_tpu.parallel``.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    extents = {DP_AXIS: dp, PP_AXIS: pp, EP_AXIS: ep, SP_AXIS: sp,
               TP_AXIS: tp}
    prod = int(np.prod(list(extents.values())))
    if prod != n:
        raise ValueError(
            f"dp*pp*ep*sp*tp = {prod} != {n} devices ({extents})")
    grid = np.asarray(devices, dtype=object).reshape(
        *[extents[a] for a in PARALLEL_AXES])
    return Mesh(grid, PARALLEL_AXES)


# Canonical axis names for the 3-D training mesh (outermost first).
# "data" is the gradient-exchange axis; when DCN splits it, the pair
# ("dcn", "data") is exactly the two-level communicator of build_mesh, so
# the DP gradient leg rides the hierarchical exchange: TP (and pipeline)
# stay inside a slice on ICI, DP crosses slices on DCN.  "model" sits
# innermost on the fastest ICI loops (TP is latency-critical), "pipe"
# between them (one ppermute per microbatch tick).
DATA_AXIS = "data"
PIPE_AXIS = "pipe"
MODEL_AXIS = "model"
THREED_AXES: Tuple[str, ...] = (DCN_AXIS, DATA_AXIS, PIPE_AXIS, MODEL_AXIS)

# Axes that shard the MODEL (parameters / stages), never the batch.  The
# complement of these in a mesh's axis_names is the gradient-exchange
# domain -- see :func:`data_axes`.
MODEL_PARALLEL_AXES: Tuple[str, ...] = (PIPE_AXIS, MODEL_AXIS)


def build_3d_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data: int = 1,
    pipe: int = 1,
    model: int = 1,
    dcn_size: int = 1,
) -> Mesh:
    """Build the named-sharding mesh for DP x pipeline x TP training.

    Axes are drawn from ``(dcn, data, pipe, model)`` outermost-first, but
    extent-1 axes are OMITTED (``data`` is always kept) so the mesh's
    gradient-exchange domain matches what the optimized exchange stack
    expects: with ``dcn_size > 1`` the data axes are exactly the
    two-level ``("dcn", "data")`` pair and the DP gradient leg rides the
    hierarchical ICI x DCN exchange; without it they are the flat
    ``("data",)`` axis.

    Args:
      devices: devices to include; defaults to ``jax.devices()``.
      data: data-parallel extent WITHIN a slice (the ICI leg of the DP
        exchange when ``dcn_size > 1``).
      pipe: pipeline-stage extent (``parallel.pipeline`` axis).
      model: tensor-parallel extent (``parallel.tp`` axis).
      dcn_size: number of slices the ``data`` axis is split over (the DCN
        leg); ``1`` keeps the mesh single-slice.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    extents = {DCN_AXIS: int(dcn_size), DATA_AXIS: int(data),
               PIPE_AXIS: int(pipe), MODEL_AXIS: int(model)}
    for name, e in extents.items():
        if e < 1:
            raise ValueError(
                f"bad 3-D mesh extent {name}={e}: extents must be >= 1")
    prod = int(np.prod(list(extents.values())))
    if prod != n:
        raise ValueError(
            f"dcn*data*pipe*model = {prod} != {n} devices ({extents})")
    axes = tuple(a for a in THREED_AXES
                 if extents[a] > 1 or a == DATA_AXIS)
    grid = np.asarray(devices, dtype=object).reshape(
        *[extents[a] for a in axes])
    return Mesh(grid, axes)


def data_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The gradient-exchange axes of ``mesh``: every axis that shards the
    BATCH rather than the model.  For a :func:`build_3d_mesh` mesh this is
    ``("dcn", "data")`` (hierarchical) or ``("data",)``; for the pure-DP
    meshes of :func:`build_mesh` it is all axes (unchanged behaviour)."""
    return tuple(a for a in mesh.axis_names
                 if a not in MODEL_PARALLEL_AXES
                 and a not in (EP_AXIS, SP_AXIS, TP_AXIS, PP_AXIS))


def model_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The model-parallel axes of ``mesh`` (complement of
    :func:`data_axes`)."""
    da = set(data_axes(mesh))
    return tuple(a for a in mesh.axis_names if a not in da)


def mesh_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The reduction axes for a mesh produced by :func:`build_mesh`."""
    return tuple(mesh.axis_names)


def mesh_size(mesh: Mesh) -> int:
    return int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
