"""Double-buffered host->device batch prefetcher.

The reference's input pipeline hands each rank a host iterator and pays
the H2D copy synchronously inside the step loop; its background thread
only hides the COLLECTIVE, not the copy.  Under JAX the device transfer
(``jax.device_put`` to the batch sharding) is itself async, so a small
producer thread that stays ``depth`` batches ahead of the consumer makes
the copy overlap the previous step's compute entirely: by the time the
training loop asks for batch i, its buffers are already on (or streaming
to) the chips.  ``depth=2`` is classic double buffering -- one batch in
flight to the device while the previous one computes.

Pairs with :func:`horovod_tpu.training.make_train_loop`:
``DevicePrefetcher(it, stack_steps=k)`` groups k host batches, stacks
them on a leading steps axis, and ships the stacked window -- exactly
the layout the k-step ``lax.scan`` loop consumes.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional

import jax


class _Stop:
    """Sentinel carrying the producer's exit: clean end or an exception."""

    def __init__(self, error: Optional[BaseException] = None):
        self.error = error


class DevicePrefetcher:
    """Iterate host batches already placed on the mesh, ``depth`` ahead.

    Parameters
    ----------
    iterator:
        Any iterable of batch pytrees (numpy/host arrays per leaf, leading
        batch dim sized for the GLOBAL batch -- same contract as
        :func:`horovod_tpu.training.shard_batch`).
    depth:
        Bounded queue depth (default 2: double buffering).  The producer
        blocks once ``depth`` device batches are unconsumed, bounding HBM
        held by staged input at ``depth * batch_bytes``.
    mesh / sharding:
        Where to put the data; defaults to the initialized mesh's
        batch sharding (leading dim split over every mesh axis).
    stack_steps:
        When > 1, group this many host batches per yielded item and stack
        each leaf on a NEW leading axis (the
        :func:`horovod_tpu.training.stack_steps` layout for
        ``make_train_loop``).  A trailing partial group (fewer than
        ``stack_steps`` batches left) is dropped -- a scan loop cannot run
        a short window; ``dropped_remainder`` reports how many host
        batches were discarded.
    """

    def __init__(self, iterator: Iterable,
                 depth: int = 2,
                 mesh=None,
                 sharding=None,
                 stack_steps: int = 1):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if stack_steps < 1:
            raise ValueError(
                f"stack_steps must be >= 1, got {stack_steps}")
        from ..training import batch_sharding, stacked_batch_sharding
        if sharding is None:
            # Stacked layout: dim 0 is the steps axis (unsharded), dim 1
            # is the global batch split over the mesh.
            sharding = stacked_batch_sharding(mesh) if stack_steps > 1 \
                else batch_sharding(mesh)
        self._sharding = sharding
        self._stack = stack_steps
        self.dropped_remainder = 0
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._produce, args=(iter(iterator),),
            name="hvd-prefetch", daemon=True)
        self._thread.start()

    # -- producer ---------------------------------------------------------
    def _put(self, item) -> bool:
        """Enqueue, giving up promptly if the consumer closed us."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self, it: Iterator) -> None:
        import numpy as np
        try:
            while not self._stop.is_set():
                if self._stack > 1:
                    group = []
                    for _ in range(self._stack):
                        try:
                            group.append(next(it))
                        except StopIteration:
                            break
                    if len(group) < self._stack:
                        self.dropped_remainder += len(group)
                        break
                    host = jax.tree.map(lambda *xs: np.stack(xs), *group)
                else:
                    try:
                        host = next(it)
                    except StopIteration:
                        break
                # device_put is async: the copy streams while the consumer
                # computes on earlier batches.
                dev = jax.tree.map(
                    lambda x: jax.device_put(x, self._sharding), host)
                if not self._put(dev):
                    return
            self._put(_Stop())
        except BaseException as e:  # surface in the consumer thread
            # Record the error BEFORE the best-effort sentinel enqueue: if
            # the sentinel is lost (queue torn down, nested failure while
            # putting), the consumer's timeout path in ``__next__`` still
            # surfaces the original exception instead of blocking forever
            # on a starved queue.
            self._error = e
            self._put(_Stop(e))

    # -- consumer ---------------------------------------------------------
    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        if self._stop.is_set():
            raise StopIteration
        while True:
            try:
                item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                # FIFO is preserved: queued good batches (and an enqueued
                # error sentinel) always drain first.  Only once the queue
                # is starved do we consult the producer's state -- a
                # recorded error re-raises here even when its sentinel
                # never landed; a dead producer with no error is a clean
                # end of input.
                if self._error is not None:
                    self._stop.set()
                    raise self._error
                if not self._thread.is_alive():
                    self._stop.set()
                    raise StopIteration
        if isinstance(item, _Stop):
            self._stop.set()
            if item.error is not None:
                raise item.error
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the producer and drop queued batches."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def prefetch_to_device(iterator: Iterable, depth: int = 2, mesh=None,
                       sharding=None, stack_steps: int = 1
                       ) -> DevicePrefetcher:
    """Functional spelling of :class:`DevicePrefetcher` (flax idiom)."""
    return DevicePrefetcher(iterator, depth=depth, mesh=mesh,
                            sharding=sharding, stack_steps=stack_steps)
