"""Input-pipeline helpers: host->device transfer that overlaps compute.

The reference's data plane (per-rank Petastorm readers, ``ElasticSampler``)
leaves H2D copies on the training thread; here a background thread stages
batches onto the mesh ahead of the step so the copy rides under compute
(:mod:`horovod_tpu.data.prefetch`).
"""

from .prefetch import DevicePrefetcher, prefetch_to_device  # noqa: F401

__all__ = ["DevicePrefetcher", "prefetch_to_device"]
