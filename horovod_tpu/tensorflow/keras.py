"""``horovod_tpu.tensorflow.keras``: the reference's canonical
``import horovod.tensorflow.keras as hvd`` path, aliasing
:mod:`horovod_tpu.keras` (same DistributedOptimizer + callbacks)."""

from ..keras import *  # noqa: F401,F403
