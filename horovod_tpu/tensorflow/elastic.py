"""``horovod_tpu.tensorflow.elastic``: TensorFlowKerasState + run.

Parity with ``horovod/tensorflow/elastic.py::TensorFlowKerasState``: the
elastic state object for keras models -- ``commit()`` snapshots
``model.get_weights()`` (+ optimizer variables and scalar attributes) in
host memory, ``restore()`` rolls back, ``sync()`` broadcasts rank 0's
weights to everyone after a rescale.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List

import numpy as np

from ..elastic.run_loop import run  # noqa: F401
from ..elastic.state import ObjectState, State  # noqa: F401


def _optimizer_weights(optimizer) -> List[np.ndarray]:
    if optimizer is None:
        return []
    vs = getattr(optimizer, "variables", None)
    if vs is None:
        return []
    vals = vs() if callable(vs) else vs
    return [np.asarray(v) for v in vals]


def _set_optimizer_weights(optimizer, weights: List[np.ndarray],
                           model=None) -> None:
    if optimizer is None or not weights:
        return
    vs = getattr(optimizer, "variables", None)
    if vs is None:
        return
    vals = vs() if callable(vs) else vs
    if len(vals) != len(weights) and model is not None:
        # A freshly joined worker's optimizer may not be built yet (no
        # slot variables); build against the model so every broadcast
        # variable has a home instead of being silently zip-truncated.
        build = getattr(optimizer, "build", None)
        if callable(build):
            build(model.trainable_variables)
            vals = vs() if callable(vs) else vs
    if len(vals) != len(weights):
        raise RuntimeError(
            f"optimizer variable count mismatch in elastic sync: local "
            f"{len(vals)} vs broadcast {len(weights)} -- the optimizers "
            "are structured differently across ranks")
    for var, w in zip(vals, weights):
        var.assign(w)


class TensorFlowKerasState(State):
    """Elastic state over a keras model (+ optimizer + scalars)::

        state = hvd.elastic.TensorFlowKerasState(model, optimizer=opt,
                                                 batch=0, epoch=0)
    """

    def __init__(self, model, optimizer=None, **kwargs):
        super().__init__()
        self.model = model
        self.optimizer = optimizer
        self._scalars = list(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._saved: Dict[str, Any] = {}
        self.commit()

    def commit(self) -> None:
        # get_weights() already returns fresh host copies; materialize
        # once and reuse for both the (usually disabled) desync check and
        # the snapshot -- commit runs at every batch boundary.
        weights = self.model.get_weights()
        self._check_desync({
            "weights": weights,
            "scalars": {k: getattr(self, k) for k in self._scalars}})
        self._saved = {
            "weights": weights,
            "opt": _optimizer_weights(self.optimizer),
            "scalars": {k: copy.deepcopy(getattr(self, k))
                        for k in self._scalars},
        }
        self._check_host_updates()

    def restore(self) -> None:
        self.model.set_weights([np.copy(w)
                                for w in self._saved["weights"]])
        if self.optimizer is not None and self._saved["opt"]:
            _set_optimizer_weights(self.optimizer, self._saved["opt"])
        for k, v in self._saved["scalars"].items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        from ..optim.functions import broadcast_, broadcast_object

        weights = self.model.get_weights()
        synced = broadcast_(
            {str(i): w for i, w in enumerate(weights)}, root_rank=0)
        self.model.set_weights([np.asarray(synced[str(i)])
                                for i in range(len(weights))])
        opt = broadcast_object(_optimizer_weights(self.optimizer),
                               root_rank=0)
        _set_optimizer_weights(self.optimizer, opt, model=self.model)
        scalars = broadcast_object(
            {k: getattr(self, k) for k in self._scalars}, root_rank=0)
        for k, v in scalars.items():
            setattr(self, k, v)
        self.commit()
