"""Cross-replica batch normalization for the TF/Keras shim.

Parity with ``horovod/tensorflow/sync_batch_norm.py::SyncBatchNormalization``:
a drop-in ``keras.layers.BatchNormalization`` whose training-time batch
statistics are averaged across every rank (of the optional process set),
so normalization behaves as if the global batch were on one device.

Math: allreduce-average E[x] and E[x^2] over the replicas and derive
``var = E[x^2] - E[x]^2`` (equal per-rank batch sizes, the same
assumption the reference makes for its group mean/variance).
"""

from __future__ import annotations

import tensorflow as tf
import keras


class SyncBatchNormalization(keras.layers.BatchNormalization):
    """``keras.layers.BatchNormalization`` with cross-rank statistics."""

    def __init__(self, *args, process_set=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._hvd_process_set = process_set

    def get_config(self):
        # Serialize the process set by NAME (registered sets are looked up
        # again at from_config time), so clone_model / to_json round-trips
        # keep reducing over the right group instead of silently falling
        # back to the global set.
        config = super().get_config()
        ps = self._hvd_process_set
        if ps is not None:
            config["process_set"] = ps if isinstance(ps, str) else ps.name
        return config

    @classmethod
    def from_config(cls, config):
        config = dict(config)
        ps_name = config.pop("process_set", None)
        if ps_name is not None:
            from ..core.process_sets import get_process_set
            config["process_set"] = get_process_set(ps_name)
        return cls(**config)

    def _moments(self, inputs, mask):
        from . import grouped_allreduce, Average, size

        mean, variance = super()._moments(inputs, mask)
        if size() == 1:
            return mean, variance
        process_set = self._hvd_process_set

        @tf.custom_gradient
        def _cross_replica_avg(m, msq):
            gm, gmsq = grouped_allreduce(
                [m, msq], op=Average, name="sync_bn",
                process_set=process_set)

            def grad(dm, dmsq):
                # Every rank's output depends on every rank's local stats
                # through the average; under SPMD the adjoint is the same
                # average applied to the upstream gradients.
                return grouped_allreduce([dm, dmsq], op=Average,
                                         name="sync_bn_bwd",
                                         process_set=process_set)

            return (gm, gmsq), grad

        mean_sq = variance + tf.square(mean)
        g_mean, g_mean_sq = _cross_replica_avg(mean, mean_sq)
        return g_mean, g_mean_sq - tf.square(g_mean)


SyncBatchNorm = SyncBatchNormalization
