"""``horovod_tpu.tensorflow``: drop-in ``horovod.tensorflow`` API.

Parity surface (reference ``horovod/tensorflow/__init__.py`` +
``mpi_ops.py``): ``init/rank/size/...``, eager tensor collectives
(``allreduce``, ``allgather``, ``broadcast``, ``alltoall``,
``grouped_allreduce``), **``DistributedGradientTape``** (wraps
``tf.GradientTape``; ``gradient()`` returns globally-reduced gradients),
``broadcast_variables``, and ``DistributedOptimizer`` for Keras.

TF stays the user-facing autograd engine on host CPU; collectives stage
through numpy onto the XLA mesh (same bridge as the torch shim).  The
design is TF2-eager-first, but the reference's TF1 session surface
(``broadcast_global_variables`` + ``BroadcastGlobalVariablesHook``) is
provided through ``tf.compat.v1``: the broadcast is a re-runnable graph
op (a ``tf.py_function`` hop into the mesh collective feeding grouped
assigns), so ``MonitoredTrainingSession``/estimator-style TF1 scripts
port unchanged.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import tensorflow as tf

from ..core.basics import (  # noqa: F401
    init, shutdown, is_initialized, size, rank, local_size, local_rank,
    cross_size, cross_rank, is_homogeneous, nccl_built, mpi_built,
    cuda_built, rocm_built, start_timeline, stop_timeline,
    gloo_built, tpu_built, mpi_threads_supported,
)
from ..core.exceptions import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt,
)
from ..core.process_sets import (  # noqa: F401
    ProcessSet, add_process_set, remove_process_set, get_process_set,
)
from . import elastic  # noqa: F401  (hvd.elastic.TensorFlowKerasState)
from .sync_batch_norm import (  # noqa: F401
    SyncBatchNorm, SyncBatchNormalization,
)
from ..collectives.reduce_op import (  # noqa: F401
    ReduceOp, Average, Sum, Min, Max, Product, Adasum,
)
from ..collectives.compression import Compression  # noqa: F401
from ..collectives import eager as _eager


def _to_stack(t) -> np.ndarray:
    return _eager.replicated_stack(np.asarray(t))


def _from_row(out, like) -> tf.Tensor:
    if isinstance(out, np.ndarray):       # host-fetched (grouped to_host)
        row = out[0]
    else:
        row = _eager.one_row(out)
    return tf.convert_to_tensor(row, dtype=like.dtype if
                                hasattr(like, "dtype") else None)


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, compression=Compression.none,
              op: Optional[ReduceOp] = None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0, process_set=None) -> tf.Tensor:
    if op is None:
        op = Sum if average is False else Average
    out = _eager.allreduce(_to_stack(tensor), op, name=name,
                           process_set=process_set,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor,
                           compression=compression)
    return _from_row(out, tensor)


def grouped_allreduce(tensors: Sequence, average=None, name=None, op=None,
                      process_set=None, compression=Compression.none,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0) -> List[tf.Tensor]:
    if op is None:
        op = Sum if average is False else Average
    tensors = list(tensors)

    def _dispatch(ts):
        outs = _eager.grouped_allreduce(
            [_to_stack(t) for t in ts], op, name=name,
            process_set=process_set, compression=compression,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, to_host=True)
        return [_from_row(o, t) for o, t in zip(outs, ts)]

    if not tf.executing_eagerly():
        # Inside a tf.function graph (keras fit): hop out via py_function
        # so the XLA-mesh collective runs eagerly (the reference registers
        # custom TF kernels for this; the bridge cost is equivalent).
        reduced = tf.py_function(lambda *ts: _dispatch(ts), tensors,
                                 [t.dtype for t in tensors])
        for r, t in zip(reduced, tensors):
            r.set_shape(t.shape)
        return reduced
    return _dispatch(tensors)


def grouped_allgather(tensors: Sequence, name=None,
                      process_set=None) -> List[tf.Tensor]:
    """Reference ``hvd.grouped_allgather``: one fused gather."""
    outs = _eager.grouped_allgather([_to_stack(t) for t in tensors],
                                    name=name, process_set=process_set)
    return [_from_row(o, t) for o, t in zip(outs, tensors)]


def grouped_reducescatter(tensors: Sequence, op: ReduceOp = Average,
                          name=None, process_set=None) -> List[tf.Tensor]:
    """Reference ``hvd.grouped_reducescatter``: one fused scatter."""
    outs = _eager.grouped_reducescatter([_to_stack(t) for t in tensors], op,
                                        name=name, process_set=process_set)
    return [_from_row(o, t) for o, t in zip(outs, tensors)]


def allgather(tensor, name: Optional[str] = None,
              process_set=None) -> tf.Tensor:
    """Reference parity: first dims MAY differ across ranks (sizes are
    exchanged first, like the reference's negotiation)."""
    out = _eager.allgather_value(np.asarray(tensor), name=name,
                                 process_set=process_set)
    return tf.convert_to_tensor(out)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set=None) -> tf.Tensor:
    out = _eager.broadcast(_to_stack(tensor), root_rank, name=name,
                           process_set=process_set)
    return _from_row(out, tensor)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set=None):
    """Reference parity (``horovod.tensorflow.alltoall``): with ``splits``
    the exchange is uneven and the result is ``(received,
    received_splits)``; without, ``tensor`` splits evenly."""
    if splits is None:
        out = _eager.alltoall(_to_stack(tensor), name=name,
                              process_set=process_set)
        return _from_row(out, tensor)
    data, rsplits = _eager.alltoallv_row(np.asarray(tensor),
                                         np.asarray(splits), name=name,
                                         process_set=process_set)
    return (tf.convert_to_tensor(data),
            tf.convert_to_tensor(rsplits.astype(np.int32)))


def reducescatter(tensor, op: ReduceOp = Average, name=None,
                  process_set=None):
    out = _eager.reducescatter(_to_stack(tensor), op, name=name,
                               process_set=process_set)
    return _from_row(out, tensor)


def barrier(process_set=None) -> None:
    _eager.barrier(process_set=process_set)


def join() -> int:
    return _eager.join()


def broadcast_variables(variables, root_rank: int = 0,
                        process_set=None) -> None:
    """Assign every variable its root-rank value (``hvd.broadcast_variables``).

    Variables are FUSED per dtype into one flat buffer and broadcast with
    a single collective per dtype: a per-variable loop would compile one
    XLA program per distinct shape (minutes of tunnel compile time for a
    real model) and pay one staging round-trip each.
    """
    variables = list(variables)
    rows = _eager.broadcast_fused([np.asarray(v) for v in variables],
                                  root_rank, name="broadcast.vars",
                                  process_set=process_set)
    for v, row in zip(variables, rows):
        v.assign(tf.convert_to_tensor(row, dtype=v.dtype))


def broadcast_global_variables(root_rank: int = 0, process_set=None):
    """Broadcast all TF1 global variables from ``root_rank``.

    Reference parity: ``horovod.tensorflow.broadcast_global_variables``
    (SURVEY.md 3.4, the TF1 half of the API).  Graph mode
    (``tf.compat.v1`` sessions): returns a re-runnable op -- a
    ``tf.py_function`` that runs the fused mesh broadcast and feeds one
    assign per variable (the reference registers a native
    ``HorovodBroadcast`` kernel; the py_function hop is this shim's
    standard graph bridge, same as ``grouped_allreduce``).  Limitation:
    ``py_function`` captures process-local Python state, so the returned
    op is NOT serializable into a GraphDef -- graphs that are frozen,
    exported, or executed by a session in a different process will fail
    to resolve it (the reference's native kernel survives those flows).
    Run the op in the process that built it, as
    ``BroadcastGlobalVariablesHook`` does.  Eager mode
    raises like the reference: eager variables never reach the
    ``global_variables()`` collection, so a silent no-op would leave
    every rank on its own init -- use ``broadcast_variables``.
    """
    v1 = tf.compat.v1
    if tf.executing_eagerly():
        raise RuntimeError(
            "hvd.broadcast_global_variables() does not support eager "
            "execution. Please use `hvd.broadcast_variables(<model/"
            "optimizer variables>)` instead.")
    variables = v1.global_variables()
    if not variables:
        return tf.no_op(name="horovod_broadcast_global_variables")

    def _dispatch(*ts):
        rows = _eager.broadcast_fused(
            [np.asarray(t) for t in ts], root_rank,
            name="broadcast.global_vars", process_set=process_set)
        return [tf.convert_to_tensor(r) for r in rows]

    outs = tf.py_function(_dispatch, [v.read_value() for v in variables],
                          [v.dtype.base_dtype for v in variables])
    assigns = []
    for v, o in zip(variables, outs):
        o.set_shape(v.shape)
        assigns.append(v1.assign(v, o))
    return tf.group(*assigns, name="horovod_broadcast_global_variables")


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """TF1 ``SessionRunHook`` broadcasting initial state from ``root_rank``.

    Reference parity: ``horovod.tensorflow.BroadcastGlobalVariablesHook``
    (SURVEY.md 3.4 -- the last TF1 surface).  Use with
    ``tf.compat.v1.train.MonitoredTrainingSession`` or estimators: the
    broadcast op is (re)built in ``begin()`` against the current graph and
    run once in ``after_create_session``, i.e. after variable
    initialization, exactly the reference's hook protocol.  The op is a
    ``py_function`` bridge (see :func:`broadcast_global_variables`): it
    must run in the process that built it and cannot ride a frozen or
    exported GraphDef -- in-process MonitoredSession/estimator use is the
    supported shape.  ``device`` is
    accepted for signature parity (placement is the mesh's concern here).
    """

    def __init__(self, root_rank: int = 0, device: str = "",
                 process_set=None):
        super().__init__()
        self.root_rank = root_rank
        self.device = device
        self.process_set = process_set
        self.bcast_op = None

    def begin(self):
        if (self.bcast_op is None
                or self.bcast_op.graph is not
                tf.compat.v1.get_default_graph()):
            with tf.device(self.device):
                self.bcast_op = broadcast_global_variables(
                    self.root_rank, process_set=self.process_set)

    def after_create_session(self, session, coord):
        session.run(self.bcast_op)


def broadcast_object(obj, root_rank: int = 0, name=None, process_set=None):
    from ..optim.functions import broadcast_object as _bo
    return _bo(obj, root_rank, process_set=process_set)


def allgather_object(obj, name=None, process_set=None) -> list:
    from ..optim.functions import allgather_object as _ago
    return _ago(obj, name=name, process_set=process_set)


class DistributedGradientTape(tf.GradientTape):
    """``tf.GradientTape`` whose ``gradient()`` allreduces the result.

    Reference: ``horovod/tensorflow/__init__.py::DistributedGradientTape``
    (the TF2 hot path in SURVEY.md 4.3).  Gradients are fused through
    ``grouped_allreduce`` -- one collective per dtype bucket rather than
    one per tensor.
    """

    def __init__(self, tape: tf.GradientTape,
                 compression=Compression.none, op: ReduceOp = Average,
                 process_set=None, sparse_as_dense: bool = False,
                 gradient_predivide_factor: float = 1.0):
        # Adopt the wrapped tape's recording state.  sparse_as_dense
        # defaults OFF like the reference: densifying an embedding grad
        # can be a huge silent memory cost, so it is explicit opt-in.
        if gradient_predivide_factor != 1.0 and op is not Average:
            raise ValueError("gradient_predivide_factor requires "
                             "op=Average (reference behavior)")
        if gradient_predivide_factor <= 0.0:
            raise ValueError("gradient_predivide_factor must be positive")
        self.__dict__.update(tape.__dict__)
        self._hvd_compression = compression
        self._hvd_op = op
        self._hvd_process_set = process_set
        self._hvd_sparse_as_dense = sparse_as_dense
        self._hvd_prescale = 1.0 / gradient_predivide_factor
        self._hvd_postscale = gradient_predivide_factor

    def gradient(self, target, sources, output_gradients=None,
                 unconnected_gradients=tf.UnconnectedGradients.NONE):
        grads = super().gradient(target, sources, output_gradients,
                                 unconnected_gradients)
        flat = tf.nest.flatten(grads)
        idx = [i for i, g in enumerate(flat) if g is not None]
        for i in idx:
            if isinstance(flat[i], tf.IndexedSlices):
                # Embedding-style sparse grads: densify before the dense
                # allreduce (reference sparse_as_dense), or refuse loudly.
                if not self._hvd_sparse_as_dense:
                    raise ValueError(
                        "IndexedSlices gradient with sparse_as_dense="
                        "False; dense allreduce needs sparse_as_dense="
                        "True")
                flat[i] = tf.convert_to_tensor(flat[i])
        if idx:
            reduced = grouped_allreduce(
                [tf.convert_to_tensor(flat[i]) for i in idx],
                op=self._hvd_op, name="gradtape",
                process_set=self._hvd_process_set,
                compression=self._hvd_compression,
                prescale_factor=self._hvd_prescale,
                postscale_factor=self._hvd_postscale)
            for i, g in zip(idx, reduced):
                flat[i] = g
        return tf.nest.pack_sequence_as(grads, flat)


def DistributedOptimizer(optimizer, compression=Compression.none,
                         op: ReduceOp = Average, process_set=None,
                         backward_passes_per_step: int = 1,
                         average_aggregated_gradients: bool = True,
                         sparse_as_dense: bool = False):
    """Keras-3 optimizer wrapper: allreduce grads in ``apply_gradients``.

    Reference: ``horovod/tensorflow/__init__.py::DistributedOptimizer``
    (wrap ``compute_gradients``); Keras 3 funnels everything through
    ``apply_gradients``, so the reduction hooks there.

    ``backward_passes_per_step > 1`` reproduces the reference's local
    gradient aggregation (``gradient_aggregation_eager.py``): gradients
    accumulate into local buffers for N-1 calls with NO communication and
    NO variable update; the Nth call allreduces the aggregate (averaged
    over N when ``average_aggregated_gradients``) and applies it.
    """
    base = optimizer.__class__
    bpps = int(backward_passes_per_step)
    if bpps < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    class _Distributed(base):
        _hvd_wrapped = True

        def _hvd_reduce_and_apply(self, grads, tvars, args, kwargs):
            idx = [i for i, g in enumerate(grads) if g is not None]
            for i in idx:
                if isinstance(grads[i], tf.IndexedSlices):
                    # Same policy as DistributedGradientTape: densify
                    # for the dense allreduce only with explicit opt-in.
                    if not sparse_as_dense:
                        raise ValueError(
                            "IndexedSlices gradient with sparse_as_dense"
                            "=False; dense allreduce needs "
                            "sparse_as_dense=True")
                    grads[i] = tf.convert_to_tensor(grads[i])
            if idx:
                reduced = grouped_allreduce(
                    [tf.convert_to_tensor(grads[i]) for i in idx],
                    op=op, name="opt", process_set=process_set)
                for i, g in zip(idx, reduced):
                    grads[i] = g
            return super().apply_gradients(zip(grads, tvars), *args,
                                           **kwargs)

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            grads_and_vars = list(grads_and_vars)
            grads = [g for g, _ in grads_and_vars]
            tvars = [v for _, v in grads_and_vars]
            if bpps == 1:
                return self._hvd_reduce_and_apply(grads, tvars, args,
                                                  kwargs)

            if not hasattr(self, "_hvd_agg_counter"):
                self._hvd_agg_counter = tf.Variable(
                    0, dtype=tf.int64, trainable=False,
                    name="hvd_agg_counter")
                self._hvd_agg_bufs = [
                    None if g is None else tf.Variable(
                        tf.zeros(g.shape, g.dtype), trainable=False,
                        name=f"hvd_agg_{i}")
                    for i, g in enumerate(grads)]
            # Validate BEFORE any buffer mutation: a mid-loop raise after
            # partial assign_adds would double-count on the next pass.
            if not sparse_as_dense and any(
                    isinstance(g, tf.IndexedSlices) for g in grads):
                raise ValueError(
                    "IndexedSlices gradient with sparse_as_dense=False; "
                    "dense aggregation needs sparse_as_dense=True")
            for buf, g in zip(self._hvd_agg_bufs, grads):
                if buf is not None and g is not None:
                    buf.assign_add(tf.convert_to_tensor(g))
            self._hvd_agg_counter.assign_add(1)

            def _boundary():
                scale = 1.0 / bpps if average_aggregated_gradients else 1.0
                agg = [None if b is None
                       else tf.cast(scale, b.dtype) * b.read_value()
                       for b in self._hvd_agg_bufs]
                with tf.control_dependencies(
                        [a for a in agg if a is not None]):
                    for b in self._hvd_agg_bufs:
                        if b is not None:
                            b.assign(tf.zeros_like(b))
                    self._hvd_agg_counter.assign(0)
                self._hvd_reduce_and_apply(agg, tvars, args, kwargs)
                return tf.convert_to_tensor(self.iterations)

            def _skip():
                return tf.convert_to_tensor(self.iterations)

            if tf.executing_eagerly():
                # Both paths return iterations, like the bpps==1 path and
                # the Keras base apply_gradients contract.
                return (_boundary()
                        if int(self._hvd_agg_counter) >= bpps
                        else _skip())
            # Slot variables must exist BEFORE tf.cond traces the
            # apply branch (variable creation is illegal inside cond).
            if hasattr(self, "build") and not getattr(self, "built",
                                                      True):
                self.build(tvars)
            return tf.cond(self._hvd_agg_counter >= bpps,
                           _boundary, _skip)

    optimizer.__class__ = _Distributed
    return optimizer
