"""Worker entry for :class:`horovod_tpu.ray.elastic.ElasticRayExecutor`.

Loads the pickled ``(fn, args, kwargs)`` payload, runs it (the function
itself uses ``horovod_tpu`` / ``horovod_tpu.elastic`` as any elastic
script would), and drops the result under ``results/rank_<r>``.
"""

from __future__ import annotations

import os
import pickle
import sys


def main() -> int:
    payload_path, results_dir = sys.argv[1], sys.argv[2]
    with open(payload_path, "rb") as f:
        fn, args, kwargs = pickle.load(f)
    result = fn(*args, **kwargs)

    import horovod_tpu as hvd
    rank = hvd.rank() if hvd.is_initialized() else \
        int(os.environ.get("HOROVOD_RANK", "0"))
    tmp = os.path.join(results_dir, f".rank_{rank}.{os.getpid()}")
    with open(tmp, "wb") as f:
        pickle.dump(result, f)
    os.replace(tmp, os.path.join(results_dir, f"rank_{rank}"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
