"""Elastic Ray executor (reference ``horovod/ray/elastic_v2.py`` parity).

``ElasticRayExecutor`` runs a python function on an elastically-managed
worker set: host membership comes from the Ray cluster (one slot per
alive node) when Ray is importable, or from any user-supplied discovery
source; workers ride the same :class:`~horovod_tpu.elastic.driver.
ElasticDriver` rescale/blacklist/heartbeat machinery as ``hvdrun
--host-discovery-script``.  The function is shipped to workers by pickle;
per-rank results come back through the run directory, rank-ordered.

The user function runs under the worker's own elastic loop: decorate
training with ``@horovod_tpu.elastic.run`` inside it exactly as a script
would (the executor deliberately does not hide that contract -- commit
boundaries are the user's to choose, reference semantics).
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
from typing import Any, Callable, List, Optional

from ..elastic.driver import ElasticDriver


def _ray_discovery_script(workdir: str, slots: int) -> str:
    """Discovery script printing one worker id per alive Ray node."""
    path = os.path.join(workdir, "ray_discovery.py")
    with open(path, "w") as f:
        f.write(
            "#!/usr/bin/env python\n"
            "import ray\n"
            "ray.init(address='auto', ignore_reinit_error=True,\n"
            "         logging_level='ERROR')\n"
            "for node in ray.nodes():\n"
            "    if node.get('Alive'):\n"
            f"        print(node['NodeID'][:12] + ':{slots}')\n")
    os.chmod(path, 0o755)
    return path


def _file_discovery_script(workdir: str, host_file: str) -> str:
    import shlex
    path = os.path.join(workdir, "file_discovery.sh")
    with open(path, "w") as f:
        f.write(f"#!/bin/sh\ncat {shlex.quote(host_file)}\n")
    os.chmod(path, 0o755)
    return path


class ElasticRayExecutor:
    """Elastic function runner over a dynamic host set.

    ``host_file``: path whose lines name the current hosts (the test/
    non-Ray discovery source; rewrite it to scale).  Without it, Ray's
    alive-node set is polled.
    """

    def __init__(self, min_workers: int = 1,
                 max_workers: Optional[int] = None,
                 slots_per_worker: int = 1, cpu: bool = False,
                 host_file: Optional[str] = None,
                 heartbeat_timeout_s: float = 0.0,
                 network_rendezvous: bool = False,
                 chaos: Optional[str] = None):
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.slots = slots_per_worker
        self.cpu = cpu
        self.host_file = host_file
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.network_rendezvous = network_rendezvous
        # HOROVOD_CHAOS spec shipped to every worker (deterministic
        # fault-injection runs; see horovod_tpu/elastic/chaos.py).
        self.chaos = chaos
        self.workdir = tempfile.mkdtemp(prefix="hvd_tpu_ray_elastic_")

    def close(self) -> None:
        """Remove the working directory (pickled payload + results)."""
        import shutil
        shutil.rmtree(self.workdir, ignore_errors=True)

    def __enter__(self) -> "ElasticRayExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None) -> List[Any]:
        """Run ``fn(*args, **kwargs)`` elastically; rank-ordered results
        from the FINAL membership epoch."""
        payload = os.path.join(self.workdir, "payload.pkl")
        with open(payload, "wb") as f:
            pickle.dump((fn, args, kwargs or {}), f)
        results_dir = os.path.join(self.workdir, "results")
        # Fresh results dir per call: stale rank files from a previous
        # run() (or an earlier, larger membership epoch) must not leak
        # into this call's output.
        if os.path.isdir(results_dir):
            import shutil
            shutil.rmtree(results_dir)
        os.makedirs(results_dir)

        if self.host_file is not None:
            discovery = _file_discovery_script(self.workdir, self.host_file)
        else:
            try:
                import ray  # noqa: F401
            except ImportError as e:
                raise ImportError(
                    "ElasticRayExecutor without host_file requires ray; "
                    "pass host_file= for the file-backed discovery "
                    "source.") from e
            discovery = _ray_discovery_script(self.workdir, self.slots)

        # The pickled fn's defining module must be importable in workers;
        # the parent's sys.path (e.g. a test dir pytest inserted) may not
        # be in PYTHONPATH, so propagate it.
        pypath = os.pathsep.join(
            [p for p in sys.path if p] +
            [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
             if p])
        extra_env = {"PYTHONPATH": pypath}
        if self.chaos:
            extra_env["HOROVOD_CHAOS"] = self.chaos
        driver = ElasticDriver(
            command=[sys.executable, "-m",
                     "horovod_tpu.ray._elastic_worker", payload,
                     results_dir],
            extra_env=extra_env,
            discovery_script=discovery,
            discovery_timeout_s=30.0 if self.host_file is None else 10.0,
            min_np=self.min_workers,
            max_np=self.max_workers,
            cpu=self.cpu,
            slots=self.slots,
            heartbeat_timeout_s=self.heartbeat_timeout_s,
            rendezvous=self.network_rendezvous,
        )
        rc = driver.run()
        if rc != 0:
            raise RuntimeError(f"elastic run failed (exit {rc})")
        results = {}
        for name in os.listdir(results_dir):
            if not name.startswith("rank_"):
                continue
            with open(os.path.join(results_dir, name), "rb") as f:
                results[int(name[len("rank_"):])] = pickle.load(f)
        # Return exactly the FINAL membership epoch's ranks: a worker from
        # an earlier (larger) epoch may have finished and written a rank
        # beyond the final size before the scale-down landed.
        from ..elastic.notify import read_assignment
        doc = read_assignment(driver.assignment_path)
        final_size = doc["size"] if doc else len(results)
        missing = [r for r in range(final_size) if r not in results]
        if missing:
            raise RuntimeError(
                f"missing results for final-epoch rank(s) {missing}")
        return [results[r] for r in range(final_size)]
