"""``horovod_tpu.ray``: Ray cluster integration (reference
``horovod/ray/runner.py::RayExecutor`` parity).

``RayExecutor`` places one worker per slot, exports the ``HOROVOD_*``
identity env + coordinator address to each, and runs a user function on
all workers.  Two backends:

* **ray** (when importable): one Ray actor per worker, placement-group
  scheduling -- the reference's model.
* **local** (always available, and the test backend): one spawned local
  process per worker, same env contract.  This doubles as a programmatic
  alternative to the ``python -m horovod_tpu.run`` CLI.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import socket
from typing import Any, Callable, List, Optional

from ..run.launch import worker_env
from .elastic import ElasticRayExecutor  # noqa: F401


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _local_worker_main(fn, args, kwargs, env, q, rank):
    os.environ.update(env)
    try:
        q.put((rank, True, fn(*args, **kwargs)))
    except Exception as e:  # noqa: BLE001 - crosses the process boundary
        q.put((rank, False, f"{type(e).__name__}: {e}"))


class RayExecutor:
    """Run a function on N workers with the framework env wired up.

    Args mirror the reference's surface where meaningful on TPU:
    ``num_workers`` slots; ``cpu=True`` forces the XLA:CPU backend in each
    worker (the test backend); ``use_ray=None`` auto-detects.
    """

    def __init__(self, num_workers: int, cpu: bool = False,
                 use_ray: Optional[bool] = None, slots_per_worker: int = 1,
                 extra_env: Optional[dict] = None):
        self.num_workers = num_workers
        self.cpu = cpu
        self.slots = slots_per_worker
        self.extra_env = dict(extra_env or {})
        if use_ray is None:
            try:
                import ray  # noqa: F401
                use_ray = True
            except ImportError:
                use_ray = False
        self.use_ray = use_ray
        self._actors = None
        self._started = False

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self._started:
            raise RuntimeError("executor already started")
        if self.use_ray:
            self._start_ray()
        self._started = True

    def _start_ray(self) -> None:
        import ray
        if not ray.is_initialized():
            ray.init()

        @ray.remote
        class _Worker:
            def set_env(self, env):
                os.environ.update(env)
                return socket.gethostname()

            def exec_fn(self, fn, args, kwargs):
                return fn(*args, **kwargs)

        self._actors = [_Worker.remote() for _ in range(self.num_workers)]

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None) -> List[Any]:
        """Execute ``fn(*args, **kwargs)`` on every worker; rank-ordered
        results.  Raises RuntimeError if any worker fails."""
        if not self._started:
            raise RuntimeError("call start() first")
        kwargs = kwargs or {}
        port = _free_port()
        envs = [{**self.extra_env,
                 **worker_env(rank=i, size=self.num_workers,
                              coordinator="127.0.0.1", port=port,
                              cpu=self.cpu, slots=self.slots)}
                for i in range(self.num_workers)]
        if self.use_ray:
            import ray
            ray.get([a.set_env.remote(e)
                     for a, e in zip(self._actors, envs)])
            return ray.get([a.exec_fn.remote(fn, args, kwargs)
                            for a in self._actors])
        return self._run_local(fn, args, kwargs, envs)

    def _run_local(self, fn, args, kwargs, envs,
                   timeout_s: float = 600.0,
                   failure_grace_s: float = 15.0) -> List[Any]:
        import queue as _queue
        import time

        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_local_worker_main,
                             args=(fn, args, kwargs, env, q, rank))
                 for rank, env in enumerate(envs)]
        for p in procs:
            p.start()
        results: dict = {}
        failures: dict = {}
        remaining = set(range(len(procs)))
        deadline = time.monotonic() + timeout_s
        term_deadline = None  # set on first failure: grace for peers'
        # secondary errors to surface, then stragglers are cut loose
        try:
            while remaining:
                now = time.monotonic()
                if now > deadline:
                    for r in sorted(remaining):
                        procs[r].terminate()
                        failures[r] = f"no result within {timeout_s}s"
                    break
                if term_deadline is not None and now > term_deadline:
                    for r in sorted(remaining):
                        procs[r].terminate()
                        failures[r] = ("terminated: still running "
                                       f"{failure_grace_s}s after a peer "
                                       "failed (likely blocked in a "
                                       "collective with the dead peer)")
                    break
                try:
                    rank, ok, value = q.get(timeout=1.0)
                    (results if ok else failures)[rank] = value
                    remaining.discard(rank)
                except _queue.Empty:
                    # Reap workers that died without reporting (segfault,
                    # os._exit); give one poll cycle for in-flight messages.
                    for r in sorted(remaining):
                        p = procs[r]
                        if not p.is_alive() and p.exitcode is not None \
                                and q.empty():
                            failures[r] = (f"exited with code {p.exitcode} "
                                           "without reporting")
                            remaining.discard(r)
                if failures and term_deadline is None:
                    term_deadline = time.monotonic() + failure_grace_s
        finally:
            for p in procs:
                p.join(timeout=30)
                if p.is_alive():
                    p.terminate()
        if failures:
            # Report every failure: the FIRST message received is often a
            # secondary "peer died" error, not the root cause.
            detail = "; ".join(f"worker {r}: {failures[r]}"
                               for r in sorted(failures))
            raise RuntimeError(f"worker(s) failed: {detail}")
        return [results[i] for i in range(self.num_workers)]

    def shutdown(self) -> None:
        if self.use_ray and self._actors is not None:
            import ray
            for a in self._actors:
                ray.kill(a)
            self._actors = None
        self._started = False
