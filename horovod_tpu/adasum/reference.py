"""NumPy reference implementation of Adasum (for tests only).

Mirrors the math of the reference's ``horovod/common/ops/adasum/adasum.h``
(recursive pairwise combination with dot-product mixing coefficients):

    adasum(a, b) = (1 - a.b / (2 |a|^2)) a  +  (1 - a.b / (2 |b|^2)) b

applied over a binary tree: level k combines the results of index groups
whose bit k differs, lower-index group first.  This file is the oracle the
XLA implementation is validated against (SURVEY.md section 7 "hard parts").
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

_TOL = 1e-30


def adasum_pair(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Combine two gradient vectors with Adasum mixing coefficients."""
    a64 = a.astype(np.float64).ravel()
    b64 = b.astype(np.float64).ravel()
    dot = float(a64 @ b64)
    anormsq = float(a64 @ a64)
    bnormsq = float(b64 @ b64)
    acoeff = 1.0 if anormsq < _TOL else 1.0 - dot / anormsq * 0.5
    bcoeff = 1.0 if bnormsq < _TOL else 1.0 - dot / bnormsq * 0.5
    return (acoeff * a.astype(np.float64) +
            bcoeff * b.astype(np.float64)).astype(a.dtype)


def adasum_reference(vectors: Sequence[np.ndarray]) -> np.ndarray:
    """Adasum over ``len(vectors)`` ranks (must be a power of two)."""
    n = len(vectors)
    assert n & (n - 1) == 0, "power-of-two rank count required"
    if n == 1:
        return vectors[0]
    half = n // 2
    lo = adasum_reference(vectors[:half])
    hi = adasum_reference(vectors[half:])
    return adasum_pair(lo, hi)
