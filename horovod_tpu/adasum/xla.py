"""Adasum over the ICI mesh via recursive doubling with ``ppermute``.

TPU-native re-implementation of the reference's scale-adaptive summation
(``horovod/common/ops/adasum/adasum.h`` recursive vector-halving
distance-doubling, ``adasum_mpi.cc``).  Instead of MPI point-to-point
messages, each level exchanges with the XOR partner through
``lax.ppermute`` over the ICI ring and mixes with

    adasum(a, b) = (1 - a.b / (2 |a|^2)) a  +  (1 - a.b / (2 |b|^2)) b

where ``a`` is the lower-index group's vector.  Dot products are taken in
float32 regardless of wire dtype (matching the reference's double-precision
scalar accumulation in spirit; f32 is the TPU-native scalar unit width).

Note on bandwidth: the reference halves the vector at each level (VHDD,
O(n) bytes total); this version exchanges full vectors (O(n log p)) which
is simple and correct.  On ICI the log p factor is cheap for the scalar
mixing to remain exact; a psum_scatter-based VHDD variant is the planned
optimization once profiled.

Validated against ``horovod_tpu.adasum.reference.adasum_reference``.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

_TOL = 1e-30


def _pair(a, b):
    """Mix two vectors; ``a`` is the lower-index group's value."""
    a32 = a.astype(jnp.float32).ravel()
    b32 = b.astype(jnp.float32).ravel()
    dot = jnp.dot(a32, b32)
    anormsq = jnp.dot(a32, a32)
    bnormsq = jnp.dot(b32, b32)
    acoeff = jnp.where(anormsq < _TOL, 1.0, 1.0 - dot / (2.0 * anormsq))
    bcoeff = jnp.where(bnormsq < _TOL, 1.0, 1.0 - dot / (2.0 * bnormsq))
    out = acoeff.astype(a.dtype) * a + bcoeff.astype(b.dtype) * b
    return out


def adasum_allreduce_hierarchical(x, dcn_axis: str = "dcn",
                                  ici_axis: str = "ici"):
    """Two-level Adasum on a ``(dcn, ici)`` mesh.

    TPU mapping of the reference's hybrid ``adasum_gpu_operations.cc``
    (node-local NCCL ReduceScatter -> cross-node Adasum over MPI ->
    node-local NCCL Allgather): slice-local ``psum_scatter`` over ICI,
    Adasum recursive doubling over DCN on each shard, ``all_gather`` back
    over ICI.  Like the reference hybrid, the mixing coefficients are
    computed independently per scattered shard.

    The intra-slice reduction is the MEAN (Adasum mixing is homogeneous --
    ``adasum(ca, cb) = c adasum(a, b)`` -- so sum vs. mean only scales the
    result; the mean keeps data-parallel gradient magnitude independent of
    slice size).
    """
    n_ici = lax.axis_size(ici_axis)
    if n_ici == 1:
        return adasum_allreduce(x, axis=dcn_axis)
    shape = x.shape
    flat = x.ravel()
    pad = (-flat.size) % n_ici
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad,), flat.dtype)])
    shard = lax.psum_scatter(flat, ici_axis, scatter_dimension=0,
                             tiled=True)
    from ..collectives.ops import _divide_in_dtype
    shard = _divide_in_dtype(shard, n_ici)  # keep the wire dtype (ints too)
    mixed = adasum_allreduce(shard, axis=dcn_axis)
    out = lax.all_gather(mixed, ici_axis, axis=0, tiled=True)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def adasum_allreduce(x, axis: str = "hvd"):
    """Adasum-allreduce ``x`` across the (power-of-two) flat mesh axis."""
    n = lax.axis_size(axis)
    if n & (n - 1) != 0:
        raise ValueError(f"Adasum requires a power-of-two world size, got {n}")
    if n == 1:
        return x
    idx = lax.axis_index(axis)
    levels = int(math.log2(n))
    y = x
    for k in range(levels):
        bit = 1 << k
        perm = [(i, i ^ bit) for i in range(n)]
        partner = lax.ppermute(y, axis, perm)
        # Lower-index group (bit clear) owns the "a" slot.
        is_lo = (idx & bit) == 0
        a = jnp.where(is_lo, y, partner)
        b = jnp.where(is_lo, partner, y)
        y = _pair(a, b)
    return y
