"""Adasum over the ICI mesh via recursive doubling with ``ppermute``.

TPU-native re-implementation of the reference's scale-adaptive summation
(``horovod/common/ops/adasum/adasum.h`` recursive vector-halving
distance-doubling, ``adasum_mpi.cc``).  Instead of MPI point-to-point
messages, each level exchanges with the XOR partner through
``lax.ppermute`` over the ICI ring and mixes with

    adasum(a, b) = (1 - a.b / (2 |a|^2)) a  +  (1 - a.b / (2 |b|^2)) b

where ``a`` is the lower-index group's vector.  Dot products are taken in
float32 regardless of wire dtype (matching the reference's double-precision
scalar accumulation in spirit; f32 is the TPU-native scalar unit width).

Bandwidth: like the reference, the vector halves at each level (VHDD) --
O(n) bytes per rank for the reduce phase plus O(n) for the rebuild
allgather, independent of world size; only the 3 mixing scalars per level
pay a log p factor.

Validated against ``horovod_tpu.adasum.reference.adasum_reference``.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np
from jax import lax

_TOL = 1e-30


def _pair(a, b):
    """Mix two vectors; ``a`` is the lower-index group's value."""
    a32 = a.astype(jnp.float32).ravel()
    b32 = b.astype(jnp.float32).ravel()
    dot = jnp.dot(a32, b32)
    anormsq = jnp.dot(a32, a32)
    bnormsq = jnp.dot(b32, b32)
    acoeff = jnp.where(anormsq < _TOL, 1.0, 1.0 - dot / (2.0 * anormsq))
    bcoeff = jnp.where(bnormsq < _TOL, 1.0, 1.0 - dot / (2.0 * bnormsq))
    out = acoeff.astype(a.dtype) * a + bcoeff.astype(b.dtype) * b
    return out


def adasum_allreduce_hierarchical(x, dcn_axis: str = "dcn",
                                  ici_axis: str = "ici", wire_codec=None):
    """Two-level Adasum on a ``(dcn, ici)`` mesh.

    TPU mapping of the reference's hybrid ``adasum_gpu_operations.cc``
    (node-local NCCL ReduceScatter -> cross-node Adasum over MPI ->
    node-local NCCL Allgather): slice-local ``psum_scatter`` over ICI,
    Adasum recursive doubling over DCN on each shard, ``all_gather`` back
    over ICI.  Like the reference hybrid, the mixing coefficients are
    computed independently per scattered shard.

    The intra-slice reduction is the MEAN (Adasum mixing is homogeneous --
    ``adasum(ca, cb) = c adasum(a, b)`` -- so sum vs. mean only scales the
    result; the mean keeps data-parallel gradient magnitude independent of
    slice size).

    ``wire_codec="fp8"`` quantizes the CROSS-SLICE (DCN) Adasum exchanges
    only -- exactly where wire bytes hurt; the intra-slice psum_scatter /
    all_gather accumulate on the wire and stay in the working dtype.
    """
    n_ici = lax.axis_size(ici_axis)
    if n_ici == 1:
        return adasum_allreduce(x, axis=dcn_axis, wire_codec=wire_codec)
    shape = x.shape
    flat = x.ravel()
    pad = (-flat.size) % n_ici
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad,), flat.dtype)])
    shard = lax.psum_scatter(flat, ici_axis, scatter_dimension=0,
                             tiled=True)
    from ..collectives.ops import _divide_in_dtype
    shard = _divide_in_dtype(shard, n_ici)  # keep the wire dtype (ints too)
    mixed = adasum_allreduce(shard, axis=dcn_axis, wire_codec=wire_codec)
    out = lax.all_gather(mixed, ici_axis, axis=0, tiled=True)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def adasum_local_tree(vectors):
    """Adasum of a list of on-device vectors, no communication.

    The same binary tree as ``adasum.reference.adasum_reference`` (level k
    combines groups whose bit k differs, lower-index group first), unrolled
    at trace time.  Used for process-set Adasum, where member vectors are
    gathered first and every device mixes locally.
    """
    n = len(vectors)
    if n & (n - 1) != 0:
        raise ValueError(f"Adasum requires a power-of-two count, got {n}")
    if n == 1:
        return vectors[0]
    half = n // 2
    return _pair(adasum_local_tree(vectors[:half]),
                 adasum_local_tree(vectors[half:]))


def _codec_permute(piece, axis, perm, wire_codec):
    """ppermute one VHDD piece, optionally over an fp8 wire.

    With ``wire_codec="fp8"`` the piece is quantized e4m3 with a per-piece
    max-abs scale; the f32 scale rides a second (scalar) ppermute and the
    receiver dequantizes back to the working dtype.  All VHDD arithmetic
    (dot products, mixing) stays in the working dtype/f32 -- fp8 touches
    only the wire, quartering the exchange bytes of an f32 bucket
    (halving fp16's).
    """
    if wire_codec is None:
        return lax.ppermute(piece, axis, perm)
    if wire_codec != "fp8":
        raise ValueError(f"unknown adasum wire codec {wire_codec!r}")
    from ..collectives.compression import fp8_dequantize, fp8_quantize
    q, scale = fp8_quantize(piece)
    recv_q = lax.ppermute(q, axis, perm)
    recv_s = lax.ppermute(scale, axis, perm)
    return fp8_dequantize(recv_q, recv_s, piece.dtype)


def adasum_allreduce(x, axis: str = "hvd", members=None, wire_codec=None):
    """Adasum-allreduce ``x`` across the (power-of-two) flat mesh axis.

    Vector-halving distance-doubling (the reference's ``adasum.h``
    FusedAllreduce schedule): at level k each rank exchanges HALF of its
    working segment with its distance-2^k partner, so the payload halves as
    the distance doubles -- O(n) bytes per rank total, not O(n log p).  The
    mixing coefficients need FULL-vector dot products, which after halving
    live distributed across the 2^(k+1)-rank merged group: each rank
    computes partials on its retained piece and the 3 scalars are summed
    over the group (an all_gather of 3 floats per level -- the analogue of
    the reference's per-level MPI scalar allreduce, negligible bytes).  A
    reverse-order distance-halving allgather rebuilds the full vector.

    ``members`` (static tuple of global ranks, power-of-two count): run the
    SAME schedule among the members only -- the masked-VHDD process-set
    variant.  The permutes pair members by their position in the tuple, so
    bytes stay O(n) per member regardless of subset or mesh size (replacing
    a gather-everything-everywhere approach that moved O(mesh * n)).
    Non-member devices trace the same program but their ppermute slots
    receive zeros and their scalar partials are masked out of the group
    sums; their output is GARBAGE -- the caller masks it back to the
    original input (``ops.allreduce`` does).

    ``wire_codec="fp8"``: every exchanged piece (reduce halves AND the
    rebuild allgather pieces) travels e4m3 with a per-piece scale --
    ``Compression.fp8`` for the Adasum BASELINE config.  See
    :func:`_codec_permute`.
    """
    n = lax.axis_size(axis)
    if members is None:
        members = tuple(range(n))
    m = len(members)
    if m & (m - 1) != 0:
        raise ValueError(f"Adasum requires a power-of-two member count, "
                         f"got {m}")
    if m == 1:
        return x
    pos_table = np.zeros((n,), np.int32)        # rank -> member position
    is_member = np.zeros((n,), bool)
    for p, r in enumerate(members):
        pos_table[r] = p
        is_member[r] = True
    idx = lax.axis_index(axis)
    pos = jnp.asarray(pos_table)[idx]
    levels = int(math.log2(m))
    shape = x.shape
    flat = x.ravel()
    pad = (-flat.size) % m  # divisible by 2 at every halving level
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    y = flat  # my piece of my (size-2^k) group's combined vector
    for k in range(levels):
        bit = 1 << k
        perm = [(members[i], members[i ^ bit]) for i in range(m)]
        half = y.shape[0] // 2
        is_lo = (pos & bit) == 0
        first, second = y[:half], y[half:]
        # Lower position keeps the first half; partner (same position
        # within its group) keeps the second -- retained pieces stay
        # aligned on the same global index range by induction.
        mine = jnp.where(is_lo, first, second)
        give = jnp.where(is_lo, second, first)
        recv = _codec_permute(give, axis, perm, wire_codec)
        a_piece = jnp.where(is_lo, mine, recv)  # lower group's vector
        b_piece = jnp.where(is_lo, recv, mine)
        a32 = a_piece.astype(jnp.float32)
        b32 = b_piece.astype(jnp.float32)
        partial = jnp.stack([jnp.dot(a32, b32), jnp.dot(a32, a32),
                             jnp.dot(b32, b32)])
        dots_all = lax.all_gather(partial, axis, axis=0)     # [n, 3]
        # Ranks in my merged group: members whose position shares my
        # position's high bits.  The static membership mask excludes
        # non-member rows of dots_all, so their garbage partials never
        # contaminate a member's group sum.
        group_of_rank = jnp.asarray(pos_table >> (k + 1))
        in_group = jnp.asarray(is_member) & (group_of_rank == (pos >> (k + 1)))
        dot, anormsq, bnormsq = jnp.sum(
            jnp.where(in_group[:, None], dots_all, 0.0), axis=0)
        acoeff = jnp.where(anormsq < _TOL, 1.0, 1.0 - dot / (2.0 * anormsq))
        bcoeff = jnp.where(bnormsq < _TOL, 1.0, 1.0 - dot / (2.0 * bnormsq))
        y = (acoeff.astype(y.dtype) * a_piece
             + bcoeff.astype(y.dtype) * b_piece)
    # Distance-halving allgather, inverting the split order.
    for k in reversed(range(levels)):
        bit = 1 << k
        perm = [(members[i], members[i ^ bit]) for i in range(m)]
        is_lo = (pos & bit) == 0
        recv = _codec_permute(y, axis, perm, wire_codec)
        y = jnp.where(is_lo, jnp.concatenate([y, recv]),
                      jnp.concatenate([recv, y]))
    if pad:
        y = y[:-pad]
    return y.reshape(shape)
