"""``horovod_tpu.mxnet``: MXNet API shim (reference ``horovod/mxnet/``).

MXNet reached end-of-life upstream (retired by Apache in 2023) and is not
installed in TPU images, but the reference ships the binding
(``horovod/mxnet/__init__.py``, ``mpi_ops.py``: tensor collectives,
``DistributedOptimizer`` wrapping ``mx.optimizer.Optimizer.update``,
``DistributedTrainer`` wrapping ``gluon.Trainer._allreduce_grads``,
``broadcast_parameters``), so the full surface exists here.  NDArrays
bridge through numpy onto the XLA mesh exactly like the TF shim's
tensors; everything below works when the ``mxnet`` package is importable
and raises with guidance otherwise.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.basics import (  # noqa: F401
    init, shutdown, is_initialized, size, rank, local_size, local_rank,
    cross_size, cross_rank, nccl_built, mpi_built, gloo_built, tpu_built,
    cuda_built, rocm_built, start_timeline, stop_timeline,
    mpi_threads_supported,
)
from ..collectives.reduce_op import (  # noqa: F401
    ReduceOp, Average, Sum, Min, Max, Product, Adasum,
)
from ..collectives.compression import Compression  # noqa: F401
from ..collectives import eager as _eager


def _require_mxnet():
    try:
        import mxnet  # noqa: F401
        return mxnet
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.mxnet tensor APIs require the `mxnet` package, "
            "which is not installed (MXNet is EOL and absent from TPU "
            "images). Use horovod_tpu (JAX), horovod_tpu.torch, or "
            "horovod_tpu.tensorflow instead.") from e


def _to_stack(nd) -> np.ndarray:
    return _eager.replicated_stack(nd.asnumpy())


def _from_row(mx, out, ctx):
    return mx.nd.array(_eager.one_row(out), ctx=ctx)


def allreduce(tensor, average: Optional[bool] = None, name=None,
              op: Optional[ReduceOp] = None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0, process_set=None):
    """``hvd.allreduce`` for NDArrays (reference ``mxnet/mpi_ops.py``)."""
    mx = _require_mxnet()
    if op is None:
        op = Sum if average is False else Average
    out = _eager.allreduce(_to_stack(tensor), op, name=name,
                           process_set=process_set,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)
    return _from_row(mx, out, tensor.context)


def allreduce_(tensor, average: Optional[bool] = None, name=None,
               op: Optional[ReduceOp] = None, process_set=None):
    """In-place variant: writes the reduced value back into ``tensor``."""
    result = allreduce(tensor, average=average, name=name, op=op,
                       process_set=process_set)
    tensor[:] = result
    return tensor


def grouped_allreduce(tensors, average: Optional[bool] = None, name=None,
                      op: Optional[ReduceOp] = None, process_set=None):
    mx = _require_mxnet()
    if op is None:
        op = Sum if average is False else Average
    outs = _eager.grouped_allreduce([_to_stack(t) for t in tensors], op,
                                    name=name, process_set=process_set)
    return [_from_row(mx, o, t.context) for o, t in zip(outs, tensors)]


def grouped_allgather(tensors, name=None, process_set=None):
    """Reference ``hvd.grouped_allgather``: one fused gather."""
    mx = _require_mxnet()
    outs = _eager.grouped_allgather([_to_stack(t) for t in tensors],
                                    name=name, process_set=process_set)
    return [_from_row(mx, o, t.context) for o, t in zip(outs, tensors)]


def grouped_reducescatter(tensors, op: ReduceOp = Average, name=None,
                          process_set=None):
    """Reference ``hvd.grouped_reducescatter``: one fused scatter."""
    mx = _require_mxnet()
    outs = _eager.grouped_reducescatter([_to_stack(t) for t in tensors],
                                        op, name=name,
                                        process_set=process_set)
    return [_from_row(mx, o, t.context) for o, t in zip(outs, tensors)]


def allgather(tensor, name=None, process_set=None):
    """Ragged-capable allgather (first dims may differ across ranks)."""
    mx = _require_mxnet()
    out = _eager.allgather_value(tensor.asnumpy(), name=name,
                                 process_set=process_set)
    return mx.nd.array(np.asarray(out), ctx=tensor.context)


def broadcast(tensor, root_rank: int = 0, name=None, process_set=None):
    mx = _require_mxnet()
    out = _eager.broadcast(_to_stack(tensor), root_rank, name=name,
                           process_set=process_set)
    return _from_row(mx, out, tensor.context)


def broadcast_(tensor, root_rank: int = 0, name=None, process_set=None):
    tensor[:] = broadcast(tensor, root_rank, name=name,
                          process_set=process_set)
    return tensor


def alltoall(tensor, splits=None, name=None, process_set=None):
    """With ``splits``: uneven exchange, returns (received, recv_splits)
    (reference ``horovod.mxnet.alltoall`` semantics)."""
    mx = _require_mxnet()
    if splits is None:
        out = _eager.alltoall(_to_stack(tensor), name=name,
                              process_set=process_set)
        return _from_row(mx, out, tensor.context)
    sp = getattr(splits, "asnumpy", lambda: splits)()
    data = tensor.asnumpy()
    out, rsplits = _eager.alltoallv_row(data, sp, name=name,
                                        process_set=process_set)
    return (mx.nd.array(out, ctx=tensor.context, dtype=data.dtype),
            mx.nd.array(rsplits, ctx=tensor.context, dtype="int32"))


def reducescatter(tensor, op: ReduceOp = Average, name=None,
                  process_set=None):
    mx = _require_mxnet()
    out = _eager.reducescatter(_to_stack(tensor), op, name=name,
                               process_set=process_set)
    return _from_row(mx, out, tensor.context)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast a gluon param dict from root (reference
    ``horovod/mxnet/__init__.py::broadcast_parameters``)."""
    _require_mxnet()
    if not hasattr(params, "items"):
        raise ValueError("broadcast_parameters expects a dict-like of "
                         "name -> Parameter/NDArray")
    for name, p in sorted(params.items()):
        nd = p.data() if hasattr(p, "data") else p
        broadcast_(nd, root_rank, name=f"broadcast.{name}")


def broadcast_object(obj, root_rank: int = 0, name=None, process_set=None):
    from ..optim.functions import broadcast_object as _bo
    return _bo(obj, root_rank, process_set=process_set)


def allgather_object(obj, name=None, process_set=None) -> list:
    from ..optim.functions import allgather_object as _ago
    return _ago(obj, name=name, process_set=process_set)


def DistributedOptimizer(optimizer, op: ReduceOp = Average,
                         process_set=None):
    """Wrap ``mx.optimizer.Optimizer`` so ``update()`` sees reduced grads
    (reference ``horovod/mxnet/__init__.py::DistributedOptimizer``)."""
    _require_mxnet()

    class _Distributed(optimizer.__class__):
        def __init__(self):
            self.__dict__.update(optimizer.__dict__)

        def _do_allreduce(self, index, grad):
            if isinstance(index, (tuple, list)):
                grouped = grouped_allreduce(
                    list(grad), op=op, name=f"grad.{index[0]}",
                    process_set=process_set)
                for g, r in zip(grad, grouped):
                    g[:] = r
            else:
                allreduce_(grad, name=f"grad.{index}", op=op,
                           process_set=process_set)

        def update(self, index, weight, grad, state):
            self._do_allreduce(index, grad)
            super().update(index, weight, grad, state)

        def update_multi_precision(self, index, weight, grad, state):
            self._do_allreduce(index, grad)
            super().update_multi_precision(index, weight, grad, state)

    return _Distributed()


def DistributedTrainer(params, optimizer, optimizer_params=None,
                       process_set=None):
    """Gluon trainer whose ``_allreduce_grads`` runs the mesh collective
    (reference ``horovod/mxnet/__init__.py::DistributedTrainer``)."""
    mx = _require_mxnet()

    class _Trainer(mx.gluon.Trainer):
        def __init__(self):
            super().__init__(params, optimizer,
                             optimizer_params or {}, kvstore=None)
            # Reference behavior: the optimizer's rescale_grad divides by
            # world size, so the collective must SUM (not average) or the
            # update would be scaled by 1/size^2.
            self._scale /= size()

        def _allreduce_grads(self):
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    for g in param.list_grad():
                        allreduce_(g, name=f"grad.{i}", op=Sum,
                                   process_set=process_set)

    return _Trainer()
