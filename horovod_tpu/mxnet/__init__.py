"""``horovod_tpu.mxnet``: MXNet API shim (reference ``horovod/mxnet/``).

MXNet reached end-of-life upstream (retired by Apache in 2023) and is not
installed in TPU images; the reference still ships the binding, so the
surface exists here for parity.  Core identity functions work without
MXNet (they don't touch NDArrays); the tensor APIs require the ``mxnet``
package and raise with guidance otherwise.
"""

from __future__ import annotations

from ..core.basics import (  # noqa: F401
    init, shutdown, is_initialized, size, rank, local_size, local_rank,
    cross_size, cross_rank, nccl_built, mpi_built, gloo_built, tpu_built,
    mpi_threads_supported,
)
from ..collectives.reduce_op import (  # noqa: F401
    ReduceOp, Average, Sum, Min, Max, Product, Adasum,
)
from ..collectives.compression import Compression  # noqa: F401

_TENSOR_APIS = (
    "allreduce", "allreduce_", "grouped_allreduce", "allgather",
    "broadcast", "broadcast_", "alltoall", "reducescatter",
    "broadcast_parameters", "broadcast_object", "DistributedOptimizer",
    "DistributedTrainer",
)


def _require_mxnet():
    try:
        import mxnet  # noqa: F401
        return mxnet
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.mxnet tensor APIs require the `mxnet` package, "
            "which is not installed (MXNet is EOL and absent from TPU "
            "images). Use horovod_tpu (JAX), horovod_tpu.torch, or "
            "horovod_tpu.tensorflow instead.") from e


def __getattr__(name: str):
    if name in _TENSOR_APIS:
        _require_mxnet()
        raise NotImplementedError(
            f"horovod_tpu.mxnet.{name}: MXNet NDArray bridging is not "
            f"implemented for the TPU backend (MXNet is EOL); the "
            f"reference surface is documented for parity only.")
    raise AttributeError(name)
