"""``horovod_tpu.keras.callbacks``: the reference's callbacks namespace
(``horovod/_keras/callbacks.py`` surface; upstream examples use
``hvd.callbacks.BroadcastGlobalVariablesCallback``)."""

from . import (  # noqa: F401
    BroadcastGlobalVariablesCallback, MetricAverageCallback,
    LearningRateWarmupCallback, LearningRateScheduleCallback,
)
