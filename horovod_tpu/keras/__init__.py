"""``horovod_tpu.keras``: Keras-facing API + callbacks.

Reference: ``horovod/keras/`` + ``horovod/_keras/callbacks.py`` --
``DistributedOptimizer`` plus the training callbacks
(``BroadcastGlobalVariablesCallback``, ``MetricAverageCallback``,
``LearningRateWarmupCallback``, ``LearningRateScheduleCallback``).
"""

from __future__ import annotations

import keras
import numpy as np

from ..core.basics import (  # noqa: F401
    init, shutdown, is_initialized, size, rank, local_size, local_rank,
    cross_size, cross_rank, nccl_built, mpi_built, gloo_built, tpu_built,
    cuda_built, rocm_built, start_timeline, stop_timeline,
)
from ..collectives.reduce_op import Average, Sum  # noqa: F401
from ..collectives.compression import Compression  # noqa: F401
from ..tensorflow import (  # noqa: F401
    DistributedOptimizer, allreduce, barrier, broadcast, broadcast_variables,
)
from ..training import steps_per_execution  # noqa: F401


def compile_args(**overrides) -> dict:
    """Keras ``model.compile`` kwargs honoring ``HOROVOD_STEPS_PER_EXEC``.

    Keras already owns a steps-per-execution scan loop
    (``model.compile(steps_per_execution=k)`` drives k steps per
    ``train_function`` call on the JAX backend); this helper routes the
    framework-wide env knob into it so keras and the native
    :func:`horovod_tpu.training.make_train_loop` runner pick up the SAME
    configuration::

        model.compile(optimizer=opt, loss=loss,
                      **hvd.keras.compile_args())

    Explicit ``overrides`` win over the env.
    """
    args = {"steps_per_execution": steps_per_execution()}
    args.update(overrides)
    return args


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast initial model/optimizer state from ``root_rank`` at the
    start of training so all workers begin identical."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_train_begin(self, logs=None):
        if self._done:
            return
        broadcast_variables(self.model.weights, self.root_rank)
        if getattr(self.model, "optimizer", None) is not None and \
                getattr(self.model.optimizer, "variables", None):
            broadcast_variables(self.model.optimizer.variables,
                                self.root_rank)
        self._done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch metrics over all workers (rank-0 logs are global)."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs:
            return
        for k, v in list(logs.items()):
            if isinstance(v, (int, float, np.floating)):
                logs[k] = float(np.asarray(
                    allreduce(np.asarray(v, np.float32), name=f"metric.{k}")))


class LearningRateWarmupCallback(keras.callbacks.Callback):
    """Linearly ramp the LR from lr/size to lr over ``warmup_epochs``
    (the reference's large-batch warmup recipe)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 steps_per_epoch: int = 100, verbose: int = 0):
        super().__init__()
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self._step = 0

    def _set_lr(self, lr: float) -> None:
        self.model.optimizer.learning_rate.assign(lr)

    def on_train_batch_begin(self, batch, logs=None):
        total = self.warmup_epochs * self.steps_per_epoch
        if self._step >= total:
            return
        frac = self._step / max(1, total)
        lr = self.initial_lr * (1.0 / size() + frac * (1 - 1.0 / size()))
        self._set_lr(lr)
        self._step += 1


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the LR by ``multiplier`` within [start_epoch, end_epoch)."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.multiplier = multiplier if callable(multiplier) else \
            (lambda epoch: multiplier)
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_epoch_begin(self, epoch, logs=None):
        if epoch < self.start_epoch or \
                (self.end_epoch is not None and epoch >= self.end_epoch):
            return
        self.model.optimizer.learning_rate.assign(
            self.initial_lr * self.multiplier(epoch))


__all__ = [
    "init", "shutdown", "is_initialized", "size", "rank", "local_size",
    "local_rank", "cross_size", "cross_rank", "nccl_built", "mpi_built",
    "gloo_built", "tpu_built", "cuda_built", "rocm_built",
    "start_timeline", "stop_timeline", "allreduce", "barrier",
    "broadcast", "broadcast_variables", "Average", "Sum", "Compression",
    "DistributedOptimizer", "BroadcastGlobalVariablesCallback",
    "MetricAverageCallback", "LearningRateWarmupCallback",
    "LearningRateScheduleCallback", "callbacks",
    "steps_per_execution", "compile_args",
]

from . import callbacks  # noqa: E402,F401  (hvd.callbacks.* namespace)
