"""Unified Pallas kernel switch + the kernel contract registry.

One environment flag, ``HOROVOD_PALLAS`` (``HVD_TPU_PALLAS``), gates
every Pallas kernel family in the package:

- ``auto`` (default): kernels run on TPU, the XLA reference runs
  elsewhere;
- ``1``: force the kernels everywhere (off-TPU they run in the Pallas
  interpreter -- slow, but numerically the kernel path; this is what the
  CPU parity tests and the CI step audit use);
- ``0``: force the XLA reference everywhere.

Per-family overrides (``HOROVOD_PALLAS_FLASH``, ``HOROVOD_PALLAS_DECODE``,
``HOROVOD_PALLAS_FUSED_UPDATE``, ``HOROVOD_PALLAS_BN``) take the same
values and win over the global flag, so a single family can be pinned
on/off while the rest follow ``HOROVOD_PALLAS``.

The legacy ``HVD_TPU_FLASH`` flag (PR 10) is subsumed: it is still
honored for the ``flash`` family (with a one-shot ``DeprecationWarning``)
but loses to ``HOROVOD_PALLAS_FLASH`` when both are set.

Kernel contracts
----------------

Pallas kernels lower to custom calls that are opaque to anything reading
the step at the HLO level, so each family registers its collective/wire
contract here: the collectives it is allowed to emit (none -- every
exchange stays in XLA where the planner, the PR 8 auditor, and the PR 9
span recorder can see it) and whether it changes any exchange's wire
bytes (never).  ``analysis.stepmodel`` reads this registry to annotate
audited steps instead of declining them, and ``analysis.trace_audit``
enforces the collective-free claim by walking every ``pallas_call``
sub-jaxpr in the traced step.
"""

from __future__ import annotations

import os
import warnings

import jax

# Kernel family -> contract.  ``collectives`` is the multiset of
# collective legs the kernel itself may emit (empty: the exchange stays
# in XLA); ``wire_delta_bytes`` is how the family changes any exchange's
# on-wire payload (always 0 -- e.g. fused_update keeps the PowerSGD P/Q
# factor psums outside the kernels, untouched).
KERNEL_CONTRACTS = {
    "flash": {
        "collectives": (),
        "wire_delta_bytes": 0,
        "site": "ops.attention.flash_attention",
        "note": "flash fwd/bwd kernels; exchange untouched",
    },
    "flash_decode": {
        "collectives": (),
        "wire_delta_bytes": 0,
        "site": "ops.attention.decode_attention",
        "note": "split-KV decode kernel; the serving step's two "
                "row-parallel psums per layer stay in XLA",
    },
    "fused_update": {
        "collectives": (),
        "wire_delta_bytes": 0,
        "site": "collectives.ops.powersgd_allreduce",
        "note": "matricize/orthonormalize/EF-residual fused; the two "
                "P/Q factor psums stay in XLA between the kernels",
    },
    "bn_bwd": {
        "collectives": (),
        "wire_delta_bytes": 0,
        "site": "ops.bn.fused_bn_backward",
        "note": "two-pass BN backward; gradient exchange untouched",
    },
}

# Per-family override env suffix (``HOROVOD_PALLAS_<suffix>``).
_FAMILY_ENV = {
    "flash": "PALLAS_FLASH",
    "flash_decode": "PALLAS_DECODE",
    "fused_update": "PALLAS_FUSED_UPDATE",
    "bn_bwd": "PALLAS_BN",
}

_warned_legacy = False


def _read(name: str):
    """Read ``HVD_TPU_<name>`` then ``HOROVOD_<name>`` (the package's
    standard env precedence, mirroring ``core.config._env``)."""
    v = os.environ.get("HVD_TPU_" + name)
    if v is None:
        v = os.environ.get("HOROVOD_" + name)
    return v


def _legacy_flash_flag():
    """The pre-unification ``HVD_TPU_FLASH`` flag, deprecation-warned."""
    global _warned_legacy
    v = os.environ.get("HVD_TPU_FLASH")
    if v is not None and not _warned_legacy:
        _warned_legacy = True
        warnings.warn(
            "HVD_TPU_FLASH is deprecated; use HOROVOD_PALLAS (all kernel "
            "families) or HOROVOD_PALLAS_FLASH (this family only)",
            DeprecationWarning, stacklevel=3)
    return v


def pallas_enabled(family: str) -> bool:
    """Whether the ``family`` kernels should run for the current call.

    Resolution order: the per-family override, then (for ``flash``) the
    legacy ``HVD_TPU_FLASH`` flag, then the global ``HOROVOD_PALLAS``,
    then ``auto`` (TPU only).  Read per call: tests flip the env between
    traces.
    """
    if family not in KERNEL_CONTRACTS:
        raise ValueError(f"unknown pallas kernel family {family!r}; "
                         f"known: {sorted(KERNEL_CONTRACTS)}")
    flag = _read(_FAMILY_ENV[family])
    if flag is None and family == "flash":
        flag = _legacy_flash_flag()
    if flag is None:
        flag = _read("PALLAS")
    if flag in (None, "", "auto"):
        return jax.default_backend() == "tpu"
    return flag != "0"


def interpret_mode() -> bool:
    """Pallas kernels interpret off-TPU (CPU tests, the CI step audit)."""
    return jax.default_backend() != "tpu"


def registered_kernels():
    return tuple(sorted(KERNEL_CONTRACTS))


def kernel_contract(family: str) -> dict:
    return dict(KERNEL_CONTRACTS[family])


def active_kernels():
    """The families whose kernels would dispatch right now."""
    return tuple(k for k in registered_kernels() if pallas_enabled(k))
