"""Pallas TPU kernels and fused ops (attention, etc.).

The reference has no op-kernel library of its own (it delegates matmuls and
attention to the host framework and ships only CUDA memcpy/scale kernels,
``horovod/common/ops/cuda/cuda_kernels.cu``); on TPU the hot ops are
first-class here.
"""

from .attention import (  # noqa: F401
    attention_reference,
    flash_attention,
)

__all__ = ["attention_reference", "flash_attention"]
