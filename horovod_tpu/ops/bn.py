"""Fused BatchNorm backward: the two-pass Pallas kernels the probe asked
for, plus a flax-compatible ``BatchNorm`` module to dispatch them.

``examples/bn_bwd_probe.py`` attributes ~45 ms of the RN50 backward to
HBM-bound BN/relu/residual chains and establishes the 7N two-pass floor:
the backward of a train-mode BN is two full passes over the activation
arena (pass 1 reads ``x``/``dy`` to reduce the per-channel sums
``dbeta = sum(dy)`` and ``dgamma = sum(dy * xhat)``; pass 2 reads them
again and writes ``dx``), and anything beyond ~5 arena reads + 1 write
is XLA failing to fuse the chain.  The two kernels here are exactly
those passes, gated by ``HOROVOD_PALLAS`` / ``HOROVOD_PALLAS_BN`` and
dispatched from the RN50 model's BN sites via the ``BatchNorm`` module
below (variable collections match ``flax.linen.BatchNorm`` --
``params/{scale,bias}``, ``batch_stats/{mean,var}`` -- and the module
class shares the name, so swapping it in changes neither the param tree
nor checkpoint layout).

Backward closed form (biased batch variance over ``N`` reduce elements,
statistics in f32 like flax):

    dx = scale * rsqrt(var + eps) * (dy - dbeta/N - xhat * dgamma/N)

The XLA reference path computes the identical formula, so the
interpreter-mode parity test pins kernel == reference == autodiff.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas import interpret_mode, pallas_enabled

_MIN_BLOCK = 8


def _row_block(n: int, preferred: int = 512) -> int:
    b = min(preferred, n) // _MIN_BLOCK * _MIN_BLOCK
    while b >= _MIN_BLOCK and n % b:
        b -= _MIN_BLOCK
    return b if b >= _MIN_BLOCK else n


def batch_stats(x):
    """f32 mean/var over every axis but the last (fast variance,
    ``E[x^2] - E[x]^2``, matching flax's default)."""
    xf = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(xf, axis=axes)
    var = jnp.maximum(jnp.mean(jnp.square(xf), axis=axes)
                      - jnp.square(mean), 0.0)
    return mean, var


# ---------------------------------------------------------------------------
# Pass 1: per-channel reductions (dbeta, dgamma).
# ---------------------------------------------------------------------------

def _reduce_kernel(x_ref, dy_ref, mean_ref, inv_ref, dbeta_ref, dgamma_ref,
                   sums_scr, *, nblocks):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sums_scr[...] = jnp.zeros_like(sums_scr)

    dy = dy_ref[...].astype(jnp.float32)
    xhat = ((x_ref[...].astype(jnp.float32) - mean_ref[...])
            * inv_ref[...])
    sums_scr[0:1, :] += jnp.sum(dy, axis=0, keepdims=True)
    sums_scr[1:2, :] += jnp.sum(dy * xhat, axis=0, keepdims=True)

    @pl.when(i == nblocks - 1)
    def _finish():
        dbeta_ref[...] = sums_scr[0:1, :]
        dgamma_ref[...] = sums_scr[1:2, :]


# ---------------------------------------------------------------------------
# Pass 2: dx.
# ---------------------------------------------------------------------------

def _dx_kernel(x_ref, dy_ref, mean_ref, inv_ref, scale_ref, dbeta_ref,
               dgamma_ref, dx_ref, *, inv_n):
    dy = dy_ref[...].astype(jnp.float32)
    xhat = ((x_ref[...].astype(jnp.float32) - mean_ref[...])
            * inv_ref[...])
    dx = (scale_ref[...] * inv_ref[...]
          * (dy - dbeta_ref[...] * inv_n - xhat * dgamma_ref[...] * inv_n))
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _bn_bwd_kernels(x2, dy2, mean, var, scale, eps):
    n, feat = x2.shape
    bn_ = _row_block(n)
    nblocks = n // bn_
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    row = lambda a: a.astype(jnp.float32).reshape(1, feat)
    blk = pl.BlockSpec((bn_, feat), lambda i: (i, 0))
    row_spec = pl.BlockSpec((1, feat), lambda i: (0, 0))
    dbeta, dgamma = pl.pallas_call(
        functools.partial(_reduce_kernel, nblocks=nblocks),
        grid=(nblocks,),
        in_specs=[blk, blk, row_spec, row_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct((1, feat), jnp.float32)] * 2,
        scratch_shapes=[pltpu.VMEM((2, feat), jnp.float32)],
        interpret=interpret_mode(),
    )(x2, dy2, row(mean), row(inv))
    dx2 = pl.pallas_call(
        functools.partial(_dx_kernel, inv_n=1.0 / n),
        grid=(nblocks,),
        in_specs=[blk, blk, row_spec, row_spec, row_spec, row_spec,
                  row_spec],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        interpret=interpret_mode(),
    )(x2, dy2, row(mean), row(inv), row(scale), dbeta, dgamma)
    return dx2, dgamma[0], dbeta[0]


def fused_bn_backward(x, scale, mean, var, dy, *, eps: float):
    """``(dx, dgamma, dbeta)`` for train-mode BN over the last axis.

    Dispatch: the two-pass Pallas kernels when the ``bn_bwd`` family is
    enabled, the identical XLA closed form otherwise.  ``x``/``dy`` keep
    their dtype on the wire (cast to f32 in-register); ``dgamma``/
    ``dbeta`` come back f32.
    """
    feat = x.shape[-1]
    n = x.size // feat
    x2 = x.reshape(n, feat)
    dy2 = dy.reshape(n, feat)
    if pallas_enabled("bn_bwd"):
        from ..timeline import spans as _spans
        _spans.note_leg("pallas/bn_bwd",
                        nbytes=7 * x.size * x.dtype.itemsize)
        dx2, dgamma, dbeta = _bn_bwd_kernels(x2, dy2, mean, var, scale,
                                             eps)
        return dx2.reshape(x.shape), dgamma, dbeta
    xf = x2.astype(jnp.float32)
    dyf = dy2.astype(jnp.float32)
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    xhat = (xf - mean.astype(jnp.float32)) * inv
    dbeta = jnp.sum(dyf, axis=0)
    dgamma = jnp.sum(dyf * xhat, axis=0)
    dx2 = (scale.astype(jnp.float32) * inv
           * (dyf - dbeta / n - xhat * dgamma / n)).astype(x.dtype)
    return dx2.reshape(x.shape), dgamma, dbeta


# ---------------------------------------------------------------------------
# Train-mode normalize with the fused backward.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def bn_train(x, scale, bias, eps):
    """``(x - mean) * rsqrt(var + eps) * scale + bias`` with batch
    statistics -- forward stays in XLA (it fuses fine), backward routes
    through ``fused_bn_backward``."""
    y, _ = _bn_train_fwd(x, scale, bias, eps)
    return y


def _bn_train_fwd(x, scale, bias, eps):
    mean, var = batch_stats(x)
    inv = jax.lax.rsqrt(var + eps)
    xf = x.astype(jnp.float32)
    y = ((xf - mean) * inv * scale.astype(jnp.float32)
         + bias.astype(jnp.float32))
    return y.astype(x.dtype), (x, scale, mean, var)


def _bn_train_bwd(eps, res, dy):
    x, scale, mean, var = res
    dx, dgamma, dbeta = fused_bn_backward(x, scale, mean, var, dy,
                                          eps=eps)
    return dx, dgamma.astype(scale.dtype), dbeta.astype(scale.dtype)


bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


class BatchNorm(nn.Module):
    """Drop-in subset of ``flax.linen.BatchNorm`` (feature axis -1,
    scale+bias always on) whose train-mode backward runs the fused
    Pallas kernels.  Same class name, param names, and batch_stats
    layout as the flax module, so ``models.resnet`` can swap between
    the two without touching checkpoints."""
    use_running_average: Optional[bool] = None
    momentum: float = 0.99
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param("use_running_average",
                                self.use_running_average,
                                use_running_average)
        feat = x.shape[-1]
        scale = self.param("scale", self.scale_init, (feat,),
                           self.param_dtype)
        bias = self.param("bias", self.bias_init, (feat,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((feat,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((feat,), jnp.float32))
        dtype = self.dtype or x.dtype
        if use_ra:
            inv = jax.lax.rsqrt(ra_var.value + self.epsilon)
            y = ((x.astype(jnp.float32) - ra_mean.value) * inv
                 * scale.astype(jnp.float32) + bias.astype(jnp.float32))
            return y.astype(dtype)
        y = bn_train(x, scale, bias, float(self.epsilon))
        if not self.is_initializing():
            # Running-stat update mirrors flax (f32 EMA; gradients never
            # flow into variables, so recomputing the stats in XLA is
            # side-effect bookkeeping, not a second backward pass).
            mean, var = batch_stats(x)
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
            ra_var.value = m * ra_var.value + (1.0 - m) * var
        return y.astype(dtype)


def use_pallas_bn() -> bool:
    """Model-construction-time dispatch for the RN50 BN sites."""
    return pallas_enabled("bn_bwd")
