"""Fused multi-head attention: Pallas TPU kernels + XLA reference.

The reference framework (Horovod) ships no attention kernels -- its BERT /
Llama workloads (BASELINE.json configs) lean on the host framework's fused
attention (torch SDPA / cuDNN flash attention).  The TPU-native equivalent
of that dependency is a Pallas flash-attention kernel pair (forward +
backward, FlashAttention-2 schedule) tiled for the MXU, with an XLA
reference implementation for CPU tests and as numerical ground truth.

Design notes (see /opt/skills/guides/pallas_guide.md):

* Grid ``(batch, heads, q_blocks, kv_blocks)`` -- the last grid dimension
  is sequential on TPU, so VMEM scratch (running max ``m``, normaliser
  ``l``, accumulator ``acc``) carries the online-softmax state across kv
  blocks; output and logsumexp are written on the final kv step.
* Softmax statistics cross the kernel boundary as ``(block, 128)``
  lane-broadcast tiles (the layout jax's own TPU flash attention uses for
  its l/m residuals); the persistent VJP residual is sliced to ``(b,h,t)``
  so only transient kernel I/O pays the lane broadcast.
* Backward is the standard two-kernel FA2 split: ``dq`` accumulates over
  kv blocks, ``dk/dv`` accumulate over q blocks; ``delta = rowsum(dO*O)``
  is precomputed by XLA (a trivially fused elementwise reduce).
* Causal masking is bottom-right aligned (query ``i`` sits at absolute
  position ``tk - tq + i``, the KV-cache/decode convention, matching
  ``attention_reference``); whole blocks above the diagonal are predicated
  off with ``@pl.when``.
* Grouped-query attention broadcasts kv heads through the BlockSpec
  ``index_map`` (query head ``h`` reads kv head ``h // rep``) instead of
  materializing repeated K/V in HBM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128          # TPU lane count: last-dim tile granularity.
_MIN_BLOCK = 8        # f32 sublane tile; smallest sane seq block.
_NEG_INF = -1e30      # Softmax mask value (finite: avoids NaN on empty rows).

# Swept on the v5e (B1 H8 S8192 D128 causal bf16 fwd+bwd, value-fetch
# fenced, WITHIN-RUN comparisons).  Round 2: kv=512 beats kv=256 by
# ~19% at S=2048 and ~39% at S=8192 -- the wider kv block halves the
# grid-iteration VMEM swaps per q block and feeds the MXU longer runs.
# Round 3 (differential scan-chains, which cancel the tunnel's
# ~60-120 ms dispatch overhead that inflated round-2's absolute
# numbers ~4x at this shape): q=512 beats q=256 by ~16% at S=8192
# (5.18 -> 4.33 ms true kernel time, ~57% MFU) and directionally at
# S=2048 -- the bigger q tile amortizes the backward's dq/dk/dv
# re-reads.  Shorter sequences clamp the block to the sequence
# automatically.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_KV = 512


def _use_pallas() -> bool:
    # Unified switch (PR 13): HOROVOD_PALLAS / HOROVOD_PALLAS_FLASH,
    # with the legacy HVD_TPU_FLASH honored behind a deprecation note.
    from . import pallas as _pallas
    return _pallas.pallas_enabled("flash")


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block(seq: int, preferred: int) -> int:
    """Largest 8-multiple block <= preferred dividing seq, else 0.

    Kernels assume blocks tile the sequence evenly and respect the f32
    8-sublane tile; sequences with no such divisor fall back to the
    reference path (dispatcher checks for 0).
    """
    b = min(preferred, seq) // _MIN_BLOCK * _MIN_BLOCK
    while b >= _MIN_BLOCK and seq % b:
        b -= _MIN_BLOCK
    return max(b, 0)


def _block_lane(seq: int, preferred: int) -> int:
    """Largest block <= preferred dividing seq that also satisfies the
    LANE-dim rule (multiple of 128, or the whole sequence), else 0.

    The whole-sequence case still requires the 8-sublane rule (the same
    block tiles q/k/v), so non-8-multiple sequences fall back like the
    non-segment path does.
    """
    if seq <= preferred:
        return seq if seq % _MIN_BLOCK == 0 else 0
    b = min(preferred, seq) // _LANES * _LANES
    while b >= _LANES and seq % b:
        b -= _LANES
    return max(b, 0)


# ---------------------------------------------------------------------------
# Reference (XLA) implementation -- ground truth + CPU fallback.
# ---------------------------------------------------------------------------

def attention_reference(q, k, v, *, causal: bool = False,
                        scale: Optional[float] = None,
                        segment_ids=None, kv_segment_ids=None):
    """Plain XLA attention. q,k,v: (batch, heads, seq, head_dim).

    Causal masking is bottom-right aligned: with ``tq < tk`` (decode with a
    KV cache), query ``i`` attends keys ``0 .. tk - tq + i``.

    ``segment_ids``/``kv_segment_ids`` (``(batch, tq)`` / ``(batch, tk)``
    int): a query attends only keys with an EQUAL segment id -- the
    packed-sequence convention (and padding isolation: give pad tokens a
    segment of their own).  A DEAD row (segment matches no key, i.e.
    pure padding) produces ZERO output and zero gradients, identical
    between this reference and the Pallas kernels.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask, logits, _NEG_INF)
    if segment_ids is not None:
        if kv_segment_ids is None:
            if q.shape[2] != k.shape[2]:
                raise ValueError("kv_segment_ids is required when "
                                 "tq != tk")
            kv_segment_ids = segment_ids
        seg = (segment_ids[:, None, :, None]
               == kv_segment_ids[:, None, None, :])
        logits = jnp.where(seg, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if segment_ids is not None:
        # DEAD rows (segment matches no key, e.g. padding): zero output
        # and zero gradients, matching the Pallas kernels -- not the
        # uniform softmax a plain -inf mask degenerates to.
        alive = jnp.max(logits, axis=-1, keepdims=True) > _NEG_INF / 2
        probs = jnp.where(alive, probs, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.astype(v.dtype)


def decode_attention(q, k, v, *, lengths, scale: Optional[float] = None,
                     block_kv: int = DEFAULT_BLOCK_KV,
                     force_reference: bool = False):
    """Single-token decode attention over a length-masked KV cache.

    ``q``: ``(b, h, 1, d)`` -- the current token's query per slot.
    ``k``/``v``: ``(b, h_kv, s, d)`` -- the cache view, where only the
    first ``lengths[i]`` positions of row ``i`` hold live keys (anything
    beyond is recycled-page garbage and must not contribute).
    ``lengths``: ``(b,)`` int, live key count per row; a row with
    ``lengths == 0`` (an idle batch slot) produces EXACTLY zero output
    via the reference's dead-row convention.

    No causal mask is needed: the current token sits at position
    ``lengths - 1`` and every cached key is at a position ``< lengths``,
    so the length mask IS the bottom-right-aligned causal mask for a
    one-token query.

    Dispatch: the split-KV flash-decoding kernel when the ``flash_decode``
    family is enabled (``HOROVOD_PALLAS`` / ``HOROVOD_PALLAS_DECODE``) and
    the cache length has a block divisor; the XLA reference otherwise.
    The kernel grids over KV page-blocks with the grouped query heads of
    one kv head as the MXU tile, carrying online-softmax partials
    (running max / normalizer / accumulator) across the sequential block
    axis -- the log-sum-exp merge of the split-KV partials.  Pages past
    ``lengths`` are either whole-block predicated off or masked per
    column, so recycled-page garbage never contributes.
    """
    if q.shape[2] != 1:
        raise ValueError(f"decode_attention expects a single-token query, "
                         f"got tq={q.shape[2]}")
    if q.shape[1] % k.shape[1]:
        raise ValueError(f"query heads {q.shape[1]} not a multiple of "
                         f"kv heads {k.shape[1]}")
    if lengths.shape != (q.shape[0],):
        raise ValueError(f"lengths must be ({q.shape[0]},), got "
                         f"{lengths.shape}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    from . import pallas as _pallas
    s = k.shape[2]
    bk = _block(s, block_kv)
    if (not force_reference and bk >= _MIN_BLOCK
            and _pallas.pallas_enabled("flash_decode")):
        return _flash_decode(q, k, v, lengths, float(scale), bk)
    rep = q.shape[1] // k.shape[1]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    kv_seg = (jnp.arange(s)[None, :]
              < lengths[:, None]).astype(jnp.int32)
    q_seg = jnp.ones((q.shape[0], 1), jnp.int32)
    return attention_reference(q, k, v, causal=False, scale=scale,
                               segment_ids=q_seg, kv_segment_ids=kv_seg)


def verify_attention(q, k, v, *, lengths, scale: Optional[float] = None,
                     block_kv: int = DEFAULT_BLOCK_KV,
                     force_reference: bool = False):
    """Width-k verify attention: the speculative-decoding generalisation
    of :func:`decode_attention` to ``w`` draft positions per slot.

    ``q``: ``(b, h, w, d)`` -- query row ``i`` is the token being
    verified at absolute position ``lengths - 1 + i`` (row 0 is exactly
    the plain decode query).  ``k``/``v``: ``(b, h_kv, s, d)`` cache
    views that ALREADY hold the w in-step-written keys.  ``lengths``:
    ``(b,)`` live key count as seen by row 0 (pre-step length + 1);
    row ``i`` sees ``lengths + i`` keys -- the length mask doubles as
    the bottom-right-aligned causal mask across the draft window, the
    same argument that makes single-token decode mask-free.

    Implementation: one :func:`decode_attention` call per row, so every
    row's softmax runs the EXACT op shapes of the plain decode step --
    the greedy-exactness contract (speculative streams bitwise equal to
    plain decode) rides on row-for-row numerical identity, not on a
    reimplementation agreeing to tolerance.  ``w`` is the speculation
    width (small), so the unrolled loop costs w kernel calls inside one
    jitted step, not w dispatches.
    """
    w = q.shape[2]
    outs = []
    for i in range(w):
        li = jnp.where(lengths > 0,
                       jnp.minimum(lengths + i, k.shape[2]), 0)
        outs.append(decode_attention(
            q[:, :, i:i + 1, :], k, v, lengths=li, scale=scale,
            block_kv=block_kv, force_reference=force_reference))
    return jnp.concatenate(outs, axis=2)


# ---------------------------------------------------------------------------
# Flash-decoding: split-KV kernel for the single-token cache read.
# ---------------------------------------------------------------------------

def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, bk, nk):
    """Grid ``(batch, kv_heads, kv_blocks)``; the last axis is sequential
    on TPU, so VMEM scratch carries the online-softmax state across KV
    blocks and the final block folds the partials -- the split-KV
    log-sum-exp merge without a second kernel launch.

    The q tile is the ``rep`` grouped query heads of this kv head
    (``(rep, d)``): decode has one token per slot, so the head group is
    the only MXU row dimension available.  Blocks wholly past
    ``lengths[b]`` are predicated off; the straddling block masks per
    column.  A dead slot (``lengths == 0``) runs no live block and
    finishes with ``l == 0`` -> exactly zero output.
    """
    ki = pl.program_id(2)
    length = len_ref[0, 0]

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(ki * bk < length)
    def _step():
        qg = q_ref[0, 0].astype(jnp.float32)          # (rep, d)
        kb = k_ref[0, 0].astype(jnp.float32)          # (bk, d)
        s = jax.lax.dot_general(qg, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = ki * bk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(cols < length, s, _NEG_INF)

        m_prev = m_scr[:, :1]                         # (rep, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                        # (rep, bk)
        alpha = jnp.exp(m_prev - m_new)               # (rep, 1)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        vb = v_ref[0, 0].astype(jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        o = acc_scr[:] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = o.astype(o_ref.dtype)


def _flash_decode(q, k, v, lengths, scale: float, bk: int):
    """Split-KV decode dispatch: ``q (b, h, 1, d)``, ``k/v (b, h_kv, s,
    d)`` -> ``(b, h, 1, d)``.  GQA folds the query-head group onto the
    sublane axis (``q4[b, kv, rep, d]``) instead of repeating K/V in HBM,
    matching the training kernels' ``h // rep`` index-map broadcast."""
    b, h, _, d = q.shape
    h_kv, s = k.shape[1], k.shape[2]
    rep = h // h_kv
    nk = s // bk
    q4 = q.reshape(b, h_kv, rep, d)
    len2 = lengths.astype(jnp.int32).reshape(b, 1)
    from ..controller import fusion as _fusion
    from ..timeline import spans as _spans
    _spans.note_leg(_fusion.plan_exchange(
        "kernel", kernel="flash_decode",
        nbytes=k.size * k.dtype.itemsize * 2).legs[0])
    kernel = functools.partial(_decode_kernel, scale=scale, bk=bk, nk=nk)
    o = pl.pallas_call(
        kernel,
        grid=(b, h_kv, nk),
        in_specs=[
            pl.BlockSpec((1, 1), lambda bi, hi, j: (bi, 0)),
            pl.BlockSpec((1, 1, rep, d), lambda bi, hi, j: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, j: (bi, hi, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, j: (bi, hi, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rep, d),
                               lambda bi, hi, j: (bi, hi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h_kv, rep, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((rep, _LANES), jnp.float32),
            pltpu.VMEM((rep, _LANES), jnp.float32),
            pltpu.VMEM((rep, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(len2, q4, k, v)
    return o.reshape(b, h, 1, d)


def _causal_mask(s, qi, ki, bq, bk, off):
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + off
    cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(rows >= cols, s, _NEG_INF)


def _seg_mask(s, qseg_ref, kseg_ref):
    """Mask logits where query/key segment ids differ (refs hold the
    ``(1, bq)`` / ``(1, bk)`` id blocks for this grid cell)."""
    qs = qseg_ref[0, 0][:, None]                  # (bq, 1)
    ks = kseg_ref[0, 0][None, :]                  # (1, bk)
    return jnp.where(qs == ks, s, _NEG_INF)


def _seg_live(live, qseg_ref, kseg_ref):
    """Combine the causal block-liveness predicate with a dynamic
    segment-range test: a q block and a kv block with disjoint
    [min, max] id ranges share NO equal pair for ANY id layout, so the
    whole block is skippable (the splash-attention pruning).  Sortedness
    is NOT a correctness precondition -- sorted packed ids merely make
    per-block ranges tight, maximising how often pruning fires.
    Skipping is numerically exact: a processed all-masked block only
    ever contributes alpha-erased garbage (before any live block) or
    p = 0 terms (after one), and the all-skipped dead-row case is
    handled by the _finish zeroing.
    """
    qs = qseg_ref[0, 0]
    ks = kseg_ref[0, 0]
    overlap = ((jnp.min(qs) <= jnp.max(ks))
               & (jnp.max(qs) >= jnp.min(ks)))
    return overlap if live is True else live & overlap


# ---------------------------------------------------------------------------
# Forward kernel.
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, has_seg,
                bq, bk, nk, off):
    if has_seg:
        qseg_ref, kseg_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        qseg_ref = kseg_ref = None
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: block is live unless it lies entirely above the diagonal;
    # with segment ids, also unless the blocks' id ranges are disjoint
    # (dynamic predicate -- packed ids are sorted, so this prunes every
    # cross-sequence block).
    live = True if not causal else (ki * bk <= qi * bq + bq - 1 + off)
    if has_seg:
        live = _seg_live(live, qseg_ref, kseg_ref)

    @pl.when(live)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk, off)
        if has_seg:
            s = _seg_mask(s, qseg_ref, kseg_ref)

        m_prev = m_scr[:, :1]                        # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)    # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                       # (bq, bk)
        alpha = jnp.exp(m_prev - m_new)              # (bq, 1)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v_blk = v_ref[0, 0].astype(jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o = acc_scr[:] / l_safe
        lse = m_scr[:, :1] + jnp.log(l_safe)
        if has_seg:
            # DEAD rows (m never rose above the mask floor): zero the
            # output, and push lse to +BIG so both backward kernels'
            # p = exp(s - lse) underflows to exactly 0 -- without this,
            # f32 absorbs log(l) into -1e30 and the backward sees
            # p = 1 PER KEY (a ~tk-fold gradient explosion on pad rows;
            # caught by review, regression-tested).
            dead = m_scr[:, :1] <= _NEG_INF / 2
            o = jnp.where(dead, 0.0, o)
            lse = jnp.where(dead, -_NEG_INF, lse)
        o_ref[0, 0] = o.astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[-2:])


def _flash_fwd(q, k, v, qseg, kseg, *, scale, causal, bq, bk):
    batch, heads, tq, d = q.shape
    tk = k.shape[2]
    rep = heads // k.shape[1]
    bq = _block(tq, bq)
    bk = _block(tk, bk)
    nq, nk = tq // bq, tk // bk
    off = tk - tq
    grid = (batch, heads, nq, nk)
    has_seg = qseg is not None
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               has_seg=has_seg, bq=bq, bk=bk, nk=nk,
                               off=off)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b, h, i, j: (b, h // rep, j, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b, h, i, j: (b, h // rep, j, 0)),
    ]
    operands = [q, k, v]
    if has_seg:
        # (batch, 1, t) with a (1, 1, block) spec: the sublane block dim
        # equals the array dim (Mosaic's last-two-dims rule); the lane
        # dim must divide by 128 or equal t (dispatcher guarantees it).
        in_specs += [
            pl.BlockSpec((1, 1, bq), lambda b, h, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j)),
        ]
        operands += [qseg[:, None, :], kseg[:, None, :]]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, _LANES), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, heads, tq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# Backward kernels.
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               scale, causal, has_seg, bq, bk, nk, off):
    if has_seg:
        qseg_ref, kseg_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
        qseg_ref = kseg_ref = None
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = True if not causal else (ki * bk <= qi * bq + bq - 1 + off)
    if has_seg:
        live = _seg_live(live, qseg_ref, kseg_ref)

    @pl.when(live)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk, off)
        if has_seg:
            s = _seg_mask(s, qseg_ref, kseg_ref)
        p = jnp.exp(s - lse)                               # (bq, bk)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                scale, causal, has_seg, bq, bk, nq, off):
    if has_seg:
        qseg_ref, kseg_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        qseg_ref = kseg_ref = None
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = True if not causal else (qi * bq + bq - 1 + off >= ki * bk)
    if has_seg:
        live = _seg_live(live, qseg_ref, kseg_ref)

    @pl.when(live)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk, off)
        if has_seg:
            s = _seg_mask(s, qseg_ref, kseg_ref)
        p = jnp.exp(s - lse)                               # (bq, bk)
        dv_scr[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                      # (bq, bk)
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:]
        dv_ref[0, 0] = dv_scr[:]


def _flash_bwd(res, g, *, scale, causal, bq, bk):
    q, k, v, o, lse, qseg, kseg = res
    batch, heads, tq, d = q.shape
    h_kv, tk = k.shape[1], k.shape[2]
    rep = heads // h_kv
    bq = _block(tq, bq)
    bk = _block(tk, bk)
    nq, nk = tq // bq, tk // bk
    off = tk - tq
    has_seg = qseg is not None

    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse_t = jnp.broadcast_to(lse[..., None], (*lse.shape, _LANES))
    delta_t = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANES))

    stat_spec_q = pl.BlockSpec((1, 1, bq, _LANES),
                               lambda b, h, i, j: (b, h, i, 0))

    dq_in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b, h, i, j: (b, h // rep, j, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b, h, i, j: (b, h // rep, j, 0)),
        pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
        stat_spec_q,
        stat_spec_q,
    ]
    dq_operands = [q, k, v, g, lse_t, delta_t]
    if has_seg:
        dq_in_specs += [
            pl.BlockSpec((1, 1, bq), lambda b, h, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j)),
        ]
        dq_operands += [qseg[:, None, :], kseg[:, None, :]]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, bq=bq, bk=bk, nk=nk, off=off),
        grid=(batch, heads, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(*dq_operands)

    # dk/dv at *query*-head granularity in f32 (per-group partials), group-
    # summed outside the kernel; transient only -- forward K/V are never
    # materialized per query head.
    stat_spec_kq = pl.BlockSpec((1, 1, bq, _LANES),
                                lambda b, h, j, i: (b, h, i, 0))
    dkv_in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b, h, j, i: (b, h // rep, j, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b, h, j, i: (b, h // rep, j, 0)),
        pl.BlockSpec((1, 1, bq, d), lambda b, h, j, i: (b, h, i, 0)),
        stat_spec_kq,
        stat_spec_kq,
    ]
    dkv_operands = [q, k, v, g, lse_t, delta_t]
    if has_seg:
        dkv_in_specs += [
            pl.BlockSpec((1, 1, bq), lambda b, h, j, i: (b, 0, i)),
            pl.BlockSpec((1, 1, bk), lambda b, h, j, i: (b, 0, j)),
        ]
        dkv_operands += [qseg[:, None, :], kseg[:, None, :]]
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          has_seg=has_seg, bq=bq, bk=bk, nq=nq, off=off),
        grid=(batch, heads, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, tk, d), jnp.float32),
            jax.ShapeDtypeStruct((batch, heads, tk, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*dkv_operands)
    if rep > 1:
        dk_h = dk_h.reshape(batch, h_kv, rep, tk, d).sum(axis=2)
        dv_h = dv_h.reshape(batch, h_kv, rep, tk, d).sum(axis=2)
    return dq, dk_h.astype(k.dtype), dv_h.astype(v.dtype)


# ---------------------------------------------------------------------------
# custom_vjp wrapper + public API.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, bq, bk):
    o, _ = _flash_fwd(q, k, v, None, None, scale=scale, causal=causal,
                      bq=bq, bk=bk)
    return o


def _flash_vjp_fwd(q, k, v, scale, causal, bq, bk):
    o, lse = _flash_fwd(q, k, v, None, None, scale=scale, causal=causal,
                        bq=bq, bk=bk)
    return o, (q, k, v, o, lse, None, None)


def _flash_vjp_bwd(scale, causal, bq, bk, res, g):
    return _flash_bwd(res, g, scale=scale, causal=causal, bq=bq, bk=bk)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# Segment-id variant: ids are integer primal operands (traced arrays), so
# they ride the custom_vjp as primals with float0 cotangents.
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_seg(q, k, v, qseg, kseg, scale, causal, bq, bk):
    o, _ = _flash_fwd(q, k, v, qseg, kseg, scale=scale, causal=causal,
                      bq=bq, bk=bk)
    return o


def _flash_seg_vjp_fwd(q, k, v, qseg, kseg, scale, causal, bq, bk):
    o, lse = _flash_fwd(q, k, v, qseg, kseg, scale=scale, causal=causal,
                        bq=bq, bk=bk)
    return o, (q, k, v, o, lse, qseg, kseg)


def _flash_seg_vjp_bwd(scale, causal, bq, bk, res, g):
    dq, dk, dv = _flash_bwd(res, g, scale=scale, causal=causal,
                            bq=bq, bk=bk)
    qseg, kseg = res[5], res[6]
    # Integer primals take float0 cotangents (jax custom_vjp contract).
    zq = jnp.zeros(qseg.shape, jax.dtypes.float0)
    zk = jnp.zeros(kseg.shape, jax.dtypes.float0)
    return dq, dk, dv, zq, zk


_flash_seg.defvjp(_flash_seg_vjp_fwd, _flash_seg_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    segment_ids=None, kv_segment_ids=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_kv: int = DEFAULT_BLOCK_KV,
                    force_reference: bool = False):
    """Fused attention. q: (b, h, t, d); k, v: (b, h_kv, s, d).

    ``h_kv`` may divide ``h`` (grouped-query attention); kv heads are
    broadcast to query heads via the kernel block index map (no HBM copy).
    ``causal=True`` requires ``t <= s`` and masks bottom-right aligned.

    ``segment_ids`` (``(b, t)`` int) restricts each query to keys with an
    EQUAL id -- packed-sequence training and padding isolation (give pad
    tokens their own id; their DEAD rows produce zero output and zero
    gradients).  ``kv_segment_ids`` (``(b, s)``) defaults to
    ``segment_ids`` when the key sequence has the same length; it is
    required for cross-length attention.  Composes with ``causal``.

    Dispatch: Pallas kernels when running on TPU (or ``HOROVOD_PALLAS=1``
    / ``HOROVOD_PALLAS_FLASH=1``, which use the interpreter off-TPU --
    slow, for tests; the legacy ``HVD_TPU_FLASH`` is still honored with a
    deprecation note), XLA reference otherwise.  Sequence lengths with no
    block-divisor >= 8 (e.g. primes) fall back to the reference
    implementation.
    """
    if q.shape[1] % k.shape[1]:
        raise ValueError(f"query heads {q.shape[1]} not a multiple of "
                         f"kv heads {k.shape[1]}")
    if causal and q.shape[2] > k.shape[2]:
        raise ValueError(
            f"causal attention requires tq <= tk, got {q.shape[2]} > "
            f"{k.shape[2]}")
    if block_q < _MIN_BLOCK or block_kv < _MIN_BLOCK:
        raise ValueError(f"block_q/block_kv must be >= {_MIN_BLOCK}, got "
                         f"{block_q}/{block_kv}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    tq, tk = q.shape[2], k.shape[2]
    if segment_ids is not None:
        if kv_segment_ids is None:
            if tq != tk:
                raise ValueError(
                    "kv_segment_ids is required when tq != tk "
                    f"({tq} != {tk})")
            kv_segment_ids = segment_ids
        if segment_ids.shape != (q.shape[0], tq):
            raise ValueError(f"segment_ids must be (batch, {tq}), got "
                             f"{segment_ids.shape}")
        if kv_segment_ids.shape != (q.shape[0], tk):
            raise ValueError(f"kv_segment_ids must be (batch, {tk}), got "
                             f"{kv_segment_ids.shape}")
        segment_ids = segment_ids.astype(jnp.int32)
        kv_segment_ids = kv_segment_ids.astype(jnp.int32)
    elif kv_segment_ids is not None:
        raise ValueError("kv_segment_ids given without segment_ids")
    if segment_ids is None:
        rbq, rbk = _block(tq, block_q), _block(tk, block_kv)
        usable_blocks = rbq >= _MIN_BLOCK and rbk >= _MIN_BLOCK
    else:
        # Segment-id blocks put the sequence on the LANE dim, so Mosaic
        # needs each block to divide by 128 or span the whole sequence;
        # search for a conforming divisor (e.g. tq=1920 -> 384) rather
        # than falling back to the O(t^2) reference.
        rbq = _block_lane(tq, block_q)
        rbk = _block_lane(tk, block_kv)
        usable_blocks = rbq >= _MIN_BLOCK and rbk >= _MIN_BLOCK
        block_q, block_kv = rbq, rbk
    if force_reference or not usable_blocks or not _use_pallas():
        if q.shape[1] != k.shape[1]:
            rep = q.shape[1] // k.shape[1]
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        return attention_reference(q, k, v, causal=causal, scale=scale,
                                   segment_ids=segment_ids,
                                   kv_segment_ids=kv_segment_ids)
    if segment_ids is not None:
        return _flash_seg(q, k, v, segment_ids, kv_segment_ids,
                          float(scale), bool(causal),
                          int(block_q), int(block_kv))
    return _flash(q, k, v, float(scale), bool(causal),
                  int(block_q), int(block_kv))
