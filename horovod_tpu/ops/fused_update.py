"""Fused PowerSGD + error-feedback update kernels.

The unfused ``collectives.ops.powersgd_allreduce`` round-trips each
bucket arena through HBM three times between its two factor psums: the
matricized bucket ``M`` is re-read for ``P = M @ Q0``, again for
``Q = M^T @ P``, and once more for the EF residual
``new_residual = acc - P @ Q_local^T`` (XLA does not fuse across the
psum boundaries, so each leg is its own HBM pass over the full arena).
The three kernel stages here fuse everything BETWEEN the collectives --
the two P/Q factor psums themselves stay in XLA, exactly where the
fusion planner, the PR 8 auditor, and the PR 9 span recorder expect
them, so the wire bytes (``2 * r * (m + c)`` f32) and the ``_EFState``
carry are unchanged whether the flag is on or off:

1. ``matricize_p``: cast + prescale + EF-residual accumulate + the
   ``P = M @ Q0`` left-factor projection, one pass over the arena;
2. (XLA) psum ``P``;
3. ``orthonormalize_q``: one modified-Gram-Schmidt round over the tiny
   ``[m, r]`` mean factor (computed once into VMEM scratch, reused by
   every grid step) fused with ``Q_local = M^T @ P``, one pass;
4. (XLA) psum ``Q``;
5. ``reconstruct_residual``: ``out = P @ Q^T`` and
   ``new_residual = acc - P @ Q_local^T`` in one final pass.

Gated by ``HOROVOD_PALLAS`` / ``HOROVOD_PALLAS_FUSED_UPDATE``; kernels
run in the Pallas interpreter off-TPU so the CPU parity tests
(``tests/test_ops_fused_update.py``) exercise the real kernel path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas import interpret_mode

_MIN_BLOCK = 8  # f32 sublane tile


def _row_block(n: int, preferred: int = 256) -> int:
    """Largest 8-multiple divisor of ``n`` <= preferred, else ``n``
    itself (single-block fallback: near-square bucket dims are not
    guaranteed a divisor; correctness never depends on the block)."""
    b = min(preferred, n) // _MIN_BLOCK * _MIN_BLOCK
    while b >= _MIN_BLOCK and n % b:
        b -= _MIN_BLOCK
    return b if b >= _MIN_BLOCK else n


# ---------------------------------------------------------------------------
# Stage 1: matricize + accumulate + left-factor projection.
# ---------------------------------------------------------------------------

def _matricize_p_kernel(x_ref, q0_ref, acc_ref, p_ref, *, prescale):
    acc = x_ref[...].astype(jnp.float32)
    if prescale != 1.0:
        acc = acc * prescale
    acc_ref[...] = acc
    p_ref[...] = jax.lax.dot_general(
        acc, q0_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _matricize_p_res_kernel(x_ref, res_ref, q0_ref, acc_ref, p_ref, *,
                            prescale):
    acc = x_ref[...].astype(jnp.float32)
    if prescale != 1.0:
        acc = acc * prescale
    acc = acc + res_ref[...]
    acc_ref[...] = acc
    p_ref[...] = jax.lax.dot_general(
        acc, q0_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def matricize_p(x_mat, res_mat, q0, *, prescale: float = 1.0):
    """``(acc, p_local)`` in one arena pass: ``acc = x*prescale + res``
    (f32), ``p_local = acc @ q0``.  ``x_mat``/``res_mat``: ``[m, c]``
    (``res_mat`` may be ``None``); ``q0``: ``[c, r]``."""
    m, c = x_mat.shape
    r = q0.shape[1]
    bm = _row_block(m)
    grid = (m // bm,)
    row_spec = pl.BlockSpec((bm, c), lambda i: (i, 0))
    q0_spec = pl.BlockSpec((c, r), lambda i: (0, 0))
    if res_mat is None:
        kernel = functools.partial(_matricize_p_kernel, prescale=prescale)
        in_specs = [row_spec, q0_spec]
        operands = (x_mat, q0)
    else:
        kernel = functools.partial(_matricize_p_res_kernel,
                                   prescale=prescale)
        in_specs = [row_spec, row_spec, q0_spec]
        operands = (x_mat, res_mat, q0)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[row_spec, pl.BlockSpec((bm, r), lambda i: (i, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((m, c), jnp.float32),
            jax.ShapeDtypeStruct((m, r), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(*operands)


# ---------------------------------------------------------------------------
# Stage 2 (post P-psum): Gram-Schmidt + right-factor projection.
# ---------------------------------------------------------------------------

def _gram_schmidt(p):
    """Modified Gram-Schmidt over the (few, static) columns -- the same
    arithmetic as ``collectives.ops._orthonormalize_columns`` with the
    columns kept 2-D ``(m, 1)`` for the VPU (``jnp.dot(u, v)`` there ==
    ``sum(u * v)`` here, f32 either way)."""
    cols = []
    for k in range(p.shape[1]):
        v = p[:, k:k + 1]
        for u in cols:
            v = v - jnp.sum(u * v) * u
        norm = jnp.sqrt(jnp.sum(v * v))
        cols.append(v / jnp.maximum(norm, 1e-12))
    return jnp.concatenate(cols, axis=1)


def _orthonormalize_q_kernel(acc_ref, p_ref, po_ref, q_ref, po_scr):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _orth():
        po = _gram_schmidt(p_ref[...])
        po_scr[...] = po
        po_ref[...] = po

    q_ref[...] = jax.lax.dot_general(
        acc_ref[...], po_scr[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def orthonormalize_q(acc_mat, p_mean):
    """``(p_orth, q_local)``: orthonormalize the psum'd ``[m, r]`` left
    factor once (VMEM scratch carries it across the sequential grid) and
    project ``q_local = acc^T @ p_orth`` in the same arena pass."""
    m, c = acc_mat.shape
    r = p_mean.shape[1]
    bc = _row_block(c)
    return pl.pallas_call(
        _orthonormalize_q_kernel,
        grid=(c // bc,),
        in_specs=[
            pl.BlockSpec((m, bc), lambda j: (0, j)),
            pl.BlockSpec((m, r), lambda j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((m, r), lambda j: (0, 0)),
            pl.BlockSpec((bc, r), lambda j: (j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, r), jnp.float32),
            jax.ShapeDtypeStruct((c, r), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((m, r), jnp.float32)],
        interpret=interpret_mode(),
    )(acc_mat, p_mean)


# ---------------------------------------------------------------------------
# Stage 3 (post Q-psum): reconstruct + EF residual.
# ---------------------------------------------------------------------------

def _reconstruct_kernel(acc_ref, po_ref, q_ref, ql_ref, out_ref, res_ref,
                        *, n_scale, postscale):
    po = po_ref[...]
    out = jax.lax.dot_general(po, q_ref[...], (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    # Same op order as the unfused path (approx, then * n for Sum, then
    # the postscale) so parity holds to f32 roundoff, not just approx.
    if n_scale != 1.0:
        out = out * n_scale
    if postscale != 1.0:
        out = out * postscale
    out_ref[...] = out
    own = jax.lax.dot_general(po, ql_ref[...], (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    res_ref[...] = acc_ref[...] - own


def reconstruct_residual(acc_mat, p_orth, q_mean, q_local, *,
                         n_scale: float = 1.0, postscale: float = 1.0):
    """``(out, new_residual)`` in one arena pass: ``out = (P @ Q^T) * n *
    postscale``; ``new_residual = acc - P @ Q_local^T`` (this rank's
    un-carried mass, the EF state)."""
    m, c = acc_mat.shape
    r = p_orth.shape[1]
    bm = _row_block(m)
    row_spec = pl.BlockSpec((bm, c), lambda i: (i, 0))
    fac_spec = pl.BlockSpec((c, r), lambda i: (0, 0))
    kernel = functools.partial(_reconstruct_kernel, n_scale=n_scale,
                               postscale=postscale)
    return pl.pallas_call(
        kernel,
        grid=(m // bm,),
        in_specs=[row_spec,
                  pl.BlockSpec((bm, r), lambda i: (i, 0)),
                  fac_spec, fac_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((m, c), jnp.float32),
            jax.ShapeDtypeStruct((m, c), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(acc_mat, p_orth, q_mean, q_local)
