"""Static collective-consistency analysis for horovod_tpu.

Two layers, one CLI:

- **trace audit** (:mod:`.trace_audit`): trace a train step without
  executing it, extract its collective graph from the jaxpr, and
  cross-check it against the fusion/arena plan -- plus desync
  (rank-dependent control flow around collectives), donation safety,
  and per-backend fence policy;
- **repo lints** (:mod:`.lints`): AST rules over the package source
  (unlocked shared state in threaded modules, host nondeterminism in
  traced step bodies, raw collectives outside the exchange layer, the
  env-var documentation registry).

CLI: ``python -m horovod_tpu.analysis [--step-audit|--lint|--all]``;
exit code 1 when unsuppressed error findings remain (the CI gate).
Accepted findings live in ``analysis_baseline.txt`` at the repo root,
one justified entry per line.
"""

from .findings import (ERROR, WARNING, Finding, apply_baseline,
                       default_baseline_path, errors, load_baseline,
                       render_findings)
from .lints import read_env_vars, rule_catalogue, run_lints
from .stepmodel import ExpectedExchange, ExpectedOp, expected_exchange
from .trace_audit import (PARALLEL3D_CONFIGS, STANDARD_CONFIGS,
                          AuditReport, audit_standard_configs,
                          audit_step, build_standard_config)

__all__ = [
    "ERROR", "WARNING", "Finding", "apply_baseline",
    "default_baseline_path", "errors", "load_baseline", "render_findings",
    "read_env_vars", "rule_catalogue", "run_lints",
    "ExpectedExchange", "ExpectedOp", "expected_exchange",
    "PARALLEL3D_CONFIGS", "STANDARD_CONFIGS", "AuditReport",
    "audit_standard_configs",
    "audit_step", "build_standard_config",
]
