"""Layer-1 static step auditor: trace, walk, cross-check -- never execute.

``audit_step`` traces a training step with ``jax.make_jaxpr`` (no device
execution, no donation side effects), extracts its collective graph with
:mod:`.jaxpr_walk`, derives the planner contract with :mod:`.stepmodel`,
and reports :class:`~horovod_tpu.analysis.findings.Finding` rows for:

- ``audit-plan-missing`` (error): a planned collective leg the trace
  never emits -- the exchange silently dropped a bucket;
- ``audit-plan-unaccounted`` (error): an emitted collective no plan row
  (nor the scalar loss/metric allowance) accounts for -- untracked wire
  traffic, the static form of the reference's mismatch stall;
- ``audit-desync-branch`` (error): ``cond``/``while`` control flow whose
  predicate is data-dependent on ``axis_index`` guarding a collective --
  ranks can disagree on whether the collective runs;
- ``audit-donation`` (error): a donated input leaf whose aval matches no
  output, so its buffer is freed with the caller still holding the
  array;
- ``audit-fence`` (error): a TPU-backed mesh whose eager fence policy
  degrades to CPU-style barrier+block, or a barrier-signature collective
  (scalar int32 psum) traced into a TPU step body;
- ``audit-collective-in-kernel`` (error): a collective primitive traced
  inside a ``pallas_call`` kernel body -- every registered kernel family
  (``ops.pallas.KERNEL_CONTRACTS``) contracts to keep its exchanges in
  XLA, where the fusion planner, this auditor, and the span recorder can
  see them.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import jaxpr_walk as _walk
from .findings import ERROR, WARNING, Finding
from .stepmodel import ExpectedExchange, expected_exchange, meta_from_step

# Scalar reductions (loss mean, metric max/min, desync probes) ride beside
# any exchange; they are matched after plan legs so a planned scalar leg
# still claims its record first.
_AUX_KINDS = frozenset({"psum", "pmax", "pmin"})


@dataclasses.dataclass
class AuditReport:
    """Outcome of one ``audit_step`` call."""
    name: str
    findings: List[Finding]
    collectives: List[_walk.CollectiveRecord]
    expected: Optional[ExpectedExchange]
    summary: Dict[str, int]

    def ok(self) -> bool:
        return not any(f.severity == ERROR for f in self.findings)

    def render(self) -> str:
        s = self.summary
        head = (f"audit {self.name}: "
                f"{s['planned_buckets']} planned bucket(s), "
                f"{s['expected_ops']} planned collective leg(s), "
                f"{s['emitted_ops']} emitted, {s['matched_ops']} matched, "
                f"{s['aux_ops']} scalar-aux -- "
                f"{'OK' if self.ok() else 'FINDINGS'}")
        lines = [head]
        lines += [f"  {f.render()}" for f in self.findings]
        return "\n".join(lines)


def _mesh_platform() -> Optional[str]:
    from ..core.state import global_state
    st = global_state()
    if st.mesh is None:
        return None
    from ..collectives.eager import _mesh_platform as mp
    return mp(st.mesh)


def _fence_findings(name: str,
                    records: Sequence[_walk.CollectiveRecord]
                    ) -> List[Finding]:
    from ..controller.fusion import _fence_policy
    findings = []
    policy = _fence_policy()
    platform = _mesh_platform()
    if platform == "tpu" and policy.startswith("barrier+block"):
        findings.append(Finding(
            rule="audit-fence", severity=ERROR, path=name,
            ident="eager-policy",
            message=f"TPU mesh resolves eager fence policy {policy!r}; "
                    "TPU transports must be compiler-scheduled"))
    if platform == "tpu":
        for r in records:
            if (r.kind == "psum" and r.elements == 1
                    and r.dtype == "int32"):
                findings.append(Finding(
                    rule="audit-fence", severity=ERROR, path=name,
                    ident=r.path,
                    message="barrier-signature collective (scalar int32 "
                            "psum) traced into a TPU step body; XLA "
                            "schedules TPU collectives -- CPU-style "
                            "barriers only serialize"))
    return findings


def _match_plan(name: str, expected: ExpectedExchange,
                records: Sequence[_walk.CollectiveRecord],
                stats_allowance: Counter) -> Tuple[List[Finding],
                                                   Dict[str, int]]:
    want = Counter(op.sig() for op in expected.ops)
    labels: Dict[Tuple[str, str, int], List[str]] = {}
    for op in expected.ops:
        labels.setdefault(op.sig(), []).append(op.label)
    matched = aux = stats = 0
    unaccounted: List[_walk.CollectiveRecord] = []
    for r in records:
        sig = r.sig()
        if want.get(sig, 0) > 0:
            want[sig] -= 1
            matched += 1
        elif stats_allowance.get(sig, 0) > 0:
            stats_allowance[sig] -= 1
            stats += 1
        elif r.kind in _AUX_KINDS and r.elements == 1:
            aux += 1
        else:
            unaccounted.append(r)

    findings = []
    for sig, n in want.items():
        if n <= 0:
            continue
        for label in labels[sig][-n:]:
            findings.append(Finding(
                rule="audit-plan-missing", severity=ERROR, path=name,
                ident=label,
                message=f"planned collective leg never emitted: "
                        f"{sig[0]} {sig[1]}[{sig[2]}] ({label})"))
    for r in unaccounted:
        findings.append(Finding(
            rule="audit-plan-unaccounted", severity=ERROR, path=name,
            ident=r.path,
            message=f"emitted collective not in the plan: {r.kind} "
                    f"{r.dtype}[{r.elements}] at {r.path}"))
    stats_left = sum(stats_allowance.values())
    counts = {"matched_ops": matched, "aux_ops": aux,
              "stats_ops": stats, "stats_unused": stats_left,
              "unaccounted_ops": len(unaccounted),
              "missing_ops": sum(n for n in want.values() if n > 0)}
    return findings, counts


def audit_step(fn, *args,
               meta: Optional[dict] = None,
               donate_argnums: Optional[Sequence[int]] = None,
               batch_stats: Any = None,
               name: str = "step") -> AuditReport:
    """Statically audit a training step against its exchange plan.

    ``fn`` is the step as the builder returned it (the
    ``_InstrumentedStep`` wrapper is unwrapped and its builder ``meta``
    picked up automatically) or any jit/shard_map callable; ``args`` are
    example arguments of the real shapes (traced, never executed, so
    donation does not consume them).  ``meta`` overrides/provides the
    builder metadata for plan matching (omit it to skip plan matching on
    unknown callables).  ``donate_argnums`` enables the donation-safety
    check; ``batch_stats`` declares a flax mutable-stats tree whose
    per-leaf averaging psums are accounted to the stats exchange.
    """
    # Builders may stack wrappers (_GuardedStep over _InstrumentedStep):
    # unwrap every layer to reach the traceable callable.
    inner = fn
    while hasattr(inner, "_fn"):
        inner = inner._fn
    if meta is None:
        meta = meta_from_step(fn)
    closed = jax.make_jaxpr(inner)(*args)

    records = _walk.collect_collectives(closed)
    findings: List[Finding] = []
    summary: Dict[str, int] = {
        "emitted_ops": len(records), "planned_buckets": 0,
        "expected_ops": 0, "matched_ops": 0, "aux_ops": 0,
        "stats_ops": 0, "unaccounted_ops": 0, "missing_ops": 0,
    }

    expected = None
    if meta is not None:
        expected = expected_exchange(args[0], meta)
        for note in expected.notes:
            findings.append(Finding(
                rule="audit-plan-unsupported" if not expected.supported
                else "audit-plan-note", severity=WARNING, path=name,
                ident="model", message=note))
        if expected.supported:
            stats_allow: Counter = Counter()
            if batch_stats is not None:
                for leaf in jax.tree.leaves(batch_stats):
                    if jnp.issubdtype(leaf.dtype, jnp.floating):
                        stats_allow[("psum", str(jnp.dtype(leaf.dtype)),
                                     int(leaf.size))] += 1
            plan_findings, counts = _match_plan(name, expected, records,
                                                stats_allow)
            findings += plan_findings
            summary.update(counts)
            summary["planned_buckets"] = len(expected.plan_rows)
            summary["expected_ops"] = len(expected.ops)

    for r in _walk.collectives_in_kernels(closed):
        findings.append(Finding(
            rule="audit-collective-in-kernel", severity=ERROR, path=name,
            ident=r.path,
            message=f"collective {r.kind} {r.dtype}[{r.elements}] traced "
                    "inside a pallas_call kernel body; kernel contracts "
                    "declare every family collective-free (in-kernel "
                    "collectives are invisible to XLA's scheduler and the "
                    "planner's wire accounting)"))

    for d in _walk.find_rank_dependent_branches(closed):
        findings.append(Finding(
            rule="audit-desync-branch", severity=ERROR, path=name,
            ident=d.path,
            message=f"rank-dependent {d.primitive} predicate guards "
                    f"collective(s) {', '.join(d.collectives)}: ranks can "
                    "diverge on whether the collective executes (desync "
                    "stall)"))

    if donate_argnums:
        for rec in _walk.check_donation(closed, args, donate_argnums):
            findings.append(Finding(
                rule="audit-donation", severity=ERROR, path=name,
                ident=f"arg{rec.argnum}.leaf{rec.leaf_index}",
                message=f"donated leaf {rec.dtype}{list(rec.shape)} of "
                        f"argument {rec.argnum} matches no output aval: "
                        "its buffer is freed while the caller still holds "
                        "the array (read-after-donate)"))

    findings += _fence_findings(name, records)
    summary["desync"] = sum(1 for f in findings
                            if f.rule == "audit-desync-branch")
    summary["donation"] = sum(1 for f in findings
                              if f.rule == "audit-donation")
    return AuditReport(name=name, findings=findings,
                       collectives=records, expected=expected,
                       summary=summary)


# -- the four reference configurations --------------------------------------

STANDARD_CONFIGS = ("plain", "zero1", "powersgd_ef", "microbatch2")

# Two-level reference configurations: same tiny tree, but the exchange
# decomposes over the (dcn, ici) communicator -- plain per-leg hier,
# hier composed with the ZeRO-1 arena, and hier with the EF codec scoped
# to the DCN hop.  They require init() on a two-level mesh
# (``build_mesh(devices, hierarchical=True, dcn_size=...)``).
HIER_CONFIGS = ("hier", "hier_zero1", "hier_powersgd_ef")

# Serving decode configurations: the tensor-parallel decode step on the
# full tp ladder and on the post-shrink mesh the elastic control plane
# leaves behind, so the exchange contract (2 row-parallel psums per
# layer of slots*d_model at the activation dtype) is gated across
# resizes, not only at the size serving happened to start at.
# ``serving_verify`` gates the speculative-decoding verify step: the
# same multiset widened by k+1 (slots*width*d_model per psum).
SERVING_CONFIGS = ("serving_decode", "serving_decode_resized",
                   "serving_verify")

# 3-D parallelism reference configurations (PR 18): the DP gradient leg
# priced over LOCAL (model-sharded) leaves and the data axes only, plus
# the declared TP/pipeline activation legs.  ``tp2`` runs TP=2 with the
# fp16 DP exchange on the hierarchical (dcn, data) pair; ``tp2_zero1``
# shards the optimizer arena over the same data axes; ``tp2_pipe_micro``
# stacks TP=2 x pipe=2 x microbatches=2 on a flat data axis.  All three
# build their own mesh over the first 8 devices.
PARALLEL3D_CONFIGS = ("tp2", "tp2_zero1", "tp2_pipe_micro")

# Threshold chosen so the tiny parameter tree below splits into TWO f32
# buckets (256 + 192 elements), exercising multi-bucket matching.
_TINY_THRESHOLD = 1024


def _tiny_params():
    a = jnp.linspace(-1.0, 1.0, 256, dtype=jnp.float32).reshape(16, 16)
    b = jnp.linspace(0.5, 1.5, 128, dtype=jnp.float32)
    c = jnp.linspace(-0.5, 0.5, 64, dtype=jnp.float32)
    return {"a": a, "b": b, "c": c}


def _tiny_loss(params, batch):
    # Per-example-mean loss touching every leaf (nonzero grads all over).
    x = batch
    s = (jnp.sum(params["a"] ** 2) + jnp.sum(params["b"] ** 2)
         + jnp.sum(params["c"] ** 2))
    return jnp.mean(x) * s


def build_standard_config(config: str):
    """Build ``(step, args, donate_argnums, name)`` for one of the four
    reference configurations (requires an initialized mesh)."""
    import optax

    from .. import training as _training
    from ..collectives.compression import Compression
    from ..core import basics as _basics
    from ..optim import distributed as _dist
    from ..optim import zero as _zero

    mesh = _basics.mesh()
    world = int(mesh.devices.size)
    params = _tiny_params()
    batch = jnp.ones((world * 2, 4), jnp.float32)

    if config == "plain":
        opt = _dist.DistributedOptimizer(
            optax.sgd(0.01), compression=Compression.fp16,
            fusion_threshold=_TINY_THRESHOLD)
        step = _training.make_train_step(_tiny_loss, opt, mesh=mesh)
        opt_state = opt.init(params)
    elif config == "zero1":
        opt = optax.sgd(0.01)
        step = _training.make_train_step(_tiny_loss, opt, mesh=mesh,
                                         zero_stage=1)
        opt_state = _zero.zero_init(opt, params, mesh=mesh)
    elif config == "powersgd_ef":
        opt = _dist.DistributedOptimizer(
            optax.sgd(0.01), compression="powersgd:2",
            fusion_threshold=_TINY_THRESHOLD)
        step = _training.make_train_step(_tiny_loss, opt, mesh=mesh)
        opt_state = opt.init(params)
    elif config == "microbatch2":
        opt = _dist.DistributedOptimizer(
            optax.sgd(0.01), compression=Compression.fp16,
            fusion_threshold=_TINY_THRESHOLD)
        step = _training.make_train_step(_tiny_loss, opt, mesh=mesh,
                                         microbatches=2)
        opt_state = opt.init(params)
    elif config in HIER_CONFIGS:
        if len(mesh.axis_names) != 2:
            raise ValueError(
                f"config {config!r} needs the two-level (dcn, ici) mesh; "
                f"init() with build_mesh(..., hierarchical=True, "
                f"dcn_size=...) first (got axes {mesh.axis_names})")
        if config == "hier":
            opt = _dist.DistributedOptimizer(
                optax.sgd(0.01), compression="ici:none,dcn:none",
                fusion_threshold=_TINY_THRESHOLD)
            step = _training.make_train_step(_tiny_loss, opt, mesh=mesh)
            opt_state = opt.init(params)
        elif config == "hier_zero1":
            opt = optax.sgd(0.01)
            step = _training.make_train_step(
                _tiny_loss, opt, mesh=mesh, zero_stage=1,
                zero_compression="ici:none,dcn:none")
            opt_state = _zero.zero_init(opt, params, mesh=mesh,
                                        compression="ici:none,dcn:none")
        else:  # hier_powersgd_ef
            opt = _dist.DistributedOptimizer(
                optax.sgd(0.01), compression="ici:none,dcn:powersgd:2",
                fusion_threshold=_TINY_THRESHOLD)
            step = _training.make_train_step(_tiny_loss, opt, mesh=mesh)
            opt_state = opt.init(params)
    elif config in SERVING_CONFIGS:
        return _build_serving_config(config)
    elif config in PARALLEL3D_CONFIGS:
        return _build_3d_config(config)
    else:
        known = (STANDARD_CONFIGS + HIER_CONFIGS + SERVING_CONFIGS
                 + PARALLEL3D_CONFIGS)
        raise ValueError(
            f"unknown standard config {config!r}; pick from {known}")
    # donate_argnums mirrors make_train_step's own (0, 1) donation.
    return step, (params, opt_state, batch), (0, 1), f"step:{config}"


def _build_3d_config(config: str):
    """``(step, args, donate, name)`` for the 3-D parallelism audits.

    Tiny TP=2 MLP (d_model=16, d_ff=32) with stacked-leading-dim sharded
    weights: ``param_specs`` put the TP shards on the ``model`` axis (and
    stage shards on ``pipe``), so the DP exchange plans over each
    device's local slices.  Each builder declares its activation contract
    in ``step._meta["model_parallel"]`` (d_model, rows per loss call,
    pipeline microbatches) -- the quantities :func:`stepmodel._expected_3d`
    prices the TP row-parallel psums and pipeline ppermute/select legs
    from.  Requires >= 8 devices; each config builds its own mesh.
    """
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    from .. import training as _training
    from ..collectives.compression import Compression
    from ..optim import distributed as _dist
    from ..optim import zero as _zero
    from ..parallel import build_3d_mesh, data_axes, tp_mlp

    if len(jax.devices()) < 8:
        raise ValueError(
            f"config {config!r} needs 8 devices for the 2x2x2 meshes "
            f"(got {len(jax.devices())})")

    d_model, d_ff, tp = 16, 32, 2
    rng = np.random.default_rng(0)

    def tp_params():
        return {
            "w_up": jnp.asarray(rng.normal(size=(tp, d_model, d_ff // tp)),
                                jnp.float32),
            "w_down": jnp.asarray(rng.normal(size=(tp, d_ff // tp, d_model)),
                                  jnp.float32),
            "bias": jnp.linspace(0.5, 1.5, d_model, dtype=jnp.float32),
        }

    tp_specs = {"w_up": P("model"), "w_down": P("model"), "bias": P()}

    def tp_loss(params, batch):
        y = tp_mlp(batch + params["bias"], params["w_up"][0],
                   params["w_down"][0], axis="model")
        return jnp.mean(y * y)

    if config in ("tp2", "tp2_zero1"):
        mesh = build_3d_mesh(jax.devices()[:8], data=2, model=2,
                             dcn_size=2)
        params = tp_params()
        batch = jnp.ones((4 * 2, d_model), jnp.float32)
        if config == "tp2":
            opt = _dist.DistributedOptimizer(
                optax.sgd(0.01), compression=Compression.fp16,
                fusion_threshold=_TINY_THRESHOLD, axes=data_axes(mesh))
            step = _training.make_train_step(tp_loss, opt, mesh=mesh,
                                             tp=tp, param_specs=tp_specs)
            opt_state = opt.init(params)
        else:
            opt = optax.sgd(0.01)
            step = _training.make_train_step(tp_loss, opt, mesh=mesh,
                                             tp=tp, zero_stage=1,
                                             param_specs=tp_specs)
            opt_state = _zero.zero_init(opt, params, mesh=mesh,
                                        param_specs=tp_specs)
        # 8 global rows / 4 data-parallel devices = 2 rows per loss call.
        step._meta["model_parallel"] = {"d_model": d_model, "act_rows": 2}
    else:  # tp2_pipe_micro
        from ..parallel import pipeline_apply, split_microbatches
        mesh = build_3d_mesh(jax.devices()[:8], data=2, pipe=2, model=2)
        params = {
            "w_up": jnp.asarray(
                rng.normal(size=(2, tp, d_model, d_ff // tp)), jnp.float32),
            "w_down": jnp.asarray(
                rng.normal(size=(2, tp, d_ff // tp, d_model)), jnp.float32),
        }
        pp_specs = {"w_up": P("pipe", "model"),
                    "w_down": P("pipe", "model")}

        def pipe_loss(sp, batch):
            mb = split_microbatches(batch, 2)

            def stage_fn(stage_params, x):
                return tp_mlp(x, stage_params["w_up"][0],
                              stage_params["w_down"][0], axis="model")

            out = pipeline_apply(stage_fn, sp, mb, axis="pipe")
            y = jnp.concatenate(list(out), axis=0)
            return jnp.mean(y * y)

        opt = _dist.DistributedOptimizer(
            optax.sgd(0.01), compression=Compression.fp16,
            fusion_threshold=_TINY_THRESHOLD, axes=data_axes(mesh))
        step = _training.make_train_step(
            pipe_loss, opt, mesh=mesh, tp=tp, pipeline_stages=2,
            microbatches=2, param_specs=pp_specs)
        opt_state = opt.init(params)
        batch = jnp.ones((2 * 8, d_model), jnp.float32)
        # 16 global rows / 2 data devices / 2 train microbatches = 4 rows
        # per loss call, halved again by the 2 pipeline microbatches.
        step._meta["model_parallel"] = {"d_model": d_model, "act_rows": 4,
                                        "pipe_microbatches": 2}
    return step, (params, opt_state, batch), (0, 1), f"step:{config}"


def _build_serving_config(config: str):
    """``(step, args, None, name)`` for the serving decode audits.

    ``serving_decode`` builds on the largest valid tp size the device
    pool allows; ``serving_decode_resized`` on the next size down --
    the mesh the control plane's shrink path lands on -- with
    ``resized_from`` provenance in the step meta so the expected model
    notes the transition.  ``serving_verify`` is the width-5 (k=4)
    speculative verify step on the full tp size: the audit must match
    the widened multiset exactly, no new declines.  No donation: the
    decode step's pool aliasing is the engine's business, not the
    trainer's.
    """
    import numpy as np
    from jax.sharding import Mesh

    from ..models.transformer import LLAMA_SERVE, LlamaLM
    from ..serving import (CacheConfig, PagedKVCache, build_decode_step,
                           build_verify_step, cache_sharding)
    from ..serving.policy import valid_tp_sizes

    cfg = LLAMA_SERVE
    sizes = valid_tp_sizes(cfg, len(jax.devices()))
    tp = sizes[-1]
    resized_from = None
    if config == "serving_decode_resized" and len(sizes) > 1:
        resized_from, tp = sizes[-1], sizes[-2]
    mesh = Mesh(np.asarray(jax.devices()[:tp], dtype=object).reshape(tp),
                ("tp",))
    ccfg = CacheConfig(
        num_layers=cfg.num_layers, num_kv_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim, slots=4, page_size=8, max_len=64)
    cache = PagedKVCache(ccfg, cache_sharding(mesh))
    model = LlamaLM(cfg, dtype=jnp.float32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 4), jnp.int32))
    if config == "serving_verify":
        width = 5
        step = build_verify_step(cfg, mesh, slots=ccfg.slots, width=width,
                                 page_size=ccfg.page_size,
                                 pages_per_slot=ccfg.pages_per_slot)
        tokens = jnp.zeros((ccfg.slots, width), jnp.int32)
    else:
        step = build_decode_step(cfg, mesh, slots=ccfg.slots,
                                 page_size=ccfg.page_size,
                                 pages_per_slot=ccfg.pages_per_slot)
        tokens = jnp.zeros((ccfg.slots,), jnp.int32)
    if resized_from is not None:
        step._meta["resized_from"] = resized_from
    args = (params, cache.k, cache.v, tokens, cache.lengths_device(),
            cache.table_device(), jnp.zeros((ccfg.slots,), bool))
    return step, args, None, f"step:{config}"


def audit_standard_configs(configs: Optional[Sequence[str]] = None
                           ) -> Dict[str, AuditReport]:
    """Audit the reference configurations (plain DP, ZeRO-1, powersgd+EF,
    microbatches=2) against their plans.  Requires ``horovod_tpu.init()``
    to have built a mesh."""
    reports = {}
    for config in (configs or STANDARD_CONFIGS):
        step, args, donate, name = build_standard_config(config)
        reports[config] = audit_step(step, *args, donate_argnums=donate,
                                     name=name)
    return reports
