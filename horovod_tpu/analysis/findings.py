"""Finding model and baseline suppression for the static-analysis plane.

Every check in :mod:`horovod_tpu.analysis` -- the jaxpr-level step auditor
and the AST repo lints -- reports :class:`Finding` rows.  A finding is
addressed by ``(rule, path, ident)``: the rule id, the file (or audited
config) it lives in, and a *stable identifier* (env-var name, enclosing
function, bucket index) that survives line-number drift.  Accepted
findings are suppressed through a baseline file whose every entry must
carry a one-line justification; an entry that stops matching anything is
itself reported, so the baseline cannot silently rot.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterable, List, Optional, Sequence, Tuple

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analysis result: a rule violation at a location.

    ``path`` is repo-relative for lints and the audited config name
    (e.g. ``step:powersgd_ef``) for trace-audit findings; ``line`` is the
    source line for lints and ``None`` for jaxpr-level findings, where
    ``ident`` carries the equation/bucket address instead.
    """
    rule: str
    severity: str
    path: str
    ident: str
    message: str
    line: Optional[int] = None

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.ident)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{self.rule} {self.severity} {loc} [{self.ident}] " \
               f"{self.message}"


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str
    ident: str        # "*" matches any ident
    justification: str
    lineno: int

    def matches(self, f: Finding) -> bool:
        return (self.rule == f.rule and self.path == f.path
                and self.ident in ("*", f.ident))


def default_baseline_path() -> str:
    """``analysis_baseline.txt`` next to the package (the repo root in a
    source checkout; absent -- hence empty -- for installed trees)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), "analysis_baseline.txt")


def load_baseline(path: Optional[str] = None) -> List[BaselineEntry]:
    """Parse a baseline file: ``rule path ident  # justification`` per
    line.  The justification is REQUIRED -- an entry without one is a
    format error (a suppression nobody can defend should not exist)."""
    if path is None:
        path = default_baseline_path()
    if not os.path.exists(path):
        return []
    entries = []
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, sep, just = line.partition("#")
            just = just.strip()
            fields = body.split()
            if len(fields) != 3 or not sep or not just:
                raise ValueError(
                    f"{path}:{lineno}: baseline entries are "
                    f"'rule path ident  # justification' (justification "
                    f"required), got {raw.rstrip()!r}")
            entries.append(BaselineEntry(fields[0], fields[1], fields[2],
                                         just, lineno))
    return entries


def apply_baseline(findings: Sequence[Finding],
                   baseline: Iterable[BaselineEntry],
                   baseline_path: str = "<baseline>",
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (kept, suppressed); a baseline entry that
    matched nothing is appended to ``kept`` as a warning so stale
    suppressions surface instead of lingering."""
    baseline = list(baseline)
    used = [False] * len(baseline)
    kept, suppressed = [], []
    for f in findings:
        hit = None
        for i, e in enumerate(baseline):
            if e.matches(f):
                hit = i
                break
        if hit is None:
            kept.append(f)
        else:
            used[hit] = True
            suppressed.append(f)
    for e, u in zip(baseline, used):
        if not u:
            kept.append(Finding(
                rule="analysis-stale-baseline", severity=WARNING,
                path=baseline_path, line=e.lineno,
                ident=f"{e.rule}:{e.path}:{e.ident}",
                message="baseline entry matched no finding; delete it "
                        f"(justification was: {e.justification!r})"))
    return kept, suppressed


def errors(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if f.severity == ERROR]


def render_findings(findings: Sequence[Finding]) -> str:
    if not findings:
        return "(no findings)"
    return "\n".join(f.render() for f in findings)
