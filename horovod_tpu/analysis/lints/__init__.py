"""AST repo lints (layer 2 of the static-analysis plane)."""

from .base import (LintContext, LintRule, all_rules, rule_catalogue,
                   run_lints)
from .envreg import EnvRegistryRule, read_env_vars, scan_env_vars
from .legplan import LegDerivationOutsidePlannerRule
from .locks import UnlockedSharedStateRule
from .nondeterminism import NondeterminismInStepRule
from .planner import CollectiveOutsidePlannerRule

__all__ = [
    "LintContext", "LintRule", "all_rules", "rule_catalogue", "run_lints",
    "EnvRegistryRule", "read_env_vars", "scan_env_vars",
    "UnlockedSharedStateRule", "NondeterminismInStepRule",
    "CollectiveOutsidePlannerRule", "LegDerivationOutsidePlannerRule",
]
