"""Layer-2 lint framework: AST rules over the ``horovod_tpu/`` tree.

Each rule is a :class:`LintRule` reporting
:class:`~horovod_tpu.analysis.findings.Finding` rows against repo-relative
paths.  The :class:`LintContext` parses every package source file once
and shares the ASTs across rules; docs are exposed for registry-style
rules (env vars must appear in ``docs/api.md``).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence

from ..findings import Finding


@dataclasses.dataclass
class SourceFile:
    relpath: str       # repo-relative, forward slashes
    source: str
    tree: ast.AST


class LintContext:
    """Parsed view of the package tree (plus docs) the rules run over."""

    def __init__(self, pkg_dir: Optional[str] = None,
                 repo_root: Optional[str] = None):
        if pkg_dir is None:
            pkg_dir = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        self.pkg_dir = pkg_dir
        self.repo_root = repo_root or os.path.dirname(pkg_dir)
        self.files: List[SourceFile] = []
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                with open(path) as f:
                    source = f.read()
                rel = os.path.relpath(path, self.repo_root).replace(
                    os.sep, "/")
                self.files.append(SourceFile(
                    relpath=rel, source=source,
                    tree=ast.parse(source, filename=rel)))

    def read_doc(self, relpath: str) -> Optional[str]:
        path = os.path.join(self.repo_root, relpath)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return f.read()


class LintRule:
    """Base rule: subclasses set ``id``/``severity``/``description`` and
    implement :meth:`run`."""
    id: str = ""
    severity: str = "error"
    description: str = ""

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, sf_or_path, ident: str, message: str,
                line: Optional[int] = None) -> Finding:
        path = sf_or_path.relpath if isinstance(sf_or_path, SourceFile) \
            else sf_or_path
        return Finding(rule=self.id, severity=self.severity, path=path,
                       ident=ident, message=message, line=line)


def all_rules() -> List[LintRule]:
    from .envreg import EnvRegistryRule
    from .legplan import LegDerivationOutsidePlannerRule
    from .locks import UnlockedSharedStateRule
    from .nondeterminism import NondeterminismInStepRule
    from .pallas_tests import PallasInterpretTestRule
    from .planner import CollectiveOutsidePlannerRule
    return [UnlockedSharedStateRule(), NondeterminismInStepRule(),
            CollectiveOutsidePlannerRule(),
            LegDerivationOutsidePlannerRule(), EnvRegistryRule(),
            PallasInterpretTestRule()]


def run_lints(pkg_dir: Optional[str] = None,
              repo_root: Optional[str] = None,
              rules: Optional[Sequence[LintRule]] = None) -> List[Finding]:
    """Run every (or the given) lint rule over the package tree."""
    ctx = LintContext(pkg_dir=pkg_dir, repo_root=repo_root)
    findings: List[Finding] = []
    for rule in (rules if rules is not None else all_rules()):
        findings.extend(rule.run(ctx))
    findings.sort(key=lambda f: (f.path, f.line or 0, f.rule, f.ident))
    return findings


def rule_catalogue() -> Dict[str, str]:
    """``{rule id: description}`` for docs/CLI help."""
    return {r.id: r.description for r in all_rules()}
