"""``lint-leg-derivation-outside-planner``: ad-hoc exchange-leg
structure built outside the planner.

Every exchange leg the runtime executes (and every tag the span
recorder and trace auditor see) must come from ONE source of truth: the
:class:`~horovod_tpu.controller.fusion.ExchangePlan` IR produced by
``plan_exchange``.  A module that constructs ``ExchangeLeg`` rows by
hand, or passes a string literal where a planned leg row belongs
(``note_leg("...")``, ``leg="..."``), is deriving exchange structure in
a second place -- the executed legs, the auditor's expected multiset and
the span timeline can then silently disagree.  The planner itself
(``controller/fusion.py``) and the span normalizer
(``timeline/spans.py``) are exempt: the former is where the rows are
made, the latter is where string tags are legally absorbed.  The
recorder's host-side timing API (``rec.span(..., leg=...)`` /
``rec.add(..., leg=...)``) is also exempt: those strings label wall-
clock attribution of host events and never claim wire bytes, so they
are not exchange structure.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..findings import Finding
from .base import LintContext, LintRule

# Files (repo-relative prefixes) allowed to build leg rows / eat tags.
_PLANNER_LAYER = ("horovod_tpu/controller/fusion.py",
                  "horovod_tpu/timeline/spans.py")


def _is_str_literal(node) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


class LegDerivationOutsidePlannerRule(LintRule):
    id = "lint-leg-derivation-outside-planner"
    severity = "error"
    description = ("exchange-leg structure (ExchangeLeg row or string "
                   "leg tag) built outside controller/fusion.py; derive "
                   "legs from plan_exchange so executors, auditor and "
                   "spans stay on one IR")

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for sf in ctx.files:
            if sf.relpath.startswith(_PLANNER_LAYER):
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                name = func.attr if isinstance(func, ast.Attribute) \
                    else func.id if isinstance(func, ast.Name) else None
                if name == "ExchangeLeg":
                    findings.append(self.finding(
                        sf, f"ExchangeLeg:{node.lineno}",
                        "ExchangeLeg constructed outside the planner; "
                        "add/extend a plan family in controller/fusion.py "
                        "and take the row from plan_exchange",
                        line=node.lineno))
                    continue
                if name == "note_leg" and node.args \
                        and _is_str_literal(node.args[0]):
                    findings.append(self.finding(
                        sf, f"note_leg:{node.lineno}",
                        "note_leg called with a string tag; pass the "
                        "planned ExchangeLeg row from plan_exchange "
                        "instead of re-deriving the tag/payload here",
                        line=node.lineno))
                    continue
                if name in ("span", "add"):
                    continue  # recorder timing API: host labels, no bytes
                for kw in node.keywords:
                    if kw.arg == "leg" and _is_str_literal(kw.value):
                        findings.append(self.finding(
                            sf, f"leg=:{node.lineno}",
                            "string literal passed as leg=; thread the "
                            "planned ExchangeLeg row (or its .tag) from "
                            "plan_exchange instead",
                            line=node.lineno))
        return findings
