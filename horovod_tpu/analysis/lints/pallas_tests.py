"""``lint-pallas-needs-interpret-test``: every ``pl.pallas_call`` site
needs an interpreter-mode parity test module.

Pallas kernels lower to custom calls that CI's CPU tier never executes
natively -- the ONLY coverage they get before a TPU run is the Pallas
interpreter (``interpret=...`` resolves true off-TPU, see
``ops.pallas.interpret_mode``).  A kernel module without an interpreter
test is dead weight that first executes in production, so this rule
requires, for every ``horovod_tpu`` source file invoking
``pallas_call``, a ``tests/test_*.py`` module that (a) carries the
kernel module's stem in its filename and (b) imports it.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List

from ..findings import Finding
from .base import LintContext, LintRule


def _pallas_call_lines(tree: ast.AST) -> List[int]:
    lines = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else \
            func.id if isinstance(func, ast.Name) else None
        if name == "pallas_call":
            lines.append(node.lineno)
    return sorted(lines)


def _test_sources(ctx: LintContext) -> Dict[str, str]:
    """``{filename: source}`` for every ``tests/test_*.py``."""
    tests_dir = os.path.join(ctx.repo_root, "tests")
    out: Dict[str, str] = {}
    if not os.path.isdir(tests_dir):
        return out
    for fname in sorted(os.listdir(tests_dir)):
        if not (fname.startswith("test_") and fname.endswith(".py")):
            continue
        with open(os.path.join(tests_dir, fname)) as f:
            out[fname] = f.read()
    return out


class PallasInterpretTestRule(LintRule):
    id = "lint-pallas-needs-interpret-test"
    severity = "error"
    description = ("pallas_call site without an interpreter-mode parity "
                   "test module (tests/test_*<module>*.py importing the "
                   "kernel module)")

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        tests = None
        for sf in ctx.files:
            lines = _pallas_call_lines(sf.tree)
            if not lines:
                continue
            if tests is None:
                tests = _test_sources(ctx)
            stem = os.path.splitext(os.path.basename(sf.relpath))[0]
            dotted = os.path.splitext(sf.relpath)[0].replace("/", ".")
            # "imports it": a plain module import or a from-import of the
            # stem both leave one of these two literal forms.
            imports = (dotted, f"import {stem}")
            covered = any(
                stem in fname and any(pat in src for pat in imports)
                for fname, src in tests.items())
            if covered:
                continue
            findings.append(self.finding(
                sf, stem,
                f"{len(lines)} pallas_call site(s) at line(s) "
                f"{', '.join(map(str, lines))} but no tests/test_*"
                f"{stem}*.py imports {dotted}; Pallas kernels are only "
                "CI-covered through an interpreter-mode parity test",
                line=lines[0]))
        return findings
