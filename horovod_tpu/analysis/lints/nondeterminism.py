"""``lint-nondeterminism-in-step``: wall-clock / host-RNG reads inside
traced step bodies.

A function handed to ``jax.jit`` / ``jax.shard_map`` / ``lax.scan`` is
traced ONCE; a ``time.time()`` or ``random.random()`` inside it bakes
one host value into the compiled program -- and if ranks trace
independently, a DIFFERENT value per rank, which desyncs every numeric
path downstream.  The rule collects function names passed to tracing
entry points in each module and scans those functions' bodies for host
nondeterminism calls (``time.*``, ``datetime.now``, ``random.*``,
``np.random.*``).  ``jax.random`` is explicitly fine (keyed, traced).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from ..findings import Finding
from .base import LintContext, LintRule, SourceFile

# Entry points whose first (or func=) argument gets traced.
_TRACE_ENTRY_ATTRS = {"jit", "shard_map", "scan", "while_loop", "cond",
                      "pmap", "checkpoint", "remat", "fori_loop", "switch"}

_TIME_ATTRS = {"time", "perf_counter", "monotonic", "process_time",
               "time_ns", "perf_counter_ns", "monotonic_ns"}
_DATETIME_ATTRS = {"now", "utcnow", "today"}


def _root_name(node: ast.AST) -> str:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _attr_chain(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return parts[::-1]


def _nondeterminism(call: ast.Call) -> str:
    """Non-empty description when ``call`` reads host time/RNG."""
    chain = _attr_chain(call.func)
    if len(chain) < 2:
        return ""
    root, attrs = chain[0], chain[1:]
    root_l = root.lower().lstrip("_")
    leaf = attrs[-1]
    if root_l in ("time",) and leaf in _TIME_ATTRS:
        return f"wall-clock read {'.'.join(chain)}()"
    if root_l in ("datetime",) and leaf in _DATETIME_ATTRS:
        return f"wall-clock read {'.'.join(chain)}()"
    if root_l in ("random",):
        return f"host RNG {'.'.join(chain)}()"
    if root_l in ("np", "numpy") and len(attrs) >= 2 \
            and attrs[0] == "random":
        return f"host RNG {'.'.join(chain)}()"
    return ""


def _traced_names(tree: ast.AST) -> Set[str]:
    """Function NAMES passed to tracing entry points in this module."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        entry = (isinstance(fn, ast.Attribute)
                 and fn.attr in _TRACE_ENTRY_ATTRS) or \
                (isinstance(fn, ast.Name) and fn.id in _TRACE_ENTRY_ATTRS)
        if not entry:
            continue
        cands = list(node.args[:2])
        cands += [kw.value for kw in node.keywords
                  if kw.arg in ("f", "fun", "body_fun", "cond_fun")]
        for arg in cands:
            if isinstance(arg, ast.Name):
                names.add(arg.id)
            elif (isinstance(arg, ast.Call)
                  and isinstance(arg.func, ast.Name)
                  and arg.func.id == "partial" and arg.args
                  and isinstance(arg.args[0], ast.Name)):
                names.add(arg.args[0].id)
    return names


class NondeterminismInStepRule(LintRule):
    id = "lint-nondeterminism-in-step"
    severity = "error"
    description = ("wall-clock or host-RNG call inside a function traced "
                   "by jit/shard_map/scan (bakes a per-rank host value "
                   "into the compiled step)")

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for sf in ctx.files:
            traced = _traced_names(sf.tree)
            if not traced:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if node.name not in traced:
                    continue
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    why = _nondeterminism(sub)
                    if why:
                        findings.append(self.finding(
                            sf, f"{node.name}:{sub.lineno}",
                            f"{why} inside traced function "
                            f"{node.name}(); thread the value in as an "
                            "argument or use jax.random",
                            line=sub.lineno))
        return findings
