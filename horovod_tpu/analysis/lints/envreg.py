"""``lint-undocumented-env``: the env-var registry rule.

Single source of truth for the "every ``HOROVOD_*`` knob the library
reads must have a row in ``docs/api.md``" contract (previously a grep
inside ``tests/test_env_docs.py``; that test now calls this rule).  Any
``_env(...)`` / ``_env_bool/int/float(...)`` call site and any literal
``os.environ`` access of a ``HOROVOD_`` / ``HVD_TPU_`` name contributes
a variable; each must appear with its ``HOROVOD_`` spelling somewhere in
the docs.  An env knob nobody can discover is a support burden.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional

from ..findings import Finding
from .base import LintContext, LintRule

_ENV_CALL = re.compile(
    r'_env(?:_bool|_int|_float)?\(\s*"([A-Z][A-Z0-9_]*)"')
# Literal os.environ reads of a fully-prefixed name.  Writes (launcher
# code exporting identity to children) count too: the variable is part
# of the public surface either way.
_ENV_LITERAL = re.compile(
    r'(?:os\.environ(?:\.get)?[\[(]\s*|getenv\(\s*)"'
    r'(?:HOROVOD_|HVD_TPU_)([A-Z][A-Z0-9_]*)"')

DOC_PATH = "docs/api.md"


def scan_env_vars(ctx: LintContext) -> Dict[str, List[str]]:
    """``{canonical_name: [repo-relative file, ...]}`` for every
    HOROVOD_* env var read in the package (canonical = prefix-less)."""
    hits: Dict[str, List[str]] = {}
    for sf in ctx.files:
        names = set(_ENV_CALL.findall(sf.source)) \
            | set(_ENV_LITERAL.findall(sf.source))
        for name in sorted(names):
            hits.setdefault(name, []).append(sf.relpath)
    return hits


def read_env_vars(pkg_dir: str,
                  repo_root: Optional[str] = None) -> Dict[str, List[str]]:
    """Standalone scan over an arbitrary package dir (test fixtures)."""
    return scan_env_vars(LintContext(pkg_dir=pkg_dir, repo_root=repo_root))


class EnvRegistryRule(LintRule):
    id = "lint-undocumented-env"
    severity = "error"
    description = ("HOROVOD_* env var read in the package but absent "
                   "from the docs/api.md registry")

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        doc = ctx.read_doc(DOC_PATH)
        if doc is None:
            return [self.finding(DOC_PATH, "missing",
                                 f"{DOC_PATH} not found; the env registry "
                                 "has nowhere to live")]
        hits = scan_env_vars(ctx)
        if not hits:
            return [self.finding("horovod_tpu", "empty-scan",
                                 "scanner found no env reads -- the regex "
                                 "rotted")]
        findings = []
        for name, files in sorted(hits.items()):
            if "HOROVOD_" + name not in doc:
                findings.append(self.finding(
                    files[0], name,
                    f"HOROVOD_{name} is read in {', '.join(files)} but "
                    f"has no row in {DOC_PATH}"))
        return findings
