"""``lint-unlocked-shared-state``: read-modify-write on shared attributes
without the owning lock, in modules that actually run threads.

Scope: modules importing ``threading`` whose classes (or module body)
start a ``Thread``.  Inside such a class, an augmented assignment to a
``self`` attribute (``self._n += 1`` -- a non-atomic read-modify-write)
must sit under a ``with self...lock...`` block; the timeline registry,
elastic coordinator and prefetcher all follow that discipline, and this
rule keeps new counters honest.  Plain assignments are exempt: a single
store is atomic under the GIL and is the documented poll pattern in
``data/prefetch.py``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..findings import Finding
from .base import LintContext, LintRule, SourceFile


def _is_thread_start(node: ast.AST) -> bool:
    """A ``threading.Thread(...)`` / ``Thread(...)`` construction."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return True
    return isinstance(fn, ast.Name) and fn.id == "Thread"


def _mentions_lock(node: ast.AST) -> bool:
    """A context-manager expression that names a lock (``self._lock``,
    ``self._cv``, ``_registry_lock`` ...)."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name and any(tok in name.lower()
                        for tok in ("lock", "_cv", "cond", "mutex")):
            return True
    return False


class _MethodScan(ast.NodeVisitor):
    """Walk one method body tracking whether we're under a lock ``with``."""

    def __init__(self):
        self.unlocked: List[ast.AugAssign] = []
        self._depth = 0

    def visit_With(self, node: ast.With) -> None:
        locked = any(_mentions_lock(item.context_expr)
                     for item in node.items)
        if locked:
            self._depth += 1
        self.generic_visit(node)
        if locked:
            self._depth -= 1

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        t = node.target
        if (self._depth == 0 and isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"):
            self.unlocked.append(node)
        self.generic_visit(node)

    # Nested defs get their own method scan via the class walk; don't
    # descend here (their lock context is their own).
    def visit_FunctionDef(self, node):  # noqa: N802
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


class UnlockedSharedStateRule(LintRule):
    id = "lint-unlocked-shared-state"
    severity = "error"
    description = ("augmented assignment to a self attribute outside a "
                   "lock, in a class that runs a thread (non-atomic "
                   "read-modify-write on shared state)")

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings = []
        for sf in ctx.files:
            if "threading" not in sf.source:
                continue
            findings.extend(self._scan_file(sf))
        return findings

    def _scan_file(self, sf: SourceFile) -> List[Finding]:
        out = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            runs_thread = any(_is_thread_start(sub)
                              for sub in ast.walk(node))
            if not runs_thread:
                continue
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                scan = _MethodScan()
                for stmt in item.body:
                    scan.visit(stmt)
                for aug in scan.unlocked:
                    attr = aug.target.attr  # type: ignore[union-attr]
                    out.append(self.finding(
                        sf, f"{node.name}.{item.name}:{attr}",
                        f"self.{attr} is read-modify-written outside a "
                        f"lock in threaded class {node.name}; wrap the "
                        "update in the owning lock",
                        line=aug.lineno))
        return out
