"""``lint-collective-outside-planner``: raw ``lax`` collectives in
library code.

Every cross-rank collective in library code must route through
``horovod_tpu.collectives.ops`` (which resolves axes/process sets,
applies wire codecs and keeps the plan accountable) -- a raw
``jax.lax.psum`` in a feature module bypasses reduce-op semantics,
process-set masking, AND the step auditor's plan model.  The exchange
layer itself (``collectives/``, ``adasum/``) is exempt: it is where the
raw primitives are supposed to live.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..findings import Finding
from .base import LintContext, LintRule

_COLLECTIVE_ATTRS = {"psum", "psum_scatter", "all_gather", "all_to_all",
                     "ppermute", "pmean", "pmax", "pmin", "pshuffle"}

# Directories (repo-relative prefixes) owning the raw primitives.
_EXCHANGE_LAYER = ("horovod_tpu/collectives/", "horovod_tpu/adasum/")


class CollectiveOutsidePlannerRule(LintRule):
    id = "lint-collective-outside-planner"
    severity = "error"
    description = ("raw jax.lax collective invoked outside the exchange "
                   "layer (bypasses ops-layer axis/codec/process-set "
                   "resolution and the plan audit)")

    def run(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for sf in ctx.files:
            if sf.relpath.startswith(_EXCHANGE_LAYER):
                continue
            for node in ast.walk(sf.tree):
                call = None
                if isinstance(node, ast.Call):
                    call = node.func
                elif isinstance(node, ast.Attribute):
                    # Bare references too (partial(lax.ppermute, ...)).
                    call = node
                if not isinstance(call, ast.Attribute):
                    continue
                if call.attr not in _COLLECTIVE_ATTRS:
                    continue
                base = call.value
                is_lax = (isinstance(base, ast.Name)
                          and base.id in ("lax", "plax")) or \
                         (isinstance(base, ast.Attribute)
                          and base.attr == "lax")
                if not is_lax:
                    continue
                if not isinstance(node, ast.Call):
                    # Count the reference site once; the Call branch
                    # reports invocations, this catches partial() use.
                    if isinstance(getattr(node, "ctx", None), ast.Store):
                        continue
                findings.append(self.finding(
                    sf, f"lax.{call.attr}:{call.lineno}",
                    f"direct lax.{call.attr} outside the exchange layer; "
                    "route through horovod_tpu.collectives.ops",
                    line=call.lineno))
        # A Call's func Attribute is also walked as an Attribute node;
        # dedupe per (path, line, attr).
        seen = set()
        unique = []
        for f in findings:
            if f.key() + (f.line,) in seen:
                continue
            seen.add(f.key() + (f.line,))
            unique.append(f)
        return unique
