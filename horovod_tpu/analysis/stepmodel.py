"""Expected-collective model: what a train step SHOULD emit.

Given the builder metadata a :func:`horovod_tpu.make_train_step` /
``make_flax_train_step`` step carries (optimizer wrap, zero stage,
microbatch count, world size), derive the exact multiset of collectives
the exchange is contracted to put on the wire -- op kind, dtype, and
element count per leg -- from the SAME planner calls the exchange makes
(``fusion.plan_buckets`` / ``ef_bucket_plan`` / ``zero.plan_arena``), so
the expectation and the emission can only diverge if the exchange code
itself diverges from its plan.

Width references:

- cast codecs: one ``psum`` per bucket, full bucket elements at the wire
  dtype (f32 buckets cast down, narrow/int buckets ride as-is);
- powersgd(r): two f32 ``psum`` legs per floating bucket of
  ``powersgd_factor_widths(size, r)`` elements -- the P/Q factor widths
  ``joinop._replay`` replays bitwise;
- topk(f): two ``all_gather`` legs per floating bucket of
  ``k = min(topk_count(size, f), size)`` elements (f32 values + int32
  indices);
- ZeRO-1: per dtype arena, one ``reduce_scatter`` of the padded arena
  plus one ``all_gather`` of the shard at the allgather codec's wire
  dtype;
- microbatches=k: per reverse-planned bucket, k ``reduce_scatter`` legs
  of the ``lcm(256, n)``-padded bucket plus one closing ``all_gather``
  of ``padded / n`` elements, all at the wire dtype;
- hierarchical (two-level ``(dcn, ici)`` mesh): per bucket one
  ``reduce_scatter`` of the ``lcm(256, n_ici)``-padded bucket over ICI,
  the DCN hop of the ``padded / n_ici`` shard under the DCN-leg codec
  (psum / powersgd P+Q / topk gathers / fp8 quantized gather), and one
  closing ICI ``all_gather`` of the shard;
- chunked: per wire-buffer chunk (``chunk_bytes / wire_itemsize``
  elements, rounded up to a multiple of n) one ``reduce_scatter`` of the
  padded piece plus one ``all_gather`` of ``piece / n`` elements.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..collectives import ops as _ops
from ..collectives.compression import (Compression, is_error_feedback,
                                       is_fp8, is_powersgd,
                                       parse_compression,
                                       powersgd_factor_widths, topk_count)


@dataclasses.dataclass(frozen=True)
class ExpectedOp:
    """One collective leg the exchange contract requires."""
    kind: str
    dtype: str
    elements: int
    label: str    # e.g. "bucket0(f32)/psum-P"

    def sig(self) -> Tuple[str, str, int]:
        return (self.kind, self.dtype, self.elements)


@dataclasses.dataclass
class ExpectedExchange:
    """The derived contract plus the plan rows it was derived from.

    ``supported=False`` means the config uses an exchange the model does
    not price (chunked/hierarchical/fp8/process-set/Adasum paths); the
    auditor then skips plan matching and reports a warning instead of
    guessing."""
    ops: List[ExpectedOp]
    plan_rows: List[dict]
    supported: bool = True
    notes: Tuple[str, ...] = ()
    # Pallas kernel families active while the step traces (from
    # ``ops.pallas.active_kernels()``).  Informational: every registered
    # contract is collective-free with zero wire delta, so the exchange
    # contract above is identical with kernels on or off; a future
    # family that DID declare collective legs would have them appended
    # to ``ops`` (priced, not declined) by ``_attach_kernel_contracts``.
    kernels: Tuple[str, ...] = ()


def _wire_dtype(comp, dtype) -> str:
    """Dtype a cast codec puts on the wire for a ``dtype`` bucket."""
    dt = jnp.dtype(dtype)
    wd = getattr(comp, "wire_dtype", None)
    if (wd is not None and jnp.issubdtype(dt, jnp.floating)
            and dt.itemsize > jnp.dtype(wd).itemsize):
        return str(jnp.dtype(wd))
    return str(dt)


def _unsupported(notes) -> ExpectedExchange:
    return ExpectedExchange(ops=[], plan_rows=[], supported=False,
                            notes=tuple(notes))


def _expected_world1(params, meta: dict) -> ExpectedExchange:
    """The single-device exchange: ``allreduce_gradients`` skips the
    fusion planner at ``axis_size == 1`` and maps the collective over the
    leaves -- one identity psum per leaf, at the codec's wire dtype
    (compress/decompress still wrap the size-1 psum).  ZeRO / microbatch /
    EF configurations never hit this path in practice; at world=1 their
    degenerate shapes are not worth modeling."""
    if meta.get("zero_stage") or int(meta.get("microbatches", 1)) > 1:
        return _unsupported(("world=1 zero/microbatch step: unmodeled "
                             "degenerate exchange",))
    optimizer = meta.get("optimizer")
    exchange = getattr(getattr(optimizer, "update", None),
                       "_hvd_exchange", None)
    if exchange is None:
        return ExpectedExchange(ops=[], plan_rows=[], notes=(
            "bare optimizer at world=1: no gradient exchange",))
    comp = parse_compression(exchange["compression"])
    if is_error_feedback(comp) or is_fp8(comp):
        return _unsupported((f"world=1 {comp.__name__} exchange: unmodeled "
                             "degenerate codec path",))
    from ..controller.fusion import exchange_chunk_bytes, hier_requested
    if hier_requested(comp) or exchange_chunk_bytes() > 0:
        return _unsupported(("world=1 chunked/hierarchical exchange: "
                             "unmodeled degenerate decomposition",))
    leaves = jax.tree.leaves(params)
    ops = [ExpectedOp("psum", _wire_dtype(comp, leaf.dtype),
                      int(leaf.size),
                      f"leaf{i}({jnp.dtype(leaf.dtype)})")
           for i, leaf in enumerate(leaves)]
    rows = [{"bucket": 0, "dtype": "per-leaf", "leaves": len(leaves),
             "elements": sum(int(l.size) for l in leaves),
             "kind": "leafwise-world1"}]
    return ExpectedExchange(ops=ops, plan_rows=rows, notes=(
        "world=1: leaf-wise identity psums (planner bypassed)",))


def meta_from_step(step) -> Optional[dict]:
    """The builder metadata riding an ``_InstrumentedStep`` wrapper (None
    for a bare jitted step -- pass ``meta=`` to ``audit_step`` then)."""
    meta = getattr(step, "_meta", None)
    return dict(meta) if isinstance(meta, dict) else None


def _attach_kernel_contracts(expected: ExpectedExchange
                             ) -> ExpectedExchange:
    """Make the expectation kernel-aware instead of declining.

    Active Pallas families are recorded on ``expected.kernels``; any
    collective legs a family's contract registers are appended to the
    priced ops (today every contract is collective-free with zero wire
    delta, so this only annotates).  ``trace_audit`` separately enforces
    the collective-free claim by walking ``pallas_call`` sub-jaxprs.
    """
    from ..ops import pallas as _pallas
    active = _pallas.active_kernels()
    if not active or not expected.supported:
        return expected
    expected.kernels = active
    for family in active:
        contract = _pallas.kernel_contract(family)
        for kind, dtype, elements in contract["collectives"]:
            expected.ops.append(ExpectedOp(
                kind, str(dtype), int(elements),
                f"kernel:{family}/{kind}"))
    return expected


def expected_exchange(params, meta: dict) -> ExpectedExchange:
    """Derive the collective contract for a step built with ``meta``
    (kernel-aware: see :func:`_attach_kernel_contracts`)."""
    expected = _attach_kernel_contracts(_expected_exchange(params, meta))
    if meta.get("guard") and expected.supported:
        # The SDC guard screen: one f32[2] psum (nonfinite count +
        # grad-norm square) riding beside the gradient exchange,
        # identical on every modeled path including world=1.  Priced
        # from the SAME plan row the step notes (audit label is
        # complete), NOT absorbed by the scalar-aux allowance --
        # elements==2 is deliberate so an unmodeled auditor flags it.
        from ..controller import fusion as _fusion
        expected.ops.extend(
            _plan_ops(_fusion.plan_exchange("guard").legs, tag=""))
    return expected


def _expected_exchange(params, meta: dict) -> ExpectedExchange:
    from ..controller.fusion import exchange_chunk_bytes, explain_plan
    from ..core.state import global_state
    from ..optim import distributed as _dist
    from ..optim import zero as _zero

    if meta.get("kind") in ("serving_decode", "serving_verify"):
        return _expected_serving_decode(meta)
    if (int(meta.get("tp", 1) or 1) > 1
            or int(meta.get("pipeline_stages", 1) or 1) > 1):
        # Model-parallel step on a build_3d_mesh: the DP leg prices over
        # the LOCAL (model-sharded) leaves and the data axes only.
        return _expected_3d(params, meta)
    world = int(meta.get("world", 1))
    if world <= 1:
        return _expected_world1(params, meta)
    leaves = jax.tree.leaves(params)
    if not leaves:
        return ExpectedExchange(ops=[], plan_rows=[])

    if meta.get("zero_stage"):
        return _expected_zero(leaves, meta, world)

    optimizer = meta.get("optimizer")
    exchange = getattr(getattr(optimizer, "update", None),
                       "_hvd_exchange", None)
    k_micro = int(meta.get("microbatches", 1))
    if k_micro > 1:
        # Mirror _microbatch_unwrap: the wrapped exchange dict moves into
        # the microbatch pipe (or EF-once), the wrap's own allreduce is
        # never traced.
        return _expected_microbatch(leaves, exchange, k_micro, world)
    if exchange is None:
        return ExpectedExchange(ops=[], plan_rows=[], notes=(
            "bare optimizer: no gradient exchange",))

    comp = parse_compression(exchange["compression"])
    notes = []
    if exchange.get("process_set") is not None:
        notes.append("process-set reduction")
    from ..collectives.reduce_op import Adasum, Average, Sum
    from ..collectives.compression import is_hier_legs
    from ..controller.fusion import hier_mesh_shape, hier_requested
    op = exchange.get("op") or Average
    if op is Adasum:
        notes.append("Adasum exchange")
    if notes:
        return _unsupported(f"unmodeled exchange path: {n}" for n in notes)

    hier_shape = hier_mesh_shape()
    hier = (hier_requested(comp) and hier_shape is not None
            and op in (Sum, Average))
    thr = exchange["fusion_threshold"]
    if is_error_feedback(comp):
        if is_hier_legs(comp) and hier_shape is None:
            return _unsupported(("per-leg EF codec on a flat mesh: the "
                                 "runtime raises (needs the (dcn, ici) "
                                 "communicator)",))
        rows = explain_plan(params, threshold_bytes=_dist._ef_threshold(thr),
                            compression=comp, register=False)
        ops = _ef_ops(rows, comp,
                      hier_shape=hier_shape if is_hier_legs(comp) else None)
        return ExpectedExchange(ops=ops, plan_rows=rows)
    if is_fp8(comp):
        return _unsupported(("unmodeled exchange path: fp8 exchange",))
    rows = explain_plan(params, threshold_bytes=thr, compression=comp,
                        register=False)
    if hier:
        n_dcn, n_ici = hier_shape
        ops = []
        for r in rows:
            ops += _hier_bucket_ops(
                f"bucket{r['bucket']}({r['dtype']})", r["elements"],
                r["dtype"], comp, n_dcn, n_ici)
        return ExpectedExchange(ops=ops, plan_rows=rows, notes=(
            f"two-level exchange on the ({n_dcn}, {n_ici}) mesh",))
    if is_hier_legs(comp):
        # Flat-mesh degrade: the DCN hop is vacuous, the psum-compatible
        # ICI codec rides the flat exchange (collective() parity).
        ops = _flat_bucket_ops(rows, comp.ici)
        return ExpectedExchange(ops=ops, plan_rows=rows, notes=(
            "per-leg codec on a flat mesh: ICI codec on the flat psum",))
    chunk = exchange_chunk_bytes()
    if chunk > 0 and op in (Sum, Average):
        return ExpectedExchange(ops=_chunked_ops(rows, comp, chunk, world),
                                plan_rows=rows,
                                notes=(f"chunked exchange ({chunk}B chunks "
                                       "of the wire buffer)",))
    return ExpectedExchange(ops=_flat_bucket_ops(rows, comp),
                            plan_rows=rows)


def _flat_bucket_ops(rows: List[dict], comp) -> List[ExpectedOp]:
    """One flat psum per bucket at the codec's wire dtype, rendered from
    the memoized ``plan_exchange("flat", ...)`` rows."""
    from ..controller import fusion as _fusion
    ops = []
    for r in rows:
        plan = _fusion.plan_exchange(
            "flat", size=int(r["elements"]), dtype=str(r["dtype"]),
            compression=comp)
        ops += _plan_ops(plan.legs,
                         tag=f"bucket{r['bucket']}({r['dtype']})")
    return ops


def _plan_ops(legs, tag=None) -> List[ExpectedOp]:
    """Render plan-IR legs' audit contracts as ExpectedOp rows -- the
    expectation IS the plan, flattened by ``fusion.ops_from_legs``."""
    from ..controller import fusion as _fusion
    return [ExpectedOp(kind, dt, elements, label)
            for kind, dt, elements, label
            in _fusion.ops_from_legs(legs, tag=tag)]


def _hier_bucket_ops(tag: str, size: int, dtype, comp, n_dcn: int,
                     n_ici: int, axes=None) -> List[ExpectedOp]:
    """The collective legs one bucket of ``ops.hierarchical_allreduce``
    emits -- the SAME memoized ``plan_exchange("hier", ...)`` rows the
    executor notes, rendered in first-operand element counts (what the
    jaxpr auditor records).  ``axes`` overrides the ``(dcn, ici)`` axis
    names for exchanges over a mesh subset (the 3-D data pair); the
    default asks the world mesh so the plan-cache entry is shared with
    the executor."""
    from ..controller import fusion as _fusion
    if axes is None:
        axes = _fusion.hier_mesh_axes() or ("dcn", "ici")
    plan = _fusion.plan_exchange(
        "hier", size=int(size), dtype=str(jnp.dtype(dtype)),
        n_dcn=int(n_dcn), n_ici=int(n_ici), compression=comp,
        dcn_axis=str(axes[0]), ici_axis=str(axes[1]))
    return _plan_ops(plan.legs, tag=tag)


def _chunked_ops(rows: List[dict], comp, chunk_bytes: int,
                 world: int) -> List[ExpectedOp]:
    """The RS+AG pieces ``ops.chunked_allreduce`` emits per bucket.

    Chunking acts on the COMPRESSED wire buffer (collective() compresses
    first), so each bucket's plan is keyed on the wire dtype/size -- the
    SAME ``plan_exchange("chunked", ...)`` entry the executor notes."""
    from ..controller import fusion as _fusion
    ops = []
    for r in rows:
        wire = _wire_dtype(comp, r["dtype"])
        tag = f"bucket{r['bucket']}({r['dtype']})"
        plan = _fusion.plan_exchange(
            "chunked", size=int(r["elements"]), dtype=wire,
            chunk_bytes=int(chunk_bytes), world=int(world))
        ops += _plan_ops(plan.legs, tag=tag)
    return ops


def _expected_serving_decode(meta: dict) -> ExpectedExchange:
    """The serving TP decode / speculative verify activation contract.

    Two row-parallel closures per decoder layer (``wo`` after attention,
    ``w_down`` after the SwiGLU), each one ``collectives.ops.allreduce``
    == one ``psum`` of the full residual activation -- ``slots * width *
    d_model`` elements at the compute dtype, where ``width`` is 1 for
    plain decode and ``k + 1`` for the speculative verify step
    (``kind=serving_verify``): the SAME two-psums-per-layer multiset,
    just wider.  Size-1-axis psums are NOT elided at trace time, so the
    contract holds at tp=1.  fp8 KV compression is wire-neutral here:
    the dequant blend is local gather arithmetic, no new collectives.

    Per-slot LoRA banks are declined, not guessed: the adapter gather is
    an indexing pattern the pricing model does not cover, and a wrong
    expectation is worse than an honest unsupported warning.
    """
    if meta.get("lora"):
        return _unsupported(("serving TP decode with per-slot LoRA banks: "
                             "unmodeled adapter exchange",))
    missing = [k for k in ("num_layers", "d_model", "slots")
               if not meta.get(k)]
    if missing:
        return _unsupported(
            (f"serving decode meta missing {'/'.join(missing)}: "
             "cannot derive activation widths",))
    from ..controller import fusion as _fusion
    layers = int(meta["num_layers"])
    width = int(meta.get("width", 1))
    elements = int(meta["slots"]) * width * int(meta["d_model"])
    dtype = str(jnp.dtype(meta.get("dtype", "float32")))
    kind_tag = ("serving-tp-verify" if meta.get("kind") == "serving_verify"
                else "serving-tp-decode")
    # The SAME memoized plan the decode step builder notes; audit labels
    # are complete, so no tag prefix.
    plan = _fusion.plan_exchange(
        "serving", kind=str(meta.get("kind", "serving_decode")),
        layers=layers, slots=int(meta["slots"]), width=width,
        d_model=int(meta["d_model"]), dtype=dtype,
        axis=str(meta.get("tp_axis", "tp")))
    ops: List[ExpectedOp] = _plan_ops(plan.legs, tag="")
    rows = [{"bucket": 0, "dtype": dtype, "leaves": 2 * layers,
             "elements": 2 * layers * elements,
             "kind": kind_tag}]
    notes = [f"serving decode: 2 row-parallel allreduces/layer x {layers} "
             f"layer(s), {elements} elements each (width {width})"]
    # A rebuilt step after an elastic resize carries provenance; the
    # contract is mesh-size invariant (the psum payload is the full
    # residual activation regardless of how many ranks reduce it), so
    # the SAME expected ops must match on the post-shrink mesh.
    if meta.get("resized_from"):
        notes.append(
            f"resized decode mesh: tp {meta['resized_from']} -> "
            f"{meta.get('tp', meta.get('world'))}; activation contract "
            "is mesh-size invariant")
    return ExpectedExchange(ops=ops, plan_rows=rows, notes=tuple(notes))


def _ef_ops(rows: List[dict], comp,
            hier_shape: Optional[Tuple[int, int]] = None) -> List[ExpectedOp]:
    """The two-leg EF exchange per floating bucket (ef_exchange).

    With ``hier_shape`` (a per-leg ``ici:...,dcn:powersgd/topk`` codec on
    the two-level mesh) each floating bucket routes through
    ``hierarchical_allreduce`` with the EF codec scoped to the DCN hop;
    non-float buckets still ride the plain flat psum.  Both shapes come
    from the memoized plan IR -- the flat path from the SAME
    ``plan_exchange("ef", ...)`` entry ``ef_exchange`` notes."""
    from ..controller import fusion as _fusion
    ops = []
    for r in rows:
        tag = f"bucket{r['bucket']}({r['dtype']})"
        floating = jnp.issubdtype(jnp.dtype(r["dtype"]), jnp.floating)
        if floating and hier_shape is not None:
            ops += _hier_bucket_ops(tag, r["elements"], r["dtype"], comp,
                                    *hier_shape)
            continue
        plan = _fusion.plan_exchange(
            "ef", size=int(r["elements"]), dtype=str(r["dtype"]),
            compression=comp)
        ops += _plan_ops(plan.legs, tag=tag)
    return ops


def _expected_microbatch(leaves, exchange, k: int, world: int
                         ) -> ExpectedExchange:
    """The backward-overlap pipe: k reduce-scatters + 1 allgather per
    reverse-planned bucket (or the EF-once path for powersgd/topk)."""
    from ..controller.fusion import explain_plan, plan_buckets
    from ..optim import distributed as _dist

    if exchange is None:
        return ExpectedExchange(ops=[], plan_rows=[], notes=(
            "bare optimizer: local microbatch accumulation only",))
    comp = parse_compression(exchange["compression"])
    if is_error_feedback(comp):
        # EF composes as ONE residual-fed exchange per step over the
        # NON-reversed ef plan (_build_microbatch_local_step).
        params_like = leaves
        rows = explain_plan(
            params_like,
            threshold_bytes=_dist._ef_threshold(
                exchange["fusion_threshold"]),
            compression=comp, register=False)
        return ExpectedExchange(ops=_ef_ops(rows, comp), plan_rows=rows,
                                notes=("EF-once-per-step microbatch pipe",))

    from ..controller import fusion as _fusion
    spec = plan_buckets(leaves, exchange["fusion_threshold"], reverse=True)
    plan = _fusion.plan_exchange(
        "microbatch",
        buffers=tuple((str(jnp.dtype(dt)), sum(s.size for s in lspecs))
                      for dt, lspecs in spec.buffers),
        k=int(k), world=int(world), compression=comp)
    nb = len(spec.buffers)
    ops, rows = [], []
    for i, (dt, lspecs) in enumerate(spec.buffers):
        rs, ag = plan.legs[i], plan.legs[nb + i]
        tag = f"bucket{i}({jnp.dtype(dt)})"
        ops += _plan_ops([rs, ag], tag=tag)
        rows.append({"bucket": i, "dtype": str(jnp.dtype(dt)),
                     "leaves": len(lspecs),
                     "elements": sum(s.size for s in lspecs),
                     "padded": rs.elements, "wire_dtype": rs.wire_dtype,
                     "codec": comp.__name__, "kind": "microbatch-pipe"})
    return ExpectedExchange(ops=ops, plan_rows=rows)


def _expected_zero(leaves, meta: dict, world: int,
                   axes_shape: Optional[Tuple[int, ...]] = None
                   ) -> ExpectedExchange:
    """ZeRO-1 arena exchange: reduce-scatter + compressed allgather.

    On the two-level ``(dcn, ici)`` mesh the multi-axis collectives
    decompose per axis (``ops.reducescatter`` loops ``psum_scatter`` in
    axis order; ``ops.allgather`` gathers in reverse order), and a
    per-leg ``ici:...,dcn:...`` codec additionally flips the scatter to
    (ici, dcn) order so only the 1/n_ici shard crosses DCN, with each
    allgather hop riding its own leg codec (``zero_apply`` parity).

    ``axes_shape`` overrides the axis decomposition for steps whose
    exchange runs over a SUBSET of the mesh (the 3-D path's data axes):
    a 2-tuple prices the per-axis decomposition over that outer/inner
    pair, any other length forces the single-axis exchange -- ``None``
    keeps the global-mesh ``hier_mesh_shape()`` probe."""
    from ..collectives.compression import is_hier_legs
    from ..controller.fusion import hier_mesh_shape
    from ..optim import zero as _zero

    comp = meta.get("zero_compression")
    comp = parse_compression(comp) if comp else Compression.none
    if is_error_feedback(comp) or is_fp8(comp):
        return _unsupported(
            (f"unmodeled zero allgather codec: {comp.__name__}",))
    from ..controller import fusion as _fusion
    spec = _zero.plan_arena(leaves, world)
    use_rs = _zero._use_reducescatter()
    if axes_shape is None:
        two_level = hier_mesh_shape()
        ax_names = _fusion.hier_mesh_axes() or ()
    else:
        two_level = tuple(int(n) for n in axes_shape) \
            if len(axes_shape) == 2 else None
        ax_names = tuple(meta.get("data_axes") or ()) \
            if two_level is not None else ()
    hier = is_hier_legs(comp) and two_level is not None
    if hier and is_fp8(comp.dcn):
        return _unsupported(("unmodeled zero DCN-leg codec: fp8 "
                             "(quantized leader gather)",))
    plan = _fusion.plan_exchange(
        "zero",
        buffers=tuple((str(jnp.dtype(b.dtype)), int(b.size),
                       int(b.padded), int(b.shard)) for b in spec.buffers),
        world=int(world), compression=comp, axes_shape=two_level,
        axes=ax_names, use_rs=use_rs)
    nb = len(spec.buffers)
    ops, rows = [], []
    notes = []
    if two_level is not None:
        n_dcn, n_ici = two_level
        notes.append(f"per-axis zero exchange on the ({n_dcn}, {n_ici}) "
                     f"mesh{' (per-leg codec)' if hier else ''}")
    for i, buf in enumerate(spec.buffers):
        if buf.size < 1:
            continue
        dt = str(jnp.dtype(buf.dtype))
        tag = f"arena{i}({dt})"
        ops += _plan_ops([plan.legs[i], plan.legs[nb + i]], tag=tag)
        rows.append({"bucket": i, "dtype": dt, "leaves": len(buf.leaves),
                     "elements": buf.size, "padded": buf.padded,
                     "shard": buf.shard, "codec": comp.__name__,
                     "kind": "zero-arena"})
    return ExpectedExchange(ops=ops, plan_rows=rows, notes=tuple(notes))


def _local_leaves(params, meta: dict):
    """Per-device leaf shapes under the step's ``param_specs``: each
    spec-named dim divided by that mesh axis's extent.  The gradient
    exchange inside ``shard_map`` plans its buckets/arena from these
    LOCAL shards, so the expectation must too.  Returns ``None`` when
    the meta carries no specs or a spec does not divide its dim."""
    from jax.sharding import PartitionSpec as P
    specs = meta.get("param_specs")
    if specs is None:
        return None
    mesh_shape = dict(meta.get("mesh_shape") or ())
    leaves = jax.tree.leaves(params)
    spec_leaves = jax.tree.flatten(
        specs, is_leaf=lambda x: x is None or isinstance(x, P))[0]
    if len(spec_leaves) != len(leaves):
        return None
    out = []
    for leaf, sp in zip(leaves, spec_leaves):
        shape = list(leaf.shape)
        if isinstance(sp, P):
            for i, entry in enumerate(sp):
                if entry is None:
                    continue
                names = entry if isinstance(entry, tuple) else (entry,)
                for nm in names:
                    ext = int(mesh_shape.get(nm, 1))
                    if ext <= 1:
                        continue
                    if i >= len(shape) or shape[i] % ext:
                        return None
                    shape[i] //= ext
        out.append(jax.ShapeDtypeStruct(tuple(shape),
                                        jnp.dtype(leaf.dtype)))
    return out


def _expected_3d(params, meta: dict) -> ExpectedExchange:
    """DP x TP x pipeline step on a ``build_3d_mesh`` (PR 18).

    Two contributions:

    - the DP gradient leg, priced with the SAME planner calls as the
      flat model but over each device's LOCAL (model-sharded) parameter
      leaves (``_local_leaves``) and the DATA-axes world only -- plain
      per-bucket psums, the two-level decomposition when the data axes
      are the ``(dcn, data)`` pair and hier is requested, the ZeRO-1
      per-axis arena exchange, or the microbatch RS+AG pipe;
    - the model-parallel activation legs of the REFERENCE 3-D configs,
      declared via ``meta["model_parallel"]`` (``d_model``, ``act_rows``
      = rows entering the loss per call, optional ``pipe_microbatches``
      and ``dtype``): per loss call, tensor parallelism contributes one
      forward + one backward row-parallel psum of the full activation;
      a pipeline stage shifts activations with one forward + one
      backward ppermute (recorded once per scan) and closes with the
      stage-select allreduce pair.  Arbitrary TP/pipeline losses carry
      no declaration and are declined, not guessed.
    """
    from ..collectives.compression import is_hier_legs
    from ..collectives.reduce_op import Average, Sum
    from ..controller.fusion import (exchange_chunk_bytes, explain_plan,
                                     hier_requested)

    tp = int(meta.get("tp", 1) or 1)
    pipe = int(meta.get("pipeline_stages", 1) or 1)
    data_mesh = tuple(int(n) for n in (meta.get("data_mesh") or ()))
    world = int(meta.get("world", 1))
    k_micro = int(meta.get("microbatches", 1))
    local = _local_leaves(params, meta)
    if local is None:
        return _unsupported((
            "model-parallel step without param_specs meta: cannot derive "
            "the local leaf shapes the exchange plans over",))
    if world <= 1:
        return _unsupported((
            "3-D step with data world 1: unmodeled degenerate exchange",))
    mp = meta.get("model_parallel")
    if not (isinstance(mp, dict) and "d_model" in mp and "act_rows" in mp):
        return _unsupported((
            "model-parallel step without a declared activation contract "
            "(meta['model_parallel'] with d_model/act_rows): the 3-D "
            "reference configs declare theirs, arbitrary TP/pipeline "
            "losses are not priced",))

    # -- the DP gradient leg over the data axes --------------------------
    if meta.get("zero_stage"):
        base = _expected_zero(
            local, meta, world,
            axes_shape=data_mesh if len(data_mesh) == 2 else ())
    else:
        optimizer = meta.get("optimizer")
        exchange = getattr(getattr(optimizer, "update", None),
                           "_hvd_exchange", None)
        if k_micro > 1:
            base = _expected_microbatch(local, exchange, k_micro, world)
        elif exchange is None:
            base = ExpectedExchange(ops=[], plan_rows=[], notes=(
                "bare optimizer: no gradient exchange",))
        else:
            comp = parse_compression(exchange["compression"])
            op = exchange.get("op") or Average
            if (is_error_feedback(comp) or is_fp8(comp)
                    or op not in (Sum, Average)
                    or exchange.get("process_set") is not None):
                return _unsupported((
                    "unmodeled 3-D DP exchange (EF/fp8 codec, non-sum op "
                    "or process set)",))
            if exchange_chunk_bytes() > 0:
                return _unsupported((
                    "unmodeled 3-D chunked DP exchange",))
            rows = explain_plan(local,
                                threshold_bytes=exchange["fusion_threshold"],
                                compression=comp, register=False)
            hier = ((hier_requested(comp) or is_hier_legs(comp))
                    and len(data_mesh) == 2)
            if hier:
                d_axes = tuple(meta.get("data_axes") or ()) or None
                hops = []
                for r in rows:
                    hops += _hier_bucket_ops(
                        f"bucket{r['bucket']}({r['dtype']})", r["elements"],
                        r["dtype"], comp, *data_mesh, axes=d_axes)
                base = ExpectedExchange(ops=hops, plan_rows=rows, notes=(
                    f"two-level DP leg on the {data_mesh} data axes",))
            elif is_hier_legs(comp):
                return _unsupported((
                    "per-leg codec without the (dcn, data) pair: the "
                    "runtime raises",))
            else:
                base = ExpectedExchange(ops=_flat_bucket_ops(rows, comp),
                                        plan_rows=rows)
    if not base.supported:
        return base

    # -- the declared model-parallel activation legs ---------------------
    d = int(mp["d_model"])
    act_rows = int(mp["act_rows"])
    act_dt = str(jnp.dtype(mp.get("dtype", "float32")))
    m_pipe = max(1, int(mp.get("pipe_microbatches", 1)))
    ops = list(base.ops)
    for mb in range(k_micro):
        tag = f"mb{mb}" if k_micro > 1 else "act"
        if pipe > 1:
            rp = act_rows // m_pipe
            # One ppermute per scan direction (jaxpr_walk records a
            # scan-body collective once), stage-select psum pair on the
            # stacked outputs.
            ops.append(ExpectedOp("ppermute", act_dt, rp * d,
                                  f"{tag}/pipe-shift-fwd"))
            ops.append(ExpectedOp("ppermute", act_dt, rp * d,
                                  f"{tag}/pipe-shift-bwd"))
            ops.append(ExpectedOp("psum", act_dt, act_rows * d,
                                  f"{tag}/pipe-out-fwd"))
            ops.append(ExpectedOp("psum", act_dt, act_rows * d,
                                  f"{tag}/pipe-out-bwd"))
            if tp > 1:
                ops.append(ExpectedOp("psum", act_dt, rp * d,
                                      f"{tag}/tp-row-fwd"))
                ops.append(ExpectedOp("psum", act_dt, rp * d,
                                      f"{tag}/tp-row-bwd"))
        elif tp > 1:
            ops.append(ExpectedOp("psum", act_dt, act_rows * d,
                                  f"{tag}/tp-row-fwd"))
            ops.append(ExpectedOp("psum", act_dt, act_rows * d,
                                  f"{tag}/tp-row-bwd"))
    notes = tuple(base.notes) + (
        f"3-D config: tp={tp} pipe={pipe} data={data_mesh or (world,)}",)
    return ExpectedExchange(ops=ops, plan_rows=base.plan_rows, notes=notes)
