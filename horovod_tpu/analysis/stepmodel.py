"""Expected-collective model: what a train step SHOULD emit.

Given the builder metadata a :func:`horovod_tpu.make_train_step` /
``make_flax_train_step`` step carries (optimizer wrap, zero stage,
microbatch count, world size), derive the exact multiset of collectives
the exchange is contracted to put on the wire -- op kind, dtype, and
element count per leg -- from the SAME planner calls the exchange makes
(``fusion.plan_buckets`` / ``ef_bucket_plan`` / ``zero.plan_arena``), so
the expectation and the emission can only diverge if the exchange code
itself diverges from its plan.

Width references:

- cast codecs: one ``psum`` per bucket, full bucket elements at the wire
  dtype (f32 buckets cast down, narrow/int buckets ride as-is);
- powersgd(r): two f32 ``psum`` legs per floating bucket of
  ``powersgd_factor_widths(size, r)`` elements -- the P/Q factor widths
  ``joinop._replay`` replays bitwise;
- topk(f): two ``all_gather`` legs per floating bucket of
  ``k = min(topk_count(size, f), size)`` elements (f32 values + int32
  indices);
- ZeRO-1: per dtype arena, one ``reduce_scatter`` of the padded arena
  plus one ``all_gather`` of the shard at the allgather codec's wire
  dtype;
- microbatches=k: per reverse-planned bucket, k ``reduce_scatter`` legs
  of the ``lcm(256, n)``-padded bucket plus one closing ``all_gather``
  of ``padded / n`` elements, all at the wire dtype.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..collectives import ops as _ops
from ..collectives.compression import (Compression, is_error_feedback,
                                       is_fp8, is_powersgd,
                                       parse_compression,
                                       powersgd_factor_widths, topk_count)


@dataclasses.dataclass(frozen=True)
class ExpectedOp:
    """One collective leg the exchange contract requires."""
    kind: str
    dtype: str
    elements: int
    label: str    # e.g. "bucket0(f32)/psum-P"

    def sig(self) -> Tuple[str, str, int]:
        return (self.kind, self.dtype, self.elements)


@dataclasses.dataclass
class ExpectedExchange:
    """The derived contract plus the plan rows it was derived from.

    ``supported=False`` means the config uses an exchange the model does
    not price (chunked/hierarchical/fp8/process-set/Adasum paths); the
    auditor then skips plan matching and reports a warning instead of
    guessing."""
    ops: List[ExpectedOp]
    plan_rows: List[dict]
    supported: bool = True
    notes: Tuple[str, ...] = ()


def _wire_dtype(comp, dtype) -> str:
    """Dtype a cast codec puts on the wire for a ``dtype`` bucket."""
    dt = jnp.dtype(dtype)
    wd = getattr(comp, "wire_dtype", None)
    if (wd is not None and jnp.issubdtype(dt, jnp.floating)
            and dt.itemsize > jnp.dtype(wd).itemsize):
        return str(jnp.dtype(wd))
    return str(dt)


def _unsupported(notes) -> ExpectedExchange:
    return ExpectedExchange(ops=[], plan_rows=[], supported=False,
                            notes=tuple(notes))


def _expected_world1(params, meta: dict) -> ExpectedExchange:
    """The single-device exchange: ``allreduce_gradients`` skips the
    fusion planner at ``axis_size == 1`` and maps the collective over the
    leaves -- one identity psum per leaf, at the codec's wire dtype
    (compress/decompress still wrap the size-1 psum).  ZeRO / microbatch /
    EF configurations never hit this path in practice; at world=1 their
    degenerate shapes are not worth modeling."""
    if meta.get("zero_stage") or int(meta.get("microbatches", 1)) > 1:
        return _unsupported(("world=1 zero/microbatch step: unmodeled "
                             "degenerate exchange",))
    optimizer = meta.get("optimizer")
    exchange = getattr(getattr(optimizer, "update", None),
                       "_hvd_exchange", None)
    if exchange is None:
        return ExpectedExchange(ops=[], plan_rows=[], notes=(
            "bare optimizer at world=1: no gradient exchange",))
    comp = parse_compression(exchange["compression"])
    if is_error_feedback(comp) or is_fp8(comp):
        return _unsupported((f"world=1 {comp.__name__} exchange: unmodeled "
                             "degenerate codec path",))
    from ..controller.fusion import exchange_chunk_bytes
    from ..core.state import global_state
    st = global_state()
    if (st.config and st.config.hierarchical_allreduce) \
            or exchange_chunk_bytes() > 0:
        return _unsupported(("world=1 chunked/hierarchical exchange: "
                             "unmodeled degenerate decomposition",))
    leaves = jax.tree.leaves(params)
    ops = [ExpectedOp("psum", _wire_dtype(comp, leaf.dtype),
                      int(leaf.size),
                      f"leaf{i}({jnp.dtype(leaf.dtype)})")
           for i, leaf in enumerate(leaves)]
    rows = [{"bucket": 0, "dtype": "per-leaf", "leaves": len(leaves),
             "elements": sum(int(l.size) for l in leaves),
             "kind": "leafwise-world1"}]
    return ExpectedExchange(ops=ops, plan_rows=rows, notes=(
        "world=1: leaf-wise identity psums (planner bypassed)",))


def meta_from_step(step) -> Optional[dict]:
    """The builder metadata riding an ``_InstrumentedStep`` wrapper (None
    for a bare jitted step -- pass ``meta=`` to ``audit_step`` then)."""
    meta = getattr(step, "_meta", None)
    return dict(meta) if isinstance(meta, dict) else None


def expected_exchange(params, meta: dict) -> ExpectedExchange:
    """Derive the collective contract for a step built with ``meta``."""
    from ..controller.fusion import exchange_chunk_bytes, explain_plan
    from ..core.state import global_state
    from ..optim import distributed as _dist
    from ..optim import zero as _zero

    if meta.get("kind") == "serving_decode":
        return _expected_serving_decode(meta)
    world = int(meta.get("world", 1))
    if world <= 1:
        return _expected_world1(params, meta)
    leaves = jax.tree.leaves(params)
    if not leaves:
        return ExpectedExchange(ops=[], plan_rows=[])

    if meta.get("zero_stage"):
        return _expected_zero(leaves, meta, world)

    optimizer = meta.get("optimizer")
    exchange = getattr(getattr(optimizer, "update", None),
                       "_hvd_exchange", None)
    k_micro = int(meta.get("microbatches", 1))
    if k_micro > 1:
        # Mirror _microbatch_unwrap: the wrapped exchange dict moves into
        # the microbatch pipe (or EF-once), the wrap's own allreduce is
        # never traced.
        return _expected_microbatch(leaves, exchange, k_micro, world)
    if exchange is None:
        return ExpectedExchange(ops=[], plan_rows=[], notes=(
            "bare optimizer: no gradient exchange",))

    comp = parse_compression(exchange["compression"])
    notes = []
    if exchange.get("process_set") is not None:
        notes.append("process-set reduction")
    from ..collectives.reduce_op import Adasum
    if exchange.get("op") is Adasum:
        notes.append("Adasum exchange")
    if is_fp8(comp):
        notes.append("fp8 exchange")
    st = global_state()
    if (st.config and st.config.hierarchical_allreduce
            and not is_error_feedback(comp)):
        notes.append("hierarchical allreduce")
    if exchange_chunk_bytes() > 0 and not is_error_feedback(comp):
        notes.append("chunked exchange")
    if notes:
        return _unsupported(f"unmodeled exchange path: {n}" for n in notes)

    thr = exchange["fusion_threshold"]
    if is_error_feedback(comp):
        rows = explain_plan(params, threshold_bytes=_dist._ef_threshold(thr),
                            compression=comp, register=False)
        return ExpectedExchange(ops=_ef_ops(rows, comp), plan_rows=rows)
    rows = explain_plan(params, threshold_bytes=thr, compression=comp,
                        register=False)
    ops = [ExpectedOp("psum", _wire_dtype(comp, r["dtype"]),
                      r["elements"],
                      f"bucket{r['bucket']}({r['dtype']})/allreduce")
           for r in rows]
    return ExpectedExchange(ops=ops, plan_rows=rows)


def _expected_serving_decode(meta: dict) -> ExpectedExchange:
    """The serving TP decode step's activation contract.

    Two row-parallel closures per decoder layer (``wo`` after attention,
    ``w_down`` after the SwiGLU), each one ``collectives.ops.allreduce``
    == one ``psum`` of the full residual activation -- ``slots * d_model``
    elements at the compute dtype.  Size-1-axis psums are NOT elided at
    trace time, so the same two-per-layer contract holds at tp=1.

    Per-slot LoRA banks are declined, not guessed: the adapter gather is
    an indexing pattern the pricing model does not cover, and a wrong
    expectation is worse than an honest unsupported warning.
    """
    if meta.get("lora"):
        return _unsupported(("serving TP decode with per-slot LoRA banks: "
                             "unmodeled adapter exchange",))
    missing = [k for k in ("num_layers", "d_model", "slots")
               if not meta.get(k)]
    if missing:
        return _unsupported(
            (f"serving decode meta missing {'/'.join(missing)}: "
             "cannot derive activation widths",))
    layers = int(meta["num_layers"])
    elements = int(meta["slots"]) * int(meta["d_model"])
    dtype = str(jnp.dtype(meta.get("dtype", "float32")))
    ops: List[ExpectedOp] = []
    for li in range(layers):
        ops.append(ExpectedOp("psum", dtype, elements,
                              f"layer{li}/attn_wo/allreduce"))
        ops.append(ExpectedOp("psum", dtype, elements,
                              f"layer{li}/mlp_down/allreduce"))
    rows = [{"bucket": 0, "dtype": dtype, "leaves": 2 * layers,
             "elements": 2 * layers * elements,
             "kind": "serving-tp-decode"}]
    return ExpectedExchange(ops=ops, plan_rows=rows, notes=(
        f"serving decode: 2 row-parallel allreduces/layer x {layers} "
        f"layer(s), {elements} elements each",))


def _ef_ops(rows: List[dict], comp) -> List[ExpectedOp]:
    """The two-leg EF exchange per floating bucket (ef_exchange)."""
    ops = []
    for r in rows:
        tag = f"bucket{r['bucket']}({r['dtype']})"
        if not jnp.issubdtype(jnp.dtype(r["dtype"]), jnp.floating):
            ops.append(ExpectedOp("psum", r["dtype"], r["elements"],
                                  f"{tag}/allreduce"))
            continue
        size = r["elements"]
        if is_powersgd(comp):
            pw, qw = powersgd_factor_widths(size, comp.rank)
            ops.append(ExpectedOp("psum", "float32", pw, f"{tag}/psum-P"))
            ops.append(ExpectedOp("psum", "float32", qw, f"{tag}/psum-Q"))
        else:
            k = min(topk_count(size, comp.fraction), size)
            ops.append(ExpectedOp("all_gather", "float32", k,
                                  f"{tag}/gather-values"))
            ops.append(ExpectedOp("all_gather", "int32", k,
                                  f"{tag}/gather-indices"))
    return ops


def _expected_microbatch(leaves, exchange, k: int, world: int
                         ) -> ExpectedExchange:
    """The backward-overlap pipe: k reduce-scatters + 1 allgather per
    reverse-planned bucket (or the EF-once path for powersgd/topk)."""
    from ..controller.fusion import explain_plan, plan_buckets
    from ..optim import distributed as _dist

    if exchange is None:
        return ExpectedExchange(ops=[], plan_rows=[], notes=(
            "bare optimizer: local microbatch accumulation only",))
    comp = parse_compression(exchange["compression"])
    if is_error_feedback(comp):
        # EF composes as ONE residual-fed exchange per step over the
        # NON-reversed ef plan (_build_microbatch_local_step).
        params_like = leaves
        rows = explain_plan(
            params_like,
            threshold_bytes=_dist._ef_threshold(
                exchange["fusion_threshold"]),
            compression=comp, register=False)
        return ExpectedExchange(ops=_ef_ops(rows, comp), plan_rows=rows,
                                notes=("EF-once-per-step microbatch pipe",))

    spec = plan_buckets(leaves, exchange["fusion_threshold"], reverse=True)
    q = _ops.microbatch_pad_quantum(world)
    ops, rows = [], []
    for i, (dt, lspecs) in enumerate(spec.buffers):
        size = sum(s.size for s in lspecs)
        padded = size + (-size) % q
        wire = _wire_dtype(comp, dt)
        tag = f"bucket{i}({jnp.dtype(dt)})"
        for j in range(k):
            ops.append(ExpectedOp("reduce_scatter", wire, padded,
                                  f"{tag}/scatter-mb{j}"))
        ops.append(ExpectedOp("all_gather", wire, padded // world,
                              f"{tag}/allgather"))
        rows.append({"bucket": i, "dtype": str(jnp.dtype(dt)),
                     "leaves": len(lspecs), "elements": size,
                     "padded": padded, "wire_dtype": wire,
                     "codec": comp.__name__, "kind": "microbatch-pipe"})
    return ExpectedExchange(ops=ops, plan_rows=rows)


def _expected_zero(leaves, meta: dict, world: int) -> ExpectedExchange:
    """ZeRO-1 arena exchange: reduce-scatter + compressed allgather."""
    from ..optim import zero as _zero

    comp = meta.get("zero_compression")
    comp = parse_compression(comp) if comp else Compression.none
    if is_error_feedback(comp) or is_fp8(comp):
        return _unsupported(
            (f"unmodeled zero allgather codec: {comp.__name__}",))
    spec = _zero.plan_arena(leaves, world)
    use_rs = _zero._use_reducescatter()
    ops, rows = [], []
    for i, buf in enumerate(spec.buffers):
        if buf.size < 1:
            continue
        dt = str(jnp.dtype(buf.dtype))
        tag = f"arena{i}({dt})"
        if use_rs:
            ops.append(ExpectedOp("reduce_scatter", dt, buf.padded,
                                  f"{tag}/reduce-scatter"))
        else:
            ops.append(ExpectedOp("psum", dt, buf.padded,
                                  f"{tag}/allreduce"))
        ops.append(ExpectedOp("all_gather", _wire_dtype(comp, buf.dtype),
                              buf.shard, f"{tag}/allgather"))
        rows.append({"bucket": i, "dtype": dt, "leaves": len(buf.leaves),
                     "elements": buf.size, "padded": buf.padded,
                     "shard": buf.shard, "codec": comp.__name__,
                     "kind": "zero-arena"})
    return ExpectedExchange(ops=ops, plan_rows=rows)
