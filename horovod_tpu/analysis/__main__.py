"""CLI / CI gate: ``python -m horovod_tpu.analysis [mode] [options]``.

Modes (default ``--all``):

- ``--lint``: AST rules over the ``horovod_tpu/`` source tree;
- ``--step-audit``: trace-audit the reference step configurations
  (plain DP, ZeRO-1, powersgd+EF, microbatches=2 on the flat mesh, the
  serving tp-decode step at full tp and on the post-shrink resized
  mesh, the 3-D parallelism trio -- TP, TP+ZeRO-1, TP+pipeline+micro
  on their own 2x2x2 meshes -- then the hierarchical trio -- plain
  hier, hier+ZeRO-1, hier+EF-on-DCN -- on a two-level remesh of the
  same virtual CPU devices) and cross-check emitted collectives
  against their plans;
- ``--all``: both.

Findings matching ``analysis_baseline.txt`` (``--baseline`` to override)
are suppressed; exit status is 1 when unsuppressed ERROR findings remain
and 0 otherwise, so the tier-1 gate is just the exit code.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List


def _parse_args(argv):
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="Static collective-consistency analysis "
                    "(trace audit + repo lints).")
    parser.add_argument("--lint", action="store_true",
                        help="run the AST repo lints")
    parser.add_argument("--step-audit", action="store_true",
                        help="trace-audit the reference step configs")
    parser.add_argument("--all", action="store_true",
                        help="run both layers (default)")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help="baseline suppression file (default: "
                             "analysis_baseline.txt at the repo root)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as JSON instead of text")
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU device count for the step "
                             "audit mesh (default 8)")
    args = parser.parse_args(argv)
    if not (args.lint or args.step_audit or args.all):
        args.all = True
    if args.all:
        args.lint = args.step_audit = True
    return args


def _run_step_audit(devices: int):
    """Audit the reference configs on a forced-CPU virtual mesh (flat
    pass, then the hierarchical configs on a two-level remesh of the same
    devices).  Must run before any jax backend initialization in this
    process."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # Audit with the Pallas kernel families forced on (interpreter mode
    # on this CPU backend) so the gate traces the kernel paths the TPU
    # runs -- the exchange contract must match with kernels enabled.
    os.environ.setdefault("HOROVOD_PALLAS", "1")
    from ..utils.platform import force_host_device_count
    force_host_device_count(devices, cpu=True)
    import horovod_tpu as hvd
    hvd.init()
    from .trace_audit import (HIER_CONFIGS, PARALLEL3D_CONFIGS,
                              SERVING_CONFIGS, audit_standard_configs)
    try:
        reports = audit_standard_configs()
        # Serving decode contract, at full tp and on the post-shrink
        # mesh the elastic control plane leaves behind.
        reports.update(audit_standard_configs(SERVING_CONFIGS))
        if devices >= 8:
            # 3-D parallelism trio (TP, TP+ZeRO-1, TP+pipeline+micro):
            # each builds its own 2x2x2 mesh over the first 8 devices,
            # so the DP-leg plan matching bites on model-parallel steps.
            reports.update(audit_standard_configs(PARALLEL3D_CONFIGS))
    finally:
        hvd.shutdown()
    if devices >= 4 and devices % 2 == 0:
        # Second pass: the same devices as a (2, n/2) two-level
        # communicator -- plain hier, hier+ZeRO-1, hier+EF-on-DCN.
        import jax
        from ..parallel.mesh import build_mesh
        hvd.init(mesh=build_mesh(jax.devices()[:devices],
                                 hierarchical=True, dcn_size=2))
        try:
            reports.update(audit_standard_configs(HIER_CONFIGS))
        finally:
            hvd.shutdown()
    return reports


def main(argv=None) -> int:
    args = _parse_args(sys.argv[1:] if argv is None else argv)
    from .findings import (ERROR, Finding, apply_baseline, errors,
                           load_baseline, render_findings)

    findings: List[Finding] = []
    summaries = {}
    if args.step_audit:
        reports = _run_step_audit(args.devices)
        for config, report in reports.items():
            findings.extend(report.findings)
            summaries[config] = report.summary
            if not args.as_json:
                print(report.render())
    if args.lint:
        from .lints import run_lints
        findings.extend(run_lints())

    baseline_path = args.baseline
    try:
        baseline = load_baseline(baseline_path)
    except ValueError as exc:
        print(f"baseline error: {exc}", file=sys.stderr)
        return 2
    from .findings import default_baseline_path
    kept, suppressed = apply_baseline(
        findings, baseline,
        baseline_path=os.path.relpath(
            baseline_path or default_baseline_path()))

    if args.as_json:
        print(json.dumps({
            "findings": [f.__dict__ for f in kept],
            "suppressed": [f.__dict__ for f in suppressed],
            "step_audit": summaries,
        }, indent=2, sort_keys=True))
    else:
        if kept:
            print(render_findings(kept))
        n_err = len(errors(kept))
        print(f"analysis: {n_err} error(s), "
              f"{len(kept) - n_err} warning(s), "
              f"{len(suppressed)} baseline-suppressed")
    return 1 if errors(kept) else 0


if __name__ == "__main__":
    sys.exit(main())
