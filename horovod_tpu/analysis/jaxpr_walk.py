"""Jaxpr mechanics for the step auditor: collective extraction, the
axis-index taint walk, and donation aval matching.

Everything here is pure jaxpr traversal -- no planner knowledge.  The
policy layer (:mod:`horovod_tpu.analysis.trace_audit`) turns the records
produced here into findings by cross-checking them against the fusion
plan.

The walker recurses through every higher-order primitive this codebase
emits (``pjit``, ``shard_map``, ``scan``, ``cond``, ``while``, custom
jvp/vjp, ``remat``) and, as a safety net, through any ``params`` value
that holds a jaxpr -- an UNRECOGNISED nesting primitive therefore still
has its collectives counted rather than silently skipped.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

try:  # jax 0.4.x private-but-stable core types
    from jax._src.core import ClosedJaxpr, Jaxpr, Literal, Var
except ImportError:  # pragma: no cover - future jax relocations
    from jax.core import ClosedJaxpr, Jaxpr, Literal, Var  # type: ignore

# Collective primitives we account for.  pmean lowers through psum; pmax /
# pmin are collectives too (used by elastic join / metrics reductions).
COLLECTIVE_PRIMS = frozenset({
    "psum", "all_gather", "reduce_scatter", "ppermute", "all_to_all",
    "pmax", "pmin", "psum_invariant",
})

# The taint source: a per-rank value.  Anything data-derived from it may
# diverge across ranks.
TAINT_SOURCES = frozenset({"axis_index"})


@dataclasses.dataclass(frozen=True)
class CollectiveRecord:
    """One collective equation found in the traced step.

    ``elements`` is the element count of the first operand (the payload
    the wire moves per leg); ``dtype`` its dtype string.  ``path`` is the
    nesting address, e.g. ``pjit/shard_map/scan[body]/eqn12``, and
    ``in_loop`` marks records inside a ``scan``/``while`` body (their
    static count is per-iteration, not per-trace).
    """
    kind: str
    dtype: str
    elements: int
    path: str
    axes: Tuple[str, ...]
    in_loop: bool = False

    def sig(self) -> Tuple[str, str, int]:
        return (self.kind, self.dtype, self.elements)


def _eqn_axes(eqn) -> Tuple[str, ...]:
    names = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
    if isinstance(names, (str, int)):
        names = (names,)
    return tuple(str(n) for n in names)


def _collective_record(eqn, path: str, in_loop: bool) -> CollectiveRecord:
    aval = eqn.invars[0].aval
    return CollectiveRecord(
        kind=eqn.primitive.name,
        dtype=str(np.dtype(aval.dtype)) if hasattr(aval, "dtype") else "?",
        elements=int(np.prod(aval.shape, dtype=np.int64))
        if hasattr(aval, "shape") else 0,
        path=path,
        axes=_eqn_axes(eqn),
        in_loop=in_loop)


def _as_jaxpr(v) -> Optional[Jaxpr]:
    if isinstance(v, ClosedJaxpr):
        return v.jaxpr
    if isinstance(v, Jaxpr):
        return v
    return None


def _param_jaxprs(eqn) -> List[Tuple[str, Jaxpr]]:
    """Every jaxpr hiding in an equation's params (tuples included)."""
    found = []
    for key, val in eqn.params.items():
        j = _as_jaxpr(val)
        if j is not None:
            found.append((key, j))
            continue
        if isinstance(val, (tuple, list)):
            for i, item in enumerate(val):
                j = _as_jaxpr(item)
                if j is not None:
                    found.append((f"{key}[{i}]", j))
    return found


def contains_collective(jaxpr: Jaxpr) -> bool:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            return True
        for _, sub in _param_jaxprs(eqn):
            if contains_collective(sub):
                return True
    return False


def collect_collectives(closed: ClosedJaxpr) -> List[CollectiveRecord]:
    """Flatten the collective graph of a traced function: one record per
    collective equation, recursing through all nesting primitives."""
    records: List[CollectiveRecord] = []

    def walk(jaxpr: Jaxpr, path: str, in_loop: bool) -> None:
        for i, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                records.append(
                    _collective_record(eqn, f"{path}/eqn{i}:{name}",
                                       in_loop))
                continue
            loop = in_loop or name in ("scan", "while")
            for key, sub in _param_jaxprs(eqn):
                walk(sub, f"{path}/{name}.{key}", loop)

    walk(closed.jaxpr, "", False)
    return records


def collectives_in_kernels(closed: ClosedJaxpr) -> List[CollectiveRecord]:
    """Collectives hiding INSIDE ``pallas_call`` kernel bodies.

    The kernel contract registry (``ops.pallas.KERNEL_CONTRACTS``)
    declares every Pallas family collective-free: a collective inside an
    opaque custom call would be invisible to XLA's collective scheduling
    (deadlock risk under any reordering) and to the planner's wire
    accounting, so the auditor treats any hit as an error rather than
    trying to price it.  Returns one record per offending equation, with
    the enclosing kernel in the path.
    """
    records: List[CollectiveRecord] = []

    def walk(jaxpr: Jaxpr, path: str, in_kernel: bool) -> None:
        for i, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            if in_kernel and name in COLLECTIVE_PRIMS:
                records.append(
                    _collective_record(eqn, f"{path}/eqn{i}:{name}",
                                       False))
                continue
            inside = in_kernel or name == "pallas_call"
            for key, sub in _param_jaxprs(eqn):
                walk(sub, f"{path}/{name}.{key}", inside)

    walk(closed.jaxpr, "", False)
    return records


# -- taint (desync) analysis ------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DesyncRecord:
    """A control-flow equation whose predicate is data-dependent on
    ``axis_index`` AND whose body contains a collective: ranks can take
    different branches, so some ranks reach the collective and others do
    not -- the static form of Horovod's runtime mismatch stall."""
    primitive: str
    path: str
    collectives: Tuple[str, ...]


def _branch_collectives(jaxpr: Jaxpr) -> Tuple[str, ...]:
    names = []

    def walk(j: Jaxpr) -> None:
        for eqn in j.eqns:
            if eqn.primitive.name in COLLECTIVE_PRIMS:
                names.append(eqn.primitive.name)
            for _, sub in _param_jaxprs(eqn):
                walk(sub)

    walk(jaxpr)
    return tuple(names)


def find_rank_dependent_branches(closed: ClosedJaxpr) -> List[DesyncRecord]:
    """Propagate axis_index taint through the jaxpr and flag ``cond`` /
    ``while`` equations with a tainted predicate guarding a collective.

    Taint is value-level: an ``axis_index`` output taints every value
    computed from it.  Feeding a tainted value as DATA into a collective
    is fine (rank masks, arena slicing); only divergent CONTROL around a
    collective is a desync hazard, so ``cond`` branches and ``while``
    bodies are what get checked.
    """
    records: List[DesyncRecord] = []

    def read(env: Dict[Var, bool], v) -> bool:
        return False if isinstance(v, Literal) else env.get(v, False)

    def walk(jaxpr: Jaxpr, in_taints: Sequence[bool], path: str
             ) -> List[bool]:
        env: Dict[Var, bool] = {}
        for var, t in zip(jaxpr.invars, in_taints):
            env[var] = bool(t)
        for var in jaxpr.constvars:
            env[var] = False

        for i, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            in_t = [read(env, v) for v in eqn.invars]
            here = f"{path}/eqn{i}:{name}"

            if name in TAINT_SOURCES:
                out_t = [True] * len(eqn.outvars)
            elif name == "cond":
                pred_t = in_t[0]
                branches = [b.jaxpr for b in eqn.params["branches"]]
                if pred_t:
                    guarded = tuple(n for b in branches
                                    for n in _branch_collectives(b))
                    if guarded:
                        records.append(DesyncRecord("cond", here, guarded))
                outs = [walk(b, in_t[1:], f"{here}.branch{k}")
                        for k, b in enumerate(branches)]
                out_t = [pred_t or any(o[j] for o in outs)
                         for j in range(len(eqn.outvars))]
            elif name == "while":
                nc, nb = (eqn.params["cond_nconsts"],
                          eqn.params["body_nconsts"])
                cond_j = eqn.params["cond_jaxpr"].jaxpr
                body_j = eqn.params["body_jaxpr"].jaxpr
                carry_t = list(in_t[nc + nb:])
                # One extra pass so taint the body introduces into the
                # carry reaches the cond check (fixpoint for this depth-1
                # lattice: a second pass cannot add taint a first+rerun
                # did not).
                for _ in range(2):
                    body_out = walk(body_j, in_t[nc:nc + nb] + carry_t,
                                    f"{here}.body")
                    carry_t = [a or b for a, b in zip(carry_t, body_out)]
                cond_out = walk(cond_j, in_t[:nc] + carry_t, f"{here}.cond")
                if any(cond_out) and contains_collective(body_j):
                    records.append(DesyncRecord(
                        "while", here, _branch_collectives(body_j)))
                out_t = carry_t
            elif name == "scan":
                nc, ncar = eqn.params["num_consts"], eqn.params["num_carry"]
                body = eqn.params["jaxpr"].jaxpr
                carry_t = list(in_t[nc:nc + ncar])
                xs_t = in_t[nc + ncar:]
                for _ in range(2):
                    outs = walk(body, in_t[:nc] + carry_t + xs_t,
                                f"{here}.body")
                    carry_t = [a or b
                               for a, b in zip(carry_t, outs[:ncar])]
                out_t = carry_t + outs[ncar:]
            else:
                subs = _param_jaxprs(eqn)
                if subs and len(subs[0][1].invars) == len(eqn.invars):
                    # pjit / shard_map / remat / custom_*_call: operands
                    # map positionally onto the inner jaxpr.
                    outs = walk(subs[0][1], in_t, f"{here}.{subs[0][0]}")
                    out_t = (outs + [any(in_t)] *
                             (len(eqn.outvars) - len(outs)))[
                                 :len(eqn.outvars)]
                else:
                    # Element-wise default: any tainted input taints every
                    # output.  Conservative but exact enough -- false
                    # positives only matter at cond/while predicates.
                    out_t = [any(in_t)] * len(eqn.outvars)

            for var, t in zip(eqn.outvars, out_t):
                if isinstance(var, Var):
                    env[var] = t
        return [read(env, v) for v in jaxpr.outvars]

    j = closed.jaxpr
    walk(j, [False] * len(j.invars), "")
    return records


# -- donation aval matching -------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DonationRecord:
    """A donated input leaf whose (shape, dtype) matches NO remaining
    output: the donated buffer cannot alias any result, so the caller's
    array is consumed without a successor -- reading it after the step
    (the usual ``params, opt_state, loss = step(params, ...)`` contract
    relies on every donated leaf having a same-aval output) is a
    use-after-free."""
    argnum: int
    leaf_index: int
    shape: Tuple[int, ...]
    dtype: str


def check_donation(closed: ClosedJaxpr, args: Sequence[Any],
                   donate_argnums: Sequence[int]) -> List[DonationRecord]:
    """Multiset-match donated input leaf avals against output avals.

    Mirrors XLA's aliasing rule: a donated buffer can only be reused by
    an output of identical shape+dtype, and each output absorbs at most
    one donation.  Non-donated inputs are not considered (they never
    donate), so spare outputs remain available for donated leaves.
    """
    flat_counts = [len(jax.tree.leaves(a)) for a in args]
    offsets = np.cumsum([0] + flat_counts)
    in_avals = list(closed.in_avals)
    out_pool: Dict[Tuple[Tuple[int, ...], str], int] = {}
    for aval in closed.out_avals:
        key = (tuple(aval.shape), str(np.dtype(aval.dtype)))
        out_pool[key] = out_pool.get(key, 0) + 1

    records = []
    for argnum in donate_argnums:
        if argnum >= len(flat_counts):
            continue
        for li, aval in enumerate(
                in_avals[offsets[argnum]:offsets[argnum + 1]]):
            key = (tuple(aval.shape), str(np.dtype(aval.dtype)))
            if out_pool.get(key, 0) > 0:
                out_pool[key] -= 1
            else:
                records.append(DonationRecord(
                    argnum=argnum, leaf_index=li,
                    shape=tuple(aval.shape), dtype=key[1]))
    return records
