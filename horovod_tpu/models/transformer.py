"""Transformer model family: Llama-style decoder LM and BERT encoder.

Workload parity: BASELINE.json names "BERT-Large pretrain (Adasum + fp16
grad compression)" and "Llama-3 8B LoRA fine-tune (large bf16 allreduce,
tensor-fusion stress)" as target configs.  The reference framework itself is
model-agnostic (it ships examples, not model code), so these are built
TPU-first rather than ported: bfloat16 activations with float32 parameters,
head/FFN dims that tile the 128-lane MXU, fused attention via the Pallas
FlashAttention kernels in ``horovod_tpu.ops.attention``, and static shapes
throughout so XLA can schedule everything onto the MXU.

LoRA (Hu et al., arXiv:2106.09685) is built into the projection layers
(``DenseGeneral`` here) rather than monkey-patched: pass ``lora_rank > 0``
and every attention/MLP projection gains a rank-``r`` adapter pair.
``lora_mask`` produces the optax mask that freezes base weights.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from horovod_tpu.ops.attention import flash_attention

Dtype = Any


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def quantize_int8(w, axis: int = 0):
    """Per-channel symmetric int8 quantization of a 2D kernel.

    ``axis`` is the reduction axis (scales live on the OTHER axis, one per
    output channel for ``axis=0``).  Returns ``{"q": int8, "scale": f32}``
    with ``w ~= q * scale``.
    """
    w32 = jnp.asarray(w, jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(w32), axis=axis) / 127.0, 1e-12)
    q = jnp.round(w32 / jnp.expand_dims(scale, axis)).astype(jnp.int8)
    return {"q": q, "scale": scale}


def _q8_init(inner):
    """Param init producing an int8-quantized kernel pytree (flax params
    can be arbitrary pytrees): sample the f32 init, quantize per output
    channel.  Random-init benchmarking only -- trained checkpoints convert
    via :func:`quantize_frozen_base`."""
    def init(key, shape):
        return quantize_int8(inner(key, shape, jnp.float32))
    return init


class Dense(nn.Module):
    """Linear layer with optional fused LoRA adapter.

    Base kernel is float32 (master weights), compute in ``dtype``.  With
    ``lora_rank > 0`` adds ``x @ A @ B * (alpha/r)``; A is Gaussian, B is
    zero-init so the adapter starts as identity (standard LoRA init).

    ``base_dtype="int8"`` stores the FROZEN base kernel as int8 with one
    f32 scale per output channel (a single pytree param ``kernel_q8``):
    ``y = (x @ q) * scale`` -- XLA fuses the int8->bf16 convert into the
    matmul operand load, so the bf16 kernel is never materialized in HBM.
    This quarters base-weight HBM vs f32 master weights, which is what
    lets Llama-3 8B LoRA fit a single 16 GB chip: LoRA training needs no
    base grads or master weights, so the base can live at int8 while the
    adapters keep full precision.
    """

    features: int
    use_bias: bool = False
    dtype: Dtype = jnp.bfloat16
    lora_rank: int = 0
    lora_alpha: float = 16.0
    kernel_init: Any = nn.initializers.lecun_normal()
    base_dtype: Optional[str] = None  # None (f32 master) or "int8"

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        if self.base_dtype == "int8":
            p = self.param("kernel_q8", _q8_init(self.kernel_init),
                           (in_features, self.features))
            y = ((x.astype(self.dtype) @ p["q"].astype(self.dtype))
                 * p["scale"].astype(self.dtype))
        elif self.base_dtype is None:
            kernel = self.param("kernel", self.kernel_init,
                                (in_features, self.features), jnp.float32)
            y = x.astype(self.dtype) @ kernel.astype(self.dtype)
        else:
            raise ValueError(f"unsupported base_dtype {self.base_dtype!r}")
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(self.dtype)
        if self.lora_rank > 0:
            a = self.param("lora_a",
                           nn.initializers.normal(stddev=0.02),
                           (in_features, self.lora_rank), jnp.float32)
            b = self.param("lora_b", nn.initializers.zeros,
                           (self.lora_rank, self.features), jnp.float32)
            scale = jnp.asarray(self.lora_alpha / self.lora_rank, self.dtype)
            y = y + (x.astype(self.dtype) @ a.astype(self.dtype)
                     @ b.astype(self.dtype)) * scale
        return y


class RMSNorm(nn.Module):
    epsilon: float = 1e-5
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones,
                           (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.epsilon)
        return (norm * scale).astype(self.dtype)


def rotary_embedding(x, positions, theta: float = 500000.0):
    """Apply RoPE. x: (b, h, t, d) with even d; positions: (b, t)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[:, None, :, None].astype(jnp.float32) * freqs
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class CausalSelfAttention(nn.Module):
    """GQA causal attention with RoPE, fused via Pallas flash attention."""

    num_heads: int
    num_kv_heads: int
    head_dim: int
    dtype: Dtype = jnp.bfloat16
    rope_theta: float = 500000.0
    lora_rank: int = 0
    base_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        dense = partial(Dense, dtype=self.dtype, lora_rank=self.lora_rank,
                        base_dtype=self.base_dtype)
        b, t, _ = x.shape
        q = dense(self.num_heads * self.head_dim, name="wq")(x)
        k = dense(self.num_kv_heads * self.head_dim, name="wk")(x)
        v = dense(self.num_kv_heads * self.head_dim, name="wv")(x)
        q = q.reshape(b, t, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, self.num_kv_heads,
                      self.head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, self.num_kv_heads,
                      self.head_dim).transpose(0, 2, 1, 3)
        q = rotary_embedding(q, positions, self.rope_theta)
        k = rotary_embedding(k, positions, self.rope_theta)
        o = flash_attention(q, k, v, causal=True, segment_ids=segment_ids)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, -1)
        return dense(x.shape[-1], name="wo")(o)


class SwiGLU(nn.Module):
    hidden: int
    dtype: Dtype = jnp.bfloat16
    lora_rank: int = 0
    base_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        dense = partial(Dense, dtype=self.dtype, lora_rank=self.lora_rank,
                        base_dtype=self.base_dtype)
        gate = dense(self.hidden, name="w_gate")(x)
        up = dense(self.hidden, name="w_up")(x)
        return dense(x.shape[-1], name="w_down")(nn.silu(gate) * up)


class DecoderBlock(nn.Module):
    num_heads: int
    num_kv_heads: int
    head_dim: int
    ffn_hidden: int
    dtype: Dtype = jnp.bfloat16
    rope_theta: float = 500000.0
    lora_rank: int = 0
    base_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        h = RMSNorm(dtype=self.dtype, name="attn_norm")(x)
        x = x + CausalSelfAttention(
            self.num_heads, self.num_kv_heads, self.head_dim,
            dtype=self.dtype, rope_theta=self.rope_theta,
            lora_rank=self.lora_rank, base_dtype=self.base_dtype,
            name="attn")(h, positions, segment_ids)
        h = RMSNorm(dtype=self.dtype, name="mlp_norm")(x)
        x = x + SwiGLU(self.ffn_hidden, dtype=self.dtype,
                       lora_rank=self.lora_rank, base_dtype=self.base_dtype,
                       name="mlp")(h)
        return x


# ---------------------------------------------------------------------------
# Llama-style decoder LM
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    d_model: int = 4096
    ffn_hidden: int = 14336
    rope_theta: float = 500000.0
    max_seq_len: int = 8192


# Llama-3 8B architecture (public config: 32 layers, 32 heads / 8 KV heads,
# d_model 4096, FFN 14336, vocab 128256, rope theta 5e5).
LLAMA3_8B = LlamaConfig()
# ~0.9B single-chip variant; same shape family, used for the
# comfortable single-chip LoRA benchmark.  (The full 8B also runs on a
# 16 GB chip via base_dtype="int8" -- see docs/benchmarks.md.)
LLAMA_1B = LlamaConfig(vocab_size=32000, num_layers=16, num_heads=16,
                       num_kv_heads=8, head_dim=128, d_model=2048,
                       ffn_hidden=5632, max_seq_len=4096)
LLAMA_TINY = LlamaConfig(vocab_size=256, num_layers=2, num_heads=4,
                         num_kv_heads=2, head_dim=16, d_model=64,
                         ffn_hidden=128, max_seq_len=128)
# Serving-test variant: full-MHA head counts (8 query AND 8 kv heads) so a
# tensor-parallel decode step divides evenly across the 8-device virtual
# mesh (kv heads shard over tp; LLAMA_TINY's 2 kv heads cap tp at 2).
LLAMA_SERVE = LlamaConfig(vocab_size=256, num_layers=2, num_heads=8,
                          num_kv_heads=8, head_dim=16, d_model=64,
                          ffn_hidden=128, max_seq_len=128)


class LlamaLM(nn.Module):
    """Decoder-only LM (Llama-3 family architecture).

    ``remat=True`` rematerializes each decoder block in the backward pass
    (``jax.checkpoint`` via ``nn.remat``): activation HBM drops from
    O(layers x tokens x d) to O(tokens x d) at ~1.3x FLOPs -- the
    standard TPU trade for long sequences / big batches.
    """

    config: LlamaConfig
    dtype: Dtype = jnp.bfloat16
    lora_rank: int = 0
    remat: bool = False
    base_dtype: Optional[str] = None  # "int8": frozen base at int8+scales

    @nn.compact
    def __call__(self, tokens, positions=None, *, segment_ids=None):
        cfg = self.config
        if positions is None:
            if segment_ids is not None:
                # Packed sequences: RoPE positions restart at each
                # segment boundary (position = offset WITHIN the packed
                # sequence, not within the buffer).
                idx = jnp.broadcast_to(jnp.arange(tokens.shape[1]),
                                       tokens.shape)
                first = jnp.concatenate(
                    [jnp.ones_like(segment_ids[:, :1], bool),
                     segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1)
                seg_start = jax.lax.cummax(
                    jnp.where(first, idx, 0), axis=1)
                positions = idx - seg_start
            else:
                positions = jnp.broadcast_to(
                    jnp.arange(tokens.shape[1]), tokens.shape)
        if self.base_dtype == "int8":
            # Tied embedding at int8 (one f32 scale per d_model channel):
            # the gather dequantizes per row; the readout folds the scale
            # into x so the [V, D] int8 table is the only big operand.
            p = self.param("tok_embed_q8",
                           _q8_init(nn.initializers.normal(stddev=0.02)),
                           (cfg.vocab_size, cfg.d_model))
            x = (p["q"][tokens].astype(self.dtype)
                 * p["scale"].astype(self.dtype))
            # Fold the channel scales into h; the big [V, D] operand stays
            # int8 in HBM (converted per-tile inside the matmul).  The
            # matmul runs in compute dtype (f32 accumulation on the MXU),
            # cast up for the softmax.
            readout = lambda h: (  # noqa: E731
                (h * p["scale"]).astype(self.dtype)
                @ p["q"].astype(self.dtype).T).astype(jnp.float32)
        else:
            emb = self.param("tok_embed",
                             nn.initializers.normal(stddev=0.02),
                             (cfg.vocab_size, cfg.d_model), jnp.float32)
            x = emb[tokens].astype(self.dtype)
            # NB the f32 spelling does NOT cost MXU rate: JAX's default
            # TPU matmul precision executes f32 dots with bf16 operands +
            # f32 accumulation, so this already runs at full MXU speed
            # (measured round 5: an explicit bf16-operand rewrite changed
            # neither step time nor the printed losses).
            readout = lambda h: h @ emb.T  # noqa: E731
        block_cls = nn.remat(DecoderBlock) if self.remat else DecoderBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
                          cfg.ffn_hidden, dtype=self.dtype,
                          rope_theta=cfg.rope_theta,
                          lora_rank=self.lora_rank,
                          base_dtype=self.base_dtype,
                          name=f"layer_{i}")(x, positions, segment_ids)
        x = RMSNorm(dtype=self.dtype, name="final_norm")(x)
        # Tied-embedding readout in f32 for stable softmax.
        return readout(x.astype(jnp.float32))


# ---------------------------------------------------------------------------
# BERT encoder
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    num_layers: int = 24
    num_heads: int = 16
    d_model: int = 1024
    ffn_hidden: int = 4096
    max_seq_len: int = 512
    type_vocab_size: int = 2


BERT_LARGE = BertConfig()
BERT_BASE = BertConfig(num_layers=12, num_heads=12, d_model=768,
                       ffn_hidden=3072)
BERT_TINY = BertConfig(vocab_size=256, num_layers=2, num_heads=4,
                       d_model=64, ffn_hidden=128, max_seq_len=128)


class EncoderBlock(nn.Module):
    num_heads: int
    ffn_hidden: int
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, segment_ids=None):
        b, t, d = x.shape
        head_dim = d // self.num_heads
        dense = partial(Dense, dtype=self.dtype, use_bias=True)
        ln = partial(nn.LayerNorm, dtype=self.dtype, epsilon=1e-12,
                     param_dtype=jnp.float32)
        # Pre-LN (stability at scale; BERT's published post-LN converges
        # identically with warmup but pre-LN is the TPU-era default).
        h = ln(name="attn_norm")(x)
        q = dense(d, name="wq")(h).reshape(b, t, self.num_heads, head_dim)
        k = dense(d, name="wk")(h).reshape(b, t, self.num_heads, head_dim)
        v = dense(d, name="wv")(h).reshape(b, t, self.num_heads, head_dim)
        o = flash_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                            v.transpose(0, 2, 1, 3), causal=False,
                            segment_ids=segment_ids)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, d)
        x = x + dense(d, name="wo")(o)
        h = ln(name="mlp_norm")(x)
        h = dense(self.ffn_hidden, name="w_in")(h)
        h = nn.gelu(h, approximate=True)
        return x + dense(d, name="w_out")(h)


class Bert(nn.Module):
    """BERT encoder with MLM + NSP heads (pretraining objective).

    ``remat=True``: see :class:`LlamaLM` -- per-block rematerialization
    for long-sequence / large-batch training.
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, token_types=None, *, pack_segment_ids=None):
        # NB ``token_types`` IS what the BERT paper calls "segment ids"
        # (the sentence-A/B embedding); ``pack_segment_ids`` is the
        # attention-isolation input (packing / padding), keyword-only so
        # the two can never be confused positionally.
        cfg = self.config
        b, t = tokens.shape
        if token_types is None:
            token_types = jnp.zeros_like(tokens)
        emb = self.param("tok_embed", nn.initializers.normal(stddev=0.02),
                         (cfg.vocab_size, cfg.d_model), jnp.float32)
        pos = self.param("pos_embed", nn.initializers.normal(stddev=0.02),
                         (cfg.max_seq_len, cfg.d_model), jnp.float32)
        typ = self.param("type_embed", nn.initializers.normal(stddev=0.02),
                         (cfg.type_vocab_size, cfg.d_model), jnp.float32)
        x = (emb[tokens] + pos[None, :t] + typ[token_types]).astype(self.dtype)
        x = nn.LayerNorm(dtype=self.dtype, epsilon=1e-12,
                         param_dtype=jnp.float32, name="embed_norm")(x)
        block_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg.num_heads, cfg.ffn_hidden,
                          dtype=self.dtype, name=f"layer_{i}")(
                              x, pack_segment_ids)
        x = nn.LayerNorm(dtype=self.dtype, epsilon=1e-12,
                         param_dtype=jnp.float32, name="final_norm")(x)
        # MLM head: transform + tied-embedding readout (f32 softmax input).
        h = Dense(cfg.d_model, use_bias=True, dtype=self.dtype,
                  name="mlm_transform")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.LayerNorm(dtype=self.dtype, epsilon=1e-12,
                         param_dtype=jnp.float32, name="mlm_norm")(h)
        # f32 spelling, full MXU speed: JAX's default TPU matmul
        # precision runs this with bf16 operands + f32 accumulation (see
        # the LlamaLM readout note; verified on-chip round 5).
        mlm_logits = h.astype(jnp.float32) @ emb.T
        # NSP head on [CLS] (position 0).
        cls = jnp.tanh(Dense(cfg.d_model, use_bias=True, dtype=self.dtype,
                             name="pooler")(x[:, 0]))
        nsp_logits = Dense(2, use_bias=True, dtype=self.dtype,
                           name="nsp")(cls).astype(jnp.float32)
        return mlm_logits, nsp_logits


def bert_tp_apply(params, config: BertConfig, tokens, token_types=None, *,
                  axis: str = "model", dtype: Dtype = jnp.float32):
    """Tensor-parallel :class:`Bert` forward over LOCAL param shards.

    The Megatron split of the encoder, as an SPMD function for use inside
    ``jax.shard_map`` over a ``build_3d_mesh`` ``model`` axis: per block,
    ``wq``/``wk``/``wv``/``w_in`` are column shards (heads and the FFN
    hidden split over tp, biases split with them -- the
    ``parallel.tp_param_specs`` layout), ``wo``/``w_out`` row shards
    closing in one psum each, and everything else (embeddings,
    layernorms, the MLM/NSP heads) replicated.  Exactly two allreduces
    per block forward, both of the full ``(b, t, d_model)`` activation;
    numerics match ``Bert.apply`` on the unsharded tree to float
    tolerance.

    ``params`` is the ``Bert.init`` variables dict (``{"params": ...}``)
    as sliced by the spec tree; requires ``num_heads`` and ``ffn_hidden``
    divisible by the tp extent.
    """
    from ..parallel.tp import copy_to_tp, row_parallel

    cfg = config
    p = params["params"]
    b, t = tokens.shape
    if token_types is None:
        token_types = jnp.zeros_like(tokens)

    def ln(x, node):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mu) * jax.lax.rsqrt(var + 1e-12)
        return (y * node["scale"] + node["bias"]).astype(dtype)

    def dense(x, node):
        return x @ node["kernel"].astype(dtype) + node["bias"].astype(dtype)

    emb = p["tok_embed"]
    x = (emb[tokens] + p["pos_embed"][None, :t]
         + p["type_embed"][token_types]).astype(dtype)
    x = ln(x, p["embed_norm"])
    for i in range(cfg.num_layers):
        blk = p[f"layer_{i}"]
        # copy_to_tp is Megatron's "f": identity forward, one backward
        # psum merging the per-rank partial input cotangents of the
        # column layers it feeds (q/k/v here, w_in below).
        h = copy_to_tp(ln(x, blk["attn_norm"]), axis=axis)
        # Local head count comes off the sliced kernel, not the mesh.
        d_local = blk["wq"]["kernel"].shape[-1]
        head_dim = cfg.d_model // cfg.num_heads
        heads_local = d_local // head_dim
        q = dense(h, blk["wq"]).reshape(b, t, heads_local, head_dim)
        k = dense(h, blk["wk"]).reshape(b, t, heads_local, head_dim)
        v = dense(h, blk["wv"]).reshape(b, t, heads_local, head_dim)
        o = flash_attention(q.transpose(0, 2, 1, 3),
                            k.transpose(0, 2, 1, 3),
                            v.transpose(0, 2, 1, 3), causal=False)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, d_local)
        x = x + row_parallel(o, blk["wo"]["kernel"].astype(dtype),
                             blk["wo"]["bias"].astype(dtype), axis=axis)
        h = copy_to_tp(ln(x, blk["mlp_norm"]), axis=axis)
        h = nn.gelu(dense(h, blk["w_in"]), approximate=True)
        x = x + row_parallel(h, blk["w_out"]["kernel"].astype(dtype),
                             blk["w_out"]["bias"].astype(dtype), axis=axis)
    x = ln(x, p["final_norm"])
    h = nn.gelu(dense(x, p["mlm_transform"]), approximate=True)
    h = ln(h, p["mlm_norm"])
    mlm_logits = h.astype(jnp.float32) @ emb.T
    cls = jnp.tanh(dense(x[:, 0], p["pooler"]))
    nsp_logits = dense(cls, p["nsp"]).astype(jnp.float32)
    return mlm_logits, nsp_logits


# ---------------------------------------------------------------------------
# LoRA utilities
# ---------------------------------------------------------------------------


def lora_mask(params) -> Any:
    """Pytree of bools: True only on ``lora_a``/``lora_b`` leaves.

    Use with ``optax.multi_transform`` (adapters -> real optimizer, base
    weights -> ``optax.set_to_zero``) to train only the adapters -- the
    Llama-LoRA workload in BASELINE.json.  Matching by param name mirrors
    how torch LoRA wrappers select ``lora_`` attributes.
    """
    def is_lora(path) -> bool:
        return any(getattr(k, "key", None) in ("lora_a", "lora_b")
                   for k in path)

    return jax.tree_util.tree_map_with_path(
        lambda p, _: is_lora(p), params)


def split_frozen(params, mask=None):
    """Split a params pytree into ``(trainable, frozen)`` by LoRA mask.

    The trainable tree carries ONLY the adapter leaves, so gradients, the
    fused allreduce, and optimizer state never touch the (possibly
    multi-GB) frozen base -- pass both trees to a step built with
    ``make_train_step(..., with_frozen=True)`` and recombine inside the
    loss with :func:`merge_frozen`.
    """
    from flax import traverse_util

    mask = lora_mask(params) if mask is None else mask
    flat_p = traverse_util.flatten_dict(params)
    flat_m = traverse_util.flatten_dict(mask)
    train = {k: v for k, v in flat_p.items() if flat_m[k]}
    frozen = {k: v for k, v in flat_p.items() if not flat_m[k]}
    return (traverse_util.unflatten_dict(train),
            traverse_util.unflatten_dict(frozen))


def merge_frozen(trainable, frozen):
    """Inverse of :func:`split_frozen` (valid inside jit: dict surgery
    only)."""
    from flax import traverse_util

    flat = dict(traverse_util.flatten_dict(frozen))
    flat.update(traverse_util.flatten_dict(trainable))
    return traverse_util.unflatten_dict(flat)


def quantize_frozen_base(params):
    """Convert a trained f32-base LoRA params tree to the ``base_dtype=
    "int8"`` layout: every non-LoRA Dense ``kernel`` becomes ``kernel_q8 =
    {"q": int8, "scale": f32/channel}``, ``tok_embed`` becomes
    ``tok_embed_q8``.  Biases, norm scales, and the LoRA adapters stay
    full precision.  The result loads into a model built with
    ``base_dtype="int8"``."""

    def walk(tree):
        if not isinstance(tree, dict):
            return tree
        out = {}
        for k, v in tree.items():
            if k == "kernel":
                out["kernel_q8"] = quantize_int8(v)
            elif k == "tok_embed":
                out["tok_embed_q8"] = quantize_int8(v)
            else:
                out[k] = walk(v)
        return out

    return walk(params)


def merge_lora(params, alpha: float = 16.0):
    """Fold trained adapters into base kernels (inference export).

    Returns a new params pytree where every Dense holding ``lora_a/b`` has
    ``kernel += A @ B * alpha/r`` and the adapter leaves removed.  ``alpha``
    must match the ``lora_alpha`` the model was built with (flax params
    don't carry module attributes, so it can't be recovered from the tree).
    """

    def merge(tree):
        if not isinstance(tree, dict):
            return tree
        if "lora_a" in tree and "kernel" in tree:
            r = tree["lora_a"].shape[1]
            delta = (tree["lora_a"] @ tree["lora_b"]) * (alpha / r)
            out = {k: v for k, v in tree.items()
                   if k not in ("lora_a", "lora_b")}
            out["kernel"] = tree["kernel"] + delta
            return out
        return {k: merge(v) for k, v in tree.items()}

    return merge(params)
