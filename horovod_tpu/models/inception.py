"""Inception V3 in flax.linen, bf16-first for the MXU.

Benchmark workload parity: Inception V3 is one of the reference's three
headline scaling workloads (~90% of linear at 128 accelerators --
``README.rst`` perf chart / ``docs/benchmarks.rst`` via
``tf_cnn_benchmarks``; SURVEY.md section 6).  Architecture follows the
original (Szegedy et al. 2015, "Rethinking the Inception Architecture"):
299x299 input, factorized 7x7 branches, grid reductions to 8x8x2048.

TPU-first choices: NHWC layout, bfloat16 compute with float32
parameters/statistics, BN after every conv (the "BasicConv2d" unit), and
concatenations along the channel (lane) dimension, which XLA fuses into
the surrounding convolutions' output writes.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ConvBN(nn.Module):
    """Conv + BN + ReLU (the Inception "BasicConv2d" unit)."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        x = self.conv(self.features, self.kernel, self.strides,
                      padding=self.padding)(x)
        x = self.norm()(x)
        return nn.relu(x)


def _pool(x, window, strides, kind="max", padding="SAME"):
    if kind == "max":
        return nn.max_pool(x, (window, window), (strides, strides), padding)
    return nn.avg_pool(x, (window, window), (strides, strides), padding)


class InceptionA(nn.Module):
    pool_features: int
    cbn: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.cbn(64, (1, 1))(x)
        b5 = self.cbn(48, (1, 1))(x)
        b5 = self.cbn(64, (5, 5))(b5)
        b3 = self.cbn(64, (1, 1))(x)
        b3 = self.cbn(96, (3, 3))(b3)
        b3 = self.cbn(96, (3, 3))(b3)
        bp = _pool(x, 3, 1, "avg")
        bp = self.cbn(self.pool_features, (1, 1))(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """35x35 -> 17x17 grid reduction."""

    cbn: ModuleDef

    @nn.compact
    def __call__(self, x):
        b3 = self.cbn(384, (3, 3), (2, 2), padding="VALID")(x)
        bd = self.cbn(64, (1, 1))(x)
        bd = self.cbn(96, (3, 3))(bd)
        bd = self.cbn(96, (3, 3), (2, 2), padding="VALID")(bd)
        bp = _pool(x, 3, 2, "max", padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7x7 branches at 17x17."""

    channels_7x7: int
    cbn: ModuleDef

    @nn.compact
    def __call__(self, x):
        c7 = self.channels_7x7
        b1 = self.cbn(192, (1, 1))(x)
        b7 = self.cbn(c7, (1, 1))(x)
        b7 = self.cbn(c7, (1, 7))(b7)
        b7 = self.cbn(192, (7, 1))(b7)
        bd = self.cbn(c7, (1, 1))(x)
        bd = self.cbn(c7, (7, 1))(bd)
        bd = self.cbn(c7, (1, 7))(bd)
        bd = self.cbn(c7, (7, 1))(bd)
        bd = self.cbn(192, (1, 7))(bd)
        bp = _pool(x, 3, 1, "avg")
        bp = self.cbn(192, (1, 1))(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """17x17 -> 8x8 grid reduction."""

    cbn: ModuleDef

    @nn.compact
    def __call__(self, x):
        b3 = self.cbn(192, (1, 1))(x)
        b3 = self.cbn(320, (3, 3), (2, 2), padding="VALID")(b3)
        b7 = self.cbn(192, (1, 1))(x)
        b7 = self.cbn(192, (1, 7))(b7)
        b7 = self.cbn(192, (7, 1))(b7)
        b7 = self.cbn(192, (3, 3), (2, 2), padding="VALID")(b7)
        bp = _pool(x, 3, 2, "max", padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Expanded-filter-bank blocks at 8x8 (output 2048 channels)."""

    cbn: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.cbn(320, (1, 1))(x)
        b3 = self.cbn(384, (1, 1))(x)
        b3 = jnp.concatenate([self.cbn(384, (1, 3))(b3),
                              self.cbn(384, (3, 1))(b3)], axis=-1)
        bd = self.cbn(448, (1, 1))(x)
        bd = self.cbn(384, (3, 3))(bd)
        bd = jnp.concatenate([self.cbn(384, (1, 3))(bd),
                              self.cbn(384, (3, 1))(bd)], axis=-1)
        bp = _pool(x, 3, 1, "avg")
        bp = self.cbn(192, (1, 1))(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """Inception V3 classifier (299x299 NHWC input).

    ``aux_logits=True`` adds the training-time auxiliary head on the
    17x17 grid (returned as a second output during training).
    """

    num_classes: int = 1000
    aux_logits: bool = False
    dropout_rate: float = 0.5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-3, dtype=self.dtype)
        cbn = partial(ConvBN, conv=conv, norm=norm)

        x = x.astype(self.dtype)
        # Stem: 299 -> 35x35x192.
        x = cbn(32, (3, 3), (2, 2), padding="VALID")(x)
        x = cbn(32, (3, 3), padding="VALID")(x)
        x = cbn(64, (3, 3))(x)
        x = _pool(x, 3, 2, "max", padding="VALID")
        x = cbn(80, (1, 1), padding="VALID")(x)
        x = cbn(192, (3, 3), padding="VALID")(x)
        x = _pool(x, 3, 2, "max", padding="VALID")
        # 35x35 Inception-A stack -> 288 channels.
        x = InceptionA(32, cbn)(x)
        x = InceptionA(64, cbn)(x)
        x = InceptionA(64, cbn)(x)
        # Reduce to 17x17x768; Inception-C stack.
        x = InceptionB(cbn)(x)
        x = InceptionC(128, cbn)(x)
        x = InceptionC(160, cbn)(x)
        x = InceptionC(160, cbn)(x)
        x = InceptionC(192, cbn)(x)
        aux = None
        if self.aux_logits and train:
            a = _pool(x, 5, 3, "avg", padding="VALID")
            a = cbn(128, (1, 1))(a)
            a = cbn(768, a.shape[1:3], padding="VALID")(a)
            a = a.reshape((a.shape[0], -1))
            aux = nn.Dense(self.num_classes, dtype=self.dtype,
                           name="aux_head")(a).astype(jnp.float32)
        # Reduce to 8x8; Inception-E stack -> 2048 channels.
        x = InceptionD(cbn)(x)
        x = InceptionE(cbn)(x)
        x = InceptionE(cbn)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        x = x.astype(jnp.float32)
        if self.aux_logits and train:
            return x, aux
        return x
