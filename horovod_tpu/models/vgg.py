"""VGG-16/19 in flax.linen, bf16-first.

Benchmark workload parity: VGG-16 is the reference's *comm-bound*
headline workload (~68% of linear at 128 accelerators, and the one where
RDMA vs TCP mattered -- ``docs/benchmarks.rst``, SURVEY.md section 6).
Its ~138M parameters (103M of them in the first FC layer) make the
gradient allreduce the bottleneck, which is exactly what it stresses in
this framework too: one fused bucket sweep moves >500 MB of fp32
gradients per step through the collective layer.

Classic configuration (Simonyan & Zisserman 2014): no batch norm
(``batch_norm=True`` opts into the modern variant), 224x224 NHWC input,
two 4096-wide FC layers -- kept as-is because those giant Dense layers
land on the MXU as single large matmuls.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Channel plan per conv stage; "M" = 2x2 max-pool.
_CFG = {
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    depth: int = 16
    num_classes: int = 1000
    batch_norm: bool = False
    dropout_rate: float = 0.5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for item in _CFG[self.depth]:
            if item == "M":
                x = nn.max_pool(x, (2, 2), (2, 2))
                continue
            x = nn.Conv(item, (3, 3), dtype=self.dtype,
                        use_bias=not self.batch_norm)(x)
            if self.batch_norm:
                x = nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        for _ in range(2):
            x = nn.Dense(4096, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def VGG16(**kw) -> VGG:
    return VGG(depth=16, **kw)


def VGG19(**kw) -> VGG:
    return VGG(depth=19, **kw)
