"""Benchmark/parity model zoo (reference workloads, TPU-first builds)."""

from .lenet import LeNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152,
)
from .inception import InceptionV3  # noqa: F401
from .vgg import VGG, VGG16, VGG19  # noqa: F401
from .transformer import (  # noqa: F401
    BERT_BASE, BERT_LARGE, BERT_TINY, Bert, BertConfig, LLAMA3_8B,
    LLAMA_1B, LLAMA_SERVE, LLAMA_TINY, LlamaConfig, LlamaLM,
    bert_tp_apply, lora_mask,
    merge_frozen,
    merge_lora, quantize_frozen_base, quantize_int8, split_frozen,
)
