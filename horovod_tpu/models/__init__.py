"""Benchmark/parity model zoo (reference workloads, TPU-first builds)."""

from .lenet import LeNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152,
)
