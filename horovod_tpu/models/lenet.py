"""LeNet-5 (MNIST) in flax.linen.

Parity workload: the reference's ``examples/pytorch_mnist.py`` CPU-reference
config (BASELINE.json configs[0]).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LeNet(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train
        x = nn.Conv(6, (5, 5), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120)(x))
        x = nn.relu(nn.Dense(84)(x))
        return nn.Dense(self.num_classes)(x)
