"""ResNet family (v1.5) in flax.linen, bf16-first for the MXU.

Benchmark workload parity: the reference's headline numbers are ResNet
throughput/scaling via ``tf_cnn_benchmarks`` and the synthetic-benchmark
examples (SURVEY.md section 6, BASELINE.json config "ResNet-50 ImageNet").
The model itself is standard ResNet-v1.5 (stride-2 in the 3x3 of the
bottleneck, as in the reference benchmarks); TPU-first choices: NHWC
layout, bfloat16 compute with float32 parameters/statistics, and channel
counts that tile the 128-lane MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..ops import bn as _bn

ModuleDef = Any


def space_to_depth(x, block: int = 2):
    """NHWC space-to-depth: ``[N,H,W,C] -> [N,H/b,W/b,b*b*C]`` with the
    (dy, dx, c) intra-block order the stem-kernel transform assumes."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, h // block, w // block, block * block * c)


def s2d_conv_init_kernel(k7):
    """Transform a standard ``[7,7,C,F]`` stem kernel into the equivalent
    ``[4,4,4C,F]`` space-to-depth kernel.

    The 7x7 stride-2 SAME conv on ``[N,224,224,C]`` equals a 4x4 stride-1
    SAME conv on the 2x2 space-to-depth input: pad the kernel to 8x8 on the
    bottom/right (those taps hit rows the 7-tap window never covers) and
    fold each 2x2 tap block into the channel dim.  This is the MLPerf-style
    TPU stem optimization -- a 3-channel 7x7 conv underutilizes the MXU's
    128 input lanes, while the folded 12-channel 4x4 tiles it 4x better.
    Exactness is verified by ``test_space_to_depth_stem_parity``.
    """
    k8 = jnp.pad(k7, ((0, 1), (0, 1), (0, 0), (0, 0)))
    c, f = k7.shape[2], k7.shape[3]
    k = k8.reshape(4, 2, 4, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
    return k.reshape(4, 4, 4 * c, f)


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    # MLPerf-style stem: fold the input's 2x2 spatial blocks into channels
    # and replace the 7x7/2 conv with the equivalent 4x4/1 (the 3-channel
    # 7x7 wastes the MXU's input lanes).  The ``conv_init`` kernel then has
    # the s2d layout [4,4,4C,F]; ``s2d_conv_init_kernel`` converts standard
    # checkpoints.  Mathematically identical output -- see its docstring.
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        # HOROVOD_PALLAS / HOROVOD_PALLAS_BN routes every BN site through
        # ops.bn.BatchNorm (fused two-pass backward); the module mirrors
        # flax's class name, param names, and batch_stats layout, so the
        # variable tree is identical either way.
        norm_cls = _bn.BatchNorm if _bn.use_pallas_bn() else nn.BatchNorm
        norm = partial(norm_cls, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        if self.space_to_depth:
            x = space_to_depth(x, 2)
            x = conv(self.num_filters, (4, 4), (1, 1), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, strides,
                                   conv=conv, norm=norm, act=nn.relu)(x)
        x = jnp.mean(x, axis=(1, 2))
        # Classifier in f32 for numerically stable softmax.
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3],
                    block_cls=BottleneckBlock)
