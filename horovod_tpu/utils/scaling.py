"""Scaling evidence: HLO collective accounting + analytic efficiency model.

BASELINE.json's north star is >=90% scaling efficiency from 1 to 256
chips (ResNet-50 and BERT-Large data-parallel).  Without pod hardware
that claim cannot be timed, so this module produces the evidence that CAN
be produced mechanically (SURVEY.md section 6, section 7 hard part 5):

1. **Wire accounting from the compiled program.**  The train step is
   compiled for an n-device mesh and the optimized HLO is parsed for
   collectives: op counts and payload bytes.  Two invariants are
   checkable per model: the per-chip collective bytes match the gradient
   (+ BN-stat) payload the fusion planner predicts, and they are
   INDEPENDENT of n -- the defining property of allreduce data
   parallelism (bytes/chip ~ 2B(n-1)/n -> 2B).  A fusion regression
   (e.g. a gradient leaf escaping the buckets, a stats tree gathering
   instead of reducing) changes these numbers and fails the assertion.
2. **Overlap-capability accounting from the emitted (pre-optimization)
   StableHLO.**  Gradient buckets are emitted as SEPARATE psums whose
   operands depend only on their own slice of the backward pass, which
   is what lets a latency-hiding scheduler start bucket k's allreduce
   while bucket k+1's gradients are still being computed.  The CPU
   backend used for virtual meshes has no latency-hiding scheduler (it
   even re-combines the buckets), so the HLO *schedule* itself is not
   checkable off-TPU; what is checked: the emitted program has the
   planned bucket structure and the compiled module donates the
   parameter buffers (in-place update, no double-buffering stall).
3. **Analytic 1->256 projection.**  Measured single-chip step time +
   measured wire bytes + published link bandwidths -> predicted
   efficiency curve, reported for both the no-overlap (worst-case) and
   full-overlap (best-case) bounds.  All constants and formulas are
   explicit below; change them, the curve moves -- there is no hidden
   calibration.

Reference anchor: the upstream benchmark recipe measures images/s at
1..256 GPUs (SURVEY.md section 6); its scaling efficiency rests on the
same two quantities -- per-rank wire bytes (NCCL ring allreduce moves
2B(n-1)/n) and backward/comm overlap -- that this module accounts for.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# HLO parsing.
# ---------------------------------------------------------------------------

_DT_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "i64": 8, "i32": 4, "i16": 2, "i8": 1,
    "i1": 1,
}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                "collective-permute")

# `f32[128,4]{1,0} all-reduce(...)` or tuple-result variadic forms; -start
# counts once, -done is skipped.
_HLO_OP_RE = re.compile(
    r"=\s*(\([^)]*\)|[a-z0-9_]+\[[\d,]*\](?:\{[^}]*\})?)\s+"
    r"(" + "|".join(_COLLECTIVES) + r")(-start|-done)?\(")

_SHAPE_RE = re.compile(r"([a-z0-9_]+)\[([\d,]*)\]")


def _shape_bytes(shape_text: str) -> int:
    total = 0
    for m in _SHAPE_RE.finditer(shape_text):
        dt, dims = m.group(1), m.group(2)
        if dt not in _DT_BYTES:
            continue
        size = 1
        for d in dims.split(","):
            if d:
                size *= int(d)
        total += size * _DT_BYTES[dt]
    return total


@dataclasses.dataclass
class CollectiveStats:
    """Per-op-kind (count, payload bytes) from one HLO module."""
    counts: Dict[str, int]
    bytes: Dict[str, int]

    @property
    def total_bytes(self) -> int:
        return sum(self.bytes.values())

    @property
    def total_count(self) -> int:
        return sum(self.counts.values())


def optimized_collective_stats(compiled_text: str) -> CollectiveStats:
    """Count collectives and payload bytes in optimized HLO
    (``jax.jit(f).lower(...).compile().as_text()``).

    Payload bytes are the RESULT shape bytes (for an allreduce the payload
    equals the result; variadic combined all-reduces report the tuple
    total).  ``-done`` halves of async pairs are skipped so a started
    collective counts once.
    """
    counts: Dict[str, int] = {}
    bytes_: Dict[str, int] = {}
    for m in _HLO_OP_RE.finditer(compiled_text):
        shape, op, phase = m.group(1), m.group(2), m.group(3)
        if phase == "-done":
            continue
        counts[op] = counts.get(op, 0) + 1
        bytes_[op] = bytes_.get(op, 0) + _shape_bytes(shape)
    return CollectiveStats(counts=counts, bytes=bytes_)


_STABLE_RE = re.compile(
    r'"stablehlo\.(all_reduce|all_gather|reduce_scatter|all_to_all|'
    r'collective_permute)".*?\)\s*->\s*(\([^)]*\)|tensor<[^>]*>)',
    re.DOTALL)

_TENSOR_RE = re.compile(r"tensor<([^>]*)>")


def _tensor_bytes(t: str) -> int:
    parts = t.split("x")
    dt = parts[-1]
    if dt not in _DT_BYTES:
        return 0
    size = 1
    for d in parts[:-1]:
        size *= int(d)
    return size * _DT_BYTES[dt]


def emitted_collective_stats(lowered_text: str) -> CollectiveStats:
    """Count the collectives OUR trace emitted (pre-XLA-optimization
    StableHLO, ``jax.jit(f).lower(...).as_text()``): one ``all_reduce``
    per fusion bucket, per BN-stat leaf, per loss scalar.  This is the
    structure the latency-hiding scheduler sees; XLA's combiner may later
    merge compatible ops (backend- and threshold-dependent)."""
    counts: Dict[str, int] = {}
    bytes_: Dict[str, int] = {}
    for m in _STABLE_RE.finditer(lowered_text):
        op = m.group(1).replace("_", "-")
        counts[op] = counts.get(op, 0) + 1
        bytes_[op] = bytes_.get(op, 0) + sum(
            _tensor_bytes(t.group(1))
            for t in _TENSOR_RE.finditer(m.group(2)))
    return CollectiveStats(counts=counts, bytes=bytes_)


def has_buffer_donation(compiled_text: str) -> bool:
    """True when the compiled module aliases inputs to outputs (donated
    params/opt-state update in place -- no double-buffered HBM copy)."""
    return "input_output_alias" in compiled_text


# ---------------------------------------------------------------------------
# Analytic efficiency model.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Published per-chip numbers (Google Cloud TPU spec sheets) plus the
    one ASSUMED constant (per-chip DCN share), kept explicit."""
    name: str
    bf16_tflops: float         # published peak
    ici_gbps: float            # published aggregate per-chip ICI (both dirs)
    ici_domain_chips: int      # max chips in one ICI domain (pod/slice)
    dcn_gbps_per_chip: float   # ASSUMED: host NIC Gbps / chips per host

    @property
    def ici_allreduce_bytes_per_s(self) -> float:
        """Effective allreduce bandwidth over ICI.

        A bidirectional ring allreduce streams the 2B(n-1)/n wire bytes
        through each chip's links; of the published aggregate (all links,
        both directions) at most HALF is usable in one direction, so the
        model charges ici_gbps/2 -- conservative for 2D/3D torus slices,
        where multi-axis schedules can use more than one ring.
        """
        return self.ici_gbps / 2 / 8 * 1e9

    @property
    def dcn_allreduce_bytes_per_s(self) -> float:
        return self.dcn_gbps_per_chip / 2 / 8 * 1e9


# Published: cloud.google.com/tpu/docs v5e (197 bf16 TFLOP/s, 1600 Gbps
# ICI, 256-chip pod) and v5p (459 bf16 TFLOP/s, 4800 Gbps ICI, 3D torus).
# DCN share assumes a 200 Gbps host NIC across 8 (v5e) / 4 (v5p) chips.
V5E = ChipSpec("v5e", 197.0, 1600.0, 256, 200.0 / 8)
V5P = ChipSpec("v5p", 459.0, 4800.0, 8960, 200.0 / 4)


def ring_allreduce_seconds(nbytes: float, n: int, bw: float) -> float:
    """Ring allreduce wall time: 2B(n-1)/n wire bytes per chip at bw."""
    if n <= 1:
        return 0.0
    return 2.0 * nbytes * (n - 1) / n / bw


def allreduce_seconds(nbytes: float, n: int, chip: ChipSpec) -> float:
    """Allreduce time on n chips: pure ICI within one domain; two-level
    (ICI reduce-scatter -> DCN allreduce on the shard -> ICI allgather,
    the ``build_mesh(hierarchical=True)`` schedule) beyond it."""
    if n <= chip.ici_domain_chips:
        return ring_allreduce_seconds(nbytes, n, chip.ici_allreduce_bytes_per_s)
    s = chip.ici_domain_chips
    g = (n + s - 1) // s               # DCN groups (full slices)
    ici = 2.0 * nbytes * (s - 1) / s / chip.ici_allreduce_bytes_per_s
    dcn = ring_allreduce_seconds(nbytes / s, g,
                                 chip.dcn_allreduce_bytes_per_s)
    return ici + dcn


@dataclasses.dataclass
class EfficiencyPoint:
    n: int
    comm_seconds: float
    eff_no_overlap: float      # worst case: collectives fully exposed
    eff_full_overlap: float    # best case: hidden behind the backward pass


def predict_efficiency(step_seconds: float, wire_bytes: float,
                       chip: ChipSpec, ns: Tuple[int, ...] = (
                           1, 2, 4, 8, 16, 32, 64, 128, 256),
                       backward_fraction: float = 2.0 / 3.0):
    """Efficiency curve for a data-parallel step.

    ``step_seconds``: measured single-chip step time (the compute that
    perfect scaling preserves).  ``wire_bytes``: per-chip collective
    payload from the HLO accounting (the allreduce input bytes B; the
    ring moves 2B(n-1)/n of traffic).  Bounds:

    * no overlap:   eff = step / (step + t_ar)
    * full overlap: eff = step / (step + max(0, t_ar - backward_fraction
      * step)) -- collectives hide behind the backward pass, which is
      ~2/3 of fwd+bwd FLOPs; anything beyond it is exposed.
    """
    out = []
    for n in ns:
        t_ar = allreduce_seconds(wire_bytes, n, chip)
        exposed = max(0.0, t_ar - backward_fraction * step_seconds)
        out.append(EfficiencyPoint(
            n=n, comm_seconds=t_ar,
            eff_no_overlap=step_seconds / (step_seconds + t_ar),
            eff_full_overlap=step_seconds / (step_seconds + exposed)))
    return out
