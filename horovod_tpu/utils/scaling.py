"""Scaling evidence: HLO collective accounting + analytic efficiency model.

BASELINE.json's north star is >=90% scaling efficiency from 1 to 256
chips (ResNet-50 and BERT-Large data-parallel).  Without pod hardware
that claim cannot be timed, so this module produces the evidence that CAN
be produced mechanically (SURVEY.md section 6, section 7 hard part 5):

1. **Wire accounting from the compiled program.**  The train step is
   compiled for an n-device mesh and the optimized HLO is parsed for
   collectives: op counts and payload bytes.  Two invariants are
   checkable per model: the per-chip collective bytes match the gradient
   (+ BN-stat) payload the fusion planner predicts, and they are
   INDEPENDENT of n -- the defining property of allreduce data
   parallelism (bytes/chip ~ 2B(n-1)/n -> 2B).  A fusion regression
   (e.g. a gradient leaf escaping the buckets, a stats tree gathering
   instead of reducing) changes these numbers and fails the assertion.
2. **Overlap-capability accounting from the emitted (pre-optimization)
   StableHLO.**  Gradient buckets are emitted as SEPARATE psums whose
   operands depend only on their own slice of the backward pass, which
   is what lets a latency-hiding scheduler start bucket k's allreduce
   while bucket k+1's gradients are still being computed.  The CPU
   backend used for virtual meshes has no latency-hiding scheduler (it
   even re-combines the buckets), so the HLO *schedule* itself is not
   checkable off-TPU; what is checked: the emitted program has the
   planned bucket structure and the compiled module donates the
   parameter buffers (in-place update, no double-buffering stall).
3. **Analytic 1->256 projection.**  Measured single-chip step time +
   measured wire bytes + published link bandwidths -> predicted
   efficiency curve, reported for both the no-overlap (worst-case) and
   full-overlap (best-case) bounds.  All constants and formulas are
   explicit below; change them, the curve moves -- there is no hidden
   calibration.

Reference anchor: the upstream benchmark recipe measures images/s at
1..256 GPUs (SURVEY.md section 6); its scaling efficiency rests on the
same two quantities -- per-rank wire bytes (NCCL ring allreduce moves
2B(n-1)/n) and backward/comm overlap -- that this module accounts for.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# HLO parsing.
# ---------------------------------------------------------------------------

_DT_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "i64": 8, "i32": 4, "i16": 2, "i8": 1,
    "i1": 1,
}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                "collective-permute")

# `f32[128,4]{1,0} all-reduce(...)` or tuple-result variadic forms; -start
# counts once, -done is skipped.
_HLO_OP_RE = re.compile(
    r"=\s*(\([^)]*\)|[a-z0-9_]+\[[\d,]*\](?:\{[^}]*\})?)\s+"
    r"(" + "|".join(_COLLECTIVES) + r")(-start|-done)?\(")

_SHAPE_RE = re.compile(r"([a-z0-9_]+)\[([\d,]*)\]")


def _shape_bytes(shape_text: str) -> int:
    total = 0
    for m in _SHAPE_RE.finditer(shape_text):
        dt, dims = m.group(1), m.group(2)
        if dt not in _DT_BYTES:
            continue
        size = 1
        for d in dims.split(","):
            if d:
                size *= int(d)
        total += size * _DT_BYTES[dt]
    return total


@dataclasses.dataclass
class CollectiveStats:
    """Per-op-kind (count, payload bytes) from one HLO module."""
    counts: Dict[str, int]
    bytes: Dict[str, int]

    @property
    def total_bytes(self) -> int:
        return sum(self.bytes.values())

    @property
    def total_count(self) -> int:
        return sum(self.counts.values())


def optimized_collective_stats(compiled_text: str) -> CollectiveStats:
    """Count collectives and payload bytes in optimized HLO
    (``jax.jit(f).lower(...).compile().as_text()``).

    Payload bytes are the RESULT shape bytes (for an allreduce the payload
    equals the result; variadic combined all-reduces report the tuple
    total).  ``-done`` halves of async pairs are skipped so a started
    collective counts once.
    """
    counts: Dict[str, int] = {}
    bytes_: Dict[str, int] = {}
    for m in _HLO_OP_RE.finditer(compiled_text):
        shape, op, phase = m.group(1), m.group(2), m.group(3)
        if phase == "-done":
            continue
        counts[op] = counts.get(op, 0) + 1
        bytes_[op] = bytes_.get(op, 0) + _shape_bytes(shape)
    return CollectiveStats(counts=counts, bytes=bytes_)


_STABLE_RE = re.compile(
    r'"stablehlo\.(all_reduce|all_gather|reduce_scatter|all_to_all|'
    r'collective_permute)".*?\)\s*->\s*(\([^)]*\)|tensor<[^>]*>)',
    re.DOTALL)

_TENSOR_RE = re.compile(r"tensor<([^>]*)>")


def _tensor_bytes(t: str) -> int:
    parts = t.split("x")
    dt = parts[-1]
    if dt not in _DT_BYTES:
        return 0
    size = 1
    for d in parts[:-1]:
        size *= int(d)
    return size * _DT_BYTES[dt]


def emitted_collective_stats(lowered_text: str) -> CollectiveStats:
    """Count the collectives OUR trace emitted (pre-XLA-optimization
    StableHLO, ``jax.jit(f).lower(...).as_text()``): one ``all_reduce``
    per fusion bucket, per BN-stat leaf, per loss scalar.  This is the
    structure the latency-hiding scheduler sees; XLA's combiner may later
    merge compatible ops (backend- and threshold-dependent)."""
    counts: Dict[str, int] = {}
    bytes_: Dict[str, int] = {}
    for m in _STABLE_RE.finditer(lowered_text):
        op = m.group(1).replace("_", "-")
        counts[op] = counts.get(op, 0) + 1
        bytes_[op] = bytes_.get(op, 0) + sum(
            _tensor_bytes(t.group(1))
            for t in _TENSOR_RE.finditer(m.group(2)))
    return CollectiveStats(counts=counts, bytes=bytes_)


def has_buffer_donation(compiled_text: str) -> bool:
    """True when the compiled module aliases inputs to outputs (donated
    params/opt-state update in place -- no double-buffered HBM copy)."""
    return "input_output_alias" in compiled_text


# ---------------------------------------------------------------------------
# Compiled-schedule overlap analysis (TPU topology AOT).
# ---------------------------------------------------------------------------
#
# ``jax.experimental.topologies.get_topology_desc(platform="tpu",
# topology_name="v5e:2x4")`` + ``lowered.compile()`` produces a REAL
# scheduled TPU executable with no TPU attached (measured round 4: the
# bundled libtpu compiles deviceless; ``is_scheduled=true`` in the
# module).  The entry computation's instruction order IS the execution
# order, so overlap is mechanically checkable: a collective hides behind
# compute iff it is emitted as an async ``-start``/``-done`` pair with
# compute instructions scheduled inside the window.  Measured capability
# matrix of this toolchain (round 4, v5e/v5p/v6e topologies alike):
# ``collective-permute`` and ``all-gather`` are emitted async;
# ``all-reduce`` and ``reduce-scatter`` are always synchronous (the
# combiner also merges every bucket psum into ONE variadic all-reduce,
# regardless of the async-collective-fusion / latency-hiding-scheduler
# compile options, which this XLA accepts but which change nothing).

_HEAD_RE = re.compile(r"^%([\w.-]+)\s*=")
_START_OP_RE = re.compile(r"\s([a-z-]+)-start\(")
_DONE_RE = re.compile(r"-done\(%([\w.-]+)[,)]")
_SYNC_COLL_RE = re.compile(
    r" (" + "|".join(_COLLECTIVES) + r")\(")
_NAME_SHAPE_RE = re.compile(r"%([\w.-]+) = (\([^)]*\)|\S+) ([a-z-]+)")
_DIM_LABELS_RE = re.compile(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")


def _clean_bytes(shape_text: str) -> int:
    """Bytes of a shape string, layout/tiling annotations stripped."""
    return _shape_bytes(re.sub(r"\{[^}]*\}", "", shape_text))


def _shape_dims(shape_text: str):
    m = _SHAPE_RE.search(re.sub(r"\{[^}]*\}", "", shape_text))
    if not m:
        return None
    return [int(d) for d in m.group(2).split(",") if d]


@dataclasses.dataclass
class ScheduleReport:
    """Mechanical overlap evidence from one scheduled TPU module."""
    sync_collectives: list          # (op, payload_bytes, schedule_idx)
    async_collectives: list         # (op, payload_bytes, start_idx, done_idx)
    async_window_seconds: float     # est. compute scheduled inside windows
    total_compute_seconds: float    # est. compute of the whole schedule
    n_instructions: int
    n_devices: int = 0              # mesh size the module was compiled for

    @property
    def sync_bytes(self) -> int:
        return sum(b for _, b, _ in self.sync_collectives)

    @property
    def async_bytes(self) -> int:
        return sum(b for _, b, _, _ in self.async_collectives)

    @staticmethod
    def _eq_payload(ops, n: int) -> float:
        """Result bytes -> EQUIVALENT allreduce payload (the B in
        2B(n-1)/n), so traffic can be projected to other mesh sizes with
        the same ring law.  Per-op result-bytes semantics differ: a
        ``collective-permute`` result is LINK bytes (one hop); an
        ``all-gather``/``all-to-all`` result is the full payload B
        (link B(n-1)/n = HALF an allreduce of the same B); a
        ``reduce-scatter`` result is the B/n shard."""
        if n <= 1:
            return float(sum(b for _, b in ops))
        ring = 2.0 * (n - 1) / n
        eq = 0.0
        for op, b in ops:
            if op in ("all-gather", "all-to-all"):
                eq += b / 2.0
            elif op == "all-reduce":
                eq += b            # result bytes == full payload == B
            elif op == "reduce-scatter":
                eq += b * n / 2.0  # result is B/n shard; link = B(n-1)/n
            else:                  # permute: result bytes ARE link bytes
                eq += b / ring
        return eq

    def async_eq_payload(self) -> float:
        """Async traffic as equivalent allreduce payload.  Requires
        ``n_devices``."""
        return self._eq_payload(
            [(op, b) for op, b, _, _ in self.async_collectives],
            self.n_devices)

    def sync_eq_payload(self) -> float:
        """Sync traffic as equivalent allreduce payload.  Identical to
        ``sync_bytes`` when every sync collective is an all-reduce (the
        usual case); differs once sync all-to-all / all-gather appear
        (e.g. the fp8 exchange codec on a plain-DP config)."""
        return self._eq_payload(
            [(op, b) for op, b, _ in self.sync_collectives],
            self.n_devices)


def _entry_instructions(compiled_text: str):
    """Instruction lines of the ENTRY computation, in schedule order."""
    lines = compiled_text.splitlines()
    out = []
    in_entry = False
    for ln in lines:
        if ln.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if ln.startswith("}"):
                break
            s = ln.strip()
            if s.startswith(("%", "ROOT ")):
                out.append(s.lstrip("ROOT ").strip())
    return out


def _module_shapes(compiled_text: str):
    """name -> (shape_text, op) for every instruction in the module."""
    shapes = {}
    for m in _NAME_SHAPE_RE.finditer(compiled_text):
        shapes[m.group(1)] = (m.group(2), m.group(3))
    return shapes


def _conv_flops(line: str, shapes) -> float:
    dims_out = _shape_dims(line.split("=", 1)[1])
    ops = re.findall(r"convolution\(%([\w.-]+), %([\w.-]+)\)", line)
    lab = _DIM_LABELS_RE.search(line)
    if not dims_out or not ops or not lab:
        return 0.0
    ker = shapes.get(ops[0][1])
    kdims = _shape_dims(ker[0]) if ker else None
    if not kdims:
        return 0.0
    out_elems = 1
    for d in dims_out:
        out_elems *= d
    kelems = 1
    for d in kdims:
        kelems *= d
    o_pos = lab.group(2).find("o")
    o_size = kdims[o_pos] if 0 <= o_pos < len(kdims) else 1
    return 2.0 * out_elems * kelems / max(o_size, 1)


def _dot_flops(line: str, shapes) -> float:
    dims_out = _shape_dims(line.split("=", 1)[1])
    ops = re.findall(r"dot\(%([\w.-]+), %([\w.-]+)\)", line)
    cm = _CONTRACT_RE.search(line)
    if not dims_out or not ops:
        return 0.0
    lhs = shapes.get(ops[0][0])
    ldims = _shape_dims(lhs[0]) if lhs else None
    if not ldims:
        return 0.0
    out_elems = 1
    for d in dims_out:
        out_elems *= d
    k = 1
    if cm:
        for i in (int(x) for x in cm.group(1).split(",") if x):
            if i < len(ldims):
                k *= ldims[i]
    else:
        k = ldims[-1]
    return 2.0 * out_elems * k


def _computation_flops(compiled_text: str, shapes) -> Dict[str, float]:
    """computation name -> conv+dot FLOPs inside it (fusion bodies)."""
    flops: Dict[str, float] = {}
    current = None
    for ln in compiled_text.splitlines():
        if ln.startswith("%") and ln.rstrip().endswith("{"):
            current = ln.split(" ", 1)[0].lstrip("%")
            flops[current] = 0.0
        elif ln.startswith("}"):
            current = None
        elif current is not None:
            s = ln.strip()
            if " convolution(" in s:
                flops[current] += _conv_flops(s, shapes)
            elif " dot(" in s:
                flops[current] += _dot_flops(s, shapes)
    return flops


_CALLS_RE = re.compile(r"calls=%([\w.-]+)")
_OPERANDS_RE = re.compile(r"\(%([\w.-]+(?:, %[\w.-]+)*)\)")


def _instr_cost_seconds(line: str, shapes, comp_flops,
                        flops_per_s: float, hbm_bytes_per_s: float) -> float:
    """Roofline estimate for one scheduled instruction: max(MXU, HBM)."""
    head, _, tail = line.partition("=")
    name = head.strip().lstrip("%").strip()
    flops = 0.0
    if " fusion(" in line:
        cm = _CALLS_RE.search(line)
        if cm:
            flops = comp_flops.get(cm.group(1), 0.0)
    elif " convolution(" in line:
        flops = _conv_flops(line, shapes)
    elif " dot(" in line:
        flops = _dot_flops(line, shapes)
    elif not any(k in line for k in (" fusion(", " convolution(", " dot(",
                                     " copy(", " transpose(", " reduce(",
                                     " select(", " add(", " multiply(")):
        return 0.0                     # bookkeeping (gte/bitcast/params/...)
    result_bytes = _clean_bytes(tail.split(" ", 2)[1] if tail else "")
    operand_bytes = 0
    om = _OPERANDS_RE.search(line)
    if om:
        for op_name in om.group(1).split(", "):
            sh = shapes.get(op_name.lstrip("%"))
            if sh:
                operand_bytes += _clean_bytes(sh[0])
    return max(flops / flops_per_s,
               (result_bytes + operand_bytes) / hbm_bytes_per_s)


def schedule_overlap_report(
        compiled_text: str, *,
        n_devices: int = 0,
        flops_per_s: float = 0.7 * 197e12,
        hbm_bytes_per_s: float = 0.8 * 819e9) -> ScheduleReport:
    """Parse a SCHEDULED TPU module for collective overlap evidence.

    Defaults model a v5e: MXU at the 70% of peak the per-op roofline
    measured for this workload class (docs/benchmarks.md), HBM at 80% of
    the 819 GB/s spec.  The estimates only weight schedule POSITIONS --
    the sync/async split itself is exact (it is read off the text).
    """
    entry = _entry_instructions(compiled_text)
    shapes = _module_shapes(compiled_text)
    comp_flops = _computation_flops(compiled_text, shapes)

    starts = {}                      # name -> (op, payload, idx)
    sync, async_ = [], []
    for i, line in enumerate(entry):
        hm = _HEAD_RE.match(line)
        sm0 = _START_OP_RE.search(line)
        if hm and sm0 and sm0.group(1) in _COLLECTIVES:
            starts[hm.group(1)] = (sm0.group(1), i)
            continue
        dm = _DONE_RE.search(line)
        if dm and dm.group(1) in starts:
            op, si = starts.pop(dm.group(1))
            # Payload = the -done result (the actual collective result,
            # matching the sync accounting; the -start result is a
            # bookkeeping tuple of operands+results+semaphores).
            payload = _clean_bytes(line.split("=", 1)[1].split(" ", 2)[1]
                                   if "=" in line else "")
            async_.append((op, payload, si, i))
            continue
        sm = _SYNC_COLL_RE.search(line)
        if sm:
            # Result shape = text between "= " and the op token; TPU
            # layout/tiling annotations (nested parens) are stripped by
            # _clean_bytes, so variadic tuple results total correctly.
            shape_text = line[line.index("=") + 1:sm.start()]
            sync.append((sm.group(1), _clean_bytes(shape_text), i))

    costs = [_instr_cost_seconds(l, shapes, comp_flops,
                                 flops_per_s, hbm_bytes_per_s)
             for l in entry]
    in_window = [False] * len(entry)
    for _, _, si, di in async_:
        for j in range(si + 1, di):
            in_window[j] = True
    return ScheduleReport(
        sync_collectives=sync,
        async_collectives=async_,
        async_window_seconds=sum(c for c, w in zip(costs, in_window) if w),
        total_compute_seconds=sum(costs),
        n_instructions=len(entry),
        n_devices=n_devices)


def predict_efficiency_scheduled(step_seconds: float, report: ScheduleReport,
                                 chip: "ChipSpec",
                                 ns: Tuple[int, ...] = (
                                     1, 2, 4, 8, 16, 32, 64, 128, 256),
                                 bandwidth_derate: float = 1.0):
    """Efficiency from the COMPILED schedule: sync collective time is
    fully exposed; async collective time hides up to the compute the
    scheduler actually placed inside the windows (measured at compile
    n, assumed n-invariant -- per-chip compute is fixed in DP scaling).

    ``bandwidth_derate`` > 1 divides the effective link bandwidth for the
    ASYNC (point-to-point) traffic: a VHDD partner exchange cannot
    provably use all torus links the way a pipelined ring can, so
    headline claims should also be quoted at a pessimistic derate (4x =
    a single link direction) -- if the window still covers the comm
    there, the overlap conclusion is bandwidth-model-independent.
    """
    out = []
    for n in ns:
        t_sync = allreduce_seconds(report.sync_eq_payload(), n, chip)
        t_async = bandwidth_derate * allreduce_seconds(
            report.async_eq_payload(), n, chip)
        exposed = t_sync + max(0.0, t_async - report.async_window_seconds)
        out.append(EfficiencyPoint(
            n=n, comm_seconds=t_sync + t_async,
            eff_no_overlap=step_seconds / (step_seconds + t_sync + t_async),
            eff_full_overlap=step_seconds / (step_seconds + exposed)))
    return out


# ---------------------------------------------------------------------------
# Analytic efficiency model.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Published per-chip numbers (Google Cloud TPU spec sheets) plus the
    one ASSUMED constant (per-chip DCN share), kept explicit."""
    name: str
    bf16_tflops: float         # published peak
    ici_gbps: float            # published aggregate per-chip ICI (both dirs)
    ici_domain_chips: int      # max chips in one ICI domain (pod/slice)
    dcn_gbps_per_chip: float   # ASSUMED: host NIC Gbps / chips per host

    @property
    def ici_allreduce_bytes_per_s(self) -> float:
        """Effective allreduce bandwidth over ICI.

        A bidirectional ring allreduce streams the 2B(n-1)/n wire bytes
        through each chip's links; of the published aggregate (all links,
        both directions) at most HALF is usable in one direction, so the
        model charges ici_gbps/2 -- conservative for 2D/3D torus slices,
        where multi-axis schedules can use more than one ring.
        """
        return self.ici_gbps / 2 / 8 * 1e9

    @property
    def dcn_allreduce_bytes_per_s(self) -> float:
        return self.dcn_gbps_per_chip / 2 / 8 * 1e9


# Published: cloud.google.com/tpu/docs v5e (197 bf16 TFLOP/s, 1600 Gbps
# ICI, 256-chip pod) and v5p (459 bf16 TFLOP/s, 4800 Gbps ICI, 3D torus).
# DCN share assumes a 200 Gbps host NIC across 8 (v5e) / 4 (v5p) chips.
V5E = ChipSpec("v5e", 197.0, 1600.0, 256, 200.0 / 8)
V5P = ChipSpec("v5p", 459.0, 4800.0, 8960, 200.0 / 4)


def ring_allreduce_seconds(nbytes: float, n: int, bw: float) -> float:
    """Ring allreduce wall time: 2B(n-1)/n wire bytes per chip at bw."""
    if n <= 1:
        return 0.0
    return 2.0 * nbytes * (n - 1) / n / bw


def allreduce_seconds(nbytes: float, n: int, chip: ChipSpec) -> float:
    """Allreduce time on n chips: pure ICI within one domain; two-level
    (ICI reduce-scatter -> DCN allreduce on the shard -> ICI allgather,
    the ``build_mesh(hierarchical=True)`` schedule) beyond it."""
    if n <= chip.ici_domain_chips:
        return ring_allreduce_seconds(nbytes, n, chip.ici_allreduce_bytes_per_s)
    s = chip.ici_domain_chips
    g = (n + s - 1) // s               # DCN groups (full slices)
    ici = 2.0 * nbytes * (s - 1) / s / chip.ici_allreduce_bytes_per_s
    dcn = ring_allreduce_seconds(nbytes / s, g,
                                 chip.dcn_allreduce_bytes_per_s)
    return ici + dcn


@dataclasses.dataclass
class EfficiencyPoint:
    n: int
    comm_seconds: float
    eff_no_overlap: float      # worst case: collectives fully exposed
    eff_full_overlap: float    # best case: hidden behind the backward pass


def predict_efficiency(step_seconds: float, wire_bytes: float,
                       chip: ChipSpec, ns: Tuple[int, ...] = (
                           1, 2, 4, 8, 16, 32, 64, 128, 256),
                       backward_fraction: float = 2.0 / 3.0):
    """Efficiency curve for a data-parallel step.

    ``step_seconds``: measured single-chip step time (the compute that
    perfect scaling preserves).  ``wire_bytes``: per-chip collective
    payload from the HLO accounting (the allreduce input bytes B; the
    ring moves 2B(n-1)/n of traffic).  Bounds:

    * no overlap:   eff = step / (step + t_ar)
    * full overlap: eff = step / (step + max(0, t_ar - backward_fraction
      * step)) -- collectives hide behind the backward pass, which is
      ~2/3 of fwd+bwd FLOPs; anything beyond it is exposed.
    """
    out = []
    for n in ns:
        t_ar = allreduce_seconds(wire_bytes, n, chip)
        exposed = max(0.0, t_ar - backward_fraction * step_seconds)
        out.append(EfficiencyPoint(
            n=n, comm_seconds=t_ar,
            eff_no_overlap=step_seconds / (step_seconds + t_ar),
            eff_full_overlap=step_seconds / (step_seconds + exposed)))
    return out
