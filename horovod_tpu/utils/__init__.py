"""Utility helpers: checkpoint/resume (SURVEY.md section 5.4)."""

from .checkpoint import (  # noqa: F401
    checkpoint_path, latest_checkpoint, restore_checkpoint, save_checkpoint,
    restore_checkpoint_sharded, save_checkpoint_sharded,
)
