"""Pre-init platform forcing shared by the launcher, tests, examples, and
the driver entry.

Running a multi-rank test/dry-run on one host needs an N-device virtual CPU
backend (the analogue of the reference's ``mpirun -np N`` localhost test
strategy, SURVEY.md section 4/7).  Both knobs involved --
``--xla_force_host_platform_device_count`` in ``XLA_FLAGS`` and
``jax_platforms`` -- only take effect if applied BEFORE jax initializes its
first backend, so every entry point that needs the virtual mesh must do the
same dance; this module is the single implementation.
"""

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def _strip_count_flag(xla_flags: str):
    """Remove every occurrence of the count flag; return (rest, counts)."""
    pattern = re.escape(_COUNT_FLAG) + r"=(\d+)"
    counts = [int(v) for v in re.findall(pattern, xla_flags)]
    rest = " ".join(re.sub(pattern, "", xla_flags).split())
    return rest, counts


def merge_host_device_flag(xla_flags: str, n: int) -> str:
    """Return ``xla_flags`` with the host-device-count flag at least ``n``.

    All existing occurrences are collapsed into one (duplicate-flag
    precedence is an XLA implementation detail we refuse to rely on) set to
    max(existing..., n).
    """
    rest, counts = _strip_count_flag(xla_flags)
    return (rest + f" {_COUNT_FLAG}={max(counts + [n])}").strip()


def set_host_device_flag(xla_flags: str, n: int) -> str:
    """Return ``xla_flags`` with the host-device-count flag EXACTLY ``n``.

    For per-worker envs (launcher slots): the worker must see its slot
    count, not whatever larger count the parent environment carried.
    """
    rest, _ = _strip_count_flag(xla_flags)
    return (rest + f" {_COUNT_FLAG}={n}").strip()


def backend_initialized() -> bool:
    """Best-effort: has jax already created a live backend in this process?

    Probes a private jax internal; any failure (renamed module/attr after a
    jax upgrade) is treated as "unknown", reported as uninitialized so
    callers proceed with the normal pre-init path.
    """
    try:
        import jax._src.xla_bridge as xla_bridge
        return bool(xla_bridge._backends)
    except Exception:
        return False


def multiprocess_cpu_supported() -> bool:
    """Can this jax run MULTI-PROCESS computations on the CPU backend?

    The ``run -np N --cpu`` localhost mode jits programs over a mesh that
    spans several processes' CPU devices; jaxlib only implements the
    cross-host CPU transfers this needs from the 0.5 line on (older
    runtimes raise ``Multiprocess computations aren't implemented on the
    CPU backend``).  Single-process virtual-device meshes
    (``force_host_device_count``) work everywhere and are not gated by
    this.
    """
    try:
        import jax
        return tuple(int(p) for p in jax.__version__.split(".")[:2]) >= (0, 5)
    except Exception:
        return False


def force_host_device_count(n: int, cpu: bool = True,
                            exact: bool = False) -> None:
    """Arrange for an ``n``-device virtual CPU backend.

    Must run before jax's first backend initialization.  With ``cpu=True``
    (the default) the default jax platform is forced to cpu as well, so
    plain ``jax.devices()`` returns the virtual mesh even when a TPU plugin
    is installed.  ``exact=True`` overrides a larger inherited count (an
    explicit user request like ``--cpu-devices 2`` means exactly 2);
    the default keeps at-least-``n`` semantics (a dryrun/test needs >= n).
    """
    fn = set_host_device_flag if exact else merge_host_device_flag
    os.environ["XLA_FLAGS"] = fn(os.environ.get("XLA_FLAGS", ""), n)
    if cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
