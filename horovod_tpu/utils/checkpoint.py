"""Checkpoint/resume helpers (SURVEY.md section 5.4).

The reference ships no checkpoint format of its own -- its documented
idiom is "rank 0 saves; on resume everyone restores and
``broadcast_parameters`` syncs" (examples + ``horovod/torch/functions.py``).
These helpers codify exactly that for pytrees:

* :func:`save_checkpoint`: rank 0 atomically writes a flat npz of the
  tree's leaves (keyed by jax keystr); a barrier makes completion global.
* :func:`restore_checkpoint`: rank 0 reads, then every leaf is broadcast
  -- correct whether or not the checkpoint path is on a shared
  filesystem.
* :func:`latest_checkpoint`: newest ``step``-stamped file in a directory.

For multi-TB sharded model states use orbax directly; this is the parity
surface for the reference's host-RAM-scale workloads.
"""

from __future__ import annotations

import io
import os
import re
from typing import Any, Optional, Tuple

import numpy as np

_STEP_KEY = "__hvd_tpu_step__"


def _flatten(tree: Any):
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(kp) or "<root>", v) for kp, v in flat], \
        treedef


def checkpoint_path(directory: str, step: int,
                    prefix: str = "ckpt") -> str:
    return os.path.join(directory, f"{prefix}_{step:010d}.npz")


def save_checkpoint(path: str, tree: Any, *, step: Optional[int] = None,
                    root_rank: int = 0) -> str:
    """Rank ``root_rank`` writes ``tree`` to ``path`` (npz, atomic);
    everyone barriers so a subsequent restore sees a complete file."""
    from ..core import basics as _basics
    from ..optim.functions import broadcast_object

    err = None
    if _basics.rank() == root_rank:
        try:
            import jax
            flat, _ = _flatten(tree)
            payload = {k: np.asarray(jax.device_get(v)) for k, v in flat}
            if step is not None:
                payload[_STEP_KEY] = np.asarray(step, np.int64)
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            buf = io.BytesIO()
            np.savez(buf, **payload)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(buf.getvalue())
            os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001 - must reach every rank
            err = f"{type(e).__name__}: {e}"
    # Error status travels through a collective every rank enters -- a
    # root-only raise would leave the other ranks stuck in the barrier.
    err = broadcast_object(err, root_rank=root_rank)
    if err:
        raise RuntimeError(f"checkpoint save failed on root: {err}")
    return path


def restore_checkpoint(path: str, like: Any, *,
                       root_rank: int = 0) -> Tuple[Any, Optional[int]]:
    """Restore a tree shaped ``like``; returns ``(tree, step)``.

    Rank ``root_rank`` reads the file; every leaf is then broadcast, so
    only the root needs the file (non-shared-filesystem resume).  Root-
    side read errors (missing file, missing leaves) are broadcast as a
    status before any tree collective, so every rank raises instead of
    the non-roots hanging in a broadcast the root never joins.
    """
    import jax

    from ..core import basics as _basics
    from ..optim.functions import broadcast_, broadcast_object

    flat, treedef = _flatten(like)
    step = None
    err = None
    values = [np.zeros(np.shape(v), np.asarray(v).dtype) for _, v in flat]
    if _basics.rank() == root_rank:
        try:
            with np.load(path) as z:
                missing = [k for k, _ in flat if k not in z.files]
                if missing:
                    raise KeyError(
                        f"checkpoint {path!r} lacks {len(missing)} "
                        f"leaf/leaves of the restore target: {missing[:5]}")
                values = []
                for k, like_v in flat:
                    a = z[k]
                    if a.dtype.kind == "V":
                        # numpy round-trips ml_dtypes (bfloat16, float8)
                        # as opaque void records; the bytes are intact, so
                        # view them back through the target's dtype.
                        a = a.view(np.dtype(np.asarray(like_v).dtype))
                    values.append(a)
                if _STEP_KEY in z.files:
                    step = int(z[_STEP_KEY])
        except Exception as e:  # noqa: BLE001 - must reach every rank
            err = f"{type(e).__name__}: {e}"
    err = broadcast_object(err, root_rank=root_rank)
    if err:
        exc = KeyError if err.startswith("KeyError") else RuntimeError
        raise exc(f"checkpoint restore failed on root: {err}")
    tree = jax.tree_util.tree_unflatten(treedef, values)
    tree = broadcast_(tree, root_rank=root_rank)
    step = broadcast_object(step, root_rank=root_rank)
    return tree, step


def latest_checkpoint(directory: str,
                      prefix: str = "ckpt") -> Optional[str]:
    """Path of the highest-step checkpoint in ``directory`` (None: none)."""
    if not os.path.isdir(directory):
        return None
    best: Tuple[int, Optional[str]] = (-1, None)
    pat = re.compile(rf"^{re.escape(prefix)}_(\d+)\.npz$")
    for name in os.listdir(directory):
        m = pat.match(name)
        if m:
            best = max(best, (int(m.group(1)),
                              os.path.join(directory, name)))
    return best[1]


def save_checkpoint_sharded(directory: str, tree: Any, *,
                            step: int = 0) -> str:
    """Sharded orbax checkpoint: every host writes its own shards.

    The pod-scale complement to :func:`save_checkpoint` (SURVEY.md 5.4:
    "orbax-style sharded checkpoint" for states too large for rank-0
    gather-and-write).  Synchronous and collective -- every process must
    call it with the same ``step``.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(directory,
                                        f"sharded_{step:010d}"))
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree, force=True)
    return path


def restore_checkpoint_sharded(directory: str, like: Any, *,
                               step: Optional[int] = None
                               ) -> Tuple[Any, Optional[int]]:
    """Restore an orbax sharded checkpoint onto ``like``'s shardings.

    ``like`` supplies structure, dtypes, AND shardings (jax.Arrays on the
    mesh restore distributed, exactly as saved).  ``step=None`` picks the
    newest step under ``directory``.
    """
    import jax
    import orbax.checkpoint as ocp

    if step is None:
        pat = re.compile(r"^sharded_(\d+)$")
        steps = [int(m.group(1)) for name in
                 (os.listdir(directory) if os.path.isdir(directory) else [])
                 if (m := pat.match(name))]
        if not steps:
            return None, None
        step = max(steps)
    path = os.path.abspath(os.path.join(directory,
                                        f"sharded_{step:010d}"))
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            np.shape(x), np.asarray(x).dtype if not hasattr(x, "dtype")
            else x.dtype,
            sharding=x.sharding if hasattr(x, "sharding") else None),
        like)
    with ocp.StandardCheckpointer() as ckptr:
        tree = ckptr.restore(path, abstract)
    return tree, step
