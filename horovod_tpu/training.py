"""High-level data-parallel training-step builder.

The reference's end-user recipe (wrap optimizer, hook gradients, launch one
process per accelerator) becomes, TPU-natively: trace ONE step function
over the mesh with ``jax.shard_map``; the batch is sharded over the mesh
axes, parameters are replicated, and the wrapped optimizer emits fused
``psum`` collectives that XLA overlaps with the backward pass.

This module is the "DistributedOptimizer user experience" glue: given a
loss function and a (Distributed)optax optimizer it returns a jitted step
with donated params/opt-state (in-place HBM update, fusion-buffer style).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import math

from .collectives import ops as _ops
from .collectives.reduce_op import Average, Sum
from .core import basics as _basics
from .optim import distributed as _dist
from .optim import zero as _zero


def _opt_state_spec(optimizer, zero_stage: int, axes, override=None):
    """Partition spec (pytree prefix) for the optimizer-state carry.

    ZeRO-1 state is arena-sharded ``P(axes)``.  An error-feedback wrap's
    state mixes specs: the per-rank residual leaves (leading world axis)
    shard ``P(axes)`` while the inner optimizer state stays replicated --
    expressed as an ``_EFState``-shaped spec prefix.  ``override`` (the
    builders' ``opt_state_specs=``) wins for everything else -- the TP
    case, where a stateful optimizer's param-shaped moments must shard
    like the params (:func:`mirror_opt_state_specs`).  Default:
    replicated."""
    if zero_stage:
        return P(axes)
    if _dist.is_ef_optimizer(optimizer):
        return _dist._EFState(residuals=P(axes), inner=P())
    if override is not None:
        return override
    return P()


def mirror_opt_state_specs(optimizer, params, param_specs):
    """Optimizer-state spec tree mirroring TP/pipeline ``param_specs``.

    A stateful optimizer (Adam moments, SGD momentum) carries param-tree-
    shaped subtrees in its state; on a model-parallel mesh those must
    shard exactly like the params or the shard_map in_specs try to place
    a full-shaped moment next to a sharded param.  This walks
    ``jax.eval_shape(optimizer.init, params)`` and substitutes
    ``param_specs`` for every subtree structurally equal to ``params``
    (scalars such as the Adam step count stay replicated).  Pass the
    result as ``make_train_step(..., opt_state_specs=...)``.
    """
    state = jax.eval_shape(optimizer.init, params)
    pstruct = jax.tree.structure(params)

    def is_param_tree(node):
        try:
            return jax.tree.structure(node) == pstruct
        except Exception:  # noqa: BLE001 - non-pytree node
            return False

    def leaf(node):
        return is_param_tree(node) or not jax.tree.leaves(node) \
            or isinstance(node, jax.ShapeDtypeStruct)

    return jax.tree.map(
        lambda n: param_specs if is_param_tree(n) else P(),
        state, is_leaf=leaf)


def batch_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Sharding that splits the leading (batch) dim over the mesh's DATA
    axes (all axes on a pure-DP mesh; the batch is replicated over the
    ``model``/``pipe`` axes of a :func:`~horovod_tpu.parallel.build_3d_mesh`
    mesh -- every TP rank and pipeline stage sees its DP shard whole)."""
    from .parallel.mesh import data_axes as _data_axes
    mesh = mesh or _basics.mesh()
    return NamedSharding(mesh, P(_data_axes(mesh)))


def shard_batch(batch: Any, mesh: Optional[Mesh] = None) -> Any:
    """Place a host-global batch onto the mesh, sharded along dim 0."""
    sharding = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def stacked_batch_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Sharding for :func:`stack_steps` output: dim 0 is the (unsharded)
    steps axis the scan loop consumes, dim 1 the global batch split over
    the mesh's data axes."""
    from .parallel.mesh import data_axes as _data_axes
    mesh = mesh or _basics.mesh()
    return NamedSharding(mesh, P(None, _data_axes(mesh)))


def shard_steps(stacked: Any, mesh: Optional[Mesh] = None) -> Any:
    """Place a k-step stacked batch (``[k, global_batch, ...]`` leaves --
    :func:`stack_steps`) onto the mesh for :func:`make_train_loop`."""
    sharding = stacked_batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), stacked)


def shard_batch_from_local(local_batch: Any,
                           mesh: Optional[Mesh] = None) -> Any:
    """Assemble the global batch from each process's local rows.

    The reference's data model: every rank loads its own shard (Petastorm
    per-rank readers, ``ElasticSampler``).  Each process passes the rows it
    owns; the global array is stitched with
    ``jax.make_array_from_process_local_data``.  Single-process, this is
    :func:`shard_batch`.
    """
    import numpy as np

    mesh = mesh or _basics.mesh()
    mesh_procs = {d.process_index for d in mesh.devices.flat}
    if len(mesh_procs) == 1:
        return shard_batch(local_batch, mesh)
    sharding = batch_sharding(mesh)

    def put(x):
        x = np.asarray(x)
        # Multiply by the processes IN THIS MESH (a process-set sub-mesh
        # may span fewer than jax.process_count()).
        global_shape = (x.shape[0] * len(mesh_procs),) + x.shape[1:]
        return jax.make_array_from_process_local_data(sharding, x,
                                                      global_shape)

    return jax.tree.map(put, local_batch)


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or _basics.mesh()
    return NamedSharding(mesh, P())


def replicate(tree: Any, mesh: Optional[Mesh] = None) -> Any:
    """Replicate parameters/optimizer state across the mesh."""
    sharding = replicated_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def sync_batch_norm(axes=None, **kwargs):
    """Flax BatchNorm whose batch statistics span the mesh.

    Reference parity: ``horovod/torch/sync_batch_norm.py`` (the torch shim
    equivalent lives at ``horovod_tpu.torch.SyncBatchNorm``).  On TPU the
    stat exchange is just ``lax.pmean`` over the mesh axes, which flax's
    BatchNorm emits natively via ``axis_name`` -- XLA fuses it with the
    surrounding reduction, so sync BN costs one small fused collective.

    Use inside a step built by :func:`make_train_step` /
    :func:`make_flax_train_step` (the mesh axes are bound by shard_map
    there).  ``axes`` defaults to the initialized mesh's axis names.
    """
    import flax.linen as nn
    axes = tuple(axes) if axes is not None else tuple(
        _basics.mesh().axis_names)
    return nn.BatchNorm(axis_name=axes if len(axes) > 1 else axes[0],
                        **kwargs)


def _resolve_zero_stage(zero_stage: Optional[int]) -> int:
    """``None`` defers to the configured default (``HOROVOD_ZERO``)."""
    if zero_stage is None:
        from .core.state import global_state
        cfg = global_state().config
        zero_stage = cfg.zero_stage if cfg is not None else 0
    if zero_stage not in (0, 1):
        raise ValueError(f"zero_stage must be 0 or 1, got {zero_stage!r}")
    return zero_stage


def steps_per_execution(default: int = 1) -> int:
    """Resolved steps-per-execution k (``HOROVOD_STEPS_PER_EXEC``).

    The keras/torch shims read this to pick up the env knob (pass it to
    ``model.compile(steps_per_execution=...)`` / use it as the torch
    micro-loop length); :func:`make_train_loop` calls it when built
    without an explicit ``steps_per_execution``.  When the autotuner's
    opt-in steps axis is active, the current sample's value wins.
    """
    from .core.state import global_state
    st = global_state()
    if st.autotuner is not None:
        return max(1, st.autotuner.steps_per_exec())
    if st.config is not None:
        return max(1, st.config.steps_per_exec)
    return max(1, default)


def _resolve_steps(k: Optional[int]) -> int:
    """``None`` defers to :func:`steps_per_execution` (env/tuner)."""
    k = steps_per_execution() if k is None else int(k)
    if k < 1:
        raise ValueError(f"steps_per_execution must be >= 1, got {k}")
    return k


def microbatches(default: int = 1) -> int:
    """Resolved microbatch count k (``HOROVOD_MICROBATCHES``).

    :func:`make_train_step` / :func:`make_flax_train_step` (and the loop
    builders) call this when built without an explicit ``microbatches``
    argument.  When the autotuner's opt-in microbatch axis is active
    (``HOROVOD_AUTOTUNE_MICROBATCH=1``) the current sample's value wins.
    k > 1 selects the backward-overlap exchange: the per-step batch splits
    into k sub-batches inside one executable and each sub-batch's gradient
    buckets reduce-scatter while the next sub-batch's backward pass runs.
    """
    from .core.state import global_state
    st = global_state()
    if st.autotuner is not None:
        return max(1, st.autotuner.microbatches())
    if st.config is not None:
        return max(1, st.config.microbatches)
    return max(1, default)


def _resolve_microbatches(k: Optional[int]) -> int:
    """``None`` defers to :func:`microbatches` (env/tuner)."""
    k = microbatches() if k is None else int(k)
    if k < 1:
        raise ValueError(f"microbatches must be >= 1, got {k}")
    return k


def _resolve_tp(tp: Optional[int]) -> int:
    """``None`` defers to the configured default (``HOROVOD_TP``)."""
    if tp is None:
        from .core.state import global_state
        cfg = global_state().config
        tp = cfg.tp if cfg is not None else 1
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    return tp


def _resolve_pipeline_stages(pipeline_stages: Optional[int]) -> int:
    """``None`` defers to the configured default
    (``HOROVOD_PIPELINE_STAGES``)."""
    if pipeline_stages is None:
        from .core.state import global_state
        cfg = global_state().config
        pipeline_stages = cfg.pipeline_stages if cfg is not None else 1
    pipeline_stages = int(pipeline_stages)
    if pipeline_stages < 1:
        raise ValueError(
            f"pipeline_stages must be >= 1, got {pipeline_stages}")
    return pipeline_stages


def _resolve_model_axes(mesh: Mesh, tp: int, pipeline_stages: int
                        ) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """``(data_axes, model_axes)`` of ``mesh`` for a step built with
    ``tp``/``pipeline_stages``, with the declared extents validated
    against the mesh shape.

    The data axes are the gradient-exchange domain: every collective the
    step emits on its own behalf (gradient allreduce, ZeRO arena
    reduce-scatter/allgather, microbatch overlap, loss average) runs over
    them ONLY, so TP's in-forward collectives on the ``model`` axis and
    the pipeline's ``ppermute`` on ``pipe`` never mix with the DP leg.
    On a pure-DP mesh the data axes are all axes -- bitwise-identical
    wiring to the pre-3D builder.
    """
    from .parallel import mesh as _pmesh
    names = tuple(mesh.axis_names)

    def check(extent: int, axis: str, knob: str) -> None:
        have = int(mesh.shape[axis]) if axis in names else 1
        if extent > 1 and have != extent:
            raise ValueError(
                f"{knob}={extent} needs a mesh {axis!r} axis of extent "
                f"{extent} (build_3d_mesh); mesh axes are "
                f"{dict(mesh.shape)}")
        if extent == 1 and have > 1:
            raise ValueError(
                f"mesh has a {axis!r} axis of extent {have} but the step "
                f"was built with {knob}={extent}; pass {knob}={have}")

    check(tp, _pmesh.MODEL_AXIS, "tp")
    check(pipeline_stages, _pmesh.PIPE_AXIS, "pipeline_stages")
    d_ax = _pmesh.data_axes(mesh)
    m_ax = tuple(a for a in names if a not in d_ax)
    return d_ax, m_ax


def _check_model_parallel_exchange(optimizer, d_ax, m_ax) -> None:
    """Reject optimizer wraps whose gradient exchange would reduce over
    the model axes.  A :func:`~horovod_tpu.DistributedOptimizer` built
    without explicit ``axes`` resolves them to ALL mesh axes at trace
    time, which on a TP/pipeline mesh would sum gradients of DIFFERENT
    parameter shards -- silently wrong math, so it fails the build."""
    if not m_ax:
        return
    upd = getattr(optimizer, "update", None)
    if not getattr(upd, "_hvd_allreduce", False):
        return  # bare optimizer: the step emits no exchange for it
    if _dist.is_ef_optimizer(optimizer):
        raise NotImplementedError(
            "error-feedback codecs (powersgd/topk) do not yet compose "
            "with tp/pipeline_stages: the residual carry is planned from "
            "the global parameter shapes, not the TP-local shards.  Use "
            "fp16/bf16 (or per-leg ici:...,dcn:fp16) compression on the "
            "DP leg instead")
    ex = getattr(upd, "_hvd_exchange", None)
    ax = ex.get("axes") if ex is not None else None
    ax = tuple((ax,) if isinstance(ax, str) else ax) if ax is not None \
        else None
    if ax != tuple(d_ax):
        raise ValueError(
            f"DistributedOptimizer on a model-parallel mesh must be built "
            f"with axes={tuple(d_ax)} (the data axes) so the gradient "
            f"exchange never reduces over the model axes {tuple(m_ax)}; "
            f"got axes={ax!r}")


def _microbatch_unwrap(optimizer):
    """Decompose an optimizer for the microbatched exchange.

    Returns ``(inner, exchange)``: the unwrapped optax optimizer plus the
    exchange parameters a :func:`~horovod_tpu.DistributedOptimizer` wrap
    would have applied (``None`` for a bare optimizer -- local microbatch
    accumulation only, no collective, matching what the bare single-shot
    step does).  The microbatched step must run the exchange itself --
    per-microbatch bucket reduce-scatter, one closing allgather -- so a
    wrapped optimizer's in-update allreduce cannot be reused: it would
    exchange every microbatch's full gradient (k times the wire traffic)
    with no overlap ordering.
    """
    upd = optimizer.update
    if not getattr(upd, "_hvd_allreduce", False):
        return optimizer, None
    if not hasattr(upd, "_hvd_inner"):
        raise ValueError(
            "microbatches > 1 cannot combine with "
            "backward_passes_per_step > 1 (both are gradient-accumulation "
            "schemes; pick one)")
    exchange = dict(upd._hvd_exchange)
    if exchange["process_set"] is not None:
        raise NotImplementedError(
            "microbatches > 1 does not support process-set reductions "
            "(the scatter-based exchange has no masked identity)")
    if exchange["op"] not in (Sum, Average):
        raise ValueError(
            "microbatches > 1 supports Sum/Average reductions only, got "
            f"{exchange['op']!r} (Adasum composes through "
            "DistributedAdasumOptimizer without microbatching)")
    from .collectives.compression import is_fp8
    if is_fp8(exchange["compression"]):
        raise NotImplementedError(
            "microbatches > 1 does not support Compression.fp8 (the "
            "quantized exchange owns its own collective); use fp16/bf16")
    # Error-feedback codecs (powersgd/topk) DO compose: the microbatched
    # step accumulates sub-batch gradients locally in f32 and runs ONE
    # residual-fed exchange per step (_build_microbatch_local_step), so
    # the residual is applied once per optimizer step, never per
    # microbatch.
    return upd._hvd_inner, exchange


def _is_ef_exchange(exchange) -> bool:
    """True when a microbatch exchange dict carries an error-feedback codec
    (powersgd/topk): the builders then accumulate locally and run ONE
    residual-fed exchange per step instead of the per-microbatch
    reduce-scatter pipe."""
    from .collectives.compression import is_error_feedback
    return is_error_feedback(exchange["compression"])


def _resolve_guard() -> Tuple[bool, float]:
    """``(guard_on, norm_limit)`` from ``HOROVOD_GUARD`` (core/guard.py).

    Resolved at step-BUILD time: the screen is part of the traced
    program.  ``auto`` (default) arms only when chaos injection or the
    desync/snapshot planes are active, so default builds stay bitwise
    identical to the unguarded trace."""
    from .core import guard as _guard
    return _guard.step_guard()


def _note_guard_leg():
    """Trace-time registration of the SDC screen's one extra psum: the
    leg row comes from the shared exchange-plan IR ("guard" family)."""
    from .controller import fusion as _fusion
    from .timeline import spans as _spans
    _spans.note_leg(_fusion.plan_exchange("guard").legs[0])


def _guard_screen_vec(grads):
    """Local half of the SDC screen: ``[nonfinite_count, sq_sum]`` f32[2].

    Summed across ranks with ONE extra psum (float32 on purpose: the
    audit fence flags scalar int32 psums as barrier-shaped).  The norm
    half is a magnitude SCREEN (sqrt of the global sum of local squared
    norms), not the exact norm of the averaged gradient -- it saturates
    to inf for |g| beyond ~1e19, which the policy treats as poisoned."""
    nonf = jnp.zeros((), jnp.float32)
    sq = jnp.zeros((), jnp.float32)
    for g in jax.tree.leaves(grads):
        if jnp.issubdtype(g.dtype, jnp.inexact):
            g32 = g.astype(jnp.float32)
            nonf = nonf + jnp.sum(~jnp.isfinite(g32)).astype(jnp.float32)
            sq = sq + jnp.sum(jnp.square(g32))
        # Integer leaves are always finite and carry no norm.
    return jnp.stack([nonf, sq])


def _guard_verdict(gvec, norm_limit):
    """``(nonfinite, norm, bad)`` from the psum'd screen vector."""
    nonfinite = gvec[0]
    norm = jnp.sqrt(gvec[1])
    bad = (nonfinite > 0) | ~jnp.isfinite(norm)
    if norm_limit and norm_limit > 0:
        bad = bad | (norm > norm_limit)
    return nonfinite, norm, bad


def _guard_select(bad, old_tree, new_tree):
    """Poisoned step -> keep the OLD tree wholesale (bitwise: params and
    EF residuals provably untouched -- the whole old carry is selected,
    not recomputed)."""
    return jax.tree.map(lambda o, n: jnp.where(bad, o, n),
                        old_tree, new_tree)


def stack_steps(batches) -> Any:
    """Stack k per-step batches into the scanned layout ``make_train_loop``
    consumes: each leaf gains a leading steps axis ``[k, batch, ...]``."""
    batches = list(batches)
    if not batches:
        raise ValueError("stack_steps needs at least one batch")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *batches)


def make_train_step(
    loss_fn: Callable[[Any, Any], jnp.ndarray],
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    donate: bool = True,
    loss_has_aux: bool = False,
    aux_mode: str = "stacked",
    with_frozen: bool = False,
    zero_stage: Optional[int] = None,
    zero_compression=None,
    microbatches: Optional[int] = None,
    tp: Optional[int] = None,
    pipeline_stages: Optional[int] = None,
    param_specs=None,
    opt_state_specs=None,
) -> Callable[[Any, Any, Any], Tuple[Any, Any, jnp.ndarray]]:
    """Build ``step(params, opt_state, batch) -> (params, opt_state, loss)``.

    ``loss_fn(params, local_batch)`` is evaluated on each device's batch
    shard; gradients flow through ``optimizer`` (wrap it with
    :func:`horovod_tpu.DistributedOptimizer` for the fused allreduce) and
    the returned loss is the global mean.

    With ``loss_has_aux``, ``loss_fn`` returns ``(loss, aux)``.
    ``aux_mode`` controls how aux crosses the mesh: ``"stacked"`` returns
    the per-device values stacked on a leading axis; ``"averaged"``
    mean-allreduces every aux leaf and returns it replicated -- use this
    for mutated model state such as BatchNorm running statistics (the
    cross-device averaging mirrors the reference's SyncBatchNorm stats
    exchange, ``horovod/torch/sync_batch_norm.py``).

    With ``with_frozen``, ``loss_fn(params, frozen, local_batch)`` and the
    step takes a fourth argument: ``step(params, opt_state, batch,
    frozen)``.  The frozen tree is replicated, NOT donated, and never
    differentiated -- gradients, the fused allreduce, and optimizer state
    span only ``params``.  This is the LoRA/adapter layout (e.g. an int8
    frozen Llama base with trainable adapters, ``models.split_frozen``).

    With ``zero_stage=1`` (default from ``HOROVOD_ZERO``) the optimizer
    state is sharded across the mesh (ZeRO-1,
    :mod:`horovod_tpu.optim.zero`): gradients are reduce-scattered, each
    chip updates its 1/n arena slice, and updated params ride an
    allgather optionally compressed via ``zero_compression``
    (``hvd.Compression.{fp16,bf16,fp8}``).  Pass the BARE optax optimizer
    (no :func:`~horovod_tpu.DistributedOptimizer` wrap) and build
    ``opt_state`` with :func:`horovod_tpu.zero_init`.

    With ``microbatches=k > 1`` (default from ``HOROVOD_MICROBATCHES``)
    the per-step batch splits into k sub-batches inside ONE executable:
    each sub-batch's gradient buckets reduce-scatter the moment its
    backward segment finishes, overlapping wire time with the next
    sub-batch's backward compute (the reference's headline
    backward-overlap, expressed as schedulable HLO).  Same optimizer
    trajectory as single-shot at the same global batch, up to documented
    accumulation-order tolerance (f32 cross-microbatch sum; bitwise at
    k=1, which is exactly the single-shot path).  Requires a
    per-example-mean loss, a local batch divisible by k, and is
    incompatible with ``zero_stage=1``, Adasum, fp8 compression, process
    sets, and ``backward_passes_per_step > 1``.

    With ``tp=t > 1`` / ``pipeline_stages=s > 1`` (defaults from
    ``HOROVOD_TP`` / ``HOROVOD_PIPELINE_STAGES``) the step runs 3-D
    parallel over a :func:`~horovod_tpu.parallel.build_3d_mesh` mesh:
    the gradient exchange, ZeRO-1 arena, microbatch overlap and loss
    average all run over the mesh's DATA axes only (``("dcn", "data")``
    when DCN splits the data axis -- the DP leg then rides the
    hierarchical ICI x DCN exchange -- else ``("data",)``), while
    ``loss_fn`` computes with TP collectives on the ``model`` axis
    (:mod:`horovod_tpu.parallel.tp`) and pipeline ``ppermute`` on
    ``pipe`` (:func:`~horovod_tpu.parallel.pipeline_apply`).  Pass
    ``param_specs``: a pytree (prefix) of ``PartitionSpec``s placing the
    stacked TP/stage parameter leaves, e.g. ``P("model")`` on a
    ``[tp, d, f/tp]`` column-stacked kernel or ``P("pipe")`` on
    ``[s, ...]`` stage-stacked leaves (each leaf arrives in ``loss_fn``
    with those leading axes of LOCAL extent 1).  A
    :func:`~horovod_tpu.DistributedOptimizer` must then be built with
    ``axes=<data axes>``; ``zero_stage=1`` needs ``zero_init(...,
    param_specs=...)`` so each device's arena holds its own TP shard.
    """
    if aux_mode not in ("stacked", "averaged"):
        raise ValueError(f"unknown aux_mode {aux_mode!r}")
    zero_stage = _resolve_zero_stage(zero_stage)
    k_micro = _resolve_microbatches(microbatches)
    if zero_stage:
        if k_micro > 1:
            raise ValueError(
                "microbatches > 1 is incompatible with zero_stage=1 (the "
                "ZeRO-1 arena reduce-scatter is already shard-based; "
                "overlap it via HOROVOD_EXCHANGE_CHUNK_MB instead)")
        _zero._reject_distributed(optimizer)
    mesh = mesh or _basics.mesh()
    tp = _resolve_tp(tp)
    pipeline_stages = _resolve_pipeline_stages(pipeline_stages)
    axes, model_ax = _resolve_model_axes(mesh, tp, pipeline_stages)
    _check_model_parallel_exchange(optimizer, axes, model_ax)
    guard_on, guard_limit = _resolve_guard()
    if k_micro > 1:
        inner, exchange = _microbatch_unwrap(optimizer)
        local_step = _build_microbatch_local_step(
            loss_fn, inner, exchange, axes, loss_has_aux, aux_mode,
            with_frozen, k_micro, guard=guard_on,
            guard_norm_limit=guard_limit,
            guard_axes=tuple(mesh.axis_names))
    else:
        local_step = _build_local_step(loss_fn, optimizer, axes,
                                       loss_has_aux, aux_mode, with_frozen,
                                       zero_stage, zero_compression,
                                       guard=guard_on,
                                       guard_norm_limit=guard_limit,
                                       guard_axes=tuple(mesh.axis_names))

    aux_spec = () if not loss_has_aux else \
        ((P(),) if aux_mode == "averaged" else (P(axes),))
    guard_spec = (P(),) if guard_on else ()
    frozen_spec = (P(),) if with_frozen else ()
    p_spec = param_specs if param_specs is not None else P()
    opt_spec = _opt_state_spec(optimizer, zero_stage,
                               tuple(mesh.axis_names),
                               override=opt_state_specs)
    shard = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(p_spec, opt_spec, P(axes)) + frozen_spec,
        out_specs=(p_spec, opt_spec, P()) + aux_spec + guard_spec,
        check_vma=False)
    donate_argnums = (0, 1) if donate else ()

    meta = {"optimizer": optimizer,
            "zero_stage": zero_stage,
            "zero_compression": zero_compression,
            "microbatches": k_micro,
            "guard": guard_on,
            "tp": tp,
            "pipeline_stages": pipeline_stages,
            "data_mesh": tuple(int(mesh.shape[a]) for a in axes),
            "data_axes": tuple(str(a) for a in axes),
            "mesh_shape": tuple((a, int(mesh.shape[a]))
                                for a in mesh.axis_names),
            "param_specs": param_specs,
            "world": int(math.prod(mesh.shape[a] for a in axes))}
    step = _maybe_tuned(shard, donate_argnums, loss_index=2, meta=meta)
    return _GuardedStep(step, meta) if guard_on else step


def _build_local_step(loss_fn, optimizer, axes, loss_has_aux, aux_mode,
                      with_frozen, zero_stage, zero_compression,
                      guard=False, guard_norm_limit=0.0, guard_axes=None):
    """The per-device step body shared by :func:`make_train_step` (one
    shard_map call) and :func:`make_train_loop` (the ``lax.scan`` body).
    Sharing the exact closure is what makes the k-step loop bitwise
    identical to k sequential step calls.

    With ``guard`` the SDC screen psums the raw LOCAL gradients' nonfinite
    count and squared norm (one extra f32[2] psum, before any exchange or
    update) and a poisoned step selects the OLD params/opt-state carry
    wholesale; the step then emits a trailing replicated ``f32[3]``
    ``[nonfinite, grad_norm, skipped]`` vector for the host policy.

    ``guard_axes`` (default ``axes``) is the screen's psum domain: on a
    model-parallel mesh it spans ALL mesh axes -- TP shards partition the
    gradient, so only the full-mesh sum gives every rank the same verdict
    (a data-axes-only sum would diverge across TP ranks and fork the
    carry)."""
    g_axes = tuple(guard_axes) if guard_axes is not None else axes

    def local_step(params, opt_state, batch, *frozen):
        lf = (lambda p, b: loss_fn(p, frozen[0], b)) if with_frozen \
            else loss_fn
        if loss_has_aux:
            (loss, aux), grads = jax.value_and_grad(
                lf, has_aux=True)(params, batch)
        else:
            loss, grads = jax.value_and_grad(lf)(params, batch)
            aux = None
        if guard:
            old_params, old_opt = params, opt_state
            _note_guard_leg()
            gvec = _ops.allreduce(_guard_screen_vec(grads), Sum,
                                  axes=g_axes)
        if zero_stage:
            params, opt_state = _zero.zero_apply(
                optimizer, grads, opt_state, params, axes=axes,
                compression=zero_compression)
        else:
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        if guard:
            nonfinite, norm, bad = _guard_verdict(gvec, guard_norm_limit)
            params = _guard_select(bad, old_params, params)
            opt_state = _guard_select(bad, old_opt, opt_state)
            guard_out = jnp.stack([nonfinite, norm,
                                   bad.astype(jnp.float32)])
        loss = _ops.allreduce(loss, Average, axes=axes)
        out = (params, opt_state, loss)
        if loss_has_aux:
            if aux_mode == "averaged":
                aux = jax.tree.map(
                    lambda v: _ops.allreduce(v, Average, axes=axes)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v, aux)
            out = out + (aux,)
        if guard:
            out = out + (guard_out,)
        return out

    return local_step


def _microbatch_grad_pipe(exchange, axes, k=1):
    """Build ``(accumulate, finalize)`` for the backward-overlap exchange.

    ``accumulate(grads, state)`` is called once per microbatch, right after
    that microbatch's backward pass: it packs the gradients into fusion
    buckets in READY order (``plan_buckets(reverse=True)`` -- last layers'
    gradients finish first) and emits one tiled ``psum_scatter`` per bucket
    IMMEDIATELY, so the collective for microbatch i is independent of (and
    schedulable under) the backward compute of microbatch i+1.  Shards
    accumulate in float32 across microbatches.  ``finalize(state, k,
    grads)`` scales the accumulated shards (1/k; 1/n for Average;
    postscale) and closes with ONE allgather per bucket.

    Wire accounting: k reduce-scatters + 1 allgather of the
    ``lcm(n, 256)``-padded bucket move an equivalent-allreduce payload of
    ``(k+1)/2`` buckets -- the overlap costs extra bytes but each piece
    rides under compute (``bench_scaling.py`` rn50-overlap gates the exact
    number).  Numerics: the cross-rank reduce runs in the wire dtype like
    the single-shot path, but the cross-MICROBATCH sum runs in f32 and the
    Average divide happens once at the end, so k>1 matches single-shot to
    accumulation-order tolerance, not bitwise (see ``make_train_step``).

    ``exchange=None`` (bare optimizer, no DistributedOptimizer wrap) does
    local f32 accumulation only -- no collective, matching the bare
    single-shot step.
    """
    from .controller.fusion import pack, plan_buckets, unpack

    if exchange is None:
        def accumulate(grads, state):
            g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
            if state is None:
                return g32
            return jax.tree.map(jnp.add, state, g32)

        def finalize(state, k, grads_like):
            return jax.tree.map(lambda a, g: (a / k).astype(g.dtype),
                                state, grads_like)

        return accumulate, finalize

    compression = exchange["compression"]
    threshold = exchange["fusion_threshold"]
    pre = exchange["prescale_factor"]
    post = exchange["postscale_factor"]

    def _plan(bufspec, n):
        # One memoized plan-IR lookup shared by accumulate/finalize (and
        # by stepmodel's expected multiset): rs rows first, then ag.
        from .controller import fusion as _fusion
        legs = _fusion.plan_exchange(
            "microbatch",
            buffers=tuple((dt, sum(s.size for s in lspecs))
                          for dt, lspecs in bufspec),
            k=int(k), world=int(n), compression=compression).legs
        return legs[:len(bufspec)], legs[len(bufspec):]

    def accumulate(grads, state):
        leaves = jax.tree.leaves(grads)
        spec = plan_buckets(leaves, threshold, reverse=True)
        bufs = pack(leaves, spec)
        n = _ops.axis_size(axes)
        q = _ops.microbatch_pad_quantum(n)
        from .timeline import spans as _spans
        rs_legs, _ag = _plan(spec.buffers, n)
        shards = []
        for i, buf in enumerate(bufs):
            c, ctx = compression.compress(buf)
            if pre != 1.0:
                c = c * jnp.asarray(pre, dtype=c.dtype)
            # Trace-time leg registration (once per trace): the overlap
            # RS leg's planned wire bytes per bucket, for straggler
            # attribution (noted once per microbatch).
            _spans.note_leg(rs_legs[i], bucket_id=i)
            shard = _ops.psum_scatter_bucket(c, axes=axes, quantum=q)
            shards.append(
                compression.decompress(shard, ctx).astype(jnp.float32))
        if state is None:
            return shards
        return [a + s for a, s in zip(state, shards)]

    def finalize(state, k, grads_like):
        leaves, treedef = jax.tree.flatten(grads_like)
        spec = plan_buckets(leaves, threshold, reverse=True)
        n = _ops.axis_size(axes)
        scale = 1.0 / k
        if exchange["op"] is Average:
            scale = scale / n
        from .timeline import spans as _spans
        _rs, ag_legs = _plan(spec.buffers, n)
        out = []
        for i, (shard, (dt, lspecs)) in enumerate(
                zip(state, spec.buffers)):
            shard = shard * scale
            if post != 1.0:
                shard = shard * post
            shard = shard.astype(dt)
            c2, ctx2 = compression.compress(shard)
            size = sum(s.size for s in lspecs)
            _spans.note_leg(ag_legs[i], bucket_id=i)
            full = _ops.allgather_bucket(c2, size, axes=axes)
            out.append(compression.decompress(full, ctx2))
        return jax.tree.unflatten(treedef, unpack(out, spec))

    return accumulate, finalize


def _split_microbatches(tree, k):
    """Reshape each leaf's leading (local-batch) dim into ``[k, b/k, ...]``
    contiguous sub-batches.  Shapes are static at trace time, so a
    non-divisible batch fails the build, not the run."""
    def split(leaf):
        b0 = leaf.shape[0] if leaf.ndim else 0
        if b0 % k:
            raise ValueError(
                f"microbatches={k} must divide the per-device batch "
                f"(got leading dim {b0}); pad or resize the batch")
        return leaf.reshape((k, b0 // k) + leaf.shape[1:])

    return jax.tree.map(split, tree)


def _build_microbatch_local_step(loss_fn, inner, exchange, axes,
                                 loss_has_aux, aux_mode, with_frozen, k,
                                 guard=False, guard_norm_limit=0.0,
                                 guard_axes=None):
    """Per-device step body for ``microbatches=k > 1``: an UNROLLED loop
    over k sub-batches whose trace interleaves each microbatch's bucket
    reduce-scatters between backward segments (the HLO-structure the
    overlap test asserts), one optimizer update on the merged gradients.

    Equivalence contract: with a per-example-MEAN loss (the usual
    ``.mean()`` losses; what the parity tests use), the mean of the k
    sub-batch gradients equals the full-batch gradient, so k>1 matches the
    single-shot step at the same global batch to accumulation-order
    tolerance.  A per-example-SUM loss would need ``prescale_factor=k`` --
    same caveat as any gradient-accumulation scheme.  ``aux_mode
    "stacked"`` gains a leading ``[k]`` axis per device; ``"averaged"``
    averages floating aux leaves over microbatches before the allreduce.
    """
    ef = exchange is not None and _is_ef_exchange(exchange)
    accumulate, finalize = _microbatch_grad_pipe(
        None if ef else exchange, axes, k=k)
    g_axes = tuple(guard_axes) if guard_axes is not None else axes

    def local_step(params, opt_state, batch, *frozen):
        lf = (lambda p, b: loss_fn(p, frozen[0], b)) if with_frozen \
            else loss_fn
        micro = _split_microbatches(batch, k)
        if ef:
            if not isinstance(opt_state, _dist._EFState):
                opt_state = _dist._EFState(*opt_state)
            residuals = tuple(r[0] for r in opt_state.residuals)
            inner_state = opt_state.inner
        else:
            inner_state = opt_state
        state, losses, auxes, grads = None, [], [], None
        for i in range(k):
            mb = jax.tree.map(lambda a: a[i], micro)
            if loss_has_aux:
                (loss_i, aux_i), grads = jax.value_and_grad(
                    lf, has_aux=True)(params, mb)
                auxes.append(aux_i)
            else:
                loss_i, grads = jax.value_and_grad(lf)(params, mb)
            losses.append(loss_i)
            state = accumulate(grads, state)
        reduced = finalize(state, k, grads)
        if guard:
            # Screen the merged gradient (already cross-rank for a wrapped
            # exchange): nonfinite sub-batch contributions have propagated
            # into it by now, and screening BEFORE ef_exchange/update means
            # the skip select below discards the residuals a poisoned
            # exchange would have produced.
            # opt_state here is still the incoming carry (normalized to
            # _EFState on the ef path), structure-matched to the new one.
            old_params, old_opt = params, opt_state
            _note_guard_leg()
            gvec = _ops.allreduce(_guard_screen_vec(reduced), Sum,
                                  axes=g_axes)
        if ef:
            reduced, new_res = _dist.ef_exchange(
                reduced, residuals, compression=exchange["compression"],
                op=exchange["op"],
                fusion_threshold=exchange["fusion_threshold"], axes=axes,
                prescale_factor=exchange["prescale_factor"],
                postscale_factor=exchange["postscale_factor"])
        updates, inner_state = inner.update(reduced, inner_state, params)
        opt_state = _dist._EFState(
            tuple(r[None] for r in new_res), inner_state) if ef \
            else inner_state
        params = optax.apply_updates(params, updates)
        if guard:
            nonfinite, norm, bad = _guard_verdict(gvec, guard_norm_limit)
            params = _guard_select(bad, old_params, params)
            opt_state = _guard_select(bad, old_opt, opt_state)
            guard_out = jnp.stack([nonfinite, norm,
                                   bad.astype(jnp.float32)])
        loss = _ops.allreduce(jnp.mean(jnp.stack(losses)), Average,
                              axes=axes)
        out = (params, opt_state, loss)
        if loss_has_aux:
            if aux_mode == "averaged":
                aux = jax.tree.map(
                    lambda *xs: jnp.mean(jnp.stack(xs), axis=0)
                    if jnp.issubdtype(xs[0].dtype, jnp.floating)
                    else xs[-1], *auxes)
                aux = jax.tree.map(
                    lambda v: _ops.allreduce(v, Average, axes=axes)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v, aux)
            else:
                aux = jax.tree.map(lambda *xs: jnp.stack(xs), *auxes)
            out = out + (aux,)
        if guard:
            out = out + (guard_out,)
        return out

    return local_step


def _build_flax_microbatch_local_step(apply_fn, inner, exchange, loss_fn,
                                      axes, k, guard=False,
                                      guard_norm_limit=0.0,
                                      guard_axes=None):
    """Flax counterpart of :func:`_build_microbatch_local_step`.

    BatchNorm note: batch statistics CHAIN through the k microbatches
    (microbatch i normalizes with the stats microbatch i-1 produced, and
    the running EMA advances k times per step) -- a real semantic
    difference from the single-shot step's one full-batch normalization,
    inherent to any microbatched BN.  Stats-free models match the
    single-shot step to accumulation tolerance; the final stats cross the
    mesh in the same one-allreduce-per-leaf exchange as single-shot.
    """
    if loss_fn is None:
        def loss_fn(logits, y):
            return _softmax_xent(logits, y)
    ef = exchange is not None and _is_ef_exchange(exchange)
    accumulate, finalize = _microbatch_grad_pipe(
        None if ef else exchange, axes, k=k)
    g_axes = tuple(guard_axes) if guard_axes is not None else axes

    def local_step(params, batch_stats, opt_state, batch):
        x, y = batch
        xs = _split_microbatches(x, k)
        ys = _split_microbatches(y, k)
        stats = batch_stats
        if ef:
            if not isinstance(opt_state, _dist._EFState):
                opt_state = _dist._EFState(*opt_state)
            residuals = tuple(r[0] for r in opt_state.residuals)
            inner_state = opt_state.inner
        else:
            inner_state = opt_state
        state, losses, grads = None, [], None
        for i in range(k):
            xi = jax.tree.map(lambda a: a[i], xs)
            yi = jax.tree.map(lambda a: a[i], ys)

            def lf(p, stats=stats, xi=xi, yi=yi):
                variables = {"params": p}
                if stats:
                    variables["batch_stats"] = stats
                    logits, mutated = apply_fn(variables, xi, train=True,
                                               mutable=["batch_stats"])
                    return (loss_fn(logits, yi),
                            mutated.get("batch_stats", {}))
                logits = apply_fn(variables, xi, train=True)
                return loss_fn(logits, yi), {}

            (loss_i, stats), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            losses.append(loss_i)
            state = accumulate(grads, state)
        reduced = finalize(state, k, grads)
        if guard:
            old_params, old_opt = params, opt_state
            _note_guard_leg()
            gvec = _ops.allreduce(_guard_screen_vec(reduced), Sum,
                                  axes=g_axes)
        if ef:
            reduced, new_res = _dist.ef_exchange(
                reduced, residuals, compression=exchange["compression"],
                op=exchange["op"],
                fusion_threshold=exchange["fusion_threshold"], axes=axes,
                prescale_factor=exchange["prescale_factor"],
                postscale_factor=exchange["postscale_factor"])
        updates, inner_state = inner.update(reduced, inner_state, params)
        opt_state = _dist._EFState(
            tuple(r[None] for r in new_res), inner_state) if ef \
            else inner_state
        params = optax.apply_updates(params, updates)
        new_stats = jax.tree.map(
            lambda v: _ops.allreduce(v, Average, axes=axes), stats)
        loss = _ops.allreduce(jnp.mean(jnp.stack(losses)), Average,
                              axes=axes)
        if guard:
            nonfinite, norm, bad = _guard_verdict(gvec, guard_norm_limit)
            params = _guard_select(bad, old_params, params)
            opt_state = _guard_select(bad, old_opt, opt_state)
            new_stats = _guard_select(bad, batch_stats, new_stats)
            guard_out = jnp.stack([nonfinite, norm,
                                   bad.astype(jnp.float32)])
            return params, new_stats, opt_state, loss, guard_out
        return params, new_stats, opt_state, loss

    return local_step


def make_train_loop(
    loss_fn: Callable[[Any, Any], jnp.ndarray],
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    steps_per_execution: Optional[int] = None,
    donate: bool = True,
    loss_has_aux: bool = False,
    aux_mode: str = "stacked",
    with_frozen: bool = False,
    zero_stage: Optional[int] = None,
    zero_compression=None,
    microbatches: Optional[int] = None,
    tp: Optional[int] = None,
    pipeline_stages: Optional[int] = None,
    param_specs=None,
    opt_state_specs=None,
) -> Callable[[Any, Any, Any], Tuple[Any, Any, jnp.ndarray]]:
    """Steps-per-execution runner: k train steps as ONE executable.

    Builds ``loop(params, opt_state, batches) -> (params, opt_state,
    losses)`` where ``batches`` stacks k per-step batches on a leading
    axis (``[k, global_batch, ...]`` per leaf -- :func:`stack_steps`, or
    :class:`horovod_tpu.data.DevicePrefetcher` with ``stack_steps=k``)
    and ``losses`` is the ``[k]`` per-step global-mean loss history.

    The k steps run inside one ``jax.lax.scan`` with the params/opt-state
    carry donated, so a whole window costs ONE host dispatch and ONE
    device->host fence instead of k of each -- the reference hides that
    host overhead behind its background thread; under XLA the loop simply
    never returns to the host.  The step body is byte-for-byte the
    :func:`make_train_step` body, so k scanned steps match k sequential
    step calls bitwise.

    ``steps_per_execution=None`` reads ``HOROVOD_STEPS_PER_EXEC``
    (autotuner steps axis wins when active -- see
    :func:`steps_per_execution`).  All other knobs (``loss_has_aux``,
    ``aux_mode``, ``with_frozen``, ``zero_stage``,  ``microbatches``...)
    behave as in :func:`make_train_step`; stacked aux gains a leading k
    axis.  ``microbatches > 1`` microbatches EACH scanned step (the two
    k's compose: steps_per_execution batches dispatches, microbatches
    overlaps the exchange inside every step).
    """
    if aux_mode not in ("stacked", "averaged"):
        raise ValueError(f"unknown aux_mode {aux_mode!r}")
    zero_stage = _resolve_zero_stage(zero_stage)
    k_micro = _resolve_microbatches(microbatches)
    if zero_stage:
        if k_micro > 1:
            raise ValueError(
                "microbatches > 1 is incompatible with zero_stage=1 (the "
                "ZeRO-1 arena reduce-scatter is already shard-based; "
                "overlap it via HOROVOD_EXCHANGE_CHUNK_MB instead)")
        _zero._reject_distributed(optimizer)
    mesh = mesh or _basics.mesh()
    tp = _resolve_tp(tp)
    pipeline_stages = _resolve_pipeline_stages(pipeline_stages)
    axes, model_ax = _resolve_model_axes(mesh, tp, pipeline_stages)
    _check_model_parallel_exchange(optimizer, axes, model_ax)
    k = _resolve_steps(steps_per_execution)
    guard_on, guard_limit = _resolve_guard()
    if k_micro > 1:
        inner, exchange = _microbatch_unwrap(optimizer)
        local_step = _build_microbatch_local_step(
            loss_fn, inner, exchange, axes, loss_has_aux, aux_mode,
            with_frozen, k_micro, guard=guard_on,
            guard_norm_limit=guard_limit,
            guard_axes=tuple(mesh.axis_names))
    else:
        local_step = _build_local_step(loss_fn, optimizer, axes,
                                       loss_has_aux, aux_mode, with_frozen,
                                       zero_stage, zero_compression,
                                       guard=guard_on,
                                       guard_norm_limit=guard_limit,
                                       guard_axes=tuple(mesh.axis_names))

    def local_loop(params, opt_state, batches, *frozen):
        def body(carry, batch):
            out = local_step(carry[0], carry[1], batch, *frozen)
            # Trailing outputs (loss[, aux][, guard]) stack on a leading
            # [k] axis; with guard the history is [k, 3] so the host
            # policy sees every scanned step, not just the last.
            return (out[0], out[1]), tuple(out[2:])

        (params, opt_state), ys = jax.lax.scan(
            body, (params, opt_state), batches, length=k)
        return (params, opt_state) + tuple(ys)

    # Batch leaves carry a leading steps axis: dim 0 scans, dim 1 shards.
    aux_spec = () if not loss_has_aux else \
        ((P(),) if aux_mode == "averaged" else (P(None, axes),))
    guard_spec = (P(),) if guard_on else ()
    frozen_spec = (P(),) if with_frozen else ()
    p_spec = param_specs if param_specs is not None else P()
    opt_spec = _opt_state_spec(optimizer, zero_stage,
                               tuple(mesh.axis_names),
                               override=opt_state_specs)
    shard = jax.shard_map(
        local_loop, mesh=mesh,
        in_specs=(p_spec, opt_spec, P(None, axes)) + frozen_spec,
        out_specs=(p_spec, opt_spec, P()) + aux_spec + guard_spec,
        check_vma=False)
    donate_argnums = (0, 1) if donate else ()

    meta = {"optimizer": optimizer,
            "zero_stage": zero_stage,
            "zero_compression": zero_compression,
            "microbatches": k_micro,
            "guard": guard_on,
            "tp": tp,
            "pipeline_stages": pipeline_stages,
            "data_mesh": tuple(int(mesh.shape[a]) for a in axes),
            "data_axes": tuple(str(a) for a in axes),
            "mesh_shape": tuple((a, int(mesh.shape[a]))
                                for a in mesh.axis_names),
            "param_specs": param_specs,
            "world": int(math.prod(mesh.shape[a] for a in axes))}
    step = _maybe_tuned(shard, donate_argnums, loss_index=2, steps=k,
                        meta=meta)
    return _GuardedStep(step, meta) if guard_on else step


def _maybe_tuned(shard, donate_argnums, loss_index: int, steps: int = 1,
                 meta: Optional[dict] = None):
    """jit the sharded step; under HOROVOD_AUTOTUNE=1 wrap it in the
    ParameterManager score loop.

    The fusion threshold is read at trace time, so each candidate needs
    its own trace -- one compiled step per trace key, observed step time
    fed back to the tuner (the reference's score loop, minus the
    background thread).  The timing fence is a VALUE FETCH of the loss,
    not ``block_until_ready``: on the tunnelled TPU the latter can return
    before execution completes (measured; see bench.py) -- the fetch adds
    a constant per-step latency that cancels in the per-config ranking.

    ``steps`` is the scan-loop steps-per-execution: one call of a k-step
    loop moves k steps' worth of gradient bytes, so the bytes/sec score
    stays comparable across loop shapes.

    ``meta`` is the builder's exchange description consumed by the
    StepReport instrumentation (optimizer, zero stage/codec, microbatch
    count, mesh size); the jitted step comes back wrapped in
    :class:`_InstrumentedStep` unless metrics are disabled.
    """
    from .core.state import global_state
    from .timeline import metrics as _metrics
    tuner = global_state().autotuner
    if tuner is None:
        fn = jax.jit(shard, donate_argnums=donate_argnums)
    else:
        import time as _time
        compiled = {}
        grad_nbytes = [0]

        def tuned_step(params, *rest):
            key = tuner.trace_key()  # every trace-time knob of this sample
            fn = compiled.get(key)
            if fn is None:
                fn = jax.jit(shard, donate_argnums=donate_argnums)
                compiled[key] = fn
            if tuner.done:
                return fn(params, *rest)
            if not grad_nbytes[0]:
                grad_nbytes[0] = sum(
                    x.size * x.dtype.itemsize
                    for x in jax.tree.leaves(params))
            t0 = _time.perf_counter()
            out = fn(params, *rest)
            float(jnp.asarray(out[loss_index]).ravel()[0])  # honest fence
            tuner.record_step(_time.perf_counter() - t0,
                              grad_nbytes[0] * steps)
            return out

        fn = tuned_step

    if not _metrics.registry().enabled:
        return fn
    return _InstrumentedStep(fn, steps, meta or {})


class _InstrumentedStep:
    """Host-side StepReport sampler around the jitted step.

    Times the DISPATCH of the underlying callable (no extra fence, no
    device work) and feeds the process-wide metrics registry a
    :class:`~horovod_tpu.timeline.metrics.StepReport` per call.  Every
    other attribute (``.lower``, AOT paths) delegates to the wrapped
    ``jax.jit`` object, and nothing is added INSIDE the traced program,
    so buffer donation and scan-loop bitwise parity are untouched.

    Exchange accounting is computed lazily from the first call's params
    (shape/dtype reads only -- before the donated buffers are consumed)
    and must match the existing bookkeeping byte-for-byte: the ZeRO-1
    path reuses ``zero_report`` and the compressed path reuses
    ``wire_payload_bytes`` over the exchange's own bucket plan, exactly
    as ``bench.py`` prices them.  A failure in the accounting degrades to
    zeros -- it must never break training.
    """

    def __init__(self, fn, steps: int, meta: dict):
        self._fn = fn
        self._steps = max(int(steps), 1)
        self._meta = meta
        self._accounting: Optional[Tuple[str, int, int]] = None
        self._step_count = 0
        # perf_counter at the previous call's return: the time until the
        # next call is the host dispatch gap (input pipeline, Python
        # glue, injected chaos delays) the span layer attributes.
        self._last_end: Optional[float] = None

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def _account(self, params) -> Tuple[str, int, int]:
        if self._accounting is None:
            try:
                self._accounting = _step_exchange_accounting(
                    params, self._meta)
            except Exception:
                self._accounting = ("unknown", 0, 0)
        return self._accounting

    def __call__(self, params, *rest):
        from .timeline import metrics as _metrics
        from .timeline import spans as _spans
        import time as _time
        reg = _metrics.registry()
        if not reg.enabled:
            return self._fn(params, *rest)
        codec, wire, raw = self._account(params)
        rec = _spans.recorder()
        step = self._step_count + self._steps
        rec.set_step(step)
        t0 = _time.perf_counter()
        t0_unix_us = _time.time() * 1e6
        gap = (t0 - self._last_end) if self._last_end is not None else 0.0
        if gap > 0:
            rec.add("dispatch_gap", gap, emit=True)
        with rec.span("dispatch", name="step"):
            out = self._fn(params, *rest)
        t1 = _time.perf_counter()
        wall = t1 - t0
        self._last_end = t1
        self._step_count += self._steps
        try:
            _metrics.record_step_report(_metrics.StepReport(
                step=self._step_count,
                wall_time_s=wall,
                steps_per_exec=self._steps,
                microbatches=int(self._meta.get("microbatches", 1)),
                zero_stage=int(self._meta.get("zero_stage", 0)),
                codec=codec,
                exchanged_bytes=wire,
                uncompressed_bytes=raw))
        except Exception:
            pass
        try:
            # Step summary wall INCLUDES the dispatch gap (a late host
            # is a late rank); the wall-clock anchor backs up to the
            # gap's start so merged traces show the full step extent.
            rec.step_boundary(step, wall + gap,
                              t0_unix_us=t0_unix_us - gap * 1e6)
        except Exception:
            pass
        return out


class _GuardedStep:
    """Host-side SDC policy around a guarded step.

    The guarded trace appends a trailing replicated ``f32[3]`` guard
    vector (``[k, 3]`` for a scan loop); this wrapper strips it from the
    outputs -- callers see exactly the unguarded signature -- and feeds
    it to :func:`horovod_tpu.core.guard.policy`, which counts the
    ``horovod_guard_*`` metrics and raises
    :class:`~horovod_tpu.core.exceptions.SustainedAnomalyError` when a
    skip streak reaches ``HOROVOD_GUARD_STREAK``.  The fetch of the tiny
    guard vector is the guard's only host cost (it does fence the step;
    that is the price of a same-step verdict).  Attribute access
    delegates to the wrapped step (``.lower``, ``._meta``, AOT paths).
    """

    def __init__(self, fn, meta: dict):
        self._fn = fn
        self._meta = meta

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __call__(self, *args):
        out = self._fn(*args)
        from .core import guard as _guard
        import numpy as np
        _guard.policy().observe(np.asarray(out[-1]))
        return out[:-1]


def _step_exchange_accounting(params, meta) -> Tuple[str, int, int]:
    """``(codec, wire_bytes_per_step, uncompressed_bytes_per_step)`` for
    the exchange a step built with ``meta`` emits, per chip per optimizer
    step.

    ZeRO-1: ``zero_report``'s ``zero1_exchanged_bytes_per_chip`` against
    its ``replicated_allreduce_bytes_per_chip`` equivalent (so the
    implied ratio matches bench.py's zero compression entry).
    DistributedOptimizer wrap: ``wire_payload_bytes`` summed over the
    exchange's own bucket plan (``ef_bucket_plan`` for error-feedback
    codecs, ``plan_buckets`` otherwise) against the raw gradient bytes.
    Bare optimizer: no collective, wire 0.  The microbatch overlap factor
    is NOT folded in -- the figure is the equivalent single-exchange
    payload (see :class:`~horovod_tpu.timeline.metrics.StepReport`).
    """
    leaves = jax.tree.leaves(params)
    raw = sum(int(x.size) * jnp.dtype(x.dtype).itemsize for x in leaves)
    optimizer = meta.get("optimizer")
    if meta.get("zero_stage"):
        rep = _zero.zero_report(optimizer, params,
                                int(meta.get("world", 1)),
                                compression=meta.get("zero_compression"))
        comp = meta.get("zero_compression")
        codec = getattr(comp, "__name__", None) or \
            (str(comp) if comp else "none")
        return (codec, int(rep["zero1_exchanged_bytes_per_chip"]),
                int(rep["replicated_allreduce_bytes_per_chip"]))
    exchange = getattr(getattr(optimizer, "update", None),
                       "_hvd_exchange", None)
    if exchange is None:
        return ("none", 0, raw)
    from .collectives.compression import (is_error_feedback,
                                          wire_payload_bytes)
    comp = exchange["compression"]
    if is_error_feedback(comp):
        spec = _dist.ef_bucket_plan(leaves, exchange["fusion_threshold"],
                                    comp)
    else:
        from .controller.fusion import plan_buckets
        spec = plan_buckets(leaves, exchange["fusion_threshold"])
    wire = 0
    for dt, lspecs in spec.buffers:
        size = sum(s.size for s in lspecs)
        wire += wire_payload_bytes(comp, size, jnp.dtype(dt).itemsize)
    return (getattr(comp, "__name__", type(comp).__name__), int(wire), raw)


def make_flax_train_step(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    loss_fn: Optional[Callable] = None,
    mesh: Optional[Mesh] = None,
    donate: bool = True,
    zero_stage: Optional[int] = None,
    zero_compression=None,
    microbatches: Optional[int] = None,
    tp: Optional[int] = None,
    pipeline_stages: Optional[int] = None,
    param_specs=None,
    opt_state_specs=None,
):
    """Data-parallel train step for flax modules with mutable batch stats.

    Returns ``step(params, batch_stats, opt_state, (x, y)) ->
    (params, batch_stats, opt_state, loss)``.  BatchNorm running statistics
    are mean-allreduced each step (the reference's SyncBatchNorm stats
    exchange); gradients flow through ``optimizer`` (wrap with
    :func:`DistributedOptimizer`).  ``loss_fn(logits, y)`` defaults to
    softmax cross-entropy with integer labels.

    ``zero_stage=1`` shards the optimizer state as in
    :func:`make_train_step` (bare optax optimizer +
    :func:`horovod_tpu.zero_init` state); batch stats stay replicated.

    ``microbatches=k > 1`` (``HOROVOD_MICROBATCHES``) runs the
    backward-overlap exchange as in :func:`make_train_step`.  BatchNorm
    statistics chain through the k sub-batches (see
    :func:`_build_flax_microbatch_local_step` for the semantics).

    ``tp``/``pipeline_stages``/``param_specs`` behave as in
    :func:`make_train_step` (3-D parallelism over a ``build_3d_mesh``
    mesh; batch stats stay replicated).
    """
    zero_stage = _resolve_zero_stage(zero_stage)
    k_micro = _resolve_microbatches(microbatches)
    if zero_stage:
        if k_micro > 1:
            raise ValueError(
                "microbatches > 1 is incompatible with zero_stage=1 (the "
                "ZeRO-1 arena reduce-scatter is already shard-based; "
                "overlap it via HOROVOD_EXCHANGE_CHUNK_MB instead)")
        _zero._reject_distributed(optimizer)
    mesh = mesh or _basics.mesh()
    tp = _resolve_tp(tp)
    pipeline_stages = _resolve_pipeline_stages(pipeline_stages)
    axes, model_ax = _resolve_model_axes(mesh, tp, pipeline_stages)
    _check_model_parallel_exchange(optimizer, axes, model_ax)
    guard_on, guard_limit = _resolve_guard()
    if k_micro > 1:
        inner, exchange = _microbatch_unwrap(optimizer)
        local_step = _build_flax_microbatch_local_step(
            apply_fn, inner, exchange, loss_fn, axes, k_micro,
            guard=guard_on, guard_norm_limit=guard_limit,
            guard_axes=tuple(mesh.axis_names))
    else:
        local_step = _build_flax_local_step(apply_fn, optimizer, loss_fn,
                                            axes, zero_stage,
                                            zero_compression,
                                            guard=guard_on,
                                            guard_norm_limit=guard_limit,
                                            guard_axes=tuple(
                                                mesh.axis_names))

    guard_spec = (P(),) if guard_on else ()
    p_spec = param_specs if param_specs is not None else P()
    opt_spec = _opt_state_spec(optimizer, zero_stage,
                               tuple(mesh.axis_names),
                               override=opt_state_specs)
    shard = jax.shard_map(local_step, mesh=mesh,
                          in_specs=(p_spec, P(), opt_spec, P(axes)),
                          out_specs=(p_spec, P(), opt_spec, P())
                          + guard_spec,
                          check_vma=False)
    donate_argnums = (0, 1, 2) if donate else ()
    # Autotune applies here too (HOROVOD_AUTOTUNE=1): loss is element 3.
    meta = {"optimizer": optimizer,
            "zero_stage": zero_stage,
            "zero_compression": zero_compression,
            "microbatches": k_micro,
            "guard": guard_on,
            "tp": tp,
            "pipeline_stages": pipeline_stages,
            "data_mesh": tuple(int(mesh.shape[a]) for a in axes),
            "data_axes": tuple(str(a) for a in axes),
            "mesh_shape": tuple((a, int(mesh.shape[a]))
                                for a in mesh.axis_names),
            "param_specs": param_specs,
            "world": int(math.prod(mesh.shape[a] for a in axes))}
    step = _maybe_tuned(shard, donate_argnums, loss_index=3, meta=meta)
    return _GuardedStep(step, meta) if guard_on else step


def _build_flax_local_step(apply_fn, optimizer, loss_fn, axes, zero_stage,
                           zero_compression, guard=False,
                           guard_norm_limit=0.0, guard_axes=None):
    """Per-device flax step body shared by :func:`make_flax_train_step`
    and :func:`make_flax_train_loop` (bitwise parity, as with
    :func:`_build_local_step`).  The guard additionally pins the OLD
    batch stats on a poisoned step -- a NaN batch pollutes the BN running
    statistics as surely as it pollutes the gradients."""
    if loss_fn is None:
        def loss_fn(logits, y):
            return _softmax_xent(logits, y)
    g_axes = tuple(guard_axes) if guard_axes is not None else axes

    def local_step(params, batch_stats, opt_state, batch):
        x, y = batch

        def lf(p):
            variables = {"params": p}
            if batch_stats:
                variables["batch_stats"] = batch_stats
                logits, mutated = apply_fn(variables, x, train=True,
                                           mutable=["batch_stats"])
                return loss_fn(logits, y), mutated.get("batch_stats", {})
            logits = apply_fn(variables, x, train=True)
            return loss_fn(logits, y), {}

        (loss, new_stats), grads = jax.value_and_grad(lf, has_aux=True)(params)
        if guard:
            old_params, old_opt = params, opt_state
            _note_guard_leg()
            gvec = _ops.allreduce(_guard_screen_vec(grads), Sum,
                                  axes=g_axes)
        if zero_stage:
            params, opt_state = _zero.zero_apply(
                optimizer, grads, opt_state, params, axes=axes,
                compression=zero_compression)
        else:
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        new_stats = jax.tree.map(
            lambda v: _ops.allreduce(v, Average, axes=axes), new_stats)
        loss = _ops.allreduce(loss, Average, axes=axes)
        if guard:
            nonfinite, norm, bad = _guard_verdict(gvec, guard_norm_limit)
            params = _guard_select(bad, old_params, params)
            opt_state = _guard_select(bad, old_opt, opt_state)
            new_stats = _guard_select(bad, batch_stats, new_stats)
            guard_out = jnp.stack([nonfinite, norm,
                                   bad.astype(jnp.float32)])
            return params, new_stats, opt_state, loss, guard_out
        return params, new_stats, opt_state, loss

    return local_step


def make_flax_train_loop(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    loss_fn: Optional[Callable] = None,
    mesh: Optional[Mesh] = None,
    steps_per_execution: Optional[int] = None,
    donate: bool = True,
    zero_stage: Optional[int] = None,
    zero_compression=None,
    microbatches: Optional[int] = None,
    tp: Optional[int] = None,
    pipeline_stages: Optional[int] = None,
    param_specs=None,
    opt_state_specs=None,
):
    """Steps-per-execution runner for flax modules with batch stats.

    Returns ``loop(params, batch_stats, opt_state, batches) -> (params,
    batch_stats, opt_state, losses)``: the :func:`make_flax_train_step`
    body scanned k times in one executable (one dispatch, one fence),
    with the params/stats/opt-state carry donated.  ``batches`` stacks k
    ``(x, y)`` pairs on a leading axis (:func:`stack_steps`); ``losses``
    is the ``[k]`` per-step loss history.  See :func:`make_train_loop`.

    Note the flax carry includes batch stats only when non-empty: an
    empty-stats model scans the same body with an empty-dict carry leaf,
    exactly as the single step does.
    """
    zero_stage = _resolve_zero_stage(zero_stage)
    k_micro = _resolve_microbatches(microbatches)
    if zero_stage:
        if k_micro > 1:
            raise ValueError(
                "microbatches > 1 is incompatible with zero_stage=1 (the "
                "ZeRO-1 arena reduce-scatter is already shard-based; "
                "overlap it via HOROVOD_EXCHANGE_CHUNK_MB instead)")
        _zero._reject_distributed(optimizer)
    mesh = mesh or _basics.mesh()
    tp = _resolve_tp(tp)
    pipeline_stages = _resolve_pipeline_stages(pipeline_stages)
    axes, model_ax = _resolve_model_axes(mesh, tp, pipeline_stages)
    _check_model_parallel_exchange(optimizer, axes, model_ax)
    k = _resolve_steps(steps_per_execution)
    guard_on, guard_limit = _resolve_guard()
    if k_micro > 1:
        inner, exchange = _microbatch_unwrap(optimizer)
        local_step = _build_flax_microbatch_local_step(
            apply_fn, inner, exchange, loss_fn, axes, k_micro,
            guard=guard_on, guard_norm_limit=guard_limit,
            guard_axes=tuple(mesh.axis_names))
    else:
        local_step = _build_flax_local_step(apply_fn, optimizer, loss_fn,
                                            axes, zero_stage,
                                            zero_compression,
                                            guard=guard_on,
                                            guard_norm_limit=guard_limit,
                                            guard_axes=tuple(
                                                mesh.axis_names))

    def local_loop(params, batch_stats, opt_state, batches):
        def body(carry, batch):
            out = local_step(*carry, batch)
            return (out[0], out[1], out[2]), tuple(out[3:])

        (params, batch_stats, opt_state), ys = jax.lax.scan(
            body, (params, batch_stats, opt_state), batches, length=k)
        return (params, batch_stats, opt_state) + tuple(ys)

    guard_spec = (P(),) if guard_on else ()
    p_spec = param_specs if param_specs is not None else P()
    opt_spec = _opt_state_spec(optimizer, zero_stage,
                               tuple(mesh.axis_names),
                               override=opt_state_specs)
    shard = jax.shard_map(local_loop, mesh=mesh,
                          in_specs=(p_spec, P(), opt_spec, P(None, axes)),
                          out_specs=(p_spec, P(), opt_spec, P())
                          + guard_spec,
                          check_vma=False)
    donate_argnums = (0, 1, 2) if donate else ()
    meta = {"optimizer": optimizer,
            "zero_stage": zero_stage,
            "zero_compression": zero_compression,
            "microbatches": k_micro,
            "guard": guard_on,
            "tp": tp,
            "pipeline_stages": pipeline_stages,
            "data_mesh": tuple(int(mesh.shape[a]) for a in axes),
            "data_axes": tuple(str(a) for a in axes),
            "mesh_shape": tuple((a, int(mesh.shape[a]))
                                for a in mesh.axis_names),
            "param_specs": param_specs,
            "world": int(math.prod(mesh.shape[a] for a in axes))}
    step = _maybe_tuned(shard, donate_argnums, loss_index=3, steps=k,
                        meta=meta)
    return _GuardedStep(step, meta) if guard_on else step


def _softmax_xent(logits, y):
    import optax as _optax
    return _optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def make_eval_step(metric_fn: Callable[[Any, Any], Any],
                   mesh: Optional[Mesh] = None):
    """Build an eval step that averages ``metric_fn`` over the mesh."""
    mesh = mesh or _basics.mesh()
    axes = tuple(mesh.axis_names)

    def local_eval(params, batch):
        m = metric_fn(params, batch)
        return jax.tree.map(
            lambda v: _ops.allreduce(v, Average, axes=axes), m)

    shard = jax.shard_map(local_eval, mesh=mesh, in_specs=(P(), P(axes)),
                          out_specs=P(), check_vma=False)
    return jax.jit(shard)
