"""Alias module: ``import horovod_tpu.torch as hvd`` (reference-style name).

The implementation lives in ``horovod_tpu.torch_api`` (the package cannot
contain a subpackage literally named ``torch`` without shadowing the real
torch inside its own modules).
"""

import sys

from . import torch_api as _impl

sys.modules[__name__] = _impl
