// Native coordination core for the eager (framework-shim) path.
//
// TPU-native rebuild of the reference's C++ runtime pieces that survive the
// move to SPMD/XLA (reference layout, SURVEY.md section 3.1):
//   - HandleManager        (horovod/torch/handle_manager.cc)
//   - TensorQueue + cycle scheduler with tensor-fusion grouping
//                          (horovod/common/tensor_queue.cc + the
//                           RunLoopOnce negotiate->fuse cycle of
//                           horovod/common/operations.cc; negotiation
//                           itself is gone -- SPMD makes every process's
//                           request set identical by construction)
//   - ResponseCache (LRU)  (horovod/common/response_cache.cc)
//   - Timeline writer      (horovod/common/timeline.cc writer thread)
//   - StallInspector       (horovod/common/stall_inspector.cc)
//
// The compute itself stays in XLA (the Python callback dispatches fused
// collectives); this library owns the *runtime* concerns: thread-safe
// bookkeeping, the background cycle thread, batching policy, and trace
// output.  Exposed as a C ABI for ctypes (no pybind11 in the image).
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread core.cc -o libhvdcore.so

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdarg>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#if defined(__linux__)
#include <pthread.h>
#include <sched.h>
#endif
#include <list>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

double now_s() {
  return std::chrono::duration<double>(Clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Logging (HOROVOD_LOG_LEVEL parity: 0=trace .. 5=fatal, default warning).
// ---------------------------------------------------------------------------

std::atomic<int> g_log_level{3};

void logmsg(int level, const char* fmt, ...) {
  if (level < g_log_level.load(std::memory_order_relaxed)) return;
  static const char* names[] = {"TRACE", "DEBUG", "INFO",
                                "WARNING", "ERROR", "FATAL"};
  char buf[1024];
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(buf, sizeof(buf), fmt, ap);
  va_end(ap);
  fprintf(stderr, "[hvdcore %s] %s\n",
          names[level < 0 ? 0 : (level > 5 ? 5 : level)], buf);
}

// ---------------------------------------------------------------------------
// HandleManager
// ---------------------------------------------------------------------------

struct HandleEntry {
  bool done = false;
  int status = 0;  // 0 ok; nonzero = error code
  std::string error;
  double created_s = now_s();
};

class HandleManager {
 public:
  int Create() {
    std::lock_guard<std::mutex> g(m_);
    int h = next_++;
    table_.emplace(h, HandleEntry{});
    return h;
  }

  bool Done(int h, int status, const char* msg) {
    std::lock_guard<std::mutex> g(m_);
    auto it = table_.find(h);
    if (it == table_.end()) return false;
    it->second.done = true;
    it->second.status = status;
    it->second.error = msg ? msg : "";
    cv_.notify_all();
    return true;
  }

  // -1 unknown, 0 pending, 1 done
  int Poll(int h) {
    std::lock_guard<std::mutex> g(m_);
    auto it = table_.find(h);
    if (it == table_.end()) return -1;
    return it->second.done ? 1 : 0;
  }

  // status (0 ok, >0 op error); -2 timeout, -3 unknown handle
  int Wait(int h, double timeout_s) {
    std::unique_lock<std::mutex> lk(m_);
    auto it = table_.find(h);
    if (it == table_.end()) return -3;
    auto pred = [&] { return table_.at(h).done; };
    if (timeout_s < 0) {
      cv_.wait(lk, pred);
    } else if (!cv_.wait_for(lk, std::chrono::duration<double>(timeout_s),
                             pred)) {
      return -2;
    }
    return table_.at(h).status;
  }

  int ErrorMsg(int h, char* buf, int n) {
    std::lock_guard<std::mutex> g(m_);
    auto it = table_.find(h);
    if (it == table_.end() || n <= 0) return -1;
    snprintf(buf, n, "%s", it->second.error.c_str());
    return static_cast<int>(it->second.error.size());
  }

  void Release(int h) {
    std::lock_guard<std::mutex> g(m_);
    table_.erase(h);
  }

  int PendingCount() {
    std::lock_guard<std::mutex> g(m_);
    int n = 0;
    for (auto& kv : table_)
      if (!kv.second.done) n++;
    return n;
  }

  // Oldest pending handle age in seconds (stall inspection), 0 if none.
  double OldestPendingAge() {
    std::lock_guard<std::mutex> g(m_);
    double t = now_s(), oldest = 0.0;
    for (auto& kv : table_)
      if (!kv.second.done) oldest = std::max(oldest, t - kv.second.created_s);
    return oldest;
  }

 private:
  std::mutex m_;
  std::condition_variable cv_;
  std::unordered_map<int, HandleEntry> table_;
  int next_ = 1;
};

HandleManager g_handles;

// ---------------------------------------------------------------------------
// TensorQueue + cycle scheduler with fusion grouping
// ---------------------------------------------------------------------------

struct Request {
  int64_t id;
  std::string name;
  int dtype;
  int64_t nbytes;
  int handle;
  double enqueued_s;
};

typedef void (*BatchCallback)(const int64_t* ids, int n);

class CycleScheduler {
 public:
  // deterministic=1: multi-controller SPMD mode.  Every process must cut
  // IDENTICAL fused batches (they jointly launch one XLA program per
  // bucket), so time- and buffer-pressure-based dispatch is disabled --
  // batches are cut only at Flush() (synchronize(), an SPMD-synchronous
  // point) and grouped in name-sorted order.  This replaces the
  // reference's cross-rank readiness negotiation with determinism by
  // construction.
  int Start(double cycle_ms, int64_t fusion_bytes, BatchCallback cb,
            double stall_warn_s, int deterministic) {
    std::lock_guard<std::mutex> g(m_);
    if (running_) return -1;
    cycle_s_ = cycle_ms / 1e3;
    fusion_bytes_ = fusion_bytes;
    cb_ = cb;
    stall_warn_s_ = stall_warn_s;
    deterministic_ = deterministic != 0;
    stop_ = false;
    flush_ = false;
    running_ = true;
    thread_ = std::thread([this] { Loop(); });
    PinThread(thread_);
    return 0;
  }

  // HOROVOD_THREAD_AFFINITY parity (reference env_parser.cc +
  // operations.cc): pin the background cycle thread to the named CPU so
  // it never migrates onto the cores feeding the device.  The reference
  // accepts a comma-separated per-thread list; this runtime has ONE
  // cycle thread, so the FIRST element applies.  Ignored when unset,
  // malformed, or out of range.
  static void PinThread(std::thread& t) {
#if defined(__linux__)
    const char* env = std::getenv("HOROVOD_THREAD_AFFINITY");
    if (!env || !*env) return;
    char* end = nullptr;
    long cpu = std::strtol(env, &end, 10);
    if (end == env || (*end != '\0' && *end != ',') ||
        cpu < 0 || cpu >= CPU_SETSIZE) return;
    cpu_set_t set;
    CPU_ZERO(&set);
    CPU_SET(static_cast<int>(cpu), &set);
    pthread_setaffinity_np(t.native_handle(), sizeof(set), &set);
#else
    (void)t;
#endif
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> g(m_);
      if (!running_) return;
      stop_ = true;
      cv_.notify_all();
    }
    thread_.join();
    {
      std::lock_guard<std::mutex> g(m_);
      running_ = false;
    }
  }

  int64_t Enqueue(const char* name, int dtype, int64_t nbytes, int handle) {
    std::lock_guard<std::mutex> g(m_);
    if (!running_) return -1;
    int64_t id = next_id_++;
    queue_.push_back(
        Request{id, name ? name : "", dtype, nbytes, handle, now_s()});
    // A full fusion buffer is dispatched without waiting out the cycle
    // (matches the reference: a response is cut when the buffer fills).
    // Not in deterministic mode: arrival order may differ per process.
    pending_bytes_ += nbytes;
    if (!deterministic_ && pending_bytes_ >= fusion_bytes_) {
      flush_ = true;
      cv_.notify_all();
    }
    return id;
  }

  void Flush() {
    std::unique_lock<std::mutex> lk(m_);
    if (!running_) return;
    flush_ = true;
    // Watermark: this flush covers only requests already enqueued.  The
    // flag may be consumed by the cycle thread AFTER later requests
    // arrive (a second Flush() returns immediately on an empty queue but
    // leaves flush_ set); without the watermark that stale wakeup would
    // sweep up the next step's partially-enqueued gradients and the
    // fused bucket composition would diverge across SPMD processes.
    flush_upto_ = next_id_ - 1;
    cv_.notify_all();
    // Wait until everything covered by this flush has been dispatched --
    // including the callback having RUN (in_flight_), so callers can rely
    // on "flush returned => batches delivered".
    drained_cv_.wait(lk, [this] {
      return !running_ ||
             ((queue_.empty() || queue_.front().id > flush_upto_) &&
              in_flight_ == 0);
    });
  }

  int Pending() {
    std::lock_guard<std::mutex> g(m_);
    return static_cast<int>(queue_.size());
  }

  void UpdateTuning(double cycle_ms, int64_t fusion_bytes) {
    std::lock_guard<std::mutex> g(m_);
    if (cycle_ms > 0) cycle_s_ = cycle_ms / 1e3;
    if (fusion_bytes > 0) fusion_bytes_ = fusion_bytes;
  }

 private:
  void Loop() {
    for (;;) {
      std::vector<Request> batch;
      {
        std::unique_lock<std::mutex> lk(m_);
        cv_.wait_for(lk, std::chrono::duration<double>(cycle_s_),
                     [this] { return stop_ || flush_; });
        if (stop_ && queue_.empty()) break;
        if (deterministic_ && !flush_ && !stop_) {
          // Cycle tick without an explicit flush: stall-check only.
          lk.unlock();
          CheckStalls();
          continue;
        }
        flush_ = false;
        if (deterministic_ && !stop_) {
          // Deterministic mode: drain only up to the flush watermark
          // (see Flush()); requests enqueued after it belong to the
          // next synchronize and must not be swept into this batch.
          while (!queue_.empty() && queue_.front().id <= flush_upto_) {
            pending_bytes_ -= queue_.front().nbytes;
            batch.push_back(queue_.front());
            queue_.pop_front();
          }
        } else {
          batch.assign(queue_.begin(), queue_.end());
          queue_.clear();
          pending_bytes_ = 0;
        }
        if (!batch.empty()) ++in_flight_;
        drained_cv_.notify_all();
      }
      if (!batch.empty()) {
        Dispatch(batch);
        std::lock_guard<std::mutex> g(m_);
        --in_flight_;
        drained_cv_.notify_all();
      }
      CheckStalls();
    }
  }

  // Group by dtype, cutting a group at the fusion threshold, and hand each
  // group to the Python callback (which runs the fused XLA collective).
  void Dispatch(const std::vector<Request>& batch) {
    std::map<int, std::vector<const Request*>> by_dtype;
    for (auto& r : batch) by_dtype[r.dtype].push_back(&r);
    for (auto& kv : by_dtype) {
      if (deterministic_) {
        // Name order is identical across SPMD processes even when
        // arrival order is not; sort so bucket composition matches.
        std::sort(kv.second.begin(), kv.second.end(),
                  [](const Request* a, const Request* b) {
                    return a->name < b->name;
                  });
      }
      std::vector<int64_t> ids;
      int64_t bytes = 0;
      for (const Request* r : kv.second) {
        if (!ids.empty() && bytes + r->nbytes > fusion_bytes_) {
          Emit(ids);
          ids.clear();
          bytes = 0;
        }
        ids.push_back(r->id);
        bytes += r->nbytes;
      }
      if (!ids.empty()) Emit(ids);
    }
  }

  void Emit(const std::vector<int64_t>& ids) {
    BatchCallback cb;
    {
      std::lock_guard<std::mutex> g(m_);
      cb = cb_;
    }
    if (cb) cb(ids.data(), static_cast<int>(ids.size()));
  }

  void CheckStalls() {
    if (stall_warn_s_ <= 0) return;
    double age = g_handles.OldestPendingAge();
    double t = now_s();
    if (age > stall_warn_s_ && t - last_stall_warn_s_ > stall_warn_s_) {
      last_stall_warn_s_ = t;
      logmsg(3,
             "stall inspector: a collective has been pending for %.1fs "
             "(threshold %.1fs) -- a peer may be stuck or the device "
             "wedged",
             age, stall_warn_s_);
    }
  }

  std::mutex m_;
  std::condition_variable cv_, drained_cv_;
  std::deque<Request> queue_;
  std::thread thread_;
  BatchCallback cb_ = nullptr;
  double cycle_s_ = 0.001;
  int64_t fusion_bytes_ = 64 << 20;
  int64_t pending_bytes_ = 0;
  double stall_warn_s_ = 60.0;
  double last_stall_warn_s_ = 0.0;
  int64_t next_id_ = 1;
  int64_t flush_upto_ = -1;
  int in_flight_ = 0;
  bool running_ = false, stop_ = false, flush_ = false;
  bool deterministic_ = false;
};

CycleScheduler g_sched;

// ---------------------------------------------------------------------------
// ResponseCache (LRU over request signatures)
// ---------------------------------------------------------------------------

class ResponseCache {
 public:
  void Configure(int capacity) {
    std::lock_guard<std::mutex> g(m_);
    capacity_ = capacity;
    EvictLocked();
  }

  int Lookup(const char* sig) {
    std::lock_guard<std::mutex> g(m_);
    auto it = index_.find(sig);
    if (it == index_.end()) {
      misses_++;
      return 0;
    }
    lru_.splice(lru_.begin(), lru_, it->second);
    hits_++;
    return 1;
  }

  void Insert(const char* sig) {
    std::lock_guard<std::mutex> g(m_);
    auto it = index_.find(sig);
    if (it != index_.end()) {
      lru_.splice(lru_.begin(), lru_, it->second);
      return;
    }
    lru_.emplace_front(sig);
    index_[lru_.front()] = lru_.begin();
    EvictLocked();
  }

  int Size() {
    std::lock_guard<std::mutex> g(m_);
    return static_cast<int>(lru_.size());
  }

  void Stats(int64_t* hits, int64_t* misses) {
    std::lock_guard<std::mutex> g(m_);
    *hits = hits_;
    *misses = misses_;
  }

 private:
  void EvictLocked() {
    while (capacity_ >= 0 && static_cast<int>(lru_.size()) > capacity_) {
      index_.erase(lru_.back());
      lru_.pop_back();
    }
  }

  std::mutex m_;
  std::list<std::string> lru_;
  std::unordered_map<std::string, std::list<std::string>::iterator> index_;
  int capacity_ = 1024;  // HOROVOD_CACHE_CAPACITY default
  int64_t hits_ = 0, misses_ = 0;
};

ResponseCache g_cache;

// ---------------------------------------------------------------------------
// Timeline writer (chrome://tracing JSON, background writer thread)
// ---------------------------------------------------------------------------

class TimelineWriter {
 public:
  int Open(const char* path) {
    std::lock_guard<std::mutex> g(m_);
    if (file_) return -1;
    file_ = fopen(path, "w");
    if (!file_) return -2;
    fputs("[\n", file_);
    first_ = true;
    stop_ = false;
    thread_ = std::thread([this] { Loop(); });
    return 0;
  }

  void Event(const char* name, const char* cat, char ph, double ts_us,
             double dur_us, int64_t tid) {
    char buf[512];
    if (ph == 'X') {
      snprintf(buf, sizeof(buf),
               "{\"name\": \"%s\", \"cat\": \"%s\", \"ph\": \"X\", "
               "\"ts\": %.3f, \"dur\": %.3f, \"pid\": 0, \"tid\": %lld}",
               name, cat, ts_us, dur_us, static_cast<long long>(tid));
    } else {
      snprintf(buf, sizeof(buf),
               "{\"name\": \"%s\", \"cat\": \"%s\", \"ph\": \"%c\", "
               "\"ts\": %.3f, \"pid\": 0, \"tid\": %lld}",
               name, cat, ph, ts_us, static_cast<long long>(tid));
    }
    std::lock_guard<std::mutex> g(m_);
    if (!file_) return;
    events_.emplace_back(buf);
    cv_.notify_one();
  }

  void Close() {
    std::thread t;
    {
      std::lock_guard<std::mutex> g(m_);
      if (!file_) return;
      stop_ = true;
      cv_.notify_all();
      t = std::move(thread_);
    }
    t.join();
    std::lock_guard<std::mutex> g(m_);
    DrainLocked();
    fputs("\n]\n", file_);
    fclose(file_);
    file_ = nullptr;
  }

 private:
  void Loop() {
    for (;;) {
      std::unique_lock<std::mutex> lk(m_);
      cv_.wait_for(lk, std::chrono::milliseconds(100),
                   [this] { return stop_ || !events_.empty(); });
      if (stop_) return;  // final drain happens in Close() under lock
      DrainLocked();
      fflush(file_);
    }
  }

  void DrainLocked() {
    for (auto& e : events_) {
      if (!first_) fputs(",\n", file_);
      first_ = false;
      fputs(e.c_str(), file_);
    }
    events_.clear();
  }

  std::mutex m_;
  std::condition_variable cv_;
  std::thread thread_;
  std::deque<std::string> events_;
  FILE* file_ = nullptr;
  bool first_ = true, stop_ = false;
};

TimelineWriter g_timeline;

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

const char* hvd_core_version() { return "hvdcore 1.0 (tpu-native)"; }

void hvd_set_log_level(int level) { g_log_level.store(level); }

int hvd_handle_create() { return g_handles.Create(); }
int hvd_handle_done(int h, int status, const char* msg) {
  return g_handles.Done(h, status, msg) ? 0 : -1;
}
int hvd_handle_poll(int h) { return g_handles.Poll(h); }
int hvd_handle_wait(int h, double timeout_s) {
  return g_handles.Wait(h, timeout_s);
}
int hvd_handle_error(int h, char* buf, int n) {
  return g_handles.ErrorMsg(h, buf, n);
}
void hvd_handle_release(int h) { g_handles.Release(h); }
int hvd_handle_pending() { return g_handles.PendingCount(); }

int hvd_sched_start(double cycle_ms, long long fusion_bytes,
                    void (*cb)(const long long*, int),
                    double stall_warn_s, int deterministic) {
  return g_sched.Start(cycle_ms, fusion_bytes,
                       reinterpret_cast<BatchCallback>(cb), stall_warn_s,
                       deterministic);
}
void hvd_sched_stop() { g_sched.Stop(); }
long long hvd_sched_enqueue(const char* name, int dtype, long long nbytes,
                            int handle) {
  return g_sched.Enqueue(name, dtype, nbytes, handle);
}
void hvd_sched_flush() { g_sched.Flush(); }
int hvd_sched_pending() { return g_sched.Pending(); }
void hvd_sched_update_tuning(double cycle_ms, long long fusion_bytes) {
  g_sched.UpdateTuning(cycle_ms, fusion_bytes);
}

void hvd_cache_configure(int capacity) { g_cache.Configure(capacity); }
int hvd_cache_lookup(const char* sig) { return g_cache.Lookup(sig); }
void hvd_cache_insert(const char* sig) { g_cache.Insert(sig); }
int hvd_cache_size() { return g_cache.Size(); }
void hvd_cache_stats(long long* hits, long long* misses) {
  int64_t h, m;
  g_cache.Stats(&h, &m);
  *hits = h;
  *misses = m;
}

int hvd_timeline_open(const char* path) { return g_timeline.Open(path); }
void hvd_timeline_event(const char* name, const char* cat, char ph,
                        double ts_us, double dur_us, long long tid) {
  g_timeline.Event(name, cat, ph, ts_us, dur_us, tid);
}
void hvd_timeline_close() { g_timeline.Close(); }

}  // extern "C"
