"""ctypes bindings for the native coordination core (``src/core.cc``).

The shared library is built on demand with the system ``g++`` (the image
ships no pybind11; the C ABI + ctypes is the reference's own
``HorovodBasics`` loading pattern, ``horovod/common/basics.py``).  Build
artifacts are content-hashed so editing ``core.cc`` rebuilds automatically,
and a failed build degrades gracefully: ``available()`` returns False and
the pure-Python fallbacks stay in charge.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Callable, Dict, List, Optional

log = logging.getLogger("horovod_tpu.core.native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "core.cc")

_lib = None
_lib_err: Optional[str] = None
_lib_lock = threading.Lock()

BatchCB = ctypes.CFUNCTYPE(None, ctypes.POINTER(ctypes.c_longlong),
                           ctypes.c_int)


def _build() -> str:
    src = open(_SRC, "rb").read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    build_dir = os.path.join(_HERE, "build")
    so_path = os.path.join(build_dir, f"libhvdcore-{tag}.so")
    if os.path.exists(so_path):
        return so_path
    os.makedirs(build_dir, exist_ok=True)
    tmp = so_path + f".tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, so_path)  # atomic under concurrent builders
    return so_path


def _bind(lib) -> None:
    lib.hvd_core_version.restype = ctypes.c_char_p
    lib.hvd_handle_wait.argtypes = [ctypes.c_int, ctypes.c_double]
    lib.hvd_handle_error.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                     ctypes.c_int]
    lib.hvd_sched_start.argtypes = [ctypes.c_double, ctypes.c_longlong,
                                    BatchCB, ctypes.c_double, ctypes.c_int]
    lib.hvd_sched_enqueue.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                      ctypes.c_longlong, ctypes.c_int]
    lib.hvd_sched_enqueue.restype = ctypes.c_longlong
    lib.hvd_sched_update_tuning.argtypes = [ctypes.c_double,
                                            ctypes.c_longlong]
    lib.hvd_cache_lookup.argtypes = [ctypes.c_char_p]
    lib.hvd_cache_insert.argtypes = [ctypes.c_char_p]
    lib.hvd_cache_stats.argtypes = [ctypes.POINTER(ctypes.c_longlong),
                                    ctypes.POINTER(ctypes.c_longlong)]
    lib.hvd_timeline_open.argtypes = [ctypes.c_char_p]
    lib.hvd_timeline_event.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                       ctypes.c_char, ctypes.c_double,
                                       ctypes.c_double, ctypes.c_longlong]


def get_lib():
    """Load (building if needed) the native core; None when unavailable."""
    global _lib, _lib_err
    with _lib_lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        if os.environ.get("HVD_TPU_NATIVE_CORE", "1") in ("0", "false"):
            _lib_err = "disabled via HVD_TPU_NATIVE_CORE=0"
            return None
        try:
            path = _build()
            lib = ctypes.CDLL(path)
            _bind(lib)
            _lib = lib
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _lib_err = f"native core build failed: {detail[:500]}"
            log.warning("%s -- falling back to pure-Python runtime",
                        _lib_err)
        return _lib


def available() -> bool:
    return get_lib() is not None


def unavailable_reason() -> Optional[str]:
    get_lib()
    return _lib_err


# ---------------------------------------------------------------------------
# Pythonic wrappers
# ---------------------------------------------------------------------------


class NativeHandles:
    """Thread-safe async-op handle table (HandleManager parity)."""

    def __init__(self, lib=None):
        self._lib = lib or get_lib()
        if self._lib is None:
            raise RuntimeError(unavailable_reason() or "native core missing")

    def create(self) -> int:
        return self._lib.hvd_handle_create()

    def done(self, h: int, status: int = 0, error: str = "") -> None:
        self._lib.hvd_handle_done(h, status, error.encode())

    def poll(self, h: int) -> int:
        return self._lib.hvd_handle_poll(h)

    def wait(self, h: int, timeout_s: float = -1.0) -> int:
        return self._lib.hvd_handle_wait(h, timeout_s)

    def error(self, h: int) -> str:
        buf = ctypes.create_string_buffer(1024)
        self._lib.hvd_handle_error(h, buf, len(buf))
        return buf.value.decode()

    def release(self, h: int) -> None:
        self._lib.hvd_handle_release(h)

    def pending(self) -> int:
        return self._lib.hvd_handle_pending()


class NativeScheduler:
    """Cycle-time micro-batching scheduler (TensorQueue + RunLoopOnce).

    Python registers payloads keyed by request id; the native background
    thread groups requests (per dtype, up to the fusion threshold) every
    cycle and invokes ``on_batch(payloads)`` from its own thread.
    """

    def __init__(self, on_batch: Callable[[List], None],
                 cycle_ms: float = 1.0,
                 fusion_bytes: int = 64 << 20,
                 stall_warn_s: float = 60.0,
                 deterministic: bool = False, lib=None):
        self._lib = lib or get_lib()
        if self._lib is None:
            raise RuntimeError(unavailable_reason() or "native core missing")
        self._payloads: Dict[int, object] = {}
        self._plock = threading.Lock()
        self._on_batch = on_batch

        def _cb(ids_ptr, n):
            ids = [ids_ptr[i] for i in range(n)]
            with self._plock:
                payloads = [self._payloads.pop(i) for i in ids
                            if i in self._payloads]
            if payloads:
                try:
                    self._on_batch(payloads)
                except Exception:  # noqa: BLE001 - background thread
                    log.exception("native scheduler batch callback failed")

        self._cb = BatchCB(_cb)  # keep a ref; C holds the raw pointer
        rc = self._lib.hvd_sched_start(cycle_ms, fusion_bytes, self._cb,
                                       stall_warn_s, int(deterministic))
        if rc != 0:
            raise RuntimeError("scheduler already running (singleton)")

    def enqueue(self, payload, name: str, dtype_code: int, nbytes: int,
                handle: int = 0) -> int:
        # The payload must be registered under the same lock the dispatch
        # callback takes, so a cycle firing between the native enqueue and
        # the registration blocks until the payload is in place.
        with self._plock:
            rid = self._lib.hvd_sched_enqueue(name.encode(), dtype_code,
                                              nbytes, handle)
            if rid < 0:
                raise RuntimeError("scheduler not running")
            self._payloads[rid] = payload
        return rid

    def flush(self) -> None:
        self._lib.hvd_sched_flush()

    def pending(self) -> int:
        return self._lib.hvd_sched_pending()

    def update_tuning(self, cycle_ms: float = -1.0,
                      fusion_bytes: int = -1) -> None:
        self._lib.hvd_sched_update_tuning(cycle_ms, fusion_bytes)

    def stop(self) -> None:
        self._lib.hvd_sched_stop()


class NativeCache:
    """LRU response-signature cache (ResponseCache parity)."""

    def __init__(self, capacity: int = 1024, lib=None):
        self._lib = lib or get_lib()
        if self._lib is None:
            raise RuntimeError(unavailable_reason() or "native core missing")
        self._lib.hvd_cache_configure(capacity)

    def lookup(self, sig: str) -> bool:
        return bool(self._lib.hvd_cache_lookup(sig.encode()))

    def insert(self, sig: str) -> None:
        self._lib.hvd_cache_insert(sig.encode())

    def __len__(self) -> int:
        return self._lib.hvd_cache_size()

    def stats(self):
        hits = ctypes.c_longlong()
        misses = ctypes.c_longlong()
        self._lib.hvd_cache_stats(ctypes.byref(hits), ctypes.byref(misses))
        return hits.value, misses.value


class NativeTimeline:
    """Background-thread chrome-trace writer (timeline.cc parity)."""

    def __init__(self, path: str, lib=None):
        self._lib = lib or get_lib()
        if self._lib is None:
            raise RuntimeError(unavailable_reason() or "native core missing")
        rc = self._lib.hvd_timeline_open(path.encode())
        if rc != 0:
            raise RuntimeError(f"timeline open failed ({rc}): {path}")

    def event(self, name: str, cat: str, ph: str, ts_us: float,
              dur_us: float = 0.0, tid: int = 0) -> None:
        self._lib.hvd_timeline_event(name.encode(), cat.encode(),
                                     ph.encode(), ts_us, dur_us, tid)

    def close(self) -> None:
        self._lib.hvd_timeline_close()
