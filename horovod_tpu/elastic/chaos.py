"""Seeded, deterministic fault injection for elastic training.

``HOROVOD_CHAOS=<spec>`` arms a process-local injector that fires faults
at commit boundaries (every ``State.commit()`` advances the chaos step
counter) so the same spec reproduces the same failure on every run.  The
spec is ``;``-separated clauses::

    HOROVOD_CHAOS="seed=42;kill@step=5,rank=1;kv_blackout@step=3,secs=2"

Each fault clause is ``<kind>@step=<k>[,rank=<r>|rank=any][,secs=<t>]
[,at=sync]`` and fires exactly once.  Kinds:

- ``kill``: the target rank exits hard (``os._exit(137)``) -- a lost
  worker, the driver notices via heartbeat loss and republishes.
- ``sigterm``: latches the preemption notice
  (:func:`horovod_tpu.elastic.preemption.trigger`) as if the cloud sent
  a termination warning.
- ``comm``: raises :class:`ChaosCommError` (a ``ConnectionError``, so it
  passes ``run_loop._comm_error_types()`` and the message-needle gate of
  ``_looks_like_comm_failure``).  With ``at=sync`` the error is armed
  instead and raised from the next eager ``synchronize``/``barrier``
  (see :func:`raise_if_armed`), modeling a wedged collective.
- ``kv_blackout``: for ``secs`` seconds every KV request fails
  client-side (``http_kv.KVClient`` checks
  :func:`kv_blackout_active`), exercising the retry policy.
- ``hb_drop``: for ``secs`` seconds heartbeat writes are suppressed
  (``core/stall.py`` writers check :func:`heartbeat_drop_active`),
  exercising driver-side staleness handling.
- ``slow``: the target rank's host thread sleeps ``secs`` at the step
  boundary -- a deterministic straggler for the cross-rank trace plane
  (``timeline/straggler.py``) to detect and attribute.
- ``nan``: latches a one-shot input-poisoning notice; the training
  driver consumes it via :func:`consume_nan_poison` /
  :func:`poison_batch` and NaNs one element of the next batch.  The
  in-step SDC guard (``HOROVOD_GUARD``) must detect and skip that step.
- ``bitflip``: latches a one-shot replica-corruption notice carrying
  the victim rank; the driver consumes it via :func:`consume_bitflip`
  and flips one mantissa bit in that rank's parameter replica
  (:func:`horovod_tpu.core.desync.corrupt_replica`).  The values stay
  finite, so only the cross-rank tripwire
  (``HOROVOD_DESYNC_CHECK_STEPS``) catches it -- the SDC drill the
  quarantine path is proved against.

``rank=any`` picks a victim with the seeded RNG -- identical on every
process because the choice depends only on (seed, fault index, size).
``secs=`` is accepted only on the duration kinds (``kv_blackout``,
``hb_drop``, ``slow``); the others reject it instead of silently
dropping it.  ``nan``/``bitflip`` clauses fire on EVERY process at the
given step (the latch records the victim rank) because the victim's
host may not be the process that owns the injection point.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

import logging

logger = logging.getLogger("horovod_tpu.elastic")

_ENV = "HOROVOD_CHAOS"
_ENV_ALT = "HVD_TPU_CHAOS"

_KINDS = ("kill", "sigterm", "comm", "kv_blackout", "hb_drop", "slow",
          "bitflip", "nan")
# Kinds with a duration; only these accept a secs= field.
_DURATION_KINDS = ("kv_blackout", "hb_drop", "slow")
# Corruption kinds fire on every process (the latch carries the victim).
_CORRUPTION_KINDS = ("bitflip", "nan")


class ChaosSpecError(ValueError):
    """Malformed HOROVOD_CHAOS specification."""


class ChaosCommError(ConnectionError):
    """Injected communication failure.

    Subclasses ``ConnectionError`` so it is already in
    ``run_loop._comm_error_types()``; the message carries the
    ``UNAVAILABLE``/``connection`` needles the classifier looks for, plus
    an explicit ``chaos`` marker.
    """


@dataclass
class ChaosFault:
    kind: str
    step: int
    rank: Optional[int]  # None == any (resolved at install time)
    secs: float = 5.0
    at_sync: bool = False
    fired: bool = False


def parse_spec(spec: str) -> (int, List[ChaosFault]):
    """``spec`` -> (seed, faults).  Raises :class:`ChaosSpecError`."""
    seed = 0
    faults: List[ChaosFault] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if clause.startswith("seed="):
            try:
                seed = int(clause[5:])
            except ValueError:
                raise ChaosSpecError(f"bad seed clause {clause!r}")
            continue
        if "@" not in clause:
            raise ChaosSpecError(
                f"bad chaos clause {clause!r}: expected "
                f"<kind>@step=<k>[,rank=<r>|rank=any][,secs=<t>][,at=sync] "
                f"with kind in {_KINDS} (secs= only on {_DURATION_KINDS})")
        kind, _, rest = clause.partition("@")
        kind = kind.strip()
        if kind not in _KINDS:
            raise ChaosSpecError(
                f"unknown chaos kind {kind!r}; choose from {_KINDS}")
        step = None
        rank: Optional[int] = None
        secs = 5.0
        at_sync = False
        for field in rest.split(","):
            field = field.strip()
            if not field:
                continue
            key, _, val = field.partition("=")
            key, val = key.strip(), val.strip()
            if key == "step":
                step = int(val)
            elif key == "rank":
                rank = None if val == "any" else int(val)
            elif key == "secs":
                if kind not in _DURATION_KINDS:
                    raise ChaosSpecError(
                        f"secs= does not apply to {kind!r} faults "
                        f"(duration kinds: {_DURATION_KINDS}); rejecting "
                        f"{clause!r} instead of silently dropping it")
                secs = float(val)
            elif key == "at":
                if val != "sync":
                    raise ChaosSpecError(
                        f"bad at= value {val!r} in {clause!r} "
                        "(only at=sync is supported)")
                at_sync = True
            else:
                raise ChaosSpecError(
                    f"unknown field {key!r} in chaos clause {clause!r}")
        if step is None:
            raise ChaosSpecError(f"chaos clause {clause!r} missing step=")
        if at_sync and kind != "comm":
            raise ChaosSpecError("at=sync only applies to comm faults")
        faults.append(ChaosFault(kind=kind, step=step, rank=rank,
                                 secs=secs, at_sync=at_sync))
    return seed, faults


class ChaosInjector:
    """Deterministic per-process fault schedule."""

    def __init__(self, spec: str, rank: int = 0, size: int = 1):
        self.spec = spec
        self.rank = int(rank)
        self.size = max(1, int(size))
        self.seed, self.faults = parse_spec(spec)
        # Resolve rank=any with the seeded RNG: depends only on
        # (seed, fault index, size), so every process agrees on the
        # victim without any communication.
        for i, f in enumerate(self.faults):
            if f.rank is None:
                rng = random.Random(self.seed * 1000003 + i)
                f.rank = rng.randrange(self.size)
        self.step = 0
        self.fired_kinds: List[str] = []

    def _fire(self, f: ChaosFault) -> None:
        f.fired = True
        self.fired_kinds.append(f.kind)
        logger.warning("chaos: firing %s at step %d (rank %d/%d)",
                       f.kind, self.step, self.rank, self.size)
        try:
            from ..timeline import metrics as _metrics
            _metrics.registry().counter(
                "horovod_chaos_faults_total",
                "Faults fired by the chaos injector").inc()
        except Exception:
            pass
        if f.kind == "kill":
            logger.warning("chaos: killing rank %d (os._exit(137))",
                           self.rank)
            os._exit(137)
        elif f.kind == "sigterm":
            from . import preemption
            preemption.trigger(
                f"chaos: injected preemption notice at step {self.step}")
        elif f.kind == "comm":
            err = ChaosCommError(
                f"UNAVAILABLE: chaos injected comm failure at step "
                f"{self.step} (rank {self.rank}): connection reset by "
                f"peer")
            if f.at_sync:
                _arm(err)
            else:
                raise err
        elif f.kind == "kv_blackout":
            _set_kv_blackout(f.secs)
        elif f.kind == "hb_drop":
            _set_hb_drop(f.secs)
        elif f.kind == "slow":
            # Deterministic straggler: stall THIS rank's host thread for
            # secs at the step boundary.  The delay lands between
            # dispatches, so the span layer books it as dispatch-gap
            # time and the straggler monitor attributes it to this rank
            # (exercised by examples/straggler_probe.py).
            logger.warning("chaos: slowing rank %d by %.3fs at step %d",
                           self.rank, f.secs, self.step)
            time.sleep(max(0.0, f.secs))
        elif f.kind == "nan":
            _set_nan_poison(f.rank if f.rank is not None else 0)
        elif f.kind == "bitflip":
            _set_bitflip(f.rank if f.rank is not None else 0)

    def on_step(self, step: Optional[int] = None) -> None:
        """Advance the chaos clock and fire any due faults.

        Without an explicit ``step`` the injector's own monotone commit
        counter is used (replayed commits after a rollback count as new
        chaos steps; the once-only latch keeps faults from re-firing).
        Corruption kinds (``bitflip``/``nan``) fire on every process --
        the victim rank rides in the latch, not in the firing condition.
        """
        if step is None:
            self.step += 1
            step = self.step
        else:
            self.step = int(step)
        for f in self.faults:
            if not f.fired and f.step == self.step and (
                    f.rank == self.rank
                    or f.kind in _CORRUPTION_KINDS):
                self._fire(f)


# --- module singleton + latches ------------------------------------------

_lock = threading.Lock()
_injector: Optional[ChaosInjector] = None
_env_checked = False
_kv_blackout_until = 0.0
_hb_drop_until = 0.0
_armed_comm_error: Optional[ChaosCommError] = None
# One-shot corruption latches: the pending victim rank, or None.
_nan_poison_pending: Optional[int] = None
_bitflip_pending: Optional[int] = None


def _set_kv_blackout(secs: float) -> None:
    global _kv_blackout_until
    _kv_blackout_until = time.monotonic() + max(0.0, secs)


def _set_nan_poison(rank: int) -> None:
    global _nan_poison_pending
    _nan_poison_pending = int(rank)


def _set_bitflip(rank: int) -> None:
    global _bitflip_pending
    _bitflip_pending = int(rank)


def consume_nan_poison() -> Optional[int]:
    """One-shot: the pending ``nan`` victim rank, or None.

    The training driver calls this before each dispatch and poisons the
    next batch (:func:`poison_batch`) when it returns a rank."""
    global _nan_poison_pending
    rank, _nan_poison_pending = _nan_poison_pending, None
    return rank


def consume_bitflip() -> Optional[int]:
    """One-shot: the pending ``bitflip`` victim rank, or None.

    The consumer flips one bit in that rank's parameter replica
    (:func:`horovod_tpu.core.desync.corrupt_replica`)."""
    global _bitflip_pending
    rank, _bitflip_pending = _bitflip_pending, None
    return rank


def poison_batch(batch):
    """NaN one element of the first floating leaf of ``batch`` (eagerly,
    host-side -- the poisoned value flows into the next dispatch exactly
    like a corrupt input shard would)."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(batch)
    for i, leaf in enumerate(leaves):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.floating) and arr.size:
            flat = arr.reshape(-1).at[0].set(jnp.nan)
            leaves[i] = flat.reshape(arr.shape)
            break
    else:
        raise ValueError("poison_batch: no floating leaf to poison")
    return jax.tree.unflatten(treedef, leaves)


def _set_hb_drop(secs: float) -> None:
    global _hb_drop_until
    _hb_drop_until = time.monotonic() + max(0.0, secs)


def _arm(err: ChaosCommError) -> None:
    global _armed_comm_error
    _armed_comm_error = err


def kv_blackout_active() -> bool:
    """True while an injected KV blackout window is open."""
    return time.monotonic() < _kv_blackout_until


def heartbeat_drop_active() -> bool:
    """True while heartbeat writes should be suppressed."""
    return time.monotonic() < _hb_drop_until


def raise_if_armed() -> None:
    """Raise a pending ``at=sync`` comm fault (called from the eager
    synchronize/barrier path); one-shot."""
    global _armed_comm_error
    if _armed_comm_error is not None:
        err, _armed_comm_error = _armed_comm_error, None
        raise err


def install(spec: str, rank: int = 0, size: int = 1) -> ChaosInjector:
    """Install (or replace) the process-wide injector for ``spec``."""
    global _injector, _env_checked
    with _lock:
        inj = ChaosInjector(spec, rank=rank, size=size)
        _injector = inj
        _env_checked = True
        logger.info("chaos: installed injector (seed=%d, %d fault(s), "
                    "rank=%d/%d)", inj.seed, len(inj.faults), rank, size)
        return inj


def maybe_install(rank: int = 0, size: int = 1) -> Optional[ChaosInjector]:
    """Install from ``HOROVOD_CHAOS``/``HVD_TPU_CHAOS`` if set.

    Idempotent across re-inits: an injector installed earlier in this
    process survives (its fired-once latches must persist through
    elastic recovery so a fault does not re-fire after the reset).
    """
    global _env_checked
    with _lock:
        if _injector is not None or _env_checked:
            return _injector
        _env_checked = True
    spec = os.environ.get(_ENV_ALT) or os.environ.get(_ENV)
    if not spec:
        return None
    return install(spec, rank=rank, size=size)


def injector() -> Optional[ChaosInjector]:
    return _injector


def corruption_armed() -> bool:
    """Does the installed spec include a corruption kind (bitflip/nan)?

    The guard's ``auto`` mode keys on this rather than on injector
    presence: latency/availability faults (``slow``, ``kill``, ...)
    cannot corrupt numerics, and arming the screen for them would add a
    guard leg -- and its host sync -- to traces that drills like the
    straggler probe expect to be attribution-neutral.
    """
    return _injector is not None and any(
        f.kind in _CORRUPTION_KINDS for f in _injector.faults)


def on_commit() -> None:
    """Commit-boundary hook: advance the injector clock if installed."""
    if _injector is not None:
        _injector.on_step()


def reset() -> None:
    """Drop the injector and clear every latch (tests only)."""
    global _injector, _env_checked, _kv_blackout_until, _hb_drop_until
    global _armed_comm_error, _nan_poison_pending, _bitflip_pending
    with _lock:
        _injector = None
        _env_checked = False
        _kv_blackout_until = 0.0
        _hb_drop_until = 0.0
        _armed_comm_error = None
        _nan_poison_pending = None
        _bitflip_pending = None
