"""Worker <-> driver signalling for elastic runs.

Reference: ``horovod/runner/elastic/worker.py`` (WorkerNotificationService:
the driver pushes a HostsUpdated ping over HTTP; workers raise
``HostsUpdatedInterrupt`` at the next commit boundary).

This runtime publishes an *assignment document* per job: the driver
atomically rewrites a JSON document ``{"epoch": N, "size": S, "port": P,
"ranks": {worker_id: rank}}`` whenever membership changes; workers poll
its epoch inside ``state.commit()``/the run loop.  Two transports behind
one worker-side API:

* **file** (localhost tests; pod slices with a shared staging volume):
  atomic rewrite + cheap stat/read polling.
* **HTTP KV** (multi-host without shared storage): ``ASSIGNMENT_ENV`` set
  to ``http://driver:port`` points workers at the launcher's HMAC-signed
  :class:`~horovod_tpu.run.http_kv.RendezvousServer` (reference: the Gloo
  rendezvous + elastic registration HTTP server).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, Optional

ASSIGNMENT_ENV = "HVD_TPU_ELASTIC_ASSIGNMENT"
WORKER_ID_ENV = "HVD_TPU_ELASTIC_WORKER_ID"
ASSIGNMENT_KEY = ("elastic", "assignment")


def write_assignment(path: str, epoch: int, size: int, port: int,
                     ranks: Dict[str, int]) -> None:
    """Atomically publish a new membership epoch (driver side)."""
    doc = {"epoch": epoch, "size": size, "port": port, "ranks": ranks}
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def read_assignment(path: str) -> Optional[dict]:
    if path.startswith("http://"):
        return _read_assignment_http(path)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _read_assignment_http(url: str) -> Optional[dict]:
    from ..run.http_kv import KVClient
    from ..run.secret import SECRET_ENV

    secret = os.environ.get(SECRET_ENV)
    if not secret:
        raise RuntimeError(
            f"{ASSIGNMENT_ENV} is an http:// rendezvous but {SECRET_ENV} "
            "is unset; the launcher must export the per-job secret")
    try:
        raw = KVClient.from_url(url, secret).get(*ASSIGNMENT_KEY)
    except (ConnectionError, OSError):
        return None
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return None


class Notifier:
    """Worker-side epoch watcher."""

    def __init__(self, path: Optional[str] = None,
                 worker_id: Optional[str] = None):
        self.path = path or os.environ.get(ASSIGNMENT_ENV)
        self.worker_id = worker_id or os.environ.get(WORKER_ID_ENV)
        self.current_epoch = -1
        doc = self.read()
        if doc:
            self.current_epoch = doc["epoch"]

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def read(self) -> Optional[dict]:
        return read_assignment(self.path) if self.path else None

    def updated(self) -> Optional[dict]:
        """The new assignment doc if the epoch advanced, else None."""
        doc = self.read()
        if doc and doc["epoch"] > self.current_epoch:
            return doc
        return None

    def accept(self, doc: dict) -> None:
        self.current_epoch = doc["epoch"]

    def excluded_from_current(self) -> Optional[bool]:
        """True when the LATEST assignment no longer ranks this worker
        (the driver evicted it -- its SIGTERM was an eviction, not a
        cloud preemption); None when unknown (no doc readable)."""
        if not self.enabled or not self.worker_id:
            return None
        doc = self.read()
        if not doc:
            return None
        return self.worker_id not in doc.get("ranks", {})

    def mark_preempted(self) -> bool:
        """Tell the driver this worker is leaving after a preemption
        notice (graceful commit-boundary exit); True on success (the
        caller retries at the next commit otherwise).

        Required even when discovery drops the host at the same time: the
        driver's rescale trigger compares desired vs CURRENT workers, and
        a cleanly-exited worker has already left both sets -- without this
        marker no new epoch would be published and the survivors would
        wait on the old assignment forever.
        """
        if not self.enabled or not self.worker_id:
            return True  # nothing to deliver to
        if self.path.startswith("http://"):
            from ..run.http_kv import KVClient
            from ..run.secret import SECRET_ENV

            secret = os.environ.get(SECRET_ENV)
            if not secret:
                return False
            try:
                KVClient.from_url(self.path, secret).put(
                    "preempted", self.worker_id, b"1")
                return True
            except (ConnectionError, OSError):  # pragma: no cover
                return False
        safe = self.worker_id.replace(":", "_").replace("/", "_")
        try:
            with open(f"{self.path}.preempted.{safe}", "w") as f:
                f.write(self.worker_id)
            return True
        except OSError:  # pragma: no cover - driver dir gone
            return False


def read_preempted_markers(path: str) -> Dict[str, str]:
    """Driver side (file transport): ``{worker_id: marker_path}`` for
    workers that marked themselves preempted -- the path is returned so
    the driver can delete EXACTLY the markers it consumed (deleting by
    glob would race a marker written between read and cleanup).  The KV
    transport is read through the driver's own store
    (:meth:`ElasticDriver._read_preempted`)."""
    import glob

    out: Dict[str, str] = {}
    for p in glob.glob(path + ".preempted.*"):
        try:
            with open(p) as f:
                wid = f.read().strip()
            if wid:
                out[wid] = p
        except OSError:  # pragma: no cover - racing cleanup
            pass
    return out
