"""Elastic driver: discovery polling, worker supervision, re-rendezvous.

Reference: ``horovod/runner/elastic/driver.py`` (+ ``registration.py``
blacklisting): poll the discovery script; on host-set change notify
workers (-> ``HostsUpdatedInterrupt``), spawn workers on new hosts,
blacklist failing slots, gate on ``--min-np``, and re-rendezvous.

TPU-native differences: the rendezvous is the JAX coordination service --
each membership epoch gets a fresh coordinator port published through the
assignment file (see ``notify.py``); workers rebuild their comm plane
against it without being respawned.  Worker processes are spawned locally
(on a pod slice the per-VM agent plays this role; locally this doubles as
the reference's localhost elastic test harness).
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..run.exec_util import TaggedProcess
from ..run.launch import apply_timeline_env, free_port, worker_env
from .discovery import HostDiscoveryScript
from .notify import ASSIGNMENT_ENV, WORKER_ID_ENV, write_assignment

logger = logging.getLogger("horovod_tpu.elastic")


class ElasticDriver:
    def __init__(self, command: List[str], discovery_script: str,
                 min_np: int = 1, max_np: Optional[int] = None,
                 cpu: bool = False, slots: int = 1, verbose: int = 0,
                 poll_interval_s: float = 1.0,
                 elastic_timeout_s: float = 600.0,
                 heartbeat_timeout_s: float = 0.0,
                 rendezvous: bool = False,
                 extra_env: Optional[Dict[str, str]] = None,
                 discovery_timeout_s: float = 10.0):
        self.command = list(command)
        self.discovery = HostDiscoveryScript(discovery_script,
                                             default_slots=slots,
                                             timeout=discovery_timeout_s)
        self.min_np = min_np
        self.max_np = max_np
        self.cpu = cpu
        self.slots = slots
        self.verbose = verbose
        self.poll_interval_s = poll_interval_s
        self.elastic_timeout_s = elastic_timeout_s
        # > 0 enables the process-level stall plane: a worker whose
        # heartbeat file (written by the elastic run loop) goes stale is
        # terminated and blacklisted like any failed worker.
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.extra_env = dict(extra_env or {})
        self.epoch = -1
        self.blacklist: set = set()
        self.workers: Dict[str, TaggedProcess] = {}  # worker_id -> proc
        # SIGTERM time per evicted worker, for SIGKILL escalation: a worker
        # wedged in a blocking collective (the very case the stall-gated
        # heartbeat detects) may never service SIGTERM.
        self._terminated_at: Dict[str, float] = {}
        self.term_grace_s = 15.0
        self._assignment_dir = tempfile.mkdtemp(prefix="hvd_tpu_elastic_")
        self.assignment_path = os.path.join(self._assignment_dir,
                                            "assignment.json")
        self._lock = threading.Lock()
        # Network rendezvous (multi-host, no shared FS): serve the
        # assignment doc + worker heartbeats over the HMAC-signed HTTP KV
        # store instead of the assignment file.
        self._rdv = None
        self._kv = None
        self._secret = None
        if rendezvous:
            from ..run.http_kv import KVClient, RendezvousServer
            from ..run.secret import make_secret_key
            self._secret = make_secret_key()
            self._rdv = RendezvousServer(self._secret)
            self._kv = KVClient("127.0.0.1", self._rdv.port, self._secret)

    # -- membership -------------------------------------------------------
    def _desired_workers(self) -> List[str]:
        hosts = self.discovery.find_available_hosts_and_slots()
        ids = []
        for host in sorted(hosts):
            for slot in range(hosts[host]):
                wid = f"{host}:{slot}"
                if wid not in self.blacklist:
                    ids.append(wid)
        if self.max_np is not None:
            ids = ids[:self.max_np]
        return ids

    def _publish(self, worker_ids: List[str], port: int) -> Dict[str, int]:
        self.epoch += 1
        ranks = {wid: i for i, wid in enumerate(sorted(worker_ids))}
        write_assignment(self.assignment_path, self.epoch,
                         len(worker_ids), port, ranks)
        if self._kv is not None:
            import json
            from .notify import ASSIGNMENT_KEY
            doc = {"epoch": self.epoch, "size": len(worker_ids),
                   "port": port, "ranks": ranks}
            self._kv.put(*ASSIGNMENT_KEY, json.dumps(doc).encode())
        logger.info("elastic epoch %d: %d worker(s), port %d",
                    self.epoch, len(worker_ids), port)
        return ranks

    def _spawn(self, wid: str, rank: int, size: int, port: int) -> None:
        # A previous incarnation of this slot may have left a heartbeat
        # file behind; its stale mtime would get the fresh worker evicted
        # before it writes its first beat.
        from ..core.stall import heartbeat_path
        try:
            os.unlink(heartbeat_path(self.assignment_path, wid))
        except OSError:
            pass
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(worker_env(rank=rank, size=size, coordinator="127.0.0.1",
                              port=port, cpu=self.cpu, slots=1,
                              local_rank=rank, local_size=size))
        # Suffix by the stable worker id: ranks are reassigned across
        # re-rendezvous, so a rank-keyed file could collide with a
        # surviving worker's live trace.
        apply_timeline_env(env, wid.replace(":", "-"))
        if self._rdv is not None:
            from ..run.secret import SECRET_ENV
            env[ASSIGNMENT_ENV] = f"http://127.0.0.1:{self._rdv.port}"
            env[SECRET_ENV] = self._secret
            try:
                self._kv.delete("hb", wid)
            except ConnectionError:  # pragma: no cover
                pass
        else:
            env[ASSIGNMENT_ENV] = self.assignment_path
        env[WORKER_ID_ENV] = wid
        self._terminated_at.pop(wid, None)
        if self.verbose:
            env["HOROVOD_LOG_LEVEL"] = "info"
        self.workers[wid] = TaggedProcess(rank, self.command, env,
                                          lock=self._lock)

    def _check_heartbeats(self) -> None:
        """Terminate workers whose heartbeat went stale (they then reap as
        failures -> blacklist -> rescale, like the reference's stall-based
        shutdown)."""
        if self.heartbeat_timeout_s <= 0:
            return
        from ..core.stall import heartbeat_age, heartbeat_path
        now = time.monotonic()
        for wid, proc in list(self.workers.items()):
            terminated = self._terminated_at.get(wid)
            if terminated is not None:
                if now - terminated > self.term_grace_s:
                    logger.warning("worker %s ignored SIGTERM for %.1fs; "
                                   "killing", wid, now - terminated)
                    proc.kill()
                continue
            age = self._kv_heartbeat_age(wid) if self._kv is not None else \
                heartbeat_age(heartbeat_path(self.assignment_path, wid))
            if age is not None and age > self.heartbeat_timeout_s:
                logger.warning(
                    "worker %s heartbeat stale for %.1fs "
                    "(> %.1fs); terminating", wid, age,
                    self.heartbeat_timeout_s)
                proc.terminate()
                self._terminated_at[wid] = now

    def _kv_heartbeat_age(self, wid: str) -> Optional[float]:
        """Age of a worker's KV heartbeat (None: no beat yet)."""
        import time as _time
        try:
            raw = self._kv.get("hb", wid)
        except ConnectionError:  # pragma: no cover - own server gone
            return None
        if raw is None:
            return None
        try:
            return max(0.0, _time.time() - float(raw))
        except ValueError:
            return None

    # -- main loop --------------------------------------------------------
    def run(self) -> int:
        try:
            return self._run()
        finally:
            if self._rdv is not None:
                self._rdv.stop()

    def _run(self) -> int:
        deadline = time.monotonic() + self.elastic_timeout_s
        desired: List[str] = []
        while len(desired) < self.min_np:
            desired = self._desired_workers()
            if len(desired) >= self.min_np:
                break
            if time.monotonic() > deadline:
                logger.error("min-np=%d not reached before elastic timeout",
                             self.min_np)
                return 1
            time.sleep(self.poll_interval_s)

        port = free_port()
        ranks = self._publish(desired, port)
        for wid in desired:
            self._spawn(wid, ranks[wid], len(desired), port)

        while True:
            time.sleep(self.poll_interval_s)
            self._check_heartbeats()
            # 1. Reap exits.
            finished_ok = []
            failed = []
            for wid, proc in list(self.workers.items()):
                code = proc.poll()
                if code is None:
                    continue
                proc.wait()
                del self.workers[wid]
                self._terminated_at.pop(wid, None)
                (finished_ok if code == 0 else failed).append((wid, code))
            for wid, code in failed:
                logger.warning("worker %s failed (exit %d); blacklisting",
                               wid, code)
                self.blacklist.add(wid)
            if not self.workers and (finished_ok or failed):
                # Everyone exited: success only if nothing failed.
                return failed[0][1] if failed else 0
            if finished_ok and self.workers:
                # Graceful finish is collective; stragglers follow shortly.
                continue

            # 2. Discover the desired set.
            desired = self._desired_workers()
            current = set(self.workers)
            if failed or set(desired) != current:
                alive = [wid for wid in desired if wid in current]
                newcomers = [wid for wid in desired if wid not in current]
                removed = [wid for wid in current if wid not in desired]
                next_set = alive + newcomers
                if len(next_set) < self.min_np:
                    logger.error("%d worker(s) < min-np=%d; aborting",
                                 len(next_set), self.min_np)
                    for proc in self.workers.values():
                        proc.terminate()
                    return 1
                port = free_port()
                ranks = self._publish(next_set, port)
                for wid in removed:
                    self.workers[wid].terminate()
                    self.workers.pop(wid, None)
                for wid in newcomers:
                    self._spawn(wid, ranks[wid], len(next_set), port)
                # Survivors pick the new epoch up from the assignment file
                # at their next commit boundary.
