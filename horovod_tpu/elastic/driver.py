"""Elastic driver: discovery polling, worker supervision, re-rendezvous.

Reference: ``horovod/runner/elastic/driver.py`` (+ ``registration.py``
blacklisting): poll the discovery script; on host-set change notify
workers (-> ``HostsUpdatedInterrupt``), spawn workers on new hosts,
blacklist failing slots, gate on ``--min-np``, and re-rendezvous.

TPU-native differences: the rendezvous is the JAX coordination service --
each membership epoch gets a fresh coordinator port published through the
assignment file (see ``notify.py``); workers rebuild their comm plane
against it without being respawned.  Worker processes are spawned locally
(on a pod slice the per-VM agent plays this role; locally this doubles as
the reference's localhost elastic test harness).
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..run.exec_util import TaggedProcess
from ..run.launch import apply_timeline_env, free_port, worker_env
from .discovery import HostDiscoveryScript
from .notify import ASSIGNMENT_ENV, WORKER_ID_ENV, write_assignment

logger = logging.getLogger("horovod_tpu.elastic")


class ElasticDriver:
    def __init__(self, command: List[str], discovery_script: str,
                 min_np: int = 1, max_np: Optional[int] = None,
                 cpu: bool = False, slots: int = 1, verbose: int = 0,
                 poll_interval_s: float = 1.0,
                 elastic_timeout_s: float = 600.0,
                 heartbeat_timeout_s: float = 0.0,
                 rendezvous: bool = False,
                 extra_env: Optional[Dict[str, str]] = None,
                 discovery_timeout_s: float = 10.0):
        self.command = list(command)
        self.discovery = HostDiscoveryScript(discovery_script,
                                             default_slots=slots,
                                             timeout=discovery_timeout_s)
        self.min_np = min_np
        self.max_np = max_np
        self.cpu = cpu
        self.slots = slots
        self.verbose = verbose
        self.poll_interval_s = poll_interval_s
        self.elastic_timeout_s = elastic_timeout_s
        # > 0 enables the process-level stall plane: a worker whose
        # heartbeat file (written by the elastic run loop) goes stale is
        # terminated and blacklisted like any failed worker.
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.extra_env = dict(extra_env or {})
        self.epoch = -1
        self.blacklist: set = set()
        self._preempted_seen: set = set()
        self._preempted_leaving: Dict[str, float] = {}  # wid -> expiry.
        # Graceful leavers: excluded from desired while departing,
        # cleared when their host leaves discovery OR after the expiry
        # (a restarted preemptible VM may rejoin, and an operator SIGTERM
        # whose host never leaves the listing must not lose the slot
        # forever -- departure is not a fault, unlike the blacklist).
        self._ever_spawned: set = set()  # KV preemption markers are
        # keyed by worker id; a reaped worker is gone from self.workers
        # by the time its marker is polled, so remember everyone.
        self._dying: List = []  # (proc, kill_deadline) for removed
        # workers: their SIGTERM may be latched as a preemption notice
        # (or ignored by a wedged collective), so escalate to SIGKILL.
        self.workers: Dict[str, TaggedProcess] = {}  # worker_id -> proc
        # SIGTERM time per evicted worker, for SIGKILL escalation: a worker
        # wedged in a blocking collective (the very case the stall-gated
        # heartbeat detects) may never service SIGTERM.
        self._terminated_at: Dict[str, float] = {}
        self.term_grace_s = 15.0
        # How long a graceful preemption excludes a slot that stays in
        # the discovery listing (~a preemptible VM's restart latency).
        self.preempt_exclusion_s = 120.0
        self._assignment_dir = tempfile.mkdtemp(prefix="hvd_tpu_elastic_")
        self.assignment_path = os.path.join(self._assignment_dir,
                                            "assignment.json")
        self._lock = threading.Lock()
        # Network rendezvous (multi-host, no shared FS): serve the
        # assignment doc + worker heartbeats over the HMAC-signed HTTP KV
        # store instead of the assignment file.
        self._rdv = None
        self._kv = None
        self._secret = None
        if rendezvous:
            from ..run.http_kv import KVClient, RendezvousServer
            from ..run.secret import make_secret_key
            self._secret = make_secret_key()
            self._rdv = RendezvousServer(self._secret)
            self._kv = KVClient("127.0.0.1", self._rdv.port, self._secret)

    # -- membership -------------------------------------------------------
    def _desired_workers(self) -> List[str]:
        hosts = self.discovery.find_available_hosts_and_slots()
        # A preemption departure is NOT a fault: the slot is excluded only
        # while leaving.  Once its host vanishes from discovery the entry
        # clears, so a reclaimed VM that comes back under the same name
        # rejoins (unlike the failure blacklist, which is permanent).
        now = time.monotonic()
        for wid in list(self._preempted_leaving):
            if wid in self.workers:
                # Still departing: pruning now would let the removal loop
                # SIGTERM it mid-step in this very iteration (its handler
                # has re-armed SIG_DFL), defeating the commit-boundary
                # exit.  Prune only once the process is gone.
                continue
            if wid.rsplit(":", 1)[0] not in hosts \
                    or now > self._preempted_leaving[wid]:
                del self._preempted_leaving[wid]
                # Re-armed: if the slot is re-spawned and preempted again
                # later, its fresh marker must be honored.
                self._preempted_seen.discard(wid)
        ids = []
        for host in sorted(hosts):
            for slot in range(hosts[host]):
                wid = f"{host}:{slot}"
                if wid not in self.blacklist and \
                        wid not in self._preempted_leaving:
                    ids.append(wid)
        if self.max_np is not None:
            ids = ids[:self.max_np]
        return ids

    def _publish(self, worker_ids: List[str], port: int) -> Dict[str, int]:
        self.epoch += 1
        ranks = {wid: i for i, wid in enumerate(sorted(worker_ids))}
        write_assignment(self.assignment_path, self.epoch,
                         len(worker_ids), port, ranks)
        if self._kv is not None:
            import json
            from .notify import ASSIGNMENT_KEY
            doc = {"epoch": self.epoch, "size": len(worker_ids),
                   "port": port, "ranks": ranks}
            self._kv.put(*ASSIGNMENT_KEY, json.dumps(doc).encode())
        logger.info("elastic epoch %d: %d worker(s), port %d",
                    self.epoch, len(worker_ids), port)
        return ranks

    def _spawn(self, wid: str, rank: int, size: int, port: int) -> None:
        self._ever_spawned.add(wid)
        # A previous incarnation of this slot may have left a heartbeat
        # file behind; its stale mtime would get the fresh worker evicted
        # before it writes its first beat.
        from ..core.stall import heartbeat_path
        try:
            os.unlink(heartbeat_path(self.assignment_path, wid))
        except OSError:
            pass
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(worker_env(rank=rank, size=size, coordinator="127.0.0.1",
                              port=port, cpu=self.cpu, slots=1,
                              local_rank=rank, local_size=size))
        # Suffix by the stable worker id: ranks are reassigned across
        # re-rendezvous, so a rank-keyed file could collide with a
        # surviving worker's live trace.
        apply_timeline_env(env, wid.replace(":", "-"))
        if self._rdv is not None:
            from ..run.secret import SECRET_ENV
            env[ASSIGNMENT_ENV] = f"http://127.0.0.1:{self._rdv.port}"
            env[SECRET_ENV] = self._secret
            try:
                self._kv.delete("hb", wid)
            except ConnectionError:  # pragma: no cover
                pass
        else:
            env[ASSIGNMENT_ENV] = self.assignment_path
        env[WORKER_ID_ENV] = wid
        self._terminated_at.pop(wid, None)
        if self.verbose:
            env["HOROVOD_LOG_LEVEL"] = "info"
        self.workers[wid] = TaggedProcess(rank, self.command, env,
                                          lock=self._lock)

    def _check_heartbeats(self) -> None:
        """Terminate workers whose heartbeat went stale (they then reap as
        failures -> blacklist -> rescale, like the reference's stall-based
        shutdown)."""
        if self.heartbeat_timeout_s <= 0:
            return
        from ..core.stall import heartbeat_age, heartbeat_path
        now = time.monotonic()
        for wid, proc in list(self.workers.items()):
            terminated = self._terminated_at.get(wid)
            if terminated is not None:
                if now - terminated > self.term_grace_s:
                    logger.warning("worker %s ignored SIGTERM for %.1fs; "
                                   "killing", wid, now - terminated)
                    proc.kill()
                continue
            age = self._kv_heartbeat_age(wid) if self._kv is not None else \
                heartbeat_age(heartbeat_path(self.assignment_path, wid))
            if age is not None and age > self.heartbeat_timeout_s:
                logger.warning(
                    "worker %s heartbeat stale for %.1fs "
                    "(> %.1fs); terminating", wid, age,
                    self.heartbeat_timeout_s)
                proc.terminate()
                self._terminated_at[wid] = now

    def _read_preempted(self) -> set:
        """Worker ids newly self-marked as preempted (graceful leavers).

        A preempted worker exits rc 0 AND its host usually vanishes from
        discovery at the same time, so neither the failure path nor the
        desired-vs-current comparison would trigger a republish -- the
        marker forces one so survivors get a fresh epoch.  Consumed
        markers are deleted (the id may be re-spawned and legitimately
        preempted again later).
        """
        from .notify import read_preempted_markers

        markers = read_preempted_markers(self.assignment_path)
        marked = set(markers)
        if self._kv is not None:
            for wid in self._ever_spawned - self._preempted_seen:
                try:
                    if self._kv.get("preempted", wid):
                        marked.add(wid)
                except ConnectionError:  # pragma: no cover
                    pass
        new = marked - self._preempted_seen - self.blacklist
        # Consume EVERY marker read this round (each is either newly
        # processed, or from a seen/blacklisted wid that will never be
        # processed and would otherwise be re-read every poll); deleting
        # only what was read cannot race a marker written after the read.
        # A blacklisted wid's stale marker counts as seen so the KV loop
        # stops polling for it.
        for wid in marked:
            if wid in self.blacklist:
                self._preempted_seen.add(wid)
            if self._kv is not None:
                try:
                    self._kv.delete("preempted", wid)
                except ConnectionError:  # pragma: no cover
                    pass
            p = markers.get(wid)
            if p is not None:
                try:
                    os.unlink(p)
                except OSError:  # pragma: no cover
                    pass
        return new

    def _kv_heartbeat_age(self, wid: str) -> Optional[float]:
        """Age of a worker's KV heartbeat (None: no beat yet)."""
        import time as _time
        try:
            raw = self._kv.get("hb", wid)
        except ConnectionError:  # pragma: no cover - own server gone
            return None
        if raw is None:
            return None
        try:
            return max(0.0, _time.time() - float(raw))
        except ValueError:
            return None

    # -- main loop --------------------------------------------------------
    def run(self) -> int:
        try:
            return self._run()
        finally:
            # Whatever the exit path (all-finished, min-np abort, error,
            # an exception out of publish/spawn), neither a removed
            # worker parked in _dying nor a live tracked worker may
            # outlive the driver as an orphan (SIGTERM may be latched by
            # the preemption handler, or ignored by a wedged collective).
            for proc, _deadline in self._dying:
                if proc.poll() is None:
                    proc.kill()
            for proc in self.workers.values():
                if proc.poll() is None:
                    proc.kill()
            if self._rdv is not None:
                self._rdv.stop()

    def _run(self) -> int:
        deadline = time.monotonic() + self.elastic_timeout_s
        desired: List[str] = []
        while len(desired) < self.min_np:
            desired = self._desired_workers()
            if len(desired) >= self.min_np:
                break
            if time.monotonic() > deadline:
                logger.error("min-np=%d not reached before elastic timeout",
                             self.min_np)
                return 1
            time.sleep(self.poll_interval_s)

        port = free_port()
        ranks = self._publish(desired, port)
        for wid in desired:
            self._spawn(wid, ranks[wid], len(desired), port)

        while True:
            time.sleep(self.poll_interval_s)
            self._check_heartbeats()
            # 0. Escalate removed-but-still-alive workers to SIGKILL.
            for proc, deadline in list(self._dying):
                if proc.poll() is not None:
                    self._dying.remove((proc, deadline))
                elif time.monotonic() > deadline:
                    proc.kill()
                    self._dying.remove((proc, deadline))
            # 1. Reap exits.
            finished_ok = []
            failed = []
            for wid, proc in list(self.workers.items()):
                code = proc.poll()
                if code is None:
                    continue
                proc.wait()
                del self.workers[wid]
                self._terminated_at.pop(wid, None)
                (finished_ok if code == 0 else failed).append((wid, code))
            for wid, code in failed:
                logger.warning("worker %s failed (exit %d); blacklisting",
                               wid, code)
                self.blacklist.add(wid)
            if not self.workers and (finished_ok or failed):
                # Everyone exited: success only if nothing failed.
                return failed[0][1] if failed else 0
            # 1b. Graceful preemption leavers: they exit rc 0 and usually
            # vanish from discovery simultaneously, so neither the
            # failure path nor desired-vs-current would republish --
            # without this the survivors wait on the old epoch forever.
            preempted = self._read_preempted()
            for wid in preempted:
                logger.warning("worker %s is leaving after a preemption "
                               "notice; republishing without it", wid)
                self._preempted_leaving[wid] = \
                    time.monotonic() + self.preempt_exclusion_s
                self._preempted_seen.add(wid)
            if finished_ok and self.workers and not preempted:
                # Graceful finish is collective; stragglers follow shortly.
                continue

            # 2. Discover the desired set.
            desired = self._desired_workers()
            current = set(self.workers)
            if failed or preempted or set(desired) != current:
                alive = [wid for wid in desired if wid in current]
                newcomers = [wid for wid in desired if wid not in current]
                removed = [wid for wid in current if wid not in desired]
                next_set = alive + newcomers
                if len(next_set) < self.min_np:
                    logger.error("%d worker(s) < min-np=%d; aborting",
                                 len(next_set), self.min_np)
                    for proc in self.workers.values():
                        # Terminal abort: SIGKILL outright -- workers'
                        # SIGTERM handlers would latch the signal as a
                        # preemption notice and keep training forever.
                        proc.kill()
                    return 1
                port = free_port()
                ranks = self._publish(next_set, port)
                for wid in removed:
                    proc = self.workers.pop(wid)
                    if wid not in self._preempted_leaving:
                        # Plain eviction: SIGTERM.  An announced graceful
                        # leaver is NOT signalled -- its handler already
                        # re-armed SIG_DFL after the platform's notice,
                        # so a driver SIGTERM would kill it mid-step
                        # before its commit-boundary exit.
                        proc.terminate()
                    # Either way, escalate to SIGKILL after the grace so
                    # a wedged or latched worker cannot leak as an
                    # orphan.
                    self._dying.append((proc, time.monotonic()
                                        + self.term_grace_s))
                for wid in newcomers:
                    self._spawn(wid, ranks[wid], len(next_set), port)
                # Survivors pick the new epoch up from the assignment file
                # at their next commit boundary.
