"""Elastic state objects: commit / restore / sync.

Parity with ``horovod/torch/elastic/state.py`` (``TorchState``) and
``horovod/common/elastic``: a :class:`State` snapshots registered values in
host memory on ``commit()`` (cheap, no disk), rolls back on ``restore()``
(after a failed collective), and ``sync()``s from rank 0 after any
rendezvous so new/restarted workers adopt the survivors' progress.

``JaxState`` holds arbitrary pytrees (params, optimizer state) plus python
scalars; pytree leaves are snapshotted with ``jax.device_get`` (host RAM,
preemption-safe) and synced with
:func:`horovod_tpu.optim.functions.broadcast_`.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

# Snapshot-ledger ring depth (entries, not steps): deep enough to step
# back past a corruption window, bounded so host RAM stays O(model).
# Cadence comes from HOROVOD_SNAPSHOT_STEPS; depth is deliberately not a
# knob -- 4 entries x N-step cadence already spans 4N steps of history.
LEDGER_DEPTH = 4


def _snapshot_steps() -> int:
    """HOROVOD_SNAPSHOT_STEPS from the live config (0 = ledger off)."""
    from ..core.state import global_state
    st = global_state()
    if st.initialized and st.config is not None:
        return max(0, int(st.config.snapshot_steps))
    return 0


def _desync_check_steps() -> int:
    """HOROVOD_DESYNC_CHECK_STEPS from the live config (0 = off)."""
    from ..core.state import global_state
    st = global_state()
    if st.initialized and st.config is not None:
        return max(0, int(st.config.desync_check_steps))
    return 0


def _tree_is_sharded(tree, world: int) -> bool:
    """True when every array leaf carries a leading ``[world, ...]``
    shard axis (the ZeRO flat-arena layout)."""
    leaves = [x for x in jax.tree.leaves(tree)
              if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1]
    if not leaves:
        return False
    return all(x.shape[0] == world for x in leaves)


class State:
    """Base elastic state: commit/restore/sync + reset listeners."""

    def __init__(self):
        self._reset_callbacks: List[Callable[[], None]] = []
        # Successful-commit counter; the run loop uses it to distinguish a
        # persistent desync from occasional recovered ones.
        self._commit_count = 0

    def register_reset_callbacks(self, callbacks) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        from ..timeline import metrics as _metrics
        _metrics.registry().counter(
            "horovod_elastic_reset_total",
            "Elastic state resets (rank-change recoveries)").inc()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self, timestamp=None, update_res=None) -> None:
        """Hook invoked when the driver announces a topology change."""
        from ..timeline import metrics as _metrics
        _metrics.registry().counter(
            "horovod_elastic_host_updates_total",
            "Elastic host-set update notifications").inc()

    def _check_host_updates(self) -> None:
        """Raise HostsUpdatedInterrupt at the commit boundary if the driver
        advanced the membership epoch (reference: commit is the interrupt
        point).  The snapshot is taken before the check, so no progress is
        lost."""
        self._commit_count += 1  # snapshot is already saved at this point
        # Chaos clock ticks at the commit boundary: the snapshot is
        # already saved, so an injected failure here costs no progress
        # beyond the replayed partial step -- same contract as
        # HostsUpdatedInterrupt.
        from . import chaos
        chaos.on_commit()
        from .run_loop import check_for_host_updates
        check_for_host_updates(self)

    def _check_desync(self, values) -> None:
        """Under HOROVOD_CHECK_DESYNC=1, verify the values about to be
        committed are identical on every rank -- BEFORE they overwrite the
        last good snapshot, so ``restore()`` still holds a converged copy
        and the run loop recovers with restore + rank-0 ``sync()`` alone
        (no re-rendezvous; :class:`~horovod_tpu.core.exceptions.DesyncError`
        is the signal)."""
        from ..core.desync import maybe_check
        maybe_check(values, name="elastic_commit")

    def commit(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError


class ObjectState(State):
    """Elastic state over plain python attributes (pickle-synced).

    Reference: ``horovod/common/elastic.py::ObjectState``.
    """

    def __init__(self, **kwargs):
        super().__init__()
        self._saved: Dict[str, Any] = {}
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._known = list(kwargs)
        self.commit()

    def commit(self) -> None:
        self._check_desync({k: getattr(self, k) for k in self._known})
        self._saved = {k: copy.deepcopy(getattr(self, k))
                       for k in self._known}
        self._check_host_updates()

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        from ..optim.functions import broadcast_object
        values = {k: getattr(self, k) for k in self._known}
        values = broadcast_object(values, root_rank=0)
        for k, v in values.items():
            setattr(self, k, v)
        self.commit()


class JaxState(State):
    """Elastic state holding pytrees (params/opt state) + scalar counters.

    Usage::

        state = hvd.elastic.JaxState(params=params, opt_state=opt_state,
                                     batch=0, epoch=0)
    """

    def __init__(self, **kwargs):
        super().__init__()
        self._tree_keys: List[str] = []
        self._scalar_keys: List[str] = []
        for k, v in kwargs.items():
            setattr(self, k, v)
            if isinstance(v, (int, float, str, bool)) or v is None:
                self._scalar_keys.append(k)
            else:
                self._tree_keys.append(k)
        self._saved_trees: Dict[str, Any] = {}
        self._saved_scalars: Dict[str, Any] = {}
        # Snapshot/rollback ledger (SDC defense plane): a bounded ring of
        # past committed carries, pushed every HOROVOD_SNAPSHOT_STEPS
        # commits.  restore() only reaches the LAST commit -- useless
        # when the last commit itself snapshotted already-corrupt state
        # (a bitflip rides undetected until the next tripwire sample);
        # rollback() steps back to a pre-anomaly entry instead.
        self._ledger: List[Dict[str, Any]] = []
        self.commit()

    def commit(self) -> None:
        self._check_desync({
            "trees": {k: getattr(self, k) for k in self._tree_keys},
            "scalars": {k: getattr(self, k) for k in self._scalar_keys}})
        self._maybe_tripwire()
        # Host-RAM snapshot (device_get): survives device-state loss on
        # preemption/rescale, the whole point of elastic commit.
        self._saved_trees = {
            k: jax.device_get(getattr(self, k)) for k in self._tree_keys}
        self._saved_scalars = {
            k: copy.deepcopy(getattr(self, k)) for k in self._scalar_keys}
        self._ledger_push()
        self._check_host_updates()

    def _maybe_tripwire(self) -> None:
        """In-band corruption tripwire, every HOROVOD_DESYNC_CHECK_STEPS
        commits: bit-checksum each replicated tree on every device and
        attribute any divergence to the minority rank(s) by majority
        vote (:class:`~horovod_tpu.core.exceptions.CorruptRankError`).

        Runs BEFORE the snapshot refresh -- like ``_check_desync`` -- so
        the last committed copy is still the converged one when the
        error propagates.  Sharded trees (the ZeRO arena) are skipped:
        their replicas differ by construction, so only trees whose every
        leaf claims full replication can testify.
        """
        from ..core.desync import tripwire_check
        n = _desync_check_steps()
        if n <= 0 or self._commit_count % n:
            return
        for k in self._tree_keys:
            tree = getattr(self, k)
            leaves = [l for l in jax.tree.leaves(tree)
                      if hasattr(l, "sharding")]
            if leaves and all(l.sharding.is_fully_replicated
                              for l in leaves):
                tripwire_check(tree, name=k)

    def _ledger_push(self) -> None:
        """Ring-buffer the snapshot just taken, every N commits.

        Entries alias the snapshot's host arrays (device_get output is
        never mutated in place, only replaced) but copy the dicts and
        scalars, so a later commit/resize cannot rewrite history.  The
        scalar copy is what makes rollback sampler-offset-aware: the
        batch/epoch counters rewind WITH the params, so the replay
        consumes the same data the rolled-back steps did.
        """
        n = _snapshot_steps()
        if n <= 0:
            return
        # _commit_count is pre-increment here (it advances inside
        # _check_host_updates): entry 0 is the constructor's commit, so
        # a rollback floor always exists.
        if self._commit_count % n:
            return
        self._ledger.append({
            "commit": self._commit_count,
            "trees": dict(self._saved_trees),
            "scalars": copy.deepcopy(self._saved_scalars)})
        while len(self._ledger) > LEDGER_DEPTH:
            self._ledger.pop(0)

    def rollback(self, before_commit: Optional[int] = None
                 ) -> Optional[Dict[str, Any]]:
        """Roll back to a ledger snapshot and make it current.

        Picks the newest entry with ``commit <= before_commit`` (pass the
        last commit known good -- e.g. detection commit minus the
        tripwire interval -- or None for the newest), DROPS the newer
        entries (they may hold poisoned state: that is why plain
        ``restore()`` is not enough), installs the entry as the committed
        snapshot, and restores it onto the live attributes.  Returns a
        report dict, or None when the ledger has no eligible entry (the
        caller falls back to ``restore()``).
        """
        entry = None
        while self._ledger:
            e = self._ledger[-1]
            if before_commit is None or e["commit"] <= int(before_commit):
                entry = e
                break
            self._ledger.pop()
        if entry is None:
            return None
        self._saved_trees = dict(entry["trees"])
        self._saved_scalars = copy.deepcopy(entry["scalars"])
        from ..timeline import metrics as _metrics
        _metrics.registry().counter(
            "horovod_guard_rollbacks_total",
            "Snapshot-ledger rollbacks (sustained anomaly / corrupt "
            "replica recoveries)").inc()
        self.restore()
        return {"commit": entry["commit"], "depth": len(self._ledger)}

    def restore(self) -> None:
        # Steps rolled back = the recovery replay cost; exported as
        # horovod_elastic_steps_to_recover on the metrics plane.  Use the
        # largest positive regression over integer counters (batch,
        # step, ...) -- bools are ints, skip them.
        lost = 0
        for k, saved in self._saved_scalars.items():
            cur = getattr(self, k, None)
            if (isinstance(cur, int) and not isinstance(cur, bool)
                    and isinstance(saved, int)
                    and not isinstance(saved, bool)):
                lost = max(lost, cur - saved)
        if lost > 0:
            from ..timeline import metrics as _metrics
            _metrics.registry().gauge(
                "horovod_elastic_steps_to_recover",
                "Steps rolled back to the last commit during the most "
                "recent elastic recovery").set(float(lost))
        for k, v in self._saved_trees.items():
            setattr(self, k, jax.tree.map(jnp.asarray, v))
        for k, v in self._saved_scalars.items():
            setattr(self, k, copy.deepcopy(v))

    def resize(self, old_size: int, new_size: int, *,
               zero_keys: Optional[List[str]] = None,
               fusion_threshold: Optional[int] = None,
               compression=None) -> Dict[str, Any]:
        """Checkpointless carry-state reconstruction after a world-size
        change (``old_size`` -> ``new_size`` processes/shards).

        Registered trees are rewritten in place:

        - ``_EFState`` wrappers (error-feedback residual carries from
          :class:`~horovod_tpu.optim.distributed.DistributedOptimizer`)
          are re-bucketed for the new world size, carrying the unsent
          residual mass instead of zeroing it.
        - ``_ZeroEFState`` wrappers and ZeRO-sharded optimizer trees
          (leaves with a leading ``[old_size, ...]`` shard axis; by
          default any registered key named ``opt_state``, override with
          ``zero_keys``) are re-laid out over the new arena plan from
          :func:`~horovod_tpu.optim.zero.plan_arena`.
        - Everything else (replicated params, scalars) is untouched --
          ``sync()`` re-broadcasts those from rank 0.

        Returns a report dict and refreshes the committed snapshot for
        the resized keys so an intermediate ``restore()`` stays
        consistent.
        """
        from ..optim import distributed as _dist
        from ..optim import zero as _zero
        report: Dict[str, Any] = {
            "old_size": int(old_size), "new_size": int(new_size),
            "resized": [], "carried_bytes": 0, "zeroed_buckets": 0,
        }
        if int(old_size) == int(new_size):
            return report
        params = getattr(self, "params", None) if hasattr(self, "params") \
            else None
        zkeys = set(zero_keys) if zero_keys is not None else {"opt_state"}
        for k in self._tree_keys:
            v = getattr(self, k)
            if isinstance(v, _dist._EFState):
                new_res, rep = _dist.ef_resize_residuals(
                    v.residuals, params, old_size, new_size,
                    fusion_threshold=fusion_threshold,
                    compression=compression)
                inner = v.inner
                if _tree_is_sharded(inner, old_size):
                    inner, zrep = _zero.zero_resize(
                        inner, params, old_size, new_size)
                    report["carried_bytes"] += zrep["carried_bytes"]
                setattr(self, k, _dist._EFState(new_res, inner))
                report["resized"].append(k)
                report["carried_bytes"] += rep["carried_bytes"]
                report["zeroed_buckets"] += rep["zeroed_buckets"]
            elif isinstance(v, _zero._ZeroEFState) or (
                    k in zkeys and _tree_is_sharded(v, old_size)):
                new_v, rep = _zero.zero_resize(
                    v, params, old_size, new_size)
                setattr(self, k, new_v)
                report["resized"].append(k)
                report["carried_bytes"] += rep["carried_bytes"]
                report["zeroed_buckets"] += rep["zeroed_buckets"]
        for k in report["resized"]:
            self._saved_trees[k] = jax.device_get(getattr(self, k))
        if report["resized"]:
            from ..timeline import metrics as _metrics
            _metrics.registry().counter(
                "horovod_ef_residual_recovered_bytes",
                "Bytes of optimizer/EF carry state reconstructed "
                "checkpointlessly across elastic resizes").inc(
                    report["carried_bytes"])
        return report

    def sync(self) -> None:
        from ..optim.functions import broadcast_, broadcast_object
        for k in self._tree_keys:
            setattr(self, k, broadcast_(jax.device_get(getattr(self, k)),
                                        root_rank=0))
        scalars = broadcast_object(
            {k: getattr(self, k) for k in self._scalar_keys}, root_rank=0)
        for k, v in scalars.items():
            setattr(self, k, v)
        self.commit()
