"""Elastic (fault-tolerant, rescalable) training.

Reference layer: ``horovod/runner/elastic/`` + framework ``elastic``
modules (SURVEY.md sections 3.5, 4.5, 5.3): state commit/restore/sync,
the ``@hvd.elastic.run`` rollback loop, host discovery, and a driver that
re-rendezvouses workers through fresh JAX-coordination epochs instead of
Gloo rendezvous rounds.
"""

from .state import State, ObjectState, JaxState  # noqa: F401
from .run_loop import run, check_for_host_updates, apply_resize  # noqa: F401
from .sampler import ElasticSampler  # noqa: F401
from .discovery import HostDiscoveryScript  # noqa: F401
from . import chaos  # noqa: F401
from .chaos import ChaosCommError, ChaosInjector  # noqa: F401
