"""Preemption notice: SIGTERM handler + optional GCE metadata poll.

SURVEY.md 5.3 ("detect preemption -- coordinator heartbeat loss / GCE
preemption notice"): an imminent preemption should become a graceful
``HostsUpdatedInterrupt`` at the NEXT COMMIT BOUNDARY, before the slice
dies -- the worker leaves with its state committed instead of dying
mid-collective and forcing the survivors through the crash-rollback
path.

Two sources feed one latched notice:

* **SIGTERM** (cloud preemptions deliver one before the kill): installed
  by ``hvd.elastic.run`` (main thread only; ``HOROVOD_ELASTIC_NO_SIGTERM=1``
  opts out, e.g. when the application owns the handler).
* **GCE metadata poll** (``HOROVOD_ELASTIC_PREEMPT_POLL=1``): a daemon
  thread polls the metadata server's ``instance/preempted`` flag; off
  GCE the poll fails a few times and stops itself.

The elastic run loop checks :func:`notice_received` at every commit
(via ``check_for_host_updates``) and once more at the loop top: a
noticed worker logs, leaves the re-rendezvous to the survivors, and
exits cleanly.
"""

from __future__ import annotations

import logging
import signal
import threading

logger = logging.getLogger("horovod_tpu.elastic")

GCE_PREEMPTED_URL = ("http://metadata.google.internal/computeMetadata/"
                     "v1/instance/preempted")

_notice = threading.Event()
_announced = threading.Event()
_reason: str = ""
_installed = False
_poller: threading.Thread = None
_poll_stop = threading.Event()


def notice_received() -> bool:
    return _notice.is_set()


def announced() -> bool:
    """The driver has been told (via the preempted marker) -- announce
    exactly once."""
    return _announced.is_set()


def set_announced() -> None:
    _announced.set()


def reason() -> str:
    return _reason


def trigger(why: str) -> None:
    """Latch the preemption notice (idempotent)."""
    global _reason
    if not _notice.is_set():
        _reason = why
        logger.warning("preemption notice (%s): will interrupt at the "
                       "next commit boundary", why)
        _notice.set()


def reset() -> None:
    """Test hook / fresh life: clear the latch."""
    global _reason
    _notice.clear()
    _announced.clear()
    _reason = ""


def stop_gce_poll(timeout: float = 6.0) -> None:
    """Stop a running metadata poll thread (idempotent)."""
    global _poller
    p = _poller
    if p is None:
        return
    _poll_stop.set()
    if p.is_alive():
        p.join(timeout=timeout)
    _poller = None
    _poll_stop.clear()


def on_runtime_reset() -> None:
    """Hook for ``core.state.GlobalState.reset`` (shutdown / re-init).

    Stops the metadata poll thread so repeated init/reset cycles don't
    leak pollers, and forgets the installed-handler latch so the next
    ``elastic.run`` re-installs cleanly.  The OS-level SIGTERM handler
    and a pending preemption NOTICE are deliberately left alone:
    ``_reinitialize`` resets the runtime mid-recovery, and clearing the
    latch there would drop a real preemption warning.
    """
    global _installed
    stop_gce_poll()
    _installed = False


def _handler(signum, frame):  # pragma: no cover - exercised in live test
    trigger(f"signal {signum}")
    # Re-arm the default action: the first SIGTERM is a notice, a second
    # one (the platform's or the driver's escalation) must still kill a
    # worker that is wedged in a blocking collective and will never reach
    # a commit boundary.
    signal.signal(signal.SIGTERM, signal.SIG_DFL)


def install_sigterm() -> bool:
    """Install the SIGTERM handler (idempotent; main thread only --
    signal.signal raises ValueError elsewhere, and a worker thread must
    not steal the application's handler)."""
    global _installed
    if _installed:
        return True
    try:
        signal.signal(signal.SIGTERM, _handler)
    except ValueError:
        logger.warning("not on the main thread; SIGTERM preemption "
                       "notice not installed")
        return False
    _installed = True
    return True


def start_gce_poll(interval_s: float = 5.0,
                   max_failures: int = 3) -> threading.Thread:
    """Poll the GCE metadata server's preempted flag in a daemon thread.

    Off GCE (no metadata server) the poll errors ``max_failures`` times
    and stops itself -- enabling the flag on non-GCE hosts is harmless.
    """
    global _poller
    if _poller is not None and _poller.is_alive():
        return _poller
    _poll_stop.clear()

    def poll():
        import urllib.request

        failures = 0
        while not (_notice.is_set() or _poll_stop.is_set()):
            try:
                req = urllib.request.Request(
                    GCE_PREEMPTED_URL,
                    headers={"Metadata-Flavor": "Google"})
                with urllib.request.urlopen(req, timeout=2) as resp:
                    if b"TRUE" in resp.read().upper():
                        trigger("GCE metadata: instance preempted")
                        return
                failures = 0
            except Exception:
                failures += 1
                if failures >= max_failures:
                    logger.info("GCE metadata server unreachable %d times;"
                                " stopping the preemption poll", failures)
                    return
            _poll_stop.wait(interval_s)

    _poller = threading.Thread(target=poll, name="hvd-preempt-poll",
                               daemon=True)
    _poller.start()
    return _poller
