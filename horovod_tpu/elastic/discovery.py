"""Host discovery for elastic jobs.

Reference: ``horovod/runner/elastic/discovery.py`` -- the driver polls a
user-supplied ``--host-discovery-script`` whose stdout lists one
``host[:slots]`` per line; the set may change at any time (scale-up,
scale-down, preemption).  On TPU, "host" is a pod-slice worker VM (or a
whole slice in multi-slice jobs); locally it is an alias for test worker
processes.
"""

from __future__ import annotations

import subprocess
from typing import Dict


class HostDiscoveryScript:
    def __init__(self, script: str, default_slots: int = 1,
                 timeout: float = 10.0):
        self.script = script
        self.default_slots = default_slots
        self.timeout = timeout
        self._last: Dict[str, int] = {}

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """Run the script; returns {host: slots}.

        A FAILING script (crash, nonzero exit, timeout) returns the last
        successful result: one transient discovery hiccup (e.g. a slow
        cluster API) must not read as "all hosts gone" and tear down a
        healthy job below min-np.  Only a successful empty listing means
        no hosts.
        """
        try:
            out = subprocess.run([self.script], capture_output=True,
                                 text=True, timeout=self.timeout)
        except (OSError, subprocess.TimeoutExpired):
            return dict(self._last)
        if out.returncode != 0:
            return dict(self._last)
        hosts: Dict[str, int] = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            host, slots = self._parse_line(line)
            hosts[host] = slots
        self._last = dict(hosts)
        return hosts

    def _parse_line(self, line: str):
        # One canonical host[:slots] splitter (IPv6-aware), shared with
        # the launcher's -H/--hostfile parsing; lenient mode because a
        # discovery script's transient garbage must not kill the driver.
        from ..run.hosts import split_host_slots
        return split_host_slots(line, self.default_slots, strict=False)
