"""ElasticSampler: rescale-aware dataset sharding.

Reference: ``horovod/torch/elastic/sampler.py`` -- shard sample indices
over ranks; record processed indices; on rescale, reshard only the
*remaining* indices so no sample is dropped or repeated within an epoch.
"""

from __future__ import annotations

import random
from typing import Iterator, List, Optional, Sequence


class ElasticSampler:
    def __init__(self, num_samples: int, shuffle: bool = True, seed: int = 0):
        self.num_samples = num_samples
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed: set = set()
        self.rank = 0
        self.size = 1
        self._reset_order()

    def _reset_order(self) -> None:
        order = list(range(self.num_samples))
        if self.shuffle:
            random.Random(self.seed + self.epoch).shuffle(order)
        self._order = order

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self.processed.clear()
        self._reset_order()

    def set_rank_and_size(self, rank: int, size: int) -> None:
        """Call after (re-)rendezvous; remaining samples are resharded."""
        self.rank = rank
        self.size = size

    def record_batch(self, indices: Sequence[int]) -> None:
        self.processed.update(int(i) for i in indices)

    def state_dict(self) -> dict:
        return {"epoch": self.epoch, "processed": sorted(self.processed)}

    def load_state_dict(self, state: dict) -> None:
        self.epoch = state["epoch"]
        self._reset_order()
        self.processed = set(state["processed"])

    @property
    def remaining(self) -> List[int]:
        return [i for i in self._order if i not in self.processed]

    def __len__(self) -> int:
        rem = len(self.remaining)
        return (rem + self.size - 1 - self.rank) // self.size

    def __iter__(self) -> Iterator[int]:
        rem = self.remaining
        # Rank-strided shard of the remaining indices.
        return iter(rem[self.rank::self.size])
