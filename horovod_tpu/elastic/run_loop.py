"""The elastic run decorator: rollback + re-rendezvous control flow.

Parity with the reference's worker loop (``hvd.elastic.run``, SURVEY.md
section 4.5)::

    loop:
      state.sync()            # broadcast from rank 0 after any reset
      try: func(state, ...)   # user training, commits at batch boundaries
      except HorovodInternalError:   -> state.restore()  (peer died)
      except HostsUpdatedInterrupt:  -> pass             (topology changed)
      shutdown; re-rendezvous; init  # full comm-plane rebuild

The comm-plane rebuild is TPU-native: tear down the JAX distributed client
and re-initialize against the coordinator/port published in the driver's
assignment file (epoch N+1), then rebuild the mesh.  A failed collective
surfaces as a jax RuntimeError/XlaRuntimeError -- the loop converts any
error carrying a distributed-runtime signature into the rollback path.
"""

from __future__ import annotations

import functools
import logging
import os
import time
from typing import Any, Callable

import jax

from ..core import basics as _basics
from ..core.exceptions import (CorruptRankError, DesyncError,
                               HorovodInternalError, HostsUpdatedInterrupt,
                               SustainedAnomalyError)
from ..core.stall import heartbeat_path  # noqa: F401  (re-export)
from .notify import Notifier
from .state import State

logger = logging.getLogger("horovod_tpu.elastic")


def _comm_error_types() -> tuple:
    """Exception types the JAX/XLA runtime raises for transport and
    coordination failures (pinned by the live peer-death test)."""
    types = [RuntimeError, OSError, TimeoutError, ConnectionError]
    try:
        from jax.errors import JaxRuntimeError
        types.append(JaxRuntimeError)
    except Exception:  # pragma: no cover - older jax
        pass
    try:  # pragma: no cover - alias of JaxRuntimeError on current jaxlib
        from jax._src.lib import xla_client
        types.append(xla_client.XlaRuntimeError)
    except Exception:
        pass
    return tuple(types)


# XLA status codes the runtime prefixes its messages with; jax maps some
# of them onto PYTHON BUILTIN exception types (measured live: a peer
# dying mid-allreduce raises ValueError("UNKNOWN: Gloo all-reduce
# failed: ... Connection closed by peer")), so type checks alone cannot
# recognize the transport layer.
_STATUS_PREFIXES = ("UNKNOWN:", "INTERNAL:", "UNAVAILABLE:",
                    "DEADLINE_EXCEEDED:", "ABORTED:", "CANCELLED:",
                    "FAILED_PRECONDITION:")


def _looks_like_comm_failure(err: BaseException) -> bool:
    """Classify an exception as a recoverable comm-plane failure.

    Two gates, both required (a user ``ValueError`` whose message merely
    mentions "connection" must not be silently converted into a
    rollback):

    1. the exception must look like it came from the runtime layer --
       either by TYPE (JaxRuntimeError / RuntimeError / OSError /
       TimeoutError) or, for the builtin types jax maps XLA status codes
       onto, by the status-code PREFIX the runtime stamps on its
       messages;
    2. the message must carry a transport/coordination signature.

    The gate set is pinned against the CURRENT jax's live error surface
    by ``test_run.py::test_peer_death_error_classification`` -- a renamed
    runtime message fails that test rather than silently converting a
    recoverable fault into a crash.
    """
    if isinstance(err, HorovodInternalError):
        return True
    from . import chaos
    if isinstance(err, chaos.ChaosCommError):
        return True  # injected faults are comm failures by construction
    # A rejected signature is a configuration bug (wrong per-job secret /
    # clock skew), not a transport failure: it subclasses RuntimeError and
    # its message mentions "rendezvous", so both gates would pass -- rule
    # it out explicitly before they run.
    try:
        from ..run.http_kv import RendezvousAuthError
        if isinstance(err, RendezvousAuthError):
            return False
    except ImportError:  # pragma: no cover - partial install
        pass
    text = f"{type(err).__name__}: {err}"
    # "rendezvous"/"urlopen error"/"timed out" cover KV-plane failures:
    # http_kv normalizes urllib's URLError (an OSError subclass, so gate
    # 1 already passes) into ConnectionError("rendezvous GET /kv/...:
    # <urlopen error ...>"), and socket timeouts surface as plain
    # "timed out" with no other signature.
    needles = ("DEADLINE_EXCEEDED", "UNAVAILABLE", "connection",
               "Connection", "gloo", "Gloo", "distributed", "heartbeat",
               "coordinator", "barrier timed out", "preempt",
               "Socket closed", "recv", "peer", "rendezvous",
               "urlopen error", "timed out", "chaos")
    if isinstance(err, _comm_error_types()):
        return any(n in text for n in needles)
    if str(err).startswith(_STATUS_PREFIXES):
        return any(n in text for n in needles)
    return False


def check_for_host_updates(state: State) -> None:
    """Raise ``HostsUpdatedInterrupt`` when the driver advanced the epoch.

    Call at commit boundaries (``JaxState.commit`` callers do this via the
    run loop; explicit calls are allowed anywhere in user code).
    """
    from . import preemption
    notifier: Notifier = getattr(state, "_hvd_notifier", None)
    if preemption.notice_received():
        if notifier is not None and notifier.enabled:
            if notifier.excluded_from_current():
                # The latest epoch already excludes this worker: the
                # SIGTERM was the DRIVER's eviction (scale-down,
                # heartbeat), not a cloud preemption -- don't mark, just
                # take the interrupt and leave via the loop top.
                state.on_hosts_updated()
                raise HostsUpdatedInterrupt()
            # Announce ONCE and keep participating: exiting now would
            # strand peers already inside the next step's collective
            # (Gloo blocks forever on a vanished member -- measured).
            # The driver answers the marker with a new epoch excluding
            # this worker, which interrupts EVERYONE at a commit
            # boundary -- the same coordinated teardown the scale-down
            # path uses (SURVEY.md 5.3 graceful preemption).
            if not preemption.announced():
                if notifier.mark_preempted():  # else: retry next commit
                    preemption.set_announced()
        else:
            # No driver to coordinate: best effort, leave at this
            # boundary with the snapshot saved.
            state.on_hosts_updated()
            raise HostsUpdatedInterrupt()
    if notifier is None or not notifier.enabled:
        return
    doc = notifier.updated()
    if doc:
        state.on_hosts_updated()
        raise HostsUpdatedInterrupt()


def _reinitialize(notifier: Notifier) -> None:
    """Full comm-plane rebuild against the latest assignment."""
    _basics.shutdown()
    doc = None
    found = False
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        doc = notifier.read()
        if doc and doc["epoch"] > notifier.current_epoch and \
                notifier.worker_id in doc["ranks"]:
            found = True
            break
        time.sleep(0.5)
    if not found:
        raise HorovodInternalError(
            "no new elastic assignment including this worker was published "
            "before the deadline (driver gone, or this worker scaled out)")
    notifier.accept(doc)
    rank, size = doc["ranks"][notifier.worker_id], doc["size"]
    os.environ["HOROVOD_RANK"] = str(rank)
    os.environ["HOROVOD_SIZE"] = str(size)
    # Single-host driver: local == global (matches run.launch.worker_env).
    os.environ["HOROVOD_LOCAL_RANK"] = str(rank)
    os.environ["HOROVOD_LOCAL_SIZE"] = str(size)
    os.environ["HVD_TPU_COORDINATOR_PORT"] = str(doc["port"])
    try:
        jax.distributed.shutdown()
    except Exception:  # pragma: no cover - client may already be gone
        pass
    # Tear the XLA backends down so jax.distributed can re-initialize in
    # process -- the TPU-native equivalent of the reference's full
    # shutdown/re-init comm-plane rebuild.
    from jax._src import xla_bridge
    xla_bridge._clear_backends()
    jax.clear_caches()
    _basics.init()


def run(func: Callable[..., Any]) -> Callable[..., Any]:
    """``@hvd.elastic.run`` decorator: ``run(train)(state, *args)``."""

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        from ..core.config import _env_bool
        from . import preemption
        if not _env_bool("ELASTIC_NO_SIGTERM"):
            preemption.install_sigterm()
        if _env_bool("ELASTIC_PREEMPT_POLL"):
            preemption.start_gce_poll()
        notifier = Notifier()
        state._hvd_notifier = notifier
        heartbeat = None
        if notifier.enabled and notifier.worker_id:
            # Liveness signal for the driver's stall plane (StallInspector
            # analogue at the process level).  Beats are gated on the stall
            # inspector: a worker wedged in a blocking collective stops
            # beating, so the driver's heartbeat timeout can evict it.
            from ..core.stall import (HeartbeatWriter, KVHeartbeatWriter,
                                      progress_gate)
            if notifier.path.startswith("http://"):
                from ..run.secret import SECRET_ENV
                heartbeat = KVHeartbeatWriter(
                    notifier.path, notifier.worker_id,
                    os.environ.get(SECRET_ENV, ""), gate=progress_gate)
            else:
                heartbeat = HeartbeatWriter(
                    heartbeat_path(notifier.path, notifier.worker_id),
                    gate=progress_gate)
        try:
            return _elastic_loop(func, state, notifier, args, kwargs)
        finally:
            if heartbeat is not None:
                heartbeat.stop()

    return wrapper


def _desync_max_retries() -> int:
    """Config-time knob (HOROVOD_DESYNC_MAX_RETRIES), read at use time like
    every other HOROVOD_* flag."""
    from ..core.state import global_state
    st = global_state()
    if st.initialized and st.config is not None:
        return st.config.desync_max_retries
    from ..core.config import load_config
    return load_config().desync_max_retries


def apply_resize(state, old_size, new_size) -> None:
    """World-size transition sequence, shared by the training loop and
    the serving control plane.

    Exactly the reset/resize steps ``_elastic_loop`` runs after a
    re-rendezvous, with no training assumptions: account lost ranks in
    ``horovod_elastic_ranks_lost``, hand the transition to
    ``state.resize`` when the carrier implements it (checkpointless
    repartition for a training carry, drain/re-prefill for a serving
    mesh), fall back to plain sync semantics when resize fails, and
    finish with ``state.on_reset()``.  ``old_size`` may be ``None``
    (first rendezvous -- nothing to resize).
    """
    if old_size is not None and new_size != old_size:
        from ..timeline import metrics as _metrics
        if new_size < old_size:
            _metrics.registry().counter(
                "horovod_elastic_ranks_lost",
                "Ranks lost across elastic recoveries").inc(
                    old_size - new_size)
        if hasattr(state, "resize"):
            try:
                report = state.resize(old_size, new_size)
                logger.info(
                    "checkpointless resize %d -> %d: %s",
                    old_size, new_size, report)
            except Exception:
                # sync() still rebroadcasts whatever rank 0
                # holds; worst case the optimizer state is
                # re-derived instead of carried.
                logger.exception(
                    "checkpointless resize %d -> %d failed; "
                    "falling back to plain sync", old_size,
                    new_size)
    state.on_reset()


def _rollback_or_restore(state) -> None:
    """Recover committed state, preferring the snapshot ledger.

    ``rollback()`` (JaxState, HOROVOD_SNAPSHOT_STEPS > 0) steps back to a
    pre-anomaly ledger entry -- the last *commit* may already hold
    poisoned state.  When the ledger is off/empty (or the carrier has no
    ledger) this degrades to plain ``restore()``.
    """
    rollback = getattr(state, "rollback", None)
    if rollback is not None:
        try:
            report = rollback()
            if report is not None:
                logger.warning("rolled back to ledger snapshot %s", report)
                return
        except Exception:
            logger.exception("snapshot-ledger rollback failed; falling "
                             "back to plain restore")
    state.restore()


def _elastic_loop(func, state, notifier, args, kwargs):
    from . import preemption

    reset_required = False
    desync_retries = 0
    commit_baseline = None  # commit count right after the last sync()
    while True:
        if preemption.notice_received():
            # Reached after the coordinated interrupt (or a comm
            # failure): state is committed, the driver already has the
            # marker, peers are rolling to the new epoch.  Leave without
            # an explicit comm-plane teardown -- the process exit closes
            # the transports (same as a scale-down removal), while an
            # in-loop jax.distributed.shutdown here would tangle its
            # coordination Shutdown barrier with the survivors'
            # re-initialization.
            logger.warning("preemption notice honored (%s); exiting "
                           "after commit", preemption.reason())
            print("preempted: exiting gracefully after commit", flush=True)
            return None
        if reset_required:
            from ..core.config import _env_bool
            old_size = _basics.size() if _basics.is_initialized() else None
            _reinitialize(notifier)
            if _env_bool("ELASTIC_PREEMPT_POLL"):
                # GlobalState.reset (inside _reinitialize's shutdown)
                # stopped the metadata poll; re-arm it for the new life.
                preemption.start_gce_poll()
            new_size = _basics.size()
            apply_resize(state, old_size, new_size)
            reset_required = False
        try:
            # sync() ends in commit(), which may itself raise
            # HostsUpdatedInterrupt -- keep it inside the catch.
            state.sync()
            commit_baseline = getattr(state, "_commit_count", 0)
            return func(state, *args, **kwargs)
        except HostsUpdatedInterrupt:
            logger.info("hosts updated; re-rendezvousing")
            reset_required = True
        except CorruptRankError as e:
            # The in-band tripwire attributed divergent replicas to
            # specific rank(s) by majority vote -- bitflip-class SDC, not
            # a membership change.  The attributed rank must not carry
            # its replica forward: it leaves at this boundary (the
            # driver's next epoch excludes it, the same teardown the
            # heartbeat-eviction path uses), while survivors roll back
            # past the corruption window and re-rendezvous into the
            # shrunk world.
            my_rank = _basics.rank() if _basics.is_initialized() else None
            if my_rank is not None and my_rank in e.ranks:
                logger.error("tripwire attributed THIS rank (%d) as "
                             "corrupt; exiting for quarantine", my_rank)
                raise
            logger.warning("tripwire attributed corrupt rank(s) %s; "
                           "rolling back and re-rendezvousing without "
                           "them", e.ranks)
            _rollback_or_restore(state)
            reset_required = True
        except SustainedAnomalyError as e:
            # The in-step guard skipped HOROVOD_GUARD_STREAK consecutive
            # updates: skipping forward cannot recover, but no membership
            # change happened either -- roll back (ledger-first) and let
            # the loop-top sync() replay from the snapshot.  Shares the
            # desync consecutive-failure cap: an anomaly that survives
            # rollback+replay (deterministically poisoned input) must not
            # spin this loop forever.
            commits = getattr(state, "_commit_count", 0)
            if commit_baseline is not None and commits > commit_baseline:
                desync_retries = 0
            commit_baseline = commits
            desync_retries += 1
            cap = _desync_max_retries()
            if desync_retries > cap:
                logger.error("sustained anomaly persisted through %d "
                             "rollback+replay attempts; giving up", cap)
                raise
            logger.warning("%s (attempt %d/%d)", e, desync_retries, cap)
            _rollback_or_restore(state)
        except DesyncError as e:
            # Raised symmetrically on every rank by the commit-boundary
            # checksum (the check runs BEFORE the snapshot is overwritten,
            # so the last commit is still converged).  No membership
            # change happened, so no re-rendezvous: restore and let the
            # loop-top sync() rebroadcast rank 0's copy.  A cause that
            # survives restore+sync (non-deterministic pipeline, an
            # unchecksummable leaf) would otherwise spin this loop
            # forever, so cap CONSECUTIVE failures: a successful in-func
            # commit since the last sync() (commit counter moved past the
            # post-sync baseline) means the last recovery worked, and the
            # count starts over.  sync()'s own commit is excluded -- it
            # always succeeds after a broadcast and would otherwise make a
            # persistent desync look like progress.
            commits = getattr(state, "_commit_count", 0)
            if commit_baseline is not None and commits > commit_baseline:
                desync_retries = 0
            commit_baseline = commits
            desync_retries += 1
            cap = _desync_max_retries()
            if desync_retries > cap:
                logger.error("replica desync persisted through %d "
                             "restore+sync attempts; giving up", cap)
                raise
            logger.warning("replica desync (%s); restoring last commit and "
                           "re-syncing from rank 0 (attempt %d/%d)", e,
                           desync_retries, cap)
            state.restore()
        except HorovodInternalError:
            logger.warning("collective failed; rolling back to last "
                           "commit")
            state.restore()
            reset_required = True
        except Exception as e:  # noqa: BLE001
            if _looks_like_comm_failure(e):
                logger.warning("comm-plane failure (%s); rolling back",
                               type(e).__name__)
                state.restore()
                reset_required = True
            else:
                raise
