"""Speculative-decoding drafters.

Speculative decoding splits each decode round into a cheap PROPOSE pass
(k draft tokens per slot) and one fixed-shape VERIFY dispatch of the
target model (:func:`~horovod_tpu.serving.decode.build_verify_step`,
width ``k + 1``).  The engine accepts each slot's longest draft prefix
that agrees with the target's own argmaxes plus the target's token at
the first disagreement -- so the emitted stream is bitwise identical to
plain greedy decode no matter how bad the drafter is; the drafter only
moves THROUGHPUT (one verify dispatch can emit up to ``k + 1`` tokens
where plain decode needs ``k + 1`` dispatches).

Two drafters:

* :class:`NgramDrafter` -- prompt-lookup drafting on the host: propose
  the continuation that followed the most recent earlier occurrence of
  the current suffix n-gram in ``prompt + emitted``.  Zero device cost,
  no state beyond the request itself, and surprisingly effective on
  repetitive streams (code, templated text, greedy toy models).
* :class:`ModelDrafter` -- a small Llama run through its OWN paged
  cache and one-token decode step on a single-device mesh (drafting is
  tiny; sharding it would waste ICI).  Keeps its cache exactly one
  token behind the target's context and rolls back rejected drafts by
  the same masking contract the target cache uses (garbage above
  ``lengths`` is unreachable).

Both expose the same four hooks the engine drives:
``on_admit(slot, req)`` after target prefill, ``propose(reqs, k,
last_tokens)`` before each verify, ``observe(slot, req, accepted)``
after it, and ``on_release(slot)`` when the slot recycles.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .decode import build_decode_step, greedy_sample, prefill_forward
from .kvcache import CacheConfig, PagedKVCache
from .scheduler import Request


class NgramDrafter:
    """Prompt-lookup drafting: no draft model, no device work.

    For each slot, search ``prompt + emitted`` (excluding the final
    token) backwards for the most recent earlier occurrence of the
    current ``ngram``-token suffix; propose the tokens that followed
    it.  Falls back to shorter suffixes, then to repeating the last
    token (a draft is never "missing" -- the verify step needs a full
    ``[slots, k]`` block and wrong drafts only cost acceptance).
    """

    def __init__(self, ngram: int = 2):
        if ngram < 1:
            raise ValueError(f"ngram must be >= 1, got {ngram}")
        self.ngram = ngram

    # -- engine hooks (stateless: everything lives on the request) -----
    def on_admit(self, slot: int, req: Request) -> None:
        pass

    def observe(self, slot: int, req: Request, accepted: int) -> None:
        pass

    def on_release(self, slot: int) -> None:
        pass

    def re_prefill(self, slot: int, req: Request) -> None:
        pass

    def propose(self, reqs: Dict[int, Request], k: int,
                last_tokens: np.ndarray) -> np.ndarray:
        slots = last_tokens.shape[0]
        out = np.zeros((slots, k), np.int32)
        for slot, req in reqs.items():
            ctx = np.concatenate([np.asarray(req.prompt, np.int32),
                                  np.asarray(req.tokens, np.int32)])
            out[slot] = self._lookup(ctx, k)
        return out

    def _lookup(self, ctx: np.ndarray, k: int) -> np.ndarray:
        n = len(ctx)
        for g in range(min(self.ngram, n - 1), 0, -1):
            suffix = ctx[n - g:]
            # Most recent earlier match of the suffix (exclude the
            # suffix's own position so the continuation is non-empty).
            for i in range(n - g - 1, -1, -1):
                if np.array_equal(ctx[i:i + g], suffix):
                    cont = ctx[i + g:i + g + k]
                    if len(cont):
                        out = np.empty((k,), np.int32)
                        out[:len(cont)] = cont
                        out[len(cont):] = cont[-1]
                        return out
        return np.full((k,), ctx[-1], np.int32)


class ModelDrafter:
    """Draft with a small Llama through its own single-device cache.

    The drafter's cache tracks the target's context minus its final
    token (that token is the round's first verify input, fed to the
    drafter as ``x0``).  During a propose round the drafter feeds
    ``x0, d1 .. d_{k-1}`` -- writing their K/V at its write head -- and
    :meth:`observe` then rolls the head back to the accepted prefix;
    rejected entries stay as masked garbage above ``lengths``, exactly
    the recycled-page contract.  If plain (non-speculative) decode ran
    in between (e.g. a control-plane drain), :meth:`propose` first
    catches the cache up token-by-token from the request's emitted
    stream, so the drafter tolerates arbitrary interleaving.
    """

    def __init__(self, config, params, *, slots: int, page_size: int,
                 max_len: int, dtype=jnp.float32):
        from jax.sharding import Mesh
        self.config = config
        self.params = params
        self.dtype = dtype
        self.mesh = Mesh(
            np.asarray(jax.devices()[:1], dtype=object).reshape(1),
            ("tp",))
        self.cache_config = CacheConfig(
            num_layers=config.num_layers,
            num_kv_heads=config.num_kv_heads, head_dim=config.head_dim,
            slots=slots, page_size=page_size, max_len=max_len,
            dtype=str(jnp.dtype(dtype)))
        self.cache = PagedKVCache(self.cache_config)
        self.step = build_decode_step(
            config, self.mesh, slots=slots, page_size=page_size,
            pages_per_slot=self.cache_config.pages_per_slot, dtype=dtype)
        self.slots = slots
        self.max_len = max_len

        def _prefill(p, toks):
            return prefill_forward(p, config, toks, dtype=dtype)

        self._prefill = jax.jit(_prefill)
        self._round_base: Dict[int, tuple] = {}

    # -- engine hooks --------------------------------------------------
    def on_admit(self, slot: int, req: Request) -> None:
        self._prefill_ctx(slot, np.asarray(req.prompt, np.int32))

    def re_prefill(self, slot: int, req: Request) -> None:
        self.cache.free_slot(slot)
        ctx = np.concatenate([np.asarray(req.prompt, np.int32),
                              np.asarray(req.tokens[:-1], np.int32)])
        self._prefill_ctx(slot, ctx)

    def on_release(self, slot: int) -> None:
        self.cache.free_slot(slot)

    def observe(self, slot: int, req: Request, accepted: int) -> None:
        # Roll the write head back to the accepted prefix: the round
        # wrote inputs (x0, d1..d_{k-1}); x0 plus the first ``accepted``
        # drafts are now real context, the rest is masked garbage.
        head = self._round_base.pop(slot, None)
        if head is None:
            return
        base, written = head
        self.cache.lengths[slot] = base + min(accepted + 1, written)

    def propose(self, reqs: Dict[int, Request], k: int,
                last_tokens: np.ndarray) -> np.ndarray:
        cache = self.cache
        # Catch up any slot whose cache trails context-minus-one (plain
        # decode rounds in between, or a full-acceptance round's +1 gap).
        self._catch_up(reqs)

        drafts = np.zeros((self.slots, k), np.int32)
        cur = np.zeros((self.slots,), np.int32)
        active = np.zeros((self.slots,), bool)
        base = np.zeros((self.slots,), np.int32)
        for slot, req in reqs.items():
            base[slot] = cache.lengths[slot]
            # A slot too close to its cap cannot host k writes; skip it
            # (its drafts stay 0 -- wrong drafts only cost acceptance).
            if base[slot] + k > self.max_len:
                continue
            cache.reserve(slot, int(base[slot]) + k)
            cur[slot] = req.tokens[-1]
            active[slot] = True
        if not active.any():
            return drafts
        for slot in reqs:
            if active[slot]:
                self._round_base[slot] = (int(base[slot]), k)
        table = cache.table_device()
        act_dev = jnp.asarray(active)
        for i in range(k):
            logits, cache.k, cache.v = self.step(
                self.params, cache.k, cache.v,
                jnp.asarray(cur), jnp.asarray(base + i), table, act_dev)
            cur = np.asarray(greedy_sample(logits))
            drafts[:, i] = np.where(active, cur, 0)
            cur = drafts[:, i].copy()
        return drafts

    # -- internals -----------------------------------------------------
    def _prefill_ctx(self, slot: int, ctx: np.ndarray) -> None:
        self.cache.reserve(slot, len(ctx))
        _, kl, vl = self._prefill(self.params, jnp.asarray(ctx)[None])
        self.cache.write_prefill(slot, kl[:, 0], vl[:, 0])

    def _catch_up(self, reqs: Dict[int, Request]) -> None:
        cache = self.cache
        while True:
            feed: Dict[int, int] = {}
            for slot, req in reqs.items():
                need = req.prompt_len + len(req.tokens) - 1
                have = int(cache.lengths[slot])
                if have < min(need, self.max_len):
                    # Token at context position ``have``.
                    pos = have
                    tok = (req.prompt[pos] if pos < req.prompt_len
                           else req.tokens[pos - req.prompt_len])
                    feed[slot] = int(tok)
            if not feed:
                return
            toks = np.zeros((self.slots,), np.int32)
            active = np.zeros((self.slots,), bool)
            for slot, tok in feed.items():
                cache.reserve(slot, int(cache.lengths[slot]) + 1)
                toks[slot] = tok
                active[slot] = True
            _, cache.k, cache.v = self.step(
                self.params, cache.k, cache.v, jnp.asarray(toks),
                cache.lengths_device(), cache.table_device(),
                jnp.asarray(active))
            for slot in feed:
                cache.lengths[slot] += 1
