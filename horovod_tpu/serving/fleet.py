"""Disaggregated serving fleet: prefill workers + decode workers.

Prefill is compute-bound (one big batched matmul over the whole
prompt); decode is bandwidth-bound (one token per step over resident
KV).  Colocating them on one mesh serializes the two regimes: every
admitted kilotoken prompt stalls the decode batch for a full prefill.
The fleet splits them -- prefill workers on their own (virtual) mesh
run :func:`~.decode.prefill_forward` and EXPORT the finished pages;
decode workers import those pages into their own
:class:`~.kvcache.PagedKVCache` and never burn a step on prompt math.

The only coupling is data: pages travel as :mod:`.kvwire` payloads
over the rendezvous KV plane (``run/http_kv.py`` chunked PUT/GET,
riding the PR 7 ``RetryPolicy``), and the f32 wire tier is bitwise, so
a disaggregated decode stream is bit-for-bit the colocated engine's
stream (per-slot logits are independent of batch composition -- the
PR 12 invariant -- and the imported pool bytes are identical).

Handoff lifecycle on the decode side::

    queued -> prefill -> handoff -> decode -> done
                 |          |
                 |          +-- pages in flight; slot occupied but
                 |              excluded from the decode batch
                 +-- admission assigned the slot; the fleet dispatched
                     the prompt to a prefill worker

A dead prefill worker (chaos ``kill``) degrades, never wedges: its
un-imported tickets' KV entries vanish, the decode worker's import
sees no manifest and falls back to a LOCAL prefill of the same prompt
(``handoffs_local``) -- the stream stays correct, only the offload is
lost.

The fleet's wall-clock model: workers are separate hosts, so one
driver-process iteration that runs prefill worker A 3ms and decode
worker B 5ms models 5ms of fleet time, not 8ms.  The serve loop keeps
the engines' virtual-clock discipline and *rebates* the serialized
remainder each iteration (``skip -= iter_real - max(per-host busy)``),
so tokens/s is measured against modeled concurrent wall with REAL
kernel timings.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..timeline import spans as _spans
from ..timeline.metrics import registry as _registry
from .controlplane import FleetScaler
from .decode import greedy_sample, prefill_forward
from .engine import ServingEngine, _pct
from .kvwire import decode_kv, encode_kv, import_pages, wire_tier
from .router import FleetRouter
from .scheduler import Request

__all__ = ["HandoffTicket", "PrefillWorker", "DecodeWorker",
           "ServingFleet", "FleetReport"]

_SCOPE = "pages"


@dataclasses.dataclass
class HandoffTicket:
    """One published prefill: the decode side needs only this to join
    the request into its batch (the pages themselves live in the KV
    plane under ``key``)."""

    rid: int
    key: str
    first: int                 # greedy first token (prefill's argmax)
    nbytes: int                # framed payload size on the wire
    worker: str                # prefill worker that produced it
    published_s: float         # virtual-clock publish instant


class PrefillWorker:
    """Prompt-only worker: runs the prefill forward, frames the K/V
    through :mod:`.kvwire`, and publishes it as a chunked KV object.

    The jitted forward mirrors ``ServingEngine._prefill`` exactly
    (same adapter-arg closure, same ``lora_alpha``), so its logits --
    and therefore the first sampled token and every exported K/V byte
    -- are bitwise what a colocated engine would have computed.
    """

    def __init__(self, name: str, config, params, kv, *,
                 page_size: int, dtype=jnp.float32,
                 tier: Optional[str] = None):
        self.name = name
        self.config = config
        self.params = params
        self.kv = kv
        self.page_size = int(page_size)
        self.tier = tier or wire_tier()
        self.alive = True
        self.prefills = 0
        self.busy_s = 0.0

        def _fwd(p, toks, ad, aid):
            return prefill_forward(p, config, toks, dtype=dtype,
                                   adapters=ad, adapter_id=aid,
                                   lora_alpha=16.0)

        self._fwd = jax.jit(_fwd)

    def run(self, req: Request, prompt_dev, now_s: float
            ) -> HandoffTicket:
        """Prefill ``req``'s prompt and publish its pages; returns the
        ticket the decode side imports against."""
        if not self.alive:
            raise RuntimeError(f"prefill worker {self.name} is dead")
        t0 = time.monotonic()
        with _spans.recorder().span("dispatch", name="fleet_prefill",
                                    leg="serving_fleet_prefill"):
            logits, kl, vl = self._fwd(self.params, prompt_dev[None],
                                       None, None)
            first = int(greedy_sample(logits[:, -1, :])[0])
            buf = encode_kv(np.asarray(kl[:, 0]), np.asarray(vl[:, 0]),
                            page_size=self.page_size, tier=self.tier)
        key = f"r{req.rid}"
        self.kv.put_large(_SCOPE, key, buf)
        self.busy_s += time.monotonic() - t0
        self.prefills += 1
        return HandoffTicket(rid=req.rid, key=key, first=first,
                             nbytes=len(buf), worker=self.name,
                             published_s=now_s)


class DecodeWorker:
    """One decode engine plus its per-run state and the import path."""

    def __init__(self, name: str, engine: ServingEngine, kv):
        self.name = name
        self.engine = engine
        self.kv = kv
        self.busy_s = 0.0
        # The auditor's serving configs read step metadata; tag the
        # role so a fleet trace distinguishes decode meshes from the
        # colocated baseline.
        engine.step._meta["fleet_role"] = "decode"
        self.st: Dict[str, Any] = {
            "completed": [], "occ_samples": [], "decode_steps": 0,
            "spec_rounds": 0, "proposed": 0, "accepted": 0,
            "prefix_queries": 0, "prefix_hits": 0,
            "prefill_cached": 0, "prefill_computed": 0,
            "session_resumes": 0,
            "last_tokens": np.zeros((engine.slots,), np.int32),
            "adapter_ids": np.zeros((engine.slots,), np.int32)}

    @property
    def scheduler(self):
        return self.engine.scheduler

    def complete_handoff(self, slot: int, req: Request,
                         ticket: HandoffTicket, now) -> Optional[int]:
        """Import a published payload into ``slot`` and join the
        request into the decode batch.  Returns the imported byte
        count, or None when the object is gone (publisher died and its
        entries were reaped) -- the caller falls back to
        :meth:`local_prefill`."""
        t0 = time.monotonic()
        with _spans.recorder().span("dispatch", name="handoff_import",
                                    leg="serving_handoff_import"):
            buf = self.kv.get_large(_SCOPE, ticket.key)
            if buf is None:
                return None
            wp = decode_kv(buf)
            import_pages(self.engine.cache, slot, wp)
            self.engine._join_decode(self.st, slot, req, ticket.first,
                                     now)
        self.kv.delete_large(_SCOPE, ticket.key)
        self.busy_s += time.monotonic() - t0
        return len(buf)

    def local_prefill(self, slot: int, req: Request, prompt_dev,
                      now) -> None:
        """Fallback: compute the prompt here (colocated-style) when no
        prefill worker can serve it."""
        t0 = time.monotonic()
        first = self.engine._do_prefill(slot, req, prompt_dev)
        self.engine._join_decode(self.st, slot, req, first, now)
        self.busy_s += time.monotonic() - t0

    def decode_step(self, now) -> float:
        t0 = time.monotonic()
        self.engine.decode_once(self.st, now)
        dt = time.monotonic() - t0
        self.busy_s += dt
        return dt


@dataclasses.dataclass
class FleetReport:
    """One fleet run's outcome (the BENCH_r20 drill's raw material)."""

    num_requests: int
    completed: int
    rejected: int
    prompt_tokens: int
    new_tokens: int
    wall_s: float                      # modeled concurrent wall
    tokens_per_s: float
    ttft_p50_s: float
    ttft_p99_s: float
    decode_steps: int
    engines: int                       # decode engines at end of run
    handoffs_streamed: int
    handoffs_local: int
    migrated: int
    kv_bytes_out: int
    kv_bytes_in: int
    slo_violation_s: float
    leaked_pages: Dict[str, int]       # per decode engine, must be all 0
    refcounts_balanced: bool
    per_engine_completed: Dict[str, int]

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class ServingFleet:
    """Router + prefill workers + decode workers on one virtual clock."""

    def __init__(self, prefill_workers: Sequence[PrefillWorker],
                 decode_workers: Sequence[DecodeWorker], kv, *,
                 router: Optional[FleetRouter] = None,
                 scaler_policy=None,
                 engine_factory: Optional[Callable[[], ServingEngine]]
                 = None):
        if not decode_workers:
            raise ValueError("a fleet needs at least one decode worker")
        self.prefill_workers = list(prefill_workers)
        self.decode = {w.name: w for w in decode_workers}
        self.kv = kv
        self.router = router or FleetRouter()
        for name, w in self.decode.items():
            self.router.register(name, w.scheduler)
        self.engine_factory = engine_factory
        self.scaler = (FleetScaler(self, policy=scaler_policy)
                       if scaler_policy is not None else None)
        self.migrated = 0
        self._rr = 0  # round-robin cursor over alive prefill workers
        reg = _registry()
        self._m_handoffs = reg.counter(
            "horovod_fleet_handoffs_total",
            "Prefill->decode handoffs by outcome (streamed = imported "
            "over the KV plane, local = fallback prefill on the decode "
            "mesh)", labelnames=("outcome",))
        self._m_kv_bytes = reg.counter(
            "horovod_fleet_kv_bytes_total",
            "Framed KV-page bytes moved over the rendezvous plane",
            labelnames=("direction",))
        self._m_handoff_lat = reg.histogram(
            "horovod_fleet_handoff_latency_seconds",
            "Publish-to-import latency of streamed handoffs")
        self._m_migrated = reg.counter(
            "horovod_fleet_migrated_total",
            "Queued requests migrated to a freshly commissioned decode "
            "engine")

    # -- FleetScaler duck-type surface -------------------------------------
    def schedulers(self) -> Dict[str, Any]:
        return {n: w.scheduler for n, w in self.decode.items()}

    @property
    def num_engines(self) -> int:
        return len(self.decode)

    def add_decode_worker(self, reason: str = "manual") -> str:
        """Grow-by-adding-capacity: commission a decode engine UNDER
        LIVE TRAFFIC.  The new engine is built by ``engine_factory``
        (same mesh spec as its siblings, so the exchange-plan compile
        cache makes the bring-up a fingerprint hit), registered with
        the router, and seeded by migrating half of the most-loaded
        sibling's queue -- arrivals it has not started are the only
        thing that moves; in-flight slots stay put."""
        if self.engine_factory is None:
            raise RuntimeError(
                "fleet has no engine_factory; cannot add capacity")
        name = f"decode{len(self.decode)}"
        worker = DecodeWorker(name, self.engine_factory(), self.kv)
        self.decode[name] = worker
        self.router.register(name, worker.scheduler)
        donor = max((w for n, w in self.decode.items() if n != name),
                    key=lambda w: len(w.scheduler.queue))
        moved = 0
        dq, nq = donor.scheduler.queue, worker.scheduler.queue
        for _ in range(len(dq) // 2):
            nq.append(dq.pop())   # newest arrivals re-home
            moved += 1
        donor.scheduler._update_gauges()
        worker.scheduler._update_gauges()
        self.migrated += moved
        self._m_migrated.inc(moved)
        _spans.recorder().add("ctl", 0.0,
                              leg=f"ctl/add-engine/{reason}")
        return name

    def kill_prefill(self, name: str) -> int:
        """Chaos: a prefill host dies.  Published-but-unimported
        objects it owns are reaped from the KV plane (their manifests
        vanish mid-handoff), so the decode side exercises the
        lost-object fallback.  Returns how many tickets were reaped."""
        reaped = 0
        for w in self.prefill_workers:
            if w.name == name and w.alive:
                w.alive = False
                for h in self._in_flight:
                    if h["ticket"].worker == name and not h["done"]:
                        self.kv.delete_large(_SCOPE, h["ticket"].key)
                        reaped += 1
        return reaped

    def _alive_prefill(self) -> List[PrefillWorker]:
        return [w for w in self.prefill_workers if w.alive]

    # -- the serve loop ----------------------------------------------------
    def serve(self, requests: Sequence[Request], *,
              kill_prefill_at_step: Optional[int] = None,
              kill_prefill_name: Optional[str] = None) -> FleetReport:
        """Run the open-loop stream across the fleet to completion."""
        pending = sorted(requests, key=lambda r: r.arrival_s)
        rejected = 0
        admissible: List[Request] = []
        for req in pending:
            cap = min(w.engine.max_len for w in self.decode.values())
            if req.prompt_len + req.max_new_tokens > cap:
                rejected += 1
            else:
                admissible.append(req)
        feed = list(admissible)
        fi = 0

        # Worker state persists across serve() calls (sessions may span
        # runs); the report must cover THIS run only, so snapshot the
        # accumulators and count deltas.
        base_completed = {n: len(w.st["completed"])
                          for n, w in self.decode.items()}
        base_steps = {n: w.st["decode_steps"]
                      for n, w in self.decode.items()}
        base_migrated = self.migrated

        start = time.monotonic()
        skip = 0.0

        def now() -> float:
            return time.monotonic() - start + skip

        prompts_dev: Dict[int, Any] = {}
        # Streamed handoffs move through three iteration phases:
        # dispatched (this iter) -> imported (next iter) -> done.  The
        # one-iteration gap keeps the ``handoff`` slot state visible
        # across at least one decode round, like a real network hop.
        self._in_flight: List[dict] = []
        handoffs_streamed = 0
        handoffs_local = 0
        kv_out = 0
        kv_in = 0
        overhead = 0.0   # serialized-in-driver time rebated each iter
        step = 0

        while True:
            step += 1
            iter_t0 = time.monotonic()
            busy: Dict[str, float] = {}

            # 1. Arrivals: route each due request to a decode engine.
            while fi < len(feed) and feed[fi].arrival_s <= now():
                req = feed[fi]
                fi += 1
                prompts_dev[req.rid] = jax.device_put(
                    jnp.asarray(req.prompt, jnp.int32))
                engine, _reason = self.router.route(req)
                self.decode[engine].scheduler.submit(req)

            # 2. Chaos fault.
            if kill_prefill_at_step is not None \
                    and step == kill_prefill_at_step:
                victim = (kill_prefill_name
                          or self.prefill_workers[0].name)
                self.kill_prefill(victim)

            # 3. Import last iteration's in-flight pages.
            for h in self._in_flight:
                w = self.decode[h["engine"]]
                t0 = time.monotonic()
                got = w.complete_handoff(h["slot"], h["req"],
                                         h["ticket"], now)
                if got is None:
                    # Publisher died and its object was reaped: the
                    # prompt is re-computed locally; the stream stays
                    # correct, only the offload is lost.
                    w.local_prefill(h["slot"], h["req"],
                                    prompts_dev[h["req"].rid], now)
                    handoffs_local += 1
                    self._m_handoffs.labels(outcome="local").inc()
                else:
                    kv_in += got
                    self._m_kv_bytes.labels(direction="in").inc(got)
                    handoffs_streamed += 1
                    self._m_handoffs.labels(outcome="streamed").inc()
                    self._m_handoff_lat.observe(
                        max(now() - h["ticket"].published_s, 0.0))
                prompts_dev.pop(h["req"].rid, None)
                h["done"] = True
                busy[h["engine"]] = busy.get(h["engine"], 0.0) \
                    + (time.monotonic() - t0)
            self._in_flight.clear()

            # 4. Admissions: new slots go to handoff (remote prefill)
            # or straight to a local prefill when no worker is alive.
            dispatch: List[dict] = []
            for name, w in self.decode.items():
                for slot, req in w.scheduler.admit(now()):
                    if self._alive_prefill():
                        w.scheduler.note_handoff(req)
                        dispatch.append({"engine": name, "slot": slot,
                                         "req": req})
                    else:
                        t0 = time.monotonic()
                        w.local_prefill(slot, req,
                                        prompts_dev.pop(req.rid), now)
                        handoffs_local += 1
                        self._m_handoffs.labels(outcome="local").inc()
                        busy[name] = busy.get(name, 0.0) \
                            + (time.monotonic() - t0)

            # 5. Dispatch prefills round-robin over alive workers.
            for d in dispatch:
                workers = self._alive_prefill()
                w = workers[self._rr % len(workers)]
                self._rr += 1
                t0 = time.monotonic()
                ticket = w.run(d["req"], prompts_dev[d["req"].rid],
                               now())
                kv_out += ticket.nbytes
                self._m_kv_bytes.labels(direction="out").inc(
                    ticket.nbytes)
                host = f"prefill:{w.name}"
                busy[host] = busy.get(host, 0.0) \
                    + (time.monotonic() - t0)
                d["ticket"] = ticket
                d["done"] = False
                self._in_flight.append(d)

            # 6. One decode round per engine with live decode slots.
            for name, w in self.decode.items():
                if w.engine._decode_slots():
                    dt = w.decode_step(now)
                    busy[name] = busy.get(name, 0.0) + dt

            # 7. Fleet controller.
            if self.scaler is not None:
                self.scaler.tick(now())

            # 8. Clock rebate: hosts ran concurrently, so the fleet
            # only aged by the busiest host's time this iteration.
            iter_real = time.monotonic() - iter_t0
            model = min(max(busy.values(), default=0.0), iter_real)
            overhead += iter_real - model
            skip -= (iter_real - model)

            has_work = (self._in_flight
                        or any(w.scheduler.has_work()
                               for w in self.decode.values()))
            if not has_work:
                if fi >= len(feed):
                    break
                gap = feed[fi].arrival_s - now()
                if gap > 0:
                    skip += gap

        wall_s = max(time.monotonic() - start - overhead, 1e-9)
        # End-of-run leak gate, per decode engine: drop the radix
        # tree's own refs, then every page must return to the pool.
        leaked: Dict[str, int] = {}
        balanced = True
        per_engine: Dict[str, int] = {}
        completed: List[Request] = []
        for name, w in self.decode.items():
            if w.engine._prefix is not None:
                w.engine._prefix.drop_all()
            leaked[name] = w.engine.cache.release_all()
            balanced = balanced and w.engine.cache.refcounts_balanced()
            done = w.st["completed"][base_completed.get(name, 0):]
            per_engine[name] = len(done)
            completed.extend(done)

        new_tokens = sum(len(r.tokens) for r in completed)
        ttfts = [r.ttft_s for r in completed if r.ttft_s is not None]
        return FleetReport(
            num_requests=len(requests), completed=len(completed),
            rejected=rejected,
            prompt_tokens=sum(r.prompt_len for r in completed),
            new_tokens=new_tokens, wall_s=wall_s,
            tokens_per_s=new_tokens / wall_s,
            ttft_p50_s=_pct(ttfts, 50), ttft_p99_s=_pct(ttfts, 99),
            decode_steps=sum(w.st["decode_steps"] - base_steps.get(n, 0)
                             for n, w in self.decode.items()),
            engines=len(self.decode),
            handoffs_streamed=handoffs_streamed,
            handoffs_local=handoffs_local,
            migrated=self.migrated - base_migrated,
            kv_bytes_out=kv_out, kv_bytes_in=kv_in,
            slo_violation_s=(self.scaler.slo_violation_s
                             if self.scaler else 0.0),
            leaked_pages=leaked, refcounts_balanced=balanced,
            per_engine_completed=per_engine)
