"""Fleet router: prefix-affinity + least-loaded dispatch across engines.

A disaggregated fleet runs several decode engines behind one front
door.  The router decides which engine's scheduler a request joins,
reading only live gauges (queue depth + active slots vs capacity --
the same numbers the ``horovod_serving_*`` families export), so the
decision needs no side channel into engine internals.

Dispatch precedence:

1. ``engine_hint`` on the request (loadgen's per-engine arrival skew,
   or a session pinned by an external LB) -- honored verbatim while
   that engine is registered.
2. Prefix affinity (``HOROVOD_FLEET_AFFINITY``, default on): requests
   whose prompts share a head hash to the same engine, so the PR 18
   radix prefix cache sees repeat prefixes instead of having them
   sprayed across pools.  The hash is CRC32 over the first
   ``affinity_tokens`` prompt tokens -- cheap, stable across runs, and
   deliberately coarser than the radix tree (the tree disambiguates
   once the request lands).
3. Overload spill: when the affinity target's load score exceeds
   ``spill_factor``x the fleet minimum, locality loses to the queue --
   the request spills to the least-loaded engine.
4. Least-loaded (no affinity, or affinity disabled): lowest
   ``(queued + active) / slots``, registration order breaking ties so
   dispatch is deterministic.

Every decision increments ``horovod_fleet_dispatch_total{engine,
reason}``; ``horovod_fleet_engines`` gauges the live registry so the
grow-under-traffic drill shows capacity arriving.
"""

from __future__ import annotations

import collections
import zlib
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..core.config import _env_bool
from ..timeline.metrics import registry as _registry
from .scheduler import ContinuousBatchScheduler, Request


class FleetRouter:
    """Routes requests to named engine schedulers off live load gauges."""

    def __init__(self, *, affinity: Optional[bool] = None,
                 affinity_tokens: int = 16,
                 spill_factor: float = 2.0) -> None:
        self.affinity = (_env_bool("FLEET_AFFINITY", True)
                         if affinity is None else bool(affinity))
        self.affinity_tokens = int(affinity_tokens)
        self.spill_factor = float(spill_factor)
        # name -> scheduler; insertion order is registration order and
        # the deterministic tie-break.
        self.engines: "collections.OrderedDict[str, ContinuousBatchScheduler]" = \
            collections.OrderedDict()
        reg = _registry()
        self._m_dispatch = reg.counter(
            "horovod_fleet_dispatch_total",
            "Fleet router dispatch decisions",
            labelnames=("engine", "reason"))
        self._m_engines = reg.gauge(
            "horovod_fleet_engines",
            "Decode engines currently registered with the fleet router")

    # -- registry ----------------------------------------------------------
    def register(self, name: str, sched: ContinuousBatchScheduler) -> None:
        self.engines[name] = sched
        self._m_engines.set(len(self.engines))

    def deregister(self, name: str) -> None:
        self.engines.pop(name, None)
        self._m_engines.set(len(self.engines))

    # -- load --------------------------------------------------------------
    def load_score(self, name: str) -> float:
        """Outstanding work per slot: ``(queued + active) / slots``.
        >1 means a backlog beyond what the decode batch can hold."""
        s = self.engines[name]
        return (len(s.queue) + len(s.active)) / max(s.slots, 1)

    def _least_loaded(self) -> str:
        return min(self.engines, key=lambda n: (self.load_score(n),
                                                self._order(n)))

    def _order(self, name: str) -> int:
        return list(self.engines).index(name)

    def prefix_key(self, prompt: Sequence[int]) -> int:
        head = np.asarray(list(prompt)[:self.affinity_tokens], np.int32)
        return zlib.crc32(head.tobytes())

    # -- dispatch ----------------------------------------------------------
    def route(self, req: Request) -> Tuple[str, str]:
        """Pick an engine for ``req``; returns ``(engine, reason)`` with
        reason one of ``hint | affinity | spill | least-loaded``."""
        if not self.engines:
            raise RuntimeError("fleet router has no registered engines")
        names = list(self.engines)
        hint = getattr(req, "engine_hint", None)
        if hint is not None and 0 <= int(hint) < len(names):
            choice, reason = names[int(hint)], "hint"
        elif self.affinity:
            target = names[self.prefix_key(req.prompt) % len(names)]
            floor = min(self.load_score(n) for n in names)
            if self.load_score(target) > self.spill_factor * max(floor,
                                                                 1e-9) \
                    and self.load_score(target) > 0:
                choice, reason = self._least_loaded(), "spill"
            else:
                choice, reason = target, "affinity"
        else:
            choice, reason = self._least_loaded(), "least-loaded"
        self._m_dispatch.labels(engine=choice, reason=reason).inc()
        return choice, reason

    def snapshot(self) -> Dict[str, float]:
        """Live load score per engine (router's own decision inputs)."""
        return {n: self.load_score(n) for n in self.engines}
